"""Autoregressive decode: one AOT-compiled step over a paged KV pool.

Every other serving path in the repo is single-shot encode→decode;
this module adds the streaming scenario (ROADMAP item 2) with the
perf shape as the contract: **per-token cost is O(1) in generated
length**, because each step re-reads a fixed-shape donated carry
instead of re-encoding the growing prefix.

The carry — donated to the step executable and re-donated every
step — is::

    {"kv": {"k1","v1"[,"kn","vn"]}   (num_pages, page_size, H, Dh)
     "lengths":     (R,) int32        tokens cached per stream slot
     "page_tables": (R, PPS) int32    logical→physical page map}

``k1/v1`` cache the *encoder cross-attention K/V projections* of
each consumed token for the unshared first layer; ``kn/vn`` for the
weight-shared ``layer_n`` (only when ``num_layers > 1``). That is
the whole loop-carried state of a Perceiver-IO decode: latents are
cheap (N×C per stream) and recomputed from the pools each step,
which keeps the cache *per-token* and therefore pageable — the same
block machinery as the ragged serve path (PAPERS: "Ragged Paged
Attention"; the stepped-executable framing follows "Compiler-First
State Space Duality and Portable O(1) Autoregressive Caching").

One step consumes a per-row *ragged chunk* of tokens — up to
``max_chunk`` prompt tokens for a prefilling row, exactly one for a
decoding row, zero for an idle slot — and emits the model's
prediction for each row's next position:

1. embed ``tokens[r, :qlens[r]]`` at positions ``lengths[r] + j``;
2. project each chunk token's K/V per kv set and scatter into the
   pools at ``(page_tables[r, pos // page_size], pos % page_size)``
   — invalid lanes are redirected to the reserved trash page 0;
3. rebuild latents ONCE per step: ``layer_1`` + scanned ``layer_n``,
   each cross-attending the pools through the ragged paged kernel
   (:func:`~perceiver_tpu.ops.paged_attention.paged_decode_attention`,
   the decode-shaped delegate of ``ragged_paged_attention``) at
   per-row ``kv_len = lengths[r] + qlens[r]``;
4. decode one query row at position ``lengths[r] + qlens[r]`` →
   vocab logits → greedy ``next_token`` (+ top-k sidecar).

Chunked prefill therefore reuses the same executable: a stream's
prompt feeds through in ``max_chunk``-token slices co-scheduled with
in-flight decode rows under one per-step token budget
(``batcher.ContinuousBatchScheduler.plan_chunks``), so the engine
owns exactly ONE compiled signature, token N costs the same as token
1, and time-to-first-token collapses from one latent rebuild *per
prompt token* to one per chunk — the decode bench
(``scripts/bench_decode.py``) pins the O(1) ratio, a TTFT gate, and
zero post-warmup compiles as merge gates.

``DecodeEngine`` drives the step host-side: a page allocator
(:class:`PagePool`), unified continuous batching (streams join and
leave mid-flight via ``batcher.ContinuousBatchScheduler`` — freed
pages recycle with no fragmentation because any page serves any
stream), per-stream token callbacks / blocking iterators, tracing
(``prefill_chunk`` / ``decode_step`` / ``token_emit`` spans), typed
events (``stream_open`` / ``stream_admitted`` / ``prefill_complete``
/ ``stream_close``), and metrics. Shedding follows the batcher
conventions: an over-capacity or expired request resolves to a typed
:class:`~perceiver_tpu.serving.batcher.Overloaded` value; a request
that can *never* fit the geometry raises
:class:`~perceiver_tpu.serving.engine.RequestTooLarge` at submit.

Unlike ``serving/engine.py`` (sync-free by lint), this module is a
consumer layer: it owns the one deliberate device sync per step
(materializing ``next_token``), exactly like ``serving/api.py``.
"""

from __future__ import annotations

import dataclasses
import queue as queue_mod
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from perceiver_tpu.cache import aot_compile
from perceiver_tpu.obs import events as events_mod
from perceiver_tpu.obs import trace as trace_mod
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY
from perceiver_tpu.serving.batcher import (
    ContinuousBatchScheduler,
    Overloaded,
)
from perceiver_tpu.serving.engine import (
    RequestTooLarge,
    resolve_exec_cache,
)
from perceiver_tpu.serving.errors import BatchError, Unavailable
from perceiver_tpu.serving.metrics import MetricsRegistry, PagePoolGauges
from perceiver_tpu.serving.prefix_cache import (
    PrefixCacheConfig,
    PrefixIndex,
    ensure_private_page,
)
from perceiver_tpu.serving.speculative import (
    SpeculativeConfig,
    greedy_accept,
)
from perceiver_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    TenantRegistry,
    TenantSpec,
)


@dataclasses.dataclass(frozen=True)
class DecodeGeometry:
    """The fixed shape of one decode executable: stream slots × paged
    pool. Everything the step compiles against derives from here, so
    the exec-cache key forks on any change (tests/test_exec_cache.py
    pins the pages × page_size fork)."""

    max_streams: int
    num_pages: int          # includes the reserved trash page 0
    page_size: int
    max_seq_len: int        # cap on prompt + generated (position table)
    top_k: int = 3
    max_chunk: int = 8      # prompt tokens one prefill chunk may carry
    spec_k: int = 0         # drafted tokens verified per step (0 = off)

    def __post_init__(self):
        if self.max_streams < 1:
            raise ValueError(f"max_streams must be >= 1, got "
                             f"{self.max_streams}")
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got "
                             f"{self.page_size}")
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved trash "
                f"page), got {self.num_pages}")
        if self.max_seq_len < 1:
            raise ValueError(f"max_seq_len must be >= 1, got "
                             f"{self.max_seq_len}")
        if not 1 <= self.max_chunk <= self.max_seq_len:
            raise ValueError(
                f"max_chunk must be in [1, max_seq_len], got "
                f"{self.max_chunk}")
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {self.spec_k}")
        if self.spec_k and self.spec_k + 1 > self.max_chunk:
            raise ValueError(
                f"spec_k {self.spec_k} needs {self.spec_k + 1} chunk "
                f"lanes (feedback + drafts) but max_chunk is "
                f"{self.max_chunk}")

    @property
    def pages_per_stream(self) -> int:
        """Page-table width: enough pages to reach ``max_seq_len``."""
        return -(-self.max_seq_len // self.page_size)

    @property
    def allocatable_pages(self) -> int:
        return self.num_pages - 1

    def pages_for(self, cached_tokens: int) -> int:
        """Pages a stream holding ``cached_tokens`` KV entries needs."""
        return max(1, -(-cached_tokens // self.page_size))

    @property
    def descriptor(self) -> str:
        # spec_k suffixes only when speculation is compiled in, so
        # every pre-existing exec-cache key (and every pinned budget
        # keyed on the descriptor) is byte-identical at spec_k == 0
        base = (f"r{self.max_streams}_p{self.num_pages}x{self.page_size}"
                f"_s{self.max_seq_len}_q{self.max_chunk}")
        return f"{base}_k{self.spec_k}" if self.spec_k else base


class PagePool:
    """Host-side refcounted free-list allocator over page indices.

    Page 0 is reserved (the trash page inactive slots scatter into)
    and never handed out. Any free page serves any stream, so recycle
    never fragments: ``free`` simply pushes pages back on the list.
    Pages carry a reference count so immutable prefix pages can be
    shared across streams (serving/prefix_cache.py): ``alloc`` hands
    out pages at refcount 1, ``incref`` adds a holder, and ``free`` is
    a decref that only returns the page to the free list when the last
    holder lets go. The allocated map is tracked to make double-free /
    aliasing bugs loud instead of silently corrupting a neighbour
    stream's cache.
    """

    # externally guarded: a PagePool has no lock of its own — every
    # alloc/free happens inside the owning engine's critical sections
    # (racecheck validates the declaration; the owner's _GUARDED
    # registry covers the call sites)
    _GUARDED_BY = "DecodeEngine._lock"

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 reserved)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO: pop() returns low indices first, so fresh allocations
        # reuse just-freed pages (cache-friendly, and makes the
        # recycle tests deterministic)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return len(self._refs)

    @property
    def _allocated(self) -> set:
        """Allocated page-id view (kept for tests / introspection)."""
        return set(self._refs)

    def refcount(self, page: int) -> int:
        """Holders of ``page`` (0 when the page is on the free list)."""
        return self._refs.get(page, 0)

    def alloc(self, n: int) -> List[int]:
        if n < 1:
            raise ValueError(f"alloc of {n} pages")
        if n > len(self._free):
            raise ValueError(
                f"pool exhausted: {n} pages requested, "
                f"{len(self._free)} free")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def incref(self, pages: Sequence[int]) -> None:
        """Add one holder to each page (prefix sharing / publication)."""
        for p in pages:
            if p not in self._refs:
                raise ValueError(
                    f"incref of unallocated page {p} (allocated: "
                    f"{sorted(self._refs)})")
            self._refs[p] += 1

    def free(self, pages: Sequence[int]) -> None:
        """Drop one holder per page; recycle pages that hit zero."""
        for p in pages:
            if p not in self._refs:
                raise ValueError(
                    f"double-free or foreign page {p} (allocated: "
                    f"{sorted(self._refs)})")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)


@dataclasses.dataclass(frozen=True)
class DecodeGraph:
    """The decode step plus everything needed to compile and carry it.

    ``fn(params, carry, tokens, qlens) -> (carry', outputs)`` with
    ``tokens (R, max_chunk) int32`` and ``qlens (R,) int32`` — row r
    consumes its first ``qlens[r]`` token lanes this step (1 for a
    decode row, up to ``max_chunk`` for a prefill chunk, 0 idle);
    ``carry`` is donate_argnums=(1,) — every leaf aliases an output
    (pools/lengths are updated in place, page_tables pass through),
    so the step's HBM high-water mark is ONE copy of the cache.
    """

    model: object
    fn: Callable
    geometry: DecodeGeometry
    policy: Policy
    pool_dtype: object
    num_kv_sets: int
    head_dim: int
    num_heads: int
    vocab_size: int
    donate_argnums: tuple = (1,)
    output_names: tuple = ("next_token", "topk_ids", "topk_scores")

    def init_params(self, seed: int = 0):
        import jax

        return self.model.init(jax.random.key(seed))

    def init_carry(self) -> Dict[str, object]:
        import jax.numpy as jnp

        g = self.geometry
        pool = (g.num_pages, g.page_size, self.num_heads, self.head_dim)
        kv = {}
        for name in (("k1", "v1") if self.num_kv_sets == 1
                     else ("k1", "v1", "kn", "vn")):
            kv[name] = jnp.zeros(pool, self.pool_dtype)
        return {
            "kv": kv,
            "lengths": jnp.zeros((g.max_streams,), jnp.int32),
            "page_tables": jnp.zeros(
                (g.max_streams, g.pages_per_stream), jnp.int32),
        }


def build_decode_graph(model, geometry: DecodeGeometry, *,
                       policy: Policy = DEFAULT_POLICY,
                       attn_impl: str = "pallas") -> DecodeGraph:
    """Build the decode step for a ``PerceiverMLM``-shaped model.

    ``attn_impl``: ``"pallas"`` is the production kernel (interpret
    mode on CPU); ``"reference"`` the pure-jax gather path — the
    sharded (dp2×tp2) canonical target lowers the reference because
    GSPMD partitions gathers/einsums, not Pallas calls.
    """
    import jax
    import jax.numpy as jnp

    from perceiver_tpu.models.perceiver import (
        cross_attention_layer_apply,
        self_attention_block_apply,
    )
    from perceiver_tpu.ops.attention import cross_attention_kv
    from perceiver_tpu.ops.linear import linear_apply
    from perceiver_tpu.ops.mlp import mlp_apply
    from perceiver_tpu.ops.norm import layer_norm_apply
    from perceiver_tpu.ops.paged_attention import (
        paged_decode_attention,
        paged_decode_attention_reference,
        tile_for_windows,
    )

    if attn_impl not in ("pallas", "reference"):
        raise ValueError(f"unknown attn_impl {attn_impl!r}")
    encoder, decoder = model.encoder, model.decoder
    n_lat, channels = encoder.latent_shape
    enc_heads = encoder.num_cross_attention_heads
    dec_heads = decoder.num_cross_attention_heads
    n_layers = encoder.num_layers
    model_max_seq = decoder.output_adapter.output_shape[0]
    if geometry.max_seq_len > model_max_seq:
        raise ValueError(
            f"geometry.max_seq_len {geometry.max_seq_len} exceeds the "
            f"model's position table {model_max_seq}")
    if channels % enc_heads:
        raise ValueError(
            f"channels {channels} not divisible by num heads {enc_heads}")
    head_dim = channels // enc_heads
    r = geometry.max_streams
    ps = geometry.page_size
    pps = geometry.pages_per_stream
    max_seq = geometry.max_seq_len
    pool_dtype = policy.compute_dtype
    vocab = decoder.output_adapter.num_classes \
        if hasattr(decoder.output_adapter, "num_classes") else None
    attn = (paged_decode_attention if attn_impl == "pallas"
            else paged_decode_attention_reference)
    q_chunk = geometry.max_chunk
    # speculative verify widens the latent rebuild to W = spec_k + 1
    # right-aligned KV windows per stream (spec_w == 1 is the plain
    # path, kept literally unchanged so its lowering — and with it the
    # exec-cache key and every pinned analysis budget — cannot drift)
    spec_w = geometry.spec_k + 1
    # flat-gather index base for the per-stream page lookup (static)
    row_base = jnp.arange(r, dtype=jnp.int32) * pps

    def fn(params, carry, tokens, qlens):
        enc_p = params["encoder"]
        lengths = carry["lengths"]
        tables = carry["page_tables"]
        offs = jnp.arange(q_chunk, dtype=jnp.int32)
        # lane j of row r lands at position lengths[r] + j; lanes past
        # qlens[r] are dead and redirect to the trash page below
        pos = jnp.clip(lengths[:, None] + offs[None, :],
                       0, max_seq - 1)                       # (R, Q)
        valid = offs[None, :] < qlens[:, None]               # (R, Q)

        # 1. embed every chunk lane at its in-stream position
        emb = encoder.input_adapter.apply_packed(
            enc_p["input_adapter"], tokens, pos,
            policy=policy)                                   # (R, Q, C)

        # 2. the O(chunk) cache update: scatter each lane's K/V into
        # its stream's page walk; dead lanes write the trash page.
        # Valid lanes never collide (positions are distinct per row,
        # pages distinct across rows), and the trash page is never
        # read back (reads are masked at kv_len), so duplicate dead
        # lanes are harmless.
        page = jnp.take(tables.reshape(-1),
                        (row_base[:, None] + pos // ps).reshape(-1))
        page = jax.lax.select(valid.reshape(-1), page,
                              jnp.zeros_like(page))          # (R*Q,)
        slot = (pos % ps).reshape(-1)

        def append(layer_params, kpool, vpool):
            kh, vh = cross_attention_kv(
                layer_params["cross"]["attn"], emb,
                num_heads=enc_heads, policy=policy)  # (R, Q, H, Dh)
            kh = kh.reshape(-1, enc_heads, head_dim)
            vh = vh.reshape(-1, enc_heads, head_dim)
            kpool = kpool.at[page, slot].set(kh.astype(kpool.dtype))
            vpool = vpool.at[page, slot].set(vh.astype(vpool.dtype))
            return kpool, vpool

        kv = dict(carry["kv"])
        kv["k1"], kv["v1"] = append(enc_p["layer_1"], kv["k1"], kv["v1"])
        if n_layers > 1:
            kv["kn"], kv["vn"] = append(enc_p["layer_n"],
                                        kv["kn"], kv["vn"])
        new_lengths = lengths + qlens.astype(lengths.dtype)

        # 3. latents from scratch over the paged pools — mirrors
        # serving/graphs._packed_encoder_apply with the ragged kernel
        # swapped for the paged one. Perceiver latents are NON-causal
        # over the cache, so speculative verify cannot reuse one
        # latent set for every drafted position: each of the W windows
        # gets its OWN latent rebuild against a right-aligned KV
        # prefix, folded into the kernel's row axis (no pages copied —
        # tile_for_windows repeats table rows and fans the lengths
        # out). Window W-1 sees the full cache, i.e. exactly the plain
        # decode view.
        if spec_w == 1:
            ver_tables, ver_lens, rows = tables, new_lengths, r
        else:
            ver_tables, ver_lens = tile_for_windows(
                tables, new_lengths, spec_w)
            rows = r * spec_w

        def one_layer(layer_params, kpool, vpool, lat):
            attn_p = layer_params["cross"]["attn"]
            xq = layer_norm_apply(attn_p["norm_q"], lat, policy=policy)
            qh = linear_apply(attn_p["mha"]["q"], xq, policy=policy)
            q = qh.reshape(rows, n_lat, enc_heads, head_dim).transpose(
                0, 2, 1, 3)
            o = attn(q, kpool, vpool, ver_tables, ver_lens,
                     scale=1.0 / (head_dim ** 0.5))
            o = o.transpose(0, 2, 1, 3).reshape(rows, n_lat,
                                                enc_heads * head_dim)
            o = linear_apply(attn_p["mha"]["out"], o, policy=policy)
            y = lat + o
            y = y + mlp_apply(layer_params["cross"]["mlp"], y,
                              policy=policy)
            return self_attention_block_apply(
                layer_params["selfs"], y,
                num_heads=encoder.num_self_attention_heads,
                policy=policy)

        latent = jnp.broadcast_to(
            policy.cast_param(enc_p["latent"])[None],
            (rows, n_lat, channels))
        latent = one_layer(enc_p["layer_1"], kv["k1"], kv["v1"], latent)
        if n_layers > 1:
            layer_n = enc_p["layer_n"]

            def body(c, _):
                return one_layer(layer_n, kv["kn"], kv["vn"],
                                 policy.cast_compute(c)), None

            latent, _ = jax.lax.scan(body, latent, None,
                                     length=n_layers - 1)

        # 4. decode ONE query row per (stream × window): the window's
        # next position — at spec_w == 1 this is the stream's next
        # position, the plain contract
        pd = params["decoder"]
        qpos = jnp.clip(ver_lens, 0, max_seq - 1)
        query = jnp.take(policy.cast_param(pd["query"]), qpos,
                         axis=0)[:, None, :]  # (rows, 1, C)
        hidden = cross_attention_layer_apply(
            pd["cross"], query, latent, num_heads=dec_heads,
            policy=policy)
        logits = linear_apply(pd["output_adapter"]["linear"], hidden,
                              policy=policy)[:, 0]  # (rows, V)
        carry_out = {"kv": kv, "lengths": new_lengths,
                     "page_tables": tables}
        if spec_w == 1:
            scores, topk_ids = jax.lax.top_k(
                logits.astype(jnp.float32), geometry.top_k)
            return carry_out, {
                "next_token": topk_ids[:, 0].astype(jnp.int32),
                "topk_ids": topk_ids.astype(jnp.int32),
                "topk_scores": scores,
            }
        # per-window greedy picks ride the same top_k op as the plain
        # path so tie-breaking is identical: spec_tokens[:, -1] is
        # bit-for-bit the next_token a non-speculative step yields
        logits32 = logits.astype(jnp.float32)
        _, ids_w = jax.lax.top_k(logits32, 1)
        spec_tokens = ids_w[:, 0].reshape(r, spec_w).astype(jnp.int32)
        last = logits32.reshape(r, spec_w, -1)[:, spec_w - 1]
        scores, topk_ids = jax.lax.top_k(last, geometry.top_k)
        return carry_out, {
            "next_token": topk_ids[:, 0].astype(jnp.int32),
            "topk_ids": topk_ids.astype(jnp.int32),
            "topk_scores": scores,
            "spec_tokens": spec_tokens,
        }

    output_names = ("next_token", "topk_ids", "topk_scores")
    if spec_w > 1:
        output_names += ("spec_tokens",)
    return DecodeGraph(
        model=model, fn=fn, geometry=geometry, policy=policy,
        pool_dtype=pool_dtype,
        num_kv_sets=1 if n_layers == 1 else 2,
        head_dim=head_dim, num_heads=enc_heads,
        vocab_size=vocab if vocab is not None else -1,
        output_names=output_names)


# --- streams -----------------------------------------------------------------

_SENTINEL = object()


@dataclasses.dataclass(frozen=True)
class DecodeResult:
    """One finished stream: generated ids + timing."""

    tokens: List[int]
    prompt_len: int
    finished: str                 # "complete" | "cancelled"
    ttft_s: Optional[float]
    cached_tokens: int = 0        # prompt span served from the prefix cache


class _Stream:
    """Engine-internal per-stream state (guarded by the engine lock)."""

    __slots__ = ("sid", "seq", "prompt", "max_new", "pages_needed",
                 "on_token", "ctx", "enqueued_at", "deadline", "slot",
                 "pages", "fed", "next_input", "generated", "tokens_q",
                 "done", "outcome", "error", "ttft_s", "submitted_at",
                 "prefill_chunks", "cached_tokens", "shared_pages",
                 "draft_pages", "draft_fed", "spec_on", "acc_ema",
                 "tenant")

    def __init__(self, sid, prompt, max_new, pages_needed, on_token,
                 ctx, now, deadline, tenant=DEFAULT_TENANT):
        self.tenant = tenant
        self.sid = sid
        self.seq = int(sid[1:])  # admission order (FIFO chunk planning)
        self.prefill_chunks = 0
        self.cached_tokens = 0   # prefix-cache hit span (page-aligned)
        self.shared_pages = 0    # leading table entries shared via the index
        self.draft_pages: List[int] = []  # draft-arena pages (speculative)
        self.draft_fed = 0       # known tokens committed to the draft cache
        self.spec_on = False     # drafting this stream (may fall back)
        self.acc_ema = 1.0       # acceptance-rate EMA (fallback trigger)
        self.prompt = prompt
        self.max_new = max_new
        self.pages_needed = pages_needed
        self.on_token = on_token
        self.ctx = ctx
        self.enqueued_at = now
        self.submitted_at = now
        self.deadline = deadline
        self.slot = -1
        self.pages: List[int] = []
        self.fed = 0
        self.next_input = int(prompt[0])
        self.generated: List[int] = []
        self.tokens_q: queue_mod.SimpleQueue = queue_mod.SimpleQueue()
        self.done = threading.Event()
        self.outcome = None           # DecodeResult | Overloaded
        self.error: Optional[BaseException] = None
        self.ttft_s: Optional[float] = None


class StreamHandle:
    """Caller-facing handle for one submitted stream.

    ``tokens()`` is a blocking iterator over generated token ids (ends
    when the stream finishes); ``result(timeout)`` blocks for the
    final :class:`DecodeResult` — or a typed
    :class:`~perceiver_tpu.serving.batcher.Overloaded` value when the
    stream was shed, following the batcher's value-not-exception
    convention. Stream errors re-raise here.
    """

    def __init__(self, stream: _Stream, engine: "DecodeEngine"):
        self._stream = stream
        self._engine = engine
        self.trace_ctx = stream.ctx

    @property
    def stream_id(self) -> str:
        return self._stream.sid

    def tokens(self):
        while True:
            tok = self._stream.tokens_q.get()
            if tok is _SENTINEL:
                return
            yield tok

    def result(self, timeout: Optional[float] = None):
        if not self._stream.done.wait(timeout):
            raise TimeoutError(
                f"stream {self._stream.sid} unfinished after {timeout}s")
        if self._stream.error is not None:
            raise self._stream.error
        return self._stream.outcome

    def done(self) -> bool:
        return self._stream.done.is_set()

    def cancel(self) -> bool:
        return self._engine._cancel(self._stream)


class DecodeEngine:
    """The stepped decode executor: ONE AOT-compiled signature, a
    shared paged KV pool, streams joining and leaving mid-flight.

    ``auto_step=True`` (default) runs a worker thread that steps
    whenever work exists; tests pass ``auto_step=False`` and drive
    :meth:`step` / :meth:`run_until_idle` deterministically.
    """

    # lock discipline (gated by check.py --race): every mutable piece
    # of scheduler state below is touched only under self._lock —
    # self._work is a Condition over the same lock, so 'with
    # self._work:' frames count. params/pool ride along because the
    # step loop swaps/mutates them while streams are in flight.
    _GUARDED = {
        "_streams": "_lock",
        "_tables": "_lock",
        "_lengths": "_lock",
        "_dirty": "_lock",
        "_seq": "_lock",
        "_closed": "_lock",
        "_failed": "_lock",
        "_carry": "_lock",
        "params": "_lock",
        "pool": "_lock",
        "prefix_index": "_lock",
        # speculative draft arena: its own pool / host mirrors / carry,
        # mutated only from the same step critical sections
        "_draft_carry": "_lock",
        "_draft_params": "_lock",
        "draft_pool": "_lock",
        "_draft_tables": "_lock",
        "_draft_lengths": "_lock",
        "_draft_dirty": "_lock",
        # per-tenant page accounting: charged at admission, credited
        # at finish — the quota enforcement ledger
        "_tenant_pages": "_lock",
    }

    def __init__(self, task, params=None, *,
                 geometry: DecodeGeometry,
                 policy: Policy = DEFAULT_POLICY,
                 attn_impl: str = "pallas",
                 exec_cache=None,
                 metrics: Optional[MetricsRegistry] = None,
                 max_queue: int = 64,
                 token_budget: Optional[int] = None,
                 prefix_cache: Optional[PrefixCacheConfig] = None,
                 speculative: Optional[SpeculativeConfig] = None,
                 tenancy: Optional[TenantRegistry] = None,
                 auto_step: bool = True,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp

        if (geometry.spec_k > 0) != (speculative is not None):
            raise ValueError(
                "speculative decoding needs both halves: geometry."
                f"spec_k (got {geometry.spec_k}) compiles the verify "
                "windows, speculative= (got "
                f"{'a config' if speculative is not None else 'None'}) "
                "supplies the draft policy")
        self.task = task
        self.geometry = geometry
        self.policy = policy
        self.speculative = speculative
        # host-side tenancy: quotas/weights only — never a compiled
        # shape, so the exec-cache key is identical with it on or off
        self.tenancy = tenancy
        self._tenant_pages: Dict[str, int] = {}
        # per-step token pacing: every decode row costs 1, the rest
        # goes to prefill chunks — host-side policy only, never a
        # compiled shape, so it is tunable without a recompile
        self.token_budget = (int(token_budget) if token_budget is not None
                             else geometry.max_streams
                             + geometry.max_chunk)
        self.exec_cache = resolve_exec_cache(exec_cache)
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.graph = build_decode_graph(
            task.build(), geometry, policy=policy, attn_impl=attn_impl)
        self.params = params if params is not None \
            else self.graph.init_params(seed)

        m = self.metrics
        self._m_active = m.gauge(
            "serving_decode_streams_active",
            "decode streams currently holding a slot")
        self._m_free_pages = m.gauge(
            "serving_decode_free_pages", "allocatable pages not in use")
        self._m_steps = m.counter(
            "serving_decode_steps_total", "decode step executions")
        self._m_tokens = m.counter(
            "serving_decode_tokens_total", "generated tokens emitted")
        self._m_streams = m.counter(
            "serving_decode_streams_total", "finished streams by outcome")
        self._m_shed = m.counter(
            "serving_decode_shed_total", "streams shed by reason")
        self._m_ttft = m.histogram(
            "serving_decode_ttft_seconds",
            "submit → first generated token")
        self._m_step_latency = m.histogram(
            "serving_decode_step_latency_seconds",
            "one decode step (dispatch + next_token sync)")
        self._m_prefill_chunks = m.counter(
            "serving_decode_prefill_chunks_total",
            "prefill chunks executed by the unified step")
        self._m_prefill_tokens = m.counter(
            "serving_decode_prefill_tokens_total",
            "prompt tokens consumed via chunked prefill")
        self._m_prefix_hits = m.counter(
            "serving_prefix_cache_hits_total",
            "admissions whose prompt matched a cached prefix")
        self._m_prefix_misses = m.counter(
            "serving_prefix_cache_misses_total",
            "admissions with no cached prefix")
        self._m_prefix_hit_tokens = m.counter(
            "serving_prefix_cache_hit_tokens_total",
            "prompt tokens served from shared prefix pages")
        self._m_prefix_evicted = m.counter(
            "serving_prefix_cache_evicted_pages_total",
            "index pages reclaimed by LRU eviction")
        self._m_prefix_pages = m.gauge(
            "serving_prefix_cache_pages",
            "pages currently held by the prefix index")
        self._m_spec_draft = m.counter(
            "serving_spec_draft_tokens_total",
            "draft-model tokens proposed for verification")
        self._m_spec_accepted = m.counter(
            "serving_spec_accepted_tokens_total",
            "drafted tokens the target accepted")
        self._m_spec_verify = m.counter(
            "serving_spec_verify_steps_total",
            "unified steps that verified at least one drafted window")
        self._m_spec_fallback = m.counter(
            "serving_spec_fallback_total",
            "streams dropped to plain decode on acceptance collapse")
        self._m_tenant_pages = m.gauge(
            "serving_tenant_pages_used",
            "KV pages charged to each tenant's quota")
        self._m_tenant_shed = m.counter(
            "serving_tenant_shed_total",
            "streams shed, by tenant and reason")
        self._m_tenant_tokens = m.counter(
            "serving_tenant_tokens_total",
            "generated tokens emitted, by tenant")
        self._m_pool_gauges = PagePoolGauges(m, arena="target")

        r = geometry.max_streams
        self.pool = PagePool(geometry.num_pages, geometry.page_size)
        # prefix sharing is an opt-in host-side discipline over the
        # same arena: enabling it changes no compiled shape — the
        # geometry descriptor (and so the exec-cache key) is identical
        # with the index on or off
        self.prefix_index: Optional[PrefixIndex] = (
            PrefixIndex(self.pool, geometry.page_size, prefix_cache)
            if prefix_cache is not None else None)
        self._m_free_pages.set(self.pool.free_pages)
        self._m_pool_gauges.update(self.pool)
        self._queue = ContinuousBatchScheduler(
            max_depth=max_queue, token_budget=self.token_budget,
            max_chunk=geometry.max_chunk, metrics=m)
        self._streams: List[Optional[_Stream]] = [None] * r
        self._tables = np.zeros((r, geometry.pages_per_stream), np.int32)
        self._lengths = np.zeros((r,), np.int32)
        self._dirty = False
        self._seq = 0
        self._closed = False
        self._failed: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)

        tokens0 = jnp.zeros((r, geometry.max_chunk), jnp.int32)
        qlens0 = jnp.zeros((r,), jnp.int32)
        jitted = jax.jit(self.graph.fn,
                         donate_argnums=self.graph.donate_argnums)
        carry = self.graph.init_carry()
        self._exe, info = aot_compile(
            jitted, (self.params, carry, tokens0, qlens0),
            cache=self.exec_cache,
            donate_argnums=self.graph.donate_argnums,
            label=f"decode:{geometry.descriptor}",
            extra_key=(geometry.descriptor,))
        if self.exec_cache is not None:
            events_mod.emit("exec_cache",  # graphcheck: ignore — exec_cache is bucket-scoped (compile plane, shared across tenants by design)
                            bucket=f"decode:{geometry.descriptor}",
                            hit=bool(info["hit"]))
        # warmup step with every slot idle: the steady state then
        # re-runs an already-warm executable — zero per-step compiles
        carry, out = self._exe(self.params, carry, tokens0, qlens0)
        np.asarray(out["next_token"])
        self._carry = carry

        # speculative draft arena: a second (smaller) stepped
        # executable with its OWN paged pool, page tables, lengths and
        # carry — never shared with the target, because the draft's
        # cache trails/leads the target's by design and prefix-shared
        # target pages must not see draft writes
        self._draft_graph = None
        self._draft_exe = None
        self._draft_carry = None
        self._draft_params = None
        self.draft_pool: Optional[PagePool] = None
        self._draft_tables: Optional[np.ndarray] = None
        self._draft_lengths: Optional[np.ndarray] = None
        self._draft_dirty = False
        self._m_draft_gauges: Optional[PagePoolGauges] = None
        if speculative is not None:
            self._init_draft(speculative, attn_impl)

        self._worker: Optional[threading.Thread] = None
        if auto_step:
            self._worker = threading.Thread(
                target=self._loop, name="decode-engine", daemon=True)
            self._worker.start()

    def _init_draft(self, spec: SpeculativeConfig,
                    attn_impl: str) -> None:
        """Build and warm the draft stepped executable (called from
        ``__init__`` only; the lock is uncontended pre-publication but
        taken anyway so the draft-state discipline holds uniformly)."""
        with self._lock:
            self._init_draft_locked(spec, attn_impl)

    def _init_draft_locked(self, spec: SpeculativeConfig,
                           attn_impl: str) -> None:
        import jax
        import jax.numpy as jnp

        g = self.geometry
        # the draft never verifies — it decodes plain, one stream of
        # proposals at a time — so its graph compiles at spec_k == 0
        draft_geometry = dataclasses.replace(g, spec_k=0)
        draft_task = (spec.draft_task if spec.draft_task is not None
                      else self.task)
        self._draft_graph = build_decode_graph(
            draft_task.build(), draft_geometry, policy=self.policy,
            attn_impl=attn_impl)
        if self._draft_graph.vocab_size != self.graph.vocab_size:
            raise ValueError(
                f"draft vocab {self._draft_graph.vocab_size} != target "
                f"vocab {self.graph.vocab_size} — proposals would not "
                "be target token ids")
        if spec.draft_params is not None:
            self._draft_params = jax.device_put(spec.draft_params)
        elif spec.draft_task is None:
            self._draft_params = self.params  # self-draft
        else:
            self._draft_params = self._draft_graph.init_params(
                spec.draft_seed)
        self.draft_pool = PagePool(g.num_pages, g.page_size)
        r = g.max_streams
        self._draft_tables = np.zeros((r, g.pages_per_stream), np.int32)
        self._draft_lengths = np.zeros((r,), np.int32)
        self._draft_dirty = False
        self._m_draft_gauges = PagePoolGauges(self.metrics, arena="draft")
        self._m_draft_gauges.update(self.draft_pool)
        tokens0 = jnp.zeros((r, g.max_chunk), jnp.int32)
        qlens0 = jnp.zeros((r,), jnp.int32)
        jitted = jax.jit(self._draft_graph.fn,
                         donate_argnums=self._draft_graph.donate_argnums)
        carry = self._draft_graph.init_carry()
        self._draft_exe, info = aot_compile(
            jitted, (self._draft_params, carry, tokens0, qlens0),
            cache=self.exec_cache,
            donate_argnums=self._draft_graph.donate_argnums,
            label=f"draft:{g.descriptor}",
            extra_key=("draft", g.descriptor))
        if self.exec_cache is not None:
            events_mod.emit("exec_cache",  # graphcheck: ignore — exec_cache is bucket-scoped (compile plane, shared across tenants by design)
                            bucket=f"draft:{g.descriptor}",
                            hit=bool(info["hit"]))
        carry, out = self._draft_exe(
            self._draft_params, carry, tokens0, qlens0)
        np.asarray(out["next_token"])
        self._draft_carry = carry

    # -- submission -------------------------------------------------------

    def _tenant_spec(self, tenant: str) -> TenantSpec:
        if self.tenancy is None:
            return TenantSpec(tenant=tenant)
        return self.tenancy.get(tenant)

    def submit(self, prompt_ids, *, max_new_tokens: int,
               timeout_ms: Optional[float] = None,
               on_token: Optional[Callable[[int], None]] = None,
               trace: Optional[trace_mod.TraceContext] = None,
               tenant: Optional[str] = None
               ) -> StreamHandle:
        """Enqueue one stream. Raises :class:`RequestTooLarge` when the
        request can never fit this engine's geometry (or its tenant's
        page quota); raises ``Unavailable("tenant_quota")`` — before
        any compute — when the tenant's held + queued pages leave no
        room; resolves the handle to a typed ``Overloaded`` when
        capacity is transiently unavailable (queue full / admission
        deadline)."""
        g = self.geometry
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        vocab = self.graph.vocab_size
        if vocab > 0 and (prompt.min() < 0 or prompt.max() >= vocab):
            raise ValueError(
                f"prompt ids outside [0, {vocab}) — not a valid token "
                "sequence for this model")
        total = int(prompt.size) + int(max_new_tokens)
        if total > g.max_seq_len:
            raise RequestTooLarge(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds the decode "
                f"geometry's max_seq_len {g.max_seq_len}")
        # the last generated token is never fed back, so the cache
        # holds total - 1 tokens at finish
        pages_needed = g.pages_for(total - 1)
        if pages_needed > g.allocatable_pages:
            raise RequestTooLarge(
                f"request needs {pages_needed} pages, pool has only "
                f"{g.allocatable_pages} allocatable "
                f"({g.num_pages} minus the reserved trash page)")
        tenant = tenant or DEFAULT_TENANT
        tspec = self._tenant_spec(tenant)
        if tspec.max_pages is not None and pages_needed > tspec.max_pages:
            raise RequestTooLarge(
                f"request needs {pages_needed} pages but tenant "
                f"{tenant!r} is capped at {tspec.max_pages}")
        now = time.monotonic()
        ctx = trace if trace is not None \
            else trace_mod.start_trace(origin="decode")
        deadline = (now + timeout_ms / 1000.0
                    if timeout_ms is not None else None)
        with self._lock:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if self._failed is not None:
                raise Unavailable("decode_engine_failed")
            if tspec.max_pages is not None:
                # quota exhaustion sheds HERE — before a slot, a page,
                # or a single device token is spent on the request.
                # held + queued both charge, so a flood tenant cannot
                # park unbounded work in the admission queue either.
                charged = (self._tenant_pages.get(tenant, 0)
                           + self._queue.tenant_queued_cost()
                           .get(tenant, 0))
                if charged + pages_needed > tspec.max_pages:
                    self._m_tenant_shed.labels(
                        tenant=tenant, reason="tenant_quota").inc()
                    events_mod.emit("tenant_shed", tenant=tenant,
                                    reason="tenant_quota")
                    raise Unavailable("tenant_quota", tenant=tenant)
            self._seq += 1
            stream = _Stream(f"s{self._seq}", prompt, int(max_new_tokens),
                             pages_needed, on_token, ctx, now, deadline,
                             tenant=tenant)
            handle = StreamHandle(stream, self)
            if not self._queue.offer(stream, cost=pages_needed,
                                     deadline=deadline, tenant=tenant):
                self._m_shed.labels(reason="queue_full").inc()  # graphcheck: ignore — aggregate shed counter predates tenancy; the tenant split rides serving_tenant_shed_total below
                self._m_tenant_shed.labels(
                    tenant=tenant, reason="queue_full").inc()
                events_mod.emit("tenant_shed", tenant=tenant,
                                reason="queue_full")
                self._resolve_shed(stream, Overloaded(
                    "queue_full", self._queue.depth))
                return handle
            self._work.notify_all()
        return handle

    # -- stepping ---------------------------------------------------------

    def _admit_locked(self, now: float) -> None:
        free_slots = sum(1 for s in self._streams if s is None)
        # index-only pages are reclaimable on demand, so they count
        # toward the admission budget — a full index never starves
        # admission (it just loses its least-recently-hit chains)
        budget = self.pool.free_pages
        if self.prefix_index is not None:
            budget += self.prefix_index.evictable_pages()
        tenant_budgets = None
        if self.tenancy is not None:
            # remaining per-tenant page headroom: entries of a tenant
            # that is out of headroom defer inside take() without
            # blocking anyone else's admission
            tenant_budgets = {}
            for t in self._queue.tenant_queued_cost():
                cap = self._tenant_spec(t).max_pages
                if cap is not None:
                    tenant_budgets[t] = max(
                        0, cap - self._tenant_pages.get(t, 0))
        admitted, shed = self._queue.take(
            budget=budget, slots=free_slots, now=now,
            tenant_budgets=tenant_budgets)
        for stream in shed:
            self._m_shed.labels(reason="deadline").inc()  # graphcheck: ignore — aggregate shed counter predates tenancy; the tenant split rides serving_tenant_shed_total below
            self._m_tenant_shed.labels(
                tenant=stream.tenant, reason="deadline").inc()
            events_mod.emit("tenant_shed", tenant=stream.tenant,
                            reason="deadline")
            self._resolve_shed(stream, Overloaded(
                "deadline", self._queue.depth))
        for stream in admitted:
            slot = next(i for i, s in enumerate(self._streams)
                        if s is None)
            stream.slot = slot
            shared: List[int] = []
            if self.prefix_index is not None:
                t_lk = time.monotonic()
                cached, shared = self.prefix_index.lookup(stream.prompt)
                stream.cached_tokens = cached
                stream.shared_pages = len(shared)
                if stream.ctx is not None:
                    stream.ctx.record(
                        "prefix_lookup", start=t_lk,
                        end=time.monotonic(), stream=stream.sid,
                        cached_tokens=cached, pages=len(shared))
                if cached > 0:
                    self._m_prefix_hits.inc()
                    self._m_prefix_hit_tokens.inc(cached)
                    events_mod.emit("prefix_cache_hit",  # graphcheck: ignore — stream-scoped; stream->tenant join via the stream_open event
                                    stream=stream.sid, tokens=cached,
                                    pages=len(shared))
                else:
                    self._m_prefix_misses.inc()
                    events_mod.emit("prefix_cache_miss",  # graphcheck: ignore — stream-scoped; stream->tenant join via the stream_open event
                                    stream=stream.sid)
            # the cached span is page-aligned and strictly shorter
            # than the prompt, so >= 1 private page is always needed
            # (the partial last page is never shared)
            private_needed = stream.pages_needed - len(shared)
            if (self.prefix_index is not None
                    and private_needed > self.pool.free_pages):
                evicted = self.prefix_index.evict(
                    private_needed - self.pool.free_pages)
                if evicted:
                    self._m_prefix_evicted.inc(evicted)
                    events_mod.emit("prefix_cache_evict", pages=evicted)  # graphcheck: ignore — LRU reclaim frees index-only pages owned by no tenant
            private = self.pool.alloc(private_needed)
            for p in private:
                # CoW discipline: every page this stream will write is
                # exclusively held — shared pages only ever serve reads
                ensure_private_page(self.pool, p)
            stream.pages = shared + private
            stream.fed = stream.cached_tokens
            if self.draft_pool is not None:
                # the draft arena has no prefix sharing (its cache is
                # private per stream) and no eviction — when it can't
                # host the stream, the stream just decodes plain
                if stream.pages_needed <= self.draft_pool.free_pages:
                    stream.draft_pages = self.draft_pool.alloc(
                        stream.pages_needed)
                    stream.spec_on = True
                    stream.draft_fed = 0
                    stream.acc_ema = 1.0
                    self._draft_tables[slot, :] = 0
                    self._draft_tables[slot, :len(stream.draft_pages)] \
                        = stream.draft_pages
                    self._draft_lengths[slot] = 0
                    self._draft_dirty = True
                else:
                    stream.spec_on = False
                self._m_draft_gauges.update(self.draft_pool)
            self._streams[slot] = stream
            self._tables[slot, :] = 0
            self._tables[slot, :len(stream.pages)] = stream.pages
            # positions continue after the cached span: the carry's
            # length row starts at cached_tokens, so the tail chunk
            # prefills (and attends) exactly as a cold stream that
            # had already written those positions
            self._lengths[slot] = stream.cached_tokens
            self._dirty = True
            # quota ledger charges the conservative pages_needed (what
            # admission budgeted), not the prefix-shared actual — two
            # tenants sharing a prefix must not double-spend headroom
            self._tenant_pages[stream.tenant] = (
                self._tenant_pages.get(stream.tenant, 0)
                + stream.pages_needed)
            self._m_tenant_pages.labels(tenant=stream.tenant).set(
                self._tenant_pages[stream.tenant])
            if stream.ctx is not None:
                stream.ctx.record("queue_wait", start=stream.enqueued_at,
                                  end=now, stream=stream.sid,
                                  tenant=stream.tenant)
            events_mod.emit("stream_open", stream=stream.sid,
                            tenant=stream.tenant)
            events_mod.emit("stream_admitted", stream=stream.sid,
                            pages=len(stream.pages),
                            tenant=stream.tenant)
            self._m_active.set(
                sum(1 for s in self._streams if s is not None))
            self._m_free_pages.set(self.pool.free_pages)
            self._m_pool_gauges.update(self.pool)
        if self.prefix_index is not None:
            self._m_prefix_pages.set(self.prefix_index.pages_indexed)

    def step(self) -> int:
        """Run one unified step over every occupied slot (admitting
        queued streams first): decode rows consume their fed-back
        token, prefilling rows consume a budget-planned prompt chunk
        — one executable, one dispatch. Returns the number of active
        streams stepped — 0 means idle. Emits/finishes streams as a
        side effect; callbacks fire outside the engine lock."""
        import jax.numpy as jnp

        emits: List[tuple] = []
        finished: List[_Stream] = []
        with self._lock:
            if self._failed is not None:
                raise Unavailable("decode_engine_failed")
            t0 = time.monotonic()
            self._admit_locked(t0)
            live = [(i, s) for i, s in enumerate(self._streams)
                    if s is not None]
            if not live:
                return 0
            r = self.geometry.max_streams
            decode_live = [(i, s) for i, s in live
                           if s.fed >= len(s.prompt)]
            prefill_live = sorted(
                ((i, s) for i, s in live if s.fed < len(s.prompt)),
                key=lambda e: e[1].seq)  # FIFO by admission order
            # speculative candidates: drafting streams far enough from
            # max_new that accepted drafts can't overshoot (the last
            # verify window's bonus token is the +1)
            spec_cand: List[tuple] = []
            desires: List[int] = []
            if self.speculative is not None:
                for i, s in decode_live:
                    kd = min(self.geometry.spec_k,
                             s.max_new - len(s.generated) - 1)
                    if s.spec_on and kd >= 1:
                        spec_cand.append((i, s))
                        desires.append(kd)
            prefill_tenants = None
            tenant_weights = None
            if self.tenancy is not None and prefill_live:
                prefill_tenants = [s.tenant for _, s in prefill_live]
                tenant_weights = {
                    t: self._tenant_spec(t).weight
                    for t in set(prefill_tenants)}
            grants, plan = self._queue.plan_speculative(
                len(decode_live), desires,
                [len(s.prompt) - s.fed for _, s in prefill_live],
                prefill_tenants, tenant_weights)
            props: Dict[int, List[int]] = {}
            if spec_cand:
                cand = [(i, s, k) for (i, s), k in zip(spec_cand, grants)
                        if k > 0]
                if cand:
                    props = self._draft_propose_locked(cand)
            tokens = np.zeros((r, self.geometry.max_chunk), np.int32)
            qlens = np.zeros((r,), np.int32)
            for i, s in decode_live:
                tokens[i, 0] = s.next_input
                p = props.get(i)
                if p:
                    # verify lanes: feedback token + the drafted run —
                    # one chunk row, exactly like a prefill chunk
                    tokens[i, 1:1 + len(p)] = p
                qlens[i] = 1 + (len(p) if p else 0)
            chunks: Dict[int, int] = {}
            for (i, s), c in zip(prefill_live, plan):
                chunks[i] = c
                if c > 0:
                    tokens[i, :c] = s.prompt[s.fed:s.fed + c]
                    qlens[i] = c
            carry = self._carry
            self._carry = None  # donated: loud failure on re-entry
            if self._dirty:
                carry["page_tables"] = jnp.asarray(self._tables)
                carry["lengths"] = jnp.asarray(self._lengths)
                self._dirty = False
            try:
                carry, out = self._exe(self.params, carry,
                                       jnp.asarray(tokens),
                                       jnp.asarray(qlens))
                # the one deliberate sync of the decode path
                next_tok = np.asarray(out["next_token"])
                spec_tok = (np.asarray(out["spec_tokens"])
                            if props else None)
            except Exception as e:
                self._fail_locked(e)
                raise
            t1 = time.monotonic()
            self._carry = carry
            lengths_before = self._lengths.copy() if props else None
            self._lengths += qlens
            self._m_steps.inc()
            self._m_step_latency.observe(t1 - t0)
            for i, s in live:
                was_prefill = s.fed < len(s.prompt)
                if was_prefill:
                    c = chunks.get(i, 0)
                    if c == 0:
                        continue  # budget-starved this step; keep FIFO
                    s.fed += c
                    s.prefill_chunks += 1
                    self._m_prefill_chunks.inc()
                    self._m_prefill_tokens.inc(c)
                    if s.ctx is not None:
                        s.ctx.record("prefill_chunk", start=t0, end=t1,
                                     stream=s.sid, chunk=c, fed=s.fed,
                                     tenant=s.tenant)
                    if s.fed < len(s.prompt):
                        continue
                    # the chunk that consumed the last prompt token
                    # already produced the first generated token below
                    events_mod.emit("prefill_complete", stream=s.sid,
                                    prompt_tokens=len(s.prompt),
                                    chunks=s.prefill_chunks,
                                    cached_tokens=s.cached_tokens,
                                    tenant=s.tenant)
                    if self.prefix_index is not None:
                        # every full prompt-only page is now written;
                        # publish the ones the index doesn't know yet
                        pub = self.prefix_index.publish(
                            s.prompt, s.pages)
                        if pub:
                            events_mod.emit("prefix_cache_publish",  # graphcheck: ignore — stream-scoped; stream->tenant join via the stream_open event
                                            stream=s.sid, pages=pub)
                        self._m_prefix_pages.set(
                            self.prefix_index.pages_indexed)
                    emitted = [int(next_tok[i])]
                else:
                    p = props.get(i)
                    if p:
                        emitted = self._verify_row_locked(
                            i, s, p, spec_tok, lengths_before, t0, t1)
                    else:
                        s.fed += 1
                        emitted = [int(next_tok[i])]
                        if s.ctx is not None:
                            s.ctx.record("decode_step", start=t0,
                                         end=t1, stream=s.sid,
                                         tenant=s.tenant)
                for tok in emitted:
                    s.generated.append(tok)
                    if s.ttft_s is None:
                        s.ttft_s = t1 - s.submitted_at
                        self._m_ttft.observe(s.ttft_s)
                    if s.ctx is not None:
                        s.ctx.record("token_emit", start=t1, end=t1,
                                     stream=s.sid,
                                     index=len(s.generated) - 1)
                    self._m_tokens.inc()  # graphcheck: ignore — aggregate token counter predates tenancy; the tenant split rides serving_tenant_tokens_total below
                    self._m_tenant_tokens.labels(tenant=s.tenant).inc()
                    emits.append((s, tok))
                s.next_input = emitted[-1]
                if len(s.generated) >= s.max_new:
                    self._finish_locked(s, "complete")
                    finished.append(s)
            self._work.notify_all()
        for s, tok in emits:
            s.tokens_q.put(tok)
            if s.on_token is not None:
                try:
                    s.on_token(tok)
                except Exception as e:  # noqa: BLE001 — fail the stream, not the loop
                    self._cancel(s, error=e)
        for s in finished:
            s.tokens_q.put(_SENTINEL)
            s.done.set()
        return len(live)

    def _draft_propose_locked(self, cand) -> Dict[int, List[int]]:
        """Run up to ``spec_k + 1`` draft-model calls proposing tokens
        for the granted decode rows (``cand``: (slot, stream, grant)).

        The draft's cache is fed each stream's *known* tokens (prompt
        + generated) — independent of the target's prefill progress or
        prefix-cache hits, which is what keeps warm-prefix admissions
        token-exact — then extended one proposal at a time through its
        own stepped executable. ``stream.draft_fed`` tracks the known
        prefix already cached; the call that consumes the last known
        token yields the first proposal. A row still catching up when
        the call cap runs out simply decodes plain this step and
        resumes next cycle, so a long prompt can never stall its
        neighbours' verify round.
        """
        import jax.numpy as jnp

        g = self.geometry
        props: Dict[int, List[int]] = {i: [] for i, _, _ in cand}
        t_d0 = time.monotonic()
        for _ in range(g.spec_k + 1):
            tokens = np.zeros((g.max_streams, g.max_chunk), np.int32)
            qlens = np.zeros((g.max_streams,), np.int32)
            yields: List[int] = []  # rows whose call emits a proposal
            for i, s, grant in cand:
                known = len(s.prompt) + len(s.generated)
                if len(props[i]) >= grant:
                    continue
                if s.draft_fed >= known and not props[i]:
                    # defensive: every known token cached but no
                    # proposal in hand — rewind one and refeed it (the
                    # rewritten KV is identical, only the length moves)
                    s.draft_fed = known - 1
                    self._draft_lengths[i] = known - 1
                    self._draft_dirty = True
                if s.draft_fed < known:
                    feed = min(known - s.draft_fed, g.max_chunk)
                    base = len(s.prompt)
                    for j in range(feed):
                        t = s.draft_fed + j
                        tokens[i, j] = (s.prompt[t] if t < base
                                        else s.generated[t - base])
                    qlens[i] = feed
                    if s.draft_fed + feed == known:
                        yields.append(i)
                else:
                    tokens[i, 0] = props[i][-1]
                    qlens[i] = 1
                    yields.append(i)
            if not qlens.any():
                break
            carry = self._draft_carry
            self._draft_carry = None  # donated: loud on re-entry
            if self._draft_dirty:
                carry["page_tables"] = jnp.asarray(self._draft_tables)
                carry["lengths"] = jnp.asarray(self._draft_lengths)
                self._draft_dirty = False
            try:
                carry, out = self._draft_exe(
                    self._draft_params, carry, jnp.asarray(tokens),
                    jnp.asarray(qlens))
                next_tok = np.asarray(out["next_token"])
            except Exception as e:
                self._fail_locked(e)
                raise
            self._draft_carry = carry
            self._draft_lengths += qlens
            for i, s, grant in cand:
                if qlens[i]:
                    # known prefix only — proposal feeds don't advance
                    s.draft_fed = min(
                        len(s.prompt) + len(s.generated),
                        s.draft_fed + int(qlens[i]))
            for i in yields:
                props[i].append(int(next_tok[i]))
        t_d1 = time.monotonic()
        for i, s, _ in cand:
            if props[i] and s.ctx is not None:
                s.ctx.record("draft", start=t_d0, end=t_d1,
                             stream=s.sid, tokens=len(props[i]))
        return props

    def _verify_row_locked(self, i: int, s: _Stream, p: List[int],
                           spec_tok: np.ndarray,
                           lengths_before: np.ndarray,
                           t0: float, t1: float) -> List[int]:
        """Apply the greedy rejection rule to one verified row and
        roll both arenas back past the first disagreement. Returns the
        tokens to emit (``accepted + 1``, never 0)."""
        kg = len(p)
        w = self.geometry.spec_k + 1
        # window w-1-kg+j is the target's greedy pick AT drafted
        # position j (conditioned on the drafts before it); the last
        # window is the full-cache view — the bonus token
        target_preds = [int(t) for t in spec_tok[i, w - 1 - kg:]]
        a, nxt = greedy_accept(p, target_preds)
        emitted = p[:a] + [int(nxt)]
        # target arena: the step cached feedback + kg drafts; keep
        # feedback + the accepted run. Rejected tails are masked by
        # kv_len immediately and overwritten by later writes, and they
        # only ever landed in refcount-1 private pages (drafted
        # positions are past the prompt), so shared CoW prefix pages
        # are untouched by construction.
        c0 = int(lengths_before[i])
        if a < kg:
            self._lengths[i] = c0 + 1 + a
            self._dirty = True
        # draft arena: its cache holds known + kg-1 proposals; keep
        # the prefix that is now confirmed known-correct
        keep = len(s.prompt) + len(s.generated) + min(a, kg - 1)
        if int(self._draft_lengths[i]) != keep:
            self._draft_lengths[i] = keep
            self._draft_dirty = True
        s.draft_fed = keep
        s.fed += 1 + a
        s.acc_ema = (self.speculative.ema_alpha * (a / kg)
                     + (1.0 - self.speculative.ema_alpha) * s.acc_ema)
        self._m_spec_draft.inc(kg)
        self._m_spec_accepted.inc(a)
        self._m_spec_verify.inc()
        events_mod.emit("spec_verify", stream=s.sid, drafted=kg,  # graphcheck: ignore — stream-scoped; stream->tenant join via the stream_open event
                        accepted=a)
        if s.ctx is not None:
            s.ctx.record("verify", start=t0, end=t1, stream=s.sid,
                         drafted=kg, accepted=a)
        if s.acc_ema < self.speculative.fallback_acceptance:
            # acceptance collapsed: drafted tokens cost real step
            # budget, so flip this stream to plain decode for good
            # and hand its draft pages back
            s.spec_on = False
            self.draft_pool.free(s.draft_pages)
            s.draft_pages = []
            self._draft_tables[i, :] = 0
            self._draft_lengths[i] = 0
            self._draft_dirty = True
            self._m_spec_fallback.inc()
            self._m_draft_gauges.update(self.draft_pool)
            events_mod.emit("spec_fallback", stream=s.sid,  # graphcheck: ignore — stream-scoped; stream->tenant join via the stream_open event
                            acceptance=round(s.acc_ema, 4))
        return emitted

    def run_until_idle(self, max_steps: int = 10_000) -> int:
        """Step until no stream is active or queued (deterministic
        test driver). Returns steps executed."""
        for n in range(max_steps):
            if self.step() == 0:
                return n
        raise RuntimeError(f"not idle after {max_steps} steps")

    def _loop(self) -> None:
        while True:
            with self._work:
                while (not self._closed and self._failed is None
                       and not self._has_work_locked()):
                    self._work.wait(0.05)
                if self._closed or self._failed is not None:
                    return
            try:
                self.step()
            except Exception:  # noqa: BLE001 — streams already failed typed
                return

    def _has_work_locked(self) -> bool:
        return (self._queue.depth > 0
                or any(s is not None for s in self._streams))

    # -- lifecycle / resolution -------------------------------------------

    def _finish_locked(self, s: _Stream, how: str) -> None:
        if s.slot >= 0:
            self.pool.free(s.pages)
            held = self._tenant_pages.get(s.tenant, 0) - s.pages_needed
            if held > 0:
                self._tenant_pages[s.tenant] = held
            else:
                self._tenant_pages.pop(s.tenant, None)
            self._m_tenant_pages.labels(tenant=s.tenant).set(
                max(0, held))
            self._streams[s.slot] = None
            self._tables[s.slot, :] = 0
            self._lengths[s.slot] = 0
            self._dirty = True
            if s.draft_pages:
                self.draft_pool.free(s.draft_pages)
                s.draft_pages = []
                self._draft_tables[s.slot, :] = 0
                self._draft_lengths[s.slot] = 0
                self._draft_dirty = True
                self._m_draft_gauges.update(self.draft_pool)
            self._m_active.set(
                sum(1 for st in self._streams if st is not None))
            self._m_free_pages.set(self.pool.free_pages)
            self._m_pool_gauges.update(self.pool)
        events_mod.emit("stream_close", stream=s.sid,
                        tokens=len(s.generated), tenant=s.tenant)
        self._m_streams.labels(outcome=how).inc()  # graphcheck: ignore — aggregate outcome counter predates tenancy; per-tenant accounting rides serving_tenant_* series
        s.outcome = DecodeResult(
            tokens=list(s.generated), prompt_len=len(s.prompt),
            finished=how, ttft_s=s.ttft_s,
            cached_tokens=s.cached_tokens)

    def _resolve_shed(self, s: _Stream, overloaded: Overloaded) -> None:
        self._m_streams.labels(outcome="shed").inc()  # graphcheck: ignore — aggregate outcome counter predates tenancy; per-tenant sheds ride serving_tenant_shed_total at the callers
        s.outcome = overloaded
        s.tokens_q.put(_SENTINEL)
        s.done.set()

    def _cancel(self, s: _Stream,
                error: Optional[BaseException] = None) -> bool:
        with self._lock:
            if s.done.is_set() or s.outcome is not None:
                return False
            if s.slot < 0:
                self._queue.remove(s)
            self._finish_locked(s, "cancelled")
            s.error = error
            self._work.notify_all()
        s.tokens_q.put(_SENTINEL)
        s.done.set()
        return True

    def _fail_locked(self, e: BaseException) -> None:
        """A step blew up mid-flight: the donated carry may be gone,
        so the engine is dead — fail every stream typed, never hang
        a caller on a future that cannot resolve."""
        self._failed = e
        err = e if isinstance(e, (Unavailable, BatchError)) else \
            BatchError(f"decode step failed: {type(e).__name__}: {e}",
                       cause=e)
        leftovers = [s for s in self._streams if s is not None]
        for s in leftovers:
            self._streams[s.slot] = None
        for s in self._queue.drain_all():
            leftovers.append(s)
        for s in leftovers:
            s.error = err
            s.tokens_q.put(_SENTINEL)
            s.done.set()
        self._work.notify_all()

    def update_params(self, params, draft_params=None) -> None:
        """Swap weights recompile-free — same treedef/shapes → same
        compiled step. Callers quiesce first (the replica cutover's
        inflight guard covers decode dispatches end-to-end); a stream
        admitted after the swap generates entirely under the new tree,
        so no stream ever mixes KV from two versions. Cached prefix
        pages are a function of the weights, so the prefix index is
        flushed here — a retained cache would serve stale KV.

        Under speculative decoding the draft tree swaps in the same
        critical section (the fleet cutover loads BOTH trees before
        calling, so target and draft can never be from different
        versions mid-traffic): pass ``draft_params`` for a separately
        checkpointed draft; a self-drafting engine tracks ``params``
        automatically; otherwise the draft tree is left alone."""
        import jax

        with self._lock:
            self.params = jax.device_put(params)
            if self.speculative is not None:
                if draft_params is not None:
                    self._draft_params = jax.device_put(draft_params)
                elif self.speculative.draft_task is None:
                    self._draft_params = self.params  # self-draft
            if self.prefix_index is not None:
                self.prefix_index.clear()
                self._m_prefix_pages.set(0)

    def flush_prefix_cache(self) -> int:
        """Drop every index-held page (tests / tenant teardown).

        Pages shared by in-flight streams survive under the streams'
        own references; returns pages released by the index."""
        with self._lock:
            if self.prefix_index is None:
                return 0
            released = self.prefix_index.clear()
            self._m_prefix_pages.set(0)
            self._m_free_pages.set(self.pool.free_pages)
            self._m_pool_gauges.update(self.pool)
            return released

    def prefix_cache_stats(self) -> Optional[Dict[str, int]]:
        """Point-in-time index accounting (None when caching is off)."""
        with self._lock:
            if self.prefix_index is None:
                return None
            return {
                "pages_indexed": self.prefix_index.pages_indexed,
                "evictable_pages": self.prefix_index.evictable_pages(),
                "hits": int(self._m_prefix_hits.value_of()),
                "misses": int(self._m_prefix_misses.value_of()),
                "hit_tokens": int(self._m_prefix_hit_tokens.value_of()),
                "evicted_pages": int(self._m_prefix_evicted.value_of()),
            }

    def speculative_stats(self) -> Optional[Dict[str, float]]:
        """Point-in-time speculative accounting (None when off)."""
        with self._lock:
            if self.speculative is None:
                return None
            drafted = self._m_spec_draft.value_of()
            accepted = self._m_spec_accepted.value_of()
            return {
                "drafted_tokens": int(drafted),
                "accepted_tokens": int(accepted),
                "verify_steps": int(self._m_spec_verify.value_of()),
                "fallbacks": int(self._m_spec_fallback.value_of()),
                "acceptance_rate": (accepted / drafted) if drafted
                else 0.0,
                "draft_free_pages": self.draft_pool.free_pages,
            }

    def tenant_page_usage(self) -> Dict[str, int]:
        """Pages currently charged per tenant (the quota ledger) —
        chaos/bench gates sample this to prove isolation held."""
        with self._lock:
            return dict(self._tenant_pages)

    @property
    def active_streams(self) -> int:
        with self._lock:
            return sum(1 for s in self._streams if s is not None)

    @property
    def queue_depth(self) -> int:
        return self._queue.depth

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted stream finished."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._work:
            while self._has_work_locked():
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                self._work.wait(0.05)
        return True

    def metrics_text(self) -> str:
        return self.metrics.render()

    def close(self, timeout: float = 5.0) -> None:
        """Drain, then stop the worker. Streams still unfinished past
        ``timeout`` resolve with a typed ``Unavailable``."""
        with self._lock:
            if self._closed:
                return
        self.drain(timeout)
        with self._lock:
            self._closed = True
            self._work.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)
        with self._lock:
            stranded = [s for s in self._streams if s is not None]
            for s in self._streams:
                if s is not None:
                    self._streams[s.slot] = None
            stranded.extend(self._queue.drain_all())
        err = Unavailable("shutting_down")
        for s in stranded:
            s.error = err
            s.tokens_q.put(_SENTINEL)
            s.done.set()
