"""Adapter tests: Fourier channel counts/values, text embedding, outputs."""

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_tpu.adapters import (
    ImageInputAdapter,
    TextInputAdapter,
    ClassificationOutputAdapter,
    SemanticSegOutputAdapter,
    TextOutputAdapter,
)
from perceiver_tpu.ops import Policy
from perceiver_tpu.ops.fourier import fourier_position_encodings

FP32 = Policy.fp32()


def test_fourier_channel_count_mnist():
    # MNIST 28x28x1 with 32 bands -> 1 + 2*(2*32+1) = 131 channels
    # (SURVEY.md §2.2; reference adapter.py:96-97).
    a = ImageInputAdapter(image_shape=(28, 28, 1), num_frequency_bands=32)
    assert a.num_input_channels == 131


def test_fourier_encoding_values():
    """Spot-check against a direct computation of the reference formula."""
    enc = fourier_position_encodings((4, 6), num_bands=3)
    assert enc.shape == (24, 2 * (2 * 3 + 1))
    # positions first: rows iterate dim-0-major (meshgrid 'ij')
    xs = np.linspace(-1, 1, 4)
    ys = np.linspace(-1, 1, 6)
    np.testing.assert_allclose(enc[0, :2], [xs[0], ys[0]], atol=1e-7)
    np.testing.assert_allclose(enc[7, :2], [xs[1], ys[1]], atol=1e-7)
    # frequencies: linspace(1, max_freq/2, bands) with max_freq = dim size
    fx = np.linspace(1.0, 4 / 2, 3)
    expected_sin_x = np.sin(np.pi * fx * xs[1])
    np.testing.assert_allclose(enc[7, 2:5], expected_sin_x, atol=1e-6)
    # cosines follow all sins
    fy = np.linspace(1.0, 6 / 2, 3)
    expected_cos_y = np.cos(np.pi * fy * ys[1])
    np.testing.assert_allclose(enc[7, 11:14], expected_cos_y, atol=1e-6)


def test_image_adapter_forward():
    a = ImageInputAdapter(image_shape=(28, 28, 1), num_frequency_bands=32)
    p = a.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    y = a.apply(p, x, policy=FP32)
    assert y.shape == (2, 784, 131)
    # first channel is the raw pixel values
    np.testing.assert_allclose(np.asarray(y[:, :, 0]),
                               np.asarray(x.reshape(2, 784)), atol=1e-6)


def test_image_adapter_rejects_wrong_shape():
    a = ImageInputAdapter(image_shape=(28, 28, 1), num_frequency_bands=4)
    p = a.init(jax.random.key(0))
    try:
        a.apply(p, jnp.zeros((2, 32, 32, 1)), policy=FP32)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_text_adapter_embedding_scale_and_pos():
    a = TextInputAdapter(vocab_size=100, max_seq_len=16,
                         num_input_channels=64)
    p = a.init(jax.random.key(0))
    assert p["embed"].shape == (100, 64) and p["pos"].shape == (16, 64)
    assert np.all(np.abs(p["embed"]) <= 0.1)
    assert np.all(np.abs(p["pos"]) <= 0.5)
    x = jnp.array([[1, 2, 3, 4]])
    y = a.apply(p, x, policy=FP32)
    assert y.shape == (1, 4, 64)
    expected = np.asarray(p["embed"])[np.array([1, 2, 3, 4])] * 8.0 \
        + np.asarray(p["pos"])[:4]
    np.testing.assert_allclose(np.asarray(y[0]), expected, atol=1e-6)


def test_classification_output_adapter_squeeze():
    a = ClassificationOutputAdapter(num_classes=10)
    assert a.output_shape == (1, 10)
    p = a.init(jax.random.key(0))
    y = a.apply(p, jnp.ones((2, 1, 10)), policy=FP32)
    assert y.shape == (2, 10)


def test_classification_output_adapter_multi_output():
    a = ClassificationOutputAdapter(num_classes=3, num_outputs=5,
                                    num_output_channels=8)
    assert a.output_shape == (5, 8)
    p = a.init(jax.random.key(0))
    y = a.apply(p, jnp.ones((2, 5, 8)), policy=FP32)
    assert y.shape == (2, 5, 3)


def test_semantic_seg_output_adapter_applies_linear():
    # The reference's forward is a no-op defect (SURVEY.md §2.6.3);
    # ours projects to class logits.
    a = SemanticSegOutputAdapter(num_classes=3, num_outputs=16,
                                 num_output_channels=8)
    p = a.init(jax.random.key(0))
    y = a.apply(p, jnp.ones((2, 16, 8)), policy=FP32)
    assert y.shape == (2, 16, 3)


def test_text_output_adapter():
    a = TextOutputAdapter(vocab_size=50, max_seq_len=12,
                          num_output_channels=8)
    assert a.output_shape == (12, 8)
    p = a.init(jax.random.key(0))
    y = a.apply(p, jnp.ones((2, 12, 8)), policy=FP32)
    assert y.shape == (2, 12, 50)
