"""Mixed-precision policy.

The reference trains in fp32 (``scripts/trainer.yaml:49`` sets
``precision: 32``). On TPU the MXU natively consumes bfloat16, so the
framework default keeps parameters in fp32 and computes in bf16, with
softmax/normalization statistics accumulated in fp32. fp32-everywhere
remains available via ``Policy.fp32()`` for parity checks.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype policy applied at module boundaries.

    param_dtype:   dtype parameters are stored in.
    compute_dtype: dtype activations/matmuls run in (MXU-friendly).
    norm_dtype:    dtype for normalization / softmax statistics.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    norm_dtype: jnp.dtype = jnp.float32

    @staticmethod
    def fp32() -> "Policy":
        return Policy(compute_dtype=jnp.float32)

    @staticmethod
    def bf16() -> "Policy":
        return Policy(compute_dtype=jnp.bfloat16)

    def cast_compute(self, x):
        return x.astype(self.compute_dtype)

    def cast_param(self, x):
        return x.astype(self.compute_dtype)


DEFAULT_POLICY = Policy()
