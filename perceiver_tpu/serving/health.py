"""Health / readiness state machine, exported via metrics
(docs/RESILIENCE.md).

Serving health is a tiny explicit machine, not an ad-hoc boolean::

    STARTING ──warmup done──> READY <──recovered── DEGRADED
                                │                      ▲
                                └──breaker(s) open─────┘
                     DEGRADED ──all buckets open──> UNAVAILABLE
                     UNAVAILABLE ──any recovery───> DEGRADED/READY

- ``STARTING``: executables still compiling; not ready.
- ``READY``: every compiled bucket serving.
- ``DEGRADED``: some buckets' breakers open — traffic that fits the
  live buckets is served, the rest gets typed ``Unavailable`` (the
  degrade-don't-die state).
- ``UNAVAILABLE``: every bucket's breaker open; nothing dispatches.

Readiness (what a load balancer should route to) is
``READY or DEGRADED``. The state is exported as the
``serving_health_state`` gauge (the enum's numeric value),
``serving_ready`` 0/1, and a ``serving_health_transitions_total``
counter labeled ``{from,to}`` so flap rates are observable.
"""

from __future__ import annotations

import enum
import threading

from perceiver_tpu.obs import events as events_mod
from perceiver_tpu.serving.metrics import MetricsRegistry


class HealthState(enum.Enum):
    STARTING = 0
    READY = 1
    DEGRADED = 2
    UNAVAILABLE = 3


class HealthMonitor:
    """Tracks one serving engine's health and mirrors it to metrics."""

    def __init__(self, metrics: MetricsRegistry):
        self._lock = threading.Lock()
        self._state = HealthState.STARTING
        self._m_state = metrics.gauge(
            "serving_health_state",
            "0=starting 1=ready 2=degraded 3=unavailable")
        self._m_ready = metrics.gauge(
            "serving_ready", "1 iff the engine should receive traffic")
        self._m_transitions = metrics.counter(
            "serving_health_transitions_total",
            "health state changes, labeled from/to")
        self._m_state.set(self._state.value)
        self._m_ready.set(0)

    @property
    def state(self) -> HealthState:
        with self._lock:
            return self._state

    @property
    def ready(self) -> bool:
        return self.state in (HealthState.READY, HealthState.DEGRADED)

    def set(self, new: HealthState) -> None:
        with self._lock:
            old = self._state
            if new is old:
                return
            self._state = new
            self._m_state.set(new.value)
            self._m_ready.set(
                1 if new in (HealthState.READY, HealthState.DEGRADED)
                else 0)
            self._m_transitions.labels(**{"from": old.name.lower(),
                                          "to": new.name.lower()}).inc()
        events_mod.emit("health_transition", old=old.name.lower(),
                        new=new.name.lower())
