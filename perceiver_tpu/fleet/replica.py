"""One fleet replica: a ServingEngine behind an RPC server.

``python -m perceiver_tpu.fleet.replica --spec spec.json`` builds the
task named in the spec, loads its params from a
:class:`~perceiver_tpu.training.checkpoint.ParamsVersionStore` version
(sha256-verified) or fresh-init, warms the engine's AOT buckets (a
warm persistent exec cache makes this **zero-compile** — the PR-4
unlock that makes replica spin-up cheap), then prints ``READY <port>``
on stdout so the supervisor can connect.

RPC ops (see ``fleet/rpc.py`` for the envelope):

``dispatch``        host arrays in, materialized host outputs out.
                    A payload carrying ``packed_ids`` routes to the
                    engine's ragged ``dispatch_packed`` path (spec key
                    ``packed_buckets`` enables it) — the router and
                    RPC envelope are payload-agnostic, so packed and
                    rectangular replicas interchange freely
``status``          health/readiness, in-flight, version, staged
                    version, compile count, breaker summary, fired
                    fault counts
``update_version``  the rolling-update cutover (below)
``stage_version``   phase 1 of the group two-phase cutover: verified
                    load into memory, traffic untouched
``commit_version``  phase 2: quiesce and swap to the staged params
                    (``distributed/serving_group.py`` drives these —
                    a group swaps only after EVERY member staged)
``abort_version``   drop a staged version (stage-phase failure)
``metrics``         Prometheus text exposition
``ping``            liveness no-op
``shutdown``        clean exit

The cutover guard is the replica-side half of the zero-downtime
protocol (docs/SERVING.md "Fleet"): ``update_version`` flips a
``_swapping`` flag (new dispatches are rejected with a typed
``Unavailable("updating")`` the router transparently retries on a
sibling), waits for in-flight dispatches to reach zero, verifies the
target version's manifest, swaps via the engine's recompile-free
``update_params``, then readmits traffic — so **no request is ever
served by a mid-swap replica**: every dispatch runs entirely on the
old params or entirely on the new.

Chaos seams: ``replica.stall`` and ``replica.crash``
(``resilience/faults.py``) fire in the dispatch handler, and
``replica.commit_crash`` at ``commit_version`` entry — the
killed-between-stage-and-swap window the ``dist_cutover_kill``
scenario exercises — all inherited by this process through the
``PERCEIVER_FAULTS`` env var exactly like every other chaos child.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Optional

import numpy as np

from perceiver_tpu.fleet.rpc import RpcServer
from perceiver_tpu.obs import trace as trace_mod
from perceiver_tpu.resilience import faults
from perceiver_tpu.serving.api import materialize, materialize_packed
from perceiver_tpu.serving.batcher import Overloaded
from perceiver_tpu.serving.errors import Unavailable


def build_task(spec: dict):
    """Instantiate the spec's task config by class name from
    ``perceiver_tpu.tasks`` (specs are JSON, so the task rides as
    ``{"task_class": ..., "task_kwargs": {...}}``)."""
    import perceiver_tpu.tasks as tasks

    cls = getattr(tasks, spec["task_class"], None)
    if cls is None:
        raise ValueError(f"unknown task class {spec['task_class']!r}")
    return cls(**spec.get("task_kwargs", {}))


class ReplicaServer:
    """Engine + RPC plumbing + the cutover guard for one replica."""

    # lock discipline (gated by check.py --race): the cutover guard
    # state, written by _update/_commit/_abort and read per dispatch;
    # _idle is a Condition over _lock. Deliberately NOT declared:
    # self.version — it is swapped with a single str assignment only
    # while the replica is quiesced (_swapping set, _inflight drained
    # to 0), so readers race only against an atomic rebind.
    _GUARDED = {
        "_inflight": "_lock",
        "_swapping": "_lock",
        "_staged": "_lock",
    }

    def __init__(self, spec: dict):
        self.spec = spec
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight = 0
        self._swapping = False
        # (version, params) held for the two-phase group cutover
        self._staged: Optional[tuple] = None
        self._stop = threading.Event()
        self._compile_events: list = []
        self._listener_registered = False
        self._register_compile_listener()

        from perceiver_tpu.serving.engine import ServingEngine

        self.version: Optional[str] = spec.get("version")
        self.store = None
        params = None
        task = build_task(spec)
        if spec.get("store_dir"):
            from perceiver_tpu.training.checkpoint import ParamsVersionStore

            self.store = ParamsVersionStore(spec["store_dir"])
            if self.version is None:
                self.version = self.store.current()
            if self.version is not None:
                # template-less restore (orbax falls back to on-disk
                # metadata): building an init-params template would
                # compile the random init and break the zero-compile
                # spin-up contract the fleet chaos gate asserts
                params = self.store.load(self.version, None)
        self.engine = ServingEngine(
            task, params,
            batch_buckets=tuple(spec.get("batch_buckets", (4,))),
            seq_buckets=tuple(spec.get("seq_buckets", (16,))),
            packed_buckets=tuple(
                tuple(tb) for tb in spec.get("packed_buckets", ())),
            breaker_failure_threshold=spec.get(
                "breaker_failure_threshold", 5),
            breaker_reset_s=spec.get("breaker_reset_s", 30.0))
        # opt-in decode engine (spec key "decode" = geometry kwargs):
        # same task/params tree, same metrics registry — one exposition
        # covers both planes, and the compile listener above counts its
        # step compile in the zero-compile spin-up budget
        self.decode_engine = None
        self._prefix_cache_cfg = None
        self._spec_cfg = None
        self._draft_version = None
        if spec.get("decode"):
            from perceiver_tpu.serving.decode import (
                DecodeEngine,
                DecodeGeometry,
            )
            from perceiver_tpu.serving.prefix_cache import PrefixCacheConfig

            dspec = dict(spec["decode"])
            self._decode_max_new = int(dspec.pop("max_new_tokens_default",
                                                 16))
            # host-side pacing knob of the unified prefill+decode
            # scheduler; everything left in dspec is geometry
            token_budget = dspec.pop("token_budget", None)
            # opt-in prefix caching (spec key "prefix_cache" = config
            # kwargs, or true for defaults) — purely host-side page
            # sharing, so it never forks the exec-cache key
            pc = dspec.pop("prefix_cache", None)
            if pc is True:
                pc = PrefixCacheConfig()
            elif isinstance(pc, dict):
                pc = PrefixCacheConfig(**pc)
            self._prefix_cache_cfg = pc
            # opt-in speculative decoding (spec key "speculative";
            # geometry's spec_k stays in dspec — it forks the compiled
            # step). "draft" holds shrink_task overrides (absent =
            # self-draft); "draft_version" names a separately
            # published draft tree in the SAME version store.
            sp = dspec.pop("speculative", None)
            spec_cfg = None
            self._draft_version = None
            if sp:
                from perceiver_tpu.serving.speculative import (
                    SpeculativeConfig,
                    shrink_task,
                )

                sp = dict(sp) if isinstance(sp, dict) else {}
                self._draft_version = sp.pop("draft_version", None)
                shrink = sp.pop("draft", None)
                draft_task = None
                if shrink is not None:
                    draft_task = shrink_task(
                        task, **(shrink if isinstance(shrink, dict)
                                 else {}))
                draft_params = None
                if self._draft_version is not None:
                    if self.store is None:
                        raise ValueError(
                            "speculative.draft_version needs a params "
                            "version store (store_dir)")
                    draft_params = self.store.load(
                        self._draft_version, None)
                spec_cfg = SpeculativeConfig(
                    draft_task=draft_task, draft_params=draft_params,
                    **sp)
            self._spec_cfg = spec_cfg
            self.decode_engine = DecodeEngine(
                task, self.engine._params_src,
                geometry=DecodeGeometry(**dspec),
                token_budget=token_budget,
                prefix_cache=pc,
                speculative=spec_cfg,
                metrics=self.engine.metrics)
        self.server = RpcServer(self.handle,
                                port=int(spec.get("port", 0)),
                                io_timeout=spec.get("io_timeout_s", 60.0))

    def _register_compile_listener(self) -> None:
        """Count XLA compile events from before engine construction —
        the fleet's zero-compile-spin-up assertion reads this count
        over RPC (``status``)."""
        try:
            import jax

            def listener(name, **kwargs):
                if "compile" in name:
                    self._compile_events.append(name)

            jax.monitoring.register_event_listener(listener)
            self._listener_registered = True
        except Exception:  # pragma: no cover - jax.monitoring drift
            # older/newer jax without the listener API: the compile
            # count degrades to unknown (-1) rather than blocking spin-up
            self._compile_events = None

    # -- RPC handler ------------------------------------------------------

    def handle(self, request: dict):
        op = request.get("op")
        if op == "dispatch":
            return self._dispatch(request["arrays"],
                                  request.get("trace"))
        if op == "status":
            return self._status()
        if op == "update_version":
            return self._update_version(request["version"])
        if op == "stage_version":
            return self._stage_version(request["version"])
        if op == "commit_version":
            return self._commit_version(request["version"])
        if op == "abort_version":
            return self._abort_version()
        if op == "metrics":
            return self.engine.metrics.render()
        if op == "ping":
            return "pong"
        if op == "shutdown":
            self._stop.set()
            return "bye"
        raise ValueError(f"unknown op {op!r}")

    def _dispatch(self, arrays: dict, wire: Optional[dict] = None) -> dict:
        # rehydrate the caller's trace (if it sent one) into a local
        # span collector — the spans ride back in the reply and the
        # router re-keys them into the request's trace
        collector = trace_mod.SpanCollector()
        ctx = trace_mod.from_wire(wire, sink=collector, origin="replica")
        admit_start = time.monotonic()
        with self._lock:
            if self._swapping:
                # mid-swap: typed rejection the router retries on a
                # sibling — this replica serves no request until the
                # param cutover completes
                raise Unavailable("updating", retry_after_s=0.05)
            self._inflight += 1
        try:
            faults.maybe_stall("replica.stall")
            faults.maybe_kill("replica.crash")
            if ctx is not None:
                # admission (lock/stall wait) is this replica's queue
                ctx.record("queue_wait", start=admit_start)
            with trace_mod.attach([ctx]):
                if "prompt_ids" in arrays:
                    outputs = self._decode_dispatch(arrays, ctx)
                elif "packed_ids" in arrays:
                    result = self.engine.dispatch_packed(arrays)
                    with trace_mod.region("device"):
                        outputs = materialize_packed(
                            result, self.engine.packed_graph)
                else:
                    result = self.engine.dispatch(arrays)
                    with trace_mod.region("device"):
                        outputs = materialize(result, self.engine.graph)
        finally:
            with self._lock:
                self._inflight -= 1
                self._idle.notify_all()
        reply = {"outputs": outputs,
                 "health": self.engine.health.state.name,
                 "version": self.version}
        if ctx is not None:
            reply["spans"] = collector.spans
        return reply

    def _decode_dispatch(self, arrays: dict, ctx) -> dict:
        """Run one decode payload (``prompt_ids`` + optional
        ``max_new_tokens``) to completion and return the full token
        array. Token-by-token streaming stays in-process behind
        ``serving/api.GenerationServer`` — the fleet RPC is
        request/response, so a decode replica trades streaming for the
        router's retry/failover semantics. A shed stream surfaces as
        the typed ``Unavailable`` the router transparently retries on
        a sibling."""
        if self.decode_engine is None:
            raise ValueError(
                "replica has no decode engine (enable with the "
                "'decode' spec key)")
        max_new = int(arrays.get("max_new_tokens", self._decode_max_new))
        handle = self.decode_engine.submit(
            arrays["prompt_ids"], max_new_tokens=max_new, trace=ctx)
        result = handle.result()
        if isinstance(result, Overloaded):
            raise Unavailable(f"decode_{result.reason}",
                              retry_after_s=0.05)
        return {"tokens": np.asarray(result.tokens, np.int32),
                "ttft_s": np.asarray(result.ttft_s or 0.0, np.float64)}

    def _status(self) -> dict:
        metrics = self.engine.metrics
        open_buckets = metrics.get("serving_breaker_open_buckets")
        with self._lock:
            inflight = self._inflight
            swapping = self._swapping
            staged = self._staged[0] if self._staged else None
        return {
            "health": self.engine.health.state.name,
            "ready": self.engine.ready and not swapping,
            "inflight": inflight,
            "swapping": swapping,
            "version": self.version,
            "staged": staged,
            "compile_events": (len(self._compile_events)
                               if self._compile_events is not None else -1),
            "breaker_open_buckets": (int(open_buckets.value)
                                     if open_buckets else 0),
            "faults_fired": faults.counts(),
            # advertised so routers/operators can see which replicas
            # share KV prefixes (None = decode absent or caching off)
            "prefix_cache": (
                {"max_pages": self._prefix_cache_cfg.max_pages}
                if self._prefix_cache_cfg is not None else None),
            # which replicas draft-and-verify, and from which tree
            # (None = decode absent or speculation off)
            "speculative": (
                {"spec_k": self.decode_engine.geometry.spec_k,
                 "self_draft": self._spec_cfg.draft_task is None,
                 "draft_version": self._draft_version}
                if self._spec_cfg is not None else None),
        }

    def _load_draft_for(self, version: str):
        """The draft tree riding along with ``version`` (two trees,
        ONE cutover): a separately checkpointed draft is published as
        ``<version>-draft`` in the same store. Returns None when this
        replica doesn't draft from its own checkpoint — a self-draft
        engine tracks the target tree inside ``update_params``.
        Loading happens BEFORE either tree is swapped, so a corrupt
        draft manifest aborts the whole cutover typed and the replica
        keeps serving the old pair."""
        if (self.decode_engine is None or self._spec_cfg is None
                or self._spec_cfg.draft_task is None):
            return None
        draft_version = f"{version}-draft"
        if draft_version not in self.store.versions():
            return None
        return self.store.load(draft_version, None)

    def _update_version(self, version: str) -> dict:
        """The cutover: quiesce → verify → swap → readmit."""
        with self._lock:
            if self._swapping:
                raise Unavailable("updating", retry_after_s=0.1)
            self._swapping = True
        try:
            with self._lock:
                while self._inflight > 0:
                    self._idle.wait(0.05)
            if self.store is None:
                raise ValueError("replica has no params version store")
            # verified load: raises CheckpointIntegrityError on a
            # corrupt manifest — crosses the wire typed, and the
            # rollout driver turns it into an auto-rollback
            params = self.store.load(version,
                                     self.engine._params_src)
            # both trees load before EITHER swaps: target and draft
            # can never come from different versions mid-traffic
            draft_params = self._load_draft_for(version)
            self.engine.update_params(params)
            if self.decode_engine is not None:
                self.decode_engine.update_params(
                    params, draft_params=draft_params)
            self.version = version
        finally:
            with self._lock:
                self._swapping = False
        return {"version": self.version}

    def _stage_version(self, version: str) -> dict:
        """Two-phase cutover, phase 1: verified load of ``version``
        into memory. Serving is untouched — the staged tree sits
        beside the live one until commit or abort. Idempotent:
        re-staging replaces the previous staged tree."""
        if self.store is None:
            raise ValueError("replica has no params version store")
        params = self.store.load(version, self.engine._params_src)
        # the draft tree stages alongside the target tree — a commit
        # later swaps both inside one quiesced window
        draft_params = self._load_draft_for(version)
        with self._lock:
            self._staged = (version, params, draft_params)
        return {"staged": version}

    def _commit_version(self, version: str) -> dict:
        """Phase 2: quiesce and swap to the STAGED params. The swap
        itself is the same atomic quiesce → ``update_params`` →
        readmit as ``update_version`` — a dispatch racing the commit
        gets the typed ``Unavailable`` retry, never torn params."""
        # the killed-between-stage-and-swap chaos window: a SIGKILL
        # here leaves this member staged-but-uncommitted while its
        # siblings may already serve the new version — the group
        # handle's rollback path owns the cleanup
        faults.maybe_kill("replica.commit_crash")
        with self._lock:
            if self._swapping:
                raise Unavailable("updating", retry_after_s=0.1)
            if self._staged is None or self._staged[0] != version:
                have = self._staged[0] if self._staged else None
                raise ValueError(
                    f"commit of {version!r} without a matching stage "
                    f"(staged: {have!r}) — the two-phase protocol "
                    f"requires stage_version first")
            self._swapping = True
        try:
            with self._lock:
                while self._inflight > 0:
                    self._idle.wait(0.05)
                version, params, draft_params = self._staged
                self._staged = None
            self.engine.update_params(params)
            if self.decode_engine is not None:
                self.decode_engine.update_params(
                    params, draft_params=draft_params)
            self.version = version
        finally:
            with self._lock:
                self._swapping = False
        return {"version": self.version}

    def _abort_version(self) -> dict:
        """Drop a staged version (stage-phase failure on a sibling)."""
        with self._lock:
            staged = self._staged
            self._staged = None
        return {"aborted": staged[0] if staged else None}

    # -- lifecycle --------------------------------------------------------

    def serve_forever(self) -> None:
        print(f"READY {self.server.port}", flush=True)
        self._stop.wait()
        self.server.close()

    def close(self) -> None:
        self._stop.set()
        if self.decode_engine is not None:
            self.decode_engine.close()
        self.server.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="fleet replica process")
    ap.add_argument("--spec", required=True,
                    help="path to the replica spec JSON")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    replica = ReplicaServer(spec)
    replica.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
