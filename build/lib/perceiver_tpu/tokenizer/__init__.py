"""WordPiece tokenizer subsystem.

The reference delegates to the Rust HuggingFace ``tokenizers`` library
(``perceiver/tokenizer.py``). Here the tokenizer is implemented natively:
a C++ core (normalize / pre-tokenize / WordPiece encode / decode / train)
exposed over ctypes, with a pure-Python engine sharing the same JSON
vocabulary format for environments without the compiled extension.
"""

from perceiver_tpu.tokenizer.vocab import (  # noqa: F401
    PAD_TOKEN,
    PAD_TOKEN_ID,
    UNK_TOKEN,
    UNK_TOKEN_ID,
    MASK_TOKEN,
    MASK_TOKEN_ID,
    SPECIAL_TOKENS,
)
from perceiver_tpu.tokenizer.wordpiece import (  # noqa: F401
    WordPieceTokenizer,
    create_tokenizer,
    load_tokenizer,
    save_tokenizer,
    train_tokenizer,
)
