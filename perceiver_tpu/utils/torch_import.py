"""Import the reference's PyTorch / PyTorch-Lightning checkpoints.

The reference publishes trained Lightning checkpoints for its MLM and
classifier recipes (reference ``README.md:72-74``); this module converts
their ``state_dict`` into this framework's parameter pytree so a
reference user can bring trained weights along when switching.

Key-path contract (derived from the reference module tree; see
``/root/reference/perceiver/model.py`` — attribute names cited inline):

* ``encoder.input_adapter.text_embedding.weight`` / ``.pos_encoding``
  (``adapter.py:116-117``) → ``encoder.input_adapter.embed`` / ``pos``
* ``encoder.latent`` (``model.py:169``) → ``encoder.latent``
* per perceiver layer (``model.py:150-166``: ``layer_1``, ``layer_n``;
  each ``Sequential(cross_attention_layer, self_attention_block)``):

  - ``<L>.0.0.module`` = Residual(CrossAttention): ``q_norm``/``kv_norm``
    (``model.py:89-90``) + ``attention.attention`` =
    ``nn.MultiheadAttention`` (``model.py:66``)
  - ``<L>.0.1.module`` = Residual(mlp): Sequential indices 0 (LayerNorm),
    1, 3 (Linear) (``model.py:20-26``)
  - ``<L>.1.<i>.0.module`` = Residual(SelfAttention): ``norm`` +
    ``attention.attention``; ``<L>.1.<i>.1.module`` = Residual(mlp)

* ``decoder.output`` (``model.py:222``) → ``decoder.query``
* ``decoder.cross_attention.{0,1}.module`` (``model.py:217``) →
  ``decoder.cross``
* ``decoder.output_adapter.linear`` (``adapter.py:146``) →
  ``decoder.output_adapter.linear``

``nn.MultiheadAttention`` stores a packed ``in_proj_weight`` (3E, E)
when q/k/v widths agree, else separate ``{q,k,v}_proj_weight``; biases
are always the packed ``in_proj_bias`` (3E). torch ``Linear`` weights
are (out, in) and compute ``x @ W.T + b``; this framework stores
(in, out) computing ``x @ w + b`` — so every weight matrix transposes.
Head splitting is contiguous-chunk in both (reshape to (..., H, E/H)),
so no per-head permutation is needed.
"""

from typing import Dict, Optional

import numpy as np

__all__ = [
    "assert_tree_matches",
    "convert_encoder",
    "convert_perceiver_params",
    "export_perceiver_params",
    "load_lightning_state_dict",
    "restore_from_torch",
]


def _t(w) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(w).T)


def _a(w) -> np.ndarray:
    return np.asarray(w)


class _SD:
    """A consumable view of a torch state dict (numpy leaves): every
    ``take`` removes the key, so unconsumed keys can be reported."""

    def __init__(self, sd: Dict[str, np.ndarray]):
        self.sd = dict(sd)

    def take(self, key: str) -> np.ndarray:
        try:
            return self.sd.pop(key)
        except KeyError:
            raise KeyError(
                f"reference checkpoint is missing key {key!r}; "
                f"nearby keys: "
                f"{[k for k in self.sd if k.startswith(key.split('.')[0])][:8]}"
            ) from None

    def has(self, key: str) -> bool:
        return key in self.sd

    def leftover(self, prefix: str = ""):
        return [k for k in self.sd if k.startswith(prefix)]


def _convert_mha(sd: _SD, prefix: str) -> dict:
    """``nn.MultiheadAttention`` params at ``prefix`` → our ``mha``."""
    if sd.has(prefix + "in_proj_weight"):
        w = _a(sd.take(prefix + "in_proj_weight"))  # (3E, E)
        e = w.shape[0] // 3
        qw, kw, vw = (_t(w[i * e:(i + 1) * e]) for i in range(3))
    else:
        qw = _t(sd.take(prefix + "q_proj_weight"))
        kw = _t(sd.take(prefix + "k_proj_weight"))
        vw = _t(sd.take(prefix + "v_proj_weight"))
    b = _a(sd.take(prefix + "in_proj_bias"))
    e = b.shape[0] // 3
    return {
        "q": {"w": qw, "b": b[:e]},
        "k": {"w": kw, "b": b[e:2 * e]},
        "v": {"w": vw, "b": b[2 * e:]},
        "out": {"w": _t(sd.take(prefix + "out_proj.weight")),
                "b": _a(sd.take(prefix + "out_proj.bias"))},
    }


def _convert_mlp(sd: _SD, prefix: str) -> dict:
    """Residual(mlp) at ``prefix`` (Sequential LN→Linear→GELU→Linear,
    reference ``model.py:20-26``) → our ``mlp``."""
    return {
        "norm": {"scale": _a(sd.take(prefix + "0.weight")),
                 "bias": _a(sd.take(prefix + "0.bias"))},
        "fc1": {"w": _t(sd.take(prefix + "1.weight")),
                "b": _a(sd.take(prefix + "1.bias"))},
        "fc2": {"w": _t(sd.take(prefix + "3.weight")),
                "b": _a(sd.take(prefix + "3.bias"))},
    }


def _convert_cross_layer(sd: _SD, prefix: str) -> dict:
    """cross_attention_layer at ``prefix`` (reference ``model.py:29-33``)
    → our ``{"attn": ..., "mlp": ...}``."""
    attn = {
        "norm_q": {"scale": _a(sd.take(prefix + "0.module.q_norm.weight")),
                   "bias": _a(sd.take(prefix + "0.module.q_norm.bias"))},
        "norm_kv": {"scale": _a(sd.take(prefix + "0.module.kv_norm.weight")),
                    "bias": _a(sd.take(prefix + "0.module.kv_norm.bias"))},
        "mha": _convert_mha(sd, prefix + "0.module.attention.attention."),
    }
    return {"attn": attn, "mlp": _convert_mlp(sd, prefix + "1.module.")}


def _convert_self_block(sd: _SD, prefix: str) -> dict:
    """self_attention_block at ``prefix`` (reference ``model.py:43-44``)
    → our stacked ``selfs`` subtree (leading axis = layer index, the
    ``lax.scan`` layout)."""
    per_layer = []
    i = 0
    while sd.has(f"{prefix}{i}.0.module.norm.weight"):
        p = f"{prefix}{i}."
        per_layer.append({
            "attn": {
                "norm": {"scale": _a(sd.take(p + "0.module.norm.weight")),
                         "bias": _a(sd.take(p + "0.module.norm.bias"))},
                "mha": _convert_mha(sd, p + "0.module.attention.attention."),
            },
            "mlp": _convert_mlp(sd, p + "1.module."),
        })
        i += 1
    if not per_layer:
        raise KeyError(f"no self-attention layers found under {prefix!r}")
    import jax

    # leading axis = layer index (the lax.scan layout)
    return jax.tree.map(lambda *xs: np.stack(xs), *per_layer)


def _convert_perceiver_layer(sd: _SD, prefix: str) -> dict:
    return {
        "cross": _convert_cross_layer(sd, prefix + "0."),
        "selfs": _convert_self_block(sd, prefix + "1."),
    }


def convert_encoder(sd: Dict[str, np.ndarray],
                    prefix: str = "encoder.") -> dict:
    """Convert a reference ``PerceiverEncoder`` state-dict subtree."""
    s = _SD({k: v for k, v in sd.items() if k.startswith(prefix)})
    out = {"latent": _a(s.take(prefix + "latent"))}
    ia = {}
    if s.has(prefix + "input_adapter.text_embedding.weight"):
        ia["embed"] = _a(s.take(prefix +
                                "input_adapter.text_embedding.weight"))
        ia["pos"] = _a(s.take(prefix + "input_adapter.pos_encoding"))
    if s.has(prefix + "input_adapter.position_encoding"):
        # image adapter's precomputed Fourier buffer — we recompute it
        s.take(prefix + "input_adapter.position_encoding")
    # always present: the framework template carries an (empty)
    # input_adapter subtree even for adapters with no learned params
    out["input_adapter"] = ia
    out["layer_1"] = _convert_perceiver_layer(s, prefix + "layer_1.")
    if s.has(prefix + "layer_n.0.0.module.q_norm.weight"):
        out["layer_n"] = _convert_perceiver_layer(s, prefix + "layer_n.")
    left = s.leftover()
    if left:
        raise ValueError(f"unconverted reference encoder keys: {left}")
    return out


def convert_perceiver_params(sd: Dict[str, np.ndarray],
                             prefix: Optional[str] = None) -> dict:
    """Convert a full reference PerceiverIO/PerceiverMLM state dict
    (e.g. a Lightning checkpoint's ``state_dict``) to this framework's
    ``{"encoder": ..., "decoder": ...}`` parameter pytree.

    ``prefix=None`` auto-detects where the model lives in the dict:
    ``model.`` (Lightning tasks, ``lightning.py:96``), ``perceiver.``
    (the ``run.py`` LAr_Perceiver save, ``run.py:102,278-281``), or
    bare keys (a directly saved model).

    Child naming differs by model family: ``PerceiverMLM`` registers
    named ``self.encoder``/``self.decoder`` attributes
    (``model.py:296-304``), but ``PerceiverIO`` subclasses
    ``nn.Sequential`` (``model.py:321-325``, ``utils.py:7``), whose
    children serialize as ``0.``/``1.`` — every real classifier and
    ``run.py`` checkpoint uses the numeric form. Both are accepted;
    numeric children are normalized to ``encoder.``/``decoder.``."""
    if prefix is None:
        for cand in ("model.", "perceiver.", ""):
            if (cand + "encoder.latent") in sd or (cand + "0.latent") in sd:
                prefix = cand
                break
        else:
            raise ValueError(
                "could not locate 'encoder.latent' (or the Sequential "
                "form '0.latent') under any known prefix ('model.', "
                "'perceiver.', '') — keys look like: "
                f"{sorted(sd)[:8]}")
    sd = {k[len(prefix):]: v for k, v in sd.items()
          if k.startswith(prefix)}
    if ("0.latent") in sd:
        # PerceiverIO-as-Sequential child names → named-attribute form
        def _norm(k):
            if k.startswith("0."):
                return "encoder." + k[2:]
            if k.startswith("1."):
                return "decoder." + k[2:]
            return k
        sd = {_norm(k): v for k, v in sd.items()}
    # loud-failure contract: trained weights outside the encoder/
    # decoder subtrees (there are none in any reference model — masking
    # and the metrics have no params) must not vanish silently
    stray = [k for k in sd
             if not k.startswith(("encoder.", "decoder."))]
    if stray:
        raise ValueError(
            f"checkpoint keys under prefix {prefix!r} outside "
            f"encoder./decoder. would be dropped: {stray[:8]}")
    enc = convert_encoder(sd)
    s = _SD({k: v for k, v in sd.items() if k.startswith("decoder.")})
    dec = {
        "query": _a(s.take("decoder.output")),
        "cross": _convert_cross_layer(s, "decoder.cross_attention."),
        "output_adapter": {
            "linear": {
                "w": _t(s.take("decoder.output_adapter.linear.weight")),
                "b": _a(s.take("decoder.output_adapter.linear.bias")),
            },
        },
    }
    left = s.leftover()
    if left:
        raise ValueError(f"unconverted reference decoder keys: {left}")
    return {"encoder": enc, "decoder": dec}


def load_lightning_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Load a Lightning ``.ckpt`` (or bare ``torch.save``d state dict /
    ``run.py``-style dict with ``model_state_dict``) as numpy arrays.

    Tries torch's safe ``weights_only=True`` first. Reference-era
    Lightning 1.5 checkpoints pickle Lightning objects alongside the
    tensors, which the safe loader rejects; set
    ``PERCEIVER_TPU_TRUST_TORCH_CKPT=1`` to permit a full unpickle —
    only for checkpoints you trust (unpickling executes code).
    """
    import os

    import torch

    try:
        obj = torch.load(path, map_location="cpu", weights_only=True)
    except Exception as safe_err:  # noqa: BLE001 — explain the knob
        if os.environ.get("PERCEIVER_TPU_TRUST_TORCH_CKPT") == "1":
            obj = torch.load(path, map_location="cpu",
                             weights_only=False)
        else:
            raise ValueError(
                f"safe (weights_only) load of {path!r} failed: "
                f"{safe_err}\nLightning-era checkpoints pickle "
                f"framework objects next to the tensors; if you trust "
                f"this file, set PERCEIVER_TPU_TRUST_TORCH_CKPT=1 to "
                f"allow a full unpickle.") from safe_err
    if isinstance(obj, dict):
        if "state_dict" in obj:          # Lightning checkpoint
            obj = obj["state_dict"]
        elif "model_state_dict" in obj:  # reference run.py:278-281 save
            obj = obj["model_state_dict"]
    return {k: v.detach().cpu().numpy() for k, v in obj.items()
            if hasattr(v, "detach")}


def assert_tree_matches(converted, template, path="params") -> None:
    """Raise if the converted tree's structure/shapes differ from the
    framework-initialized template (catches config mismatches loudly
    instead of at the first jitted apply)."""
    if isinstance(template, dict):
        if not isinstance(converted, dict):
            raise ValueError(f"{path}: expected subtree, got leaf")
        missing = set(template) - set(converted)
        extra = set(converted) - set(template)
        if missing or extra:
            raise ValueError(f"{path}: missing keys {sorted(missing)}, "
                             f"unexpected keys {sorted(extra)}")
        for k in template:
            assert_tree_matches(converted[k], template[k], f"{path}.{k}")
    else:
        t_shape = tuple(getattr(template, "shape", ()))
        c_shape = tuple(np.shape(converted))
        if t_shape != c_shape:
            raise ValueError(f"{path}: shape {c_shape} != expected "
                             f"{t_shape} (checkpoint/config mismatch?)")


def restore_from_torch(path: str, template: Optional[dict] = None,
                       prefix: Optional[str] = None) -> dict:
    """One-call import: load + convert (+ validate against a template
    pytree from ``model.init`` when given), returning numpy leaves."""
    params = convert_perceiver_params(load_lightning_state_dict(path),
                                      prefix=prefix)
    if template is not None:
        assert_tree_matches(params, template)
    return params


# --- export (the reverse direction) ---------------------------------------

def _unstack(tree):
    """Inverse of the self-block stacking: stacked leaves (layer axis
    0) → list of per-layer trees."""
    import jax

    leaves = jax.tree.leaves(tree)
    n = leaves[0].shape[0]
    return [jax.tree.map(lambda x, i=i: np.asarray(x[i]), tree)
            for i in range(n)]


def _export_mha(mha: dict, out: Dict[str, np.ndarray], prefix: str):
    qw, kw, vw = (_t(mha[k]["w"]) for k in ("q", "k", "v"))
    e = qw.shape[0]
    if kw.shape[1] == e and vw.shape[1] == e:
        # torch packs q/k/v when all widths agree
        out[prefix + "in_proj_weight"] = np.concatenate([qw, kw, vw])
    else:
        out[prefix + "q_proj_weight"] = qw
        out[prefix + "k_proj_weight"] = kw
        out[prefix + "v_proj_weight"] = vw
    out[prefix + "in_proj_bias"] = np.concatenate(
        [_a(mha[k]["b"]) for k in ("q", "k", "v")])
    out[prefix + "out_proj.weight"] = _t(mha["out"]["w"])
    out[prefix + "out_proj.bias"] = _a(mha["out"]["b"])


def _export_mlp(mlp: dict, out: Dict[str, np.ndarray], prefix: str):
    out[prefix + "0.weight"] = _a(mlp["norm"]["scale"])
    out[prefix + "0.bias"] = _a(mlp["norm"]["bias"])
    out[prefix + "1.weight"] = _t(mlp["fc1"]["w"])
    out[prefix + "1.bias"] = _a(mlp["fc1"]["b"])
    out[prefix + "3.weight"] = _t(mlp["fc2"]["w"])
    out[prefix + "3.bias"] = _a(mlp["fc2"]["b"])


def _export_cross(cross: dict, out: Dict[str, np.ndarray], prefix: str):
    attn = cross["attn"]
    out[prefix + "0.module.q_norm.weight"] = _a(attn["norm_q"]["scale"])
    out[prefix + "0.module.q_norm.bias"] = _a(attn["norm_q"]["bias"])
    out[prefix + "0.module.kv_norm.weight"] = _a(attn["norm_kv"]["scale"])
    out[prefix + "0.module.kv_norm.bias"] = _a(attn["norm_kv"]["bias"])
    _export_mha(attn["mha"], out, prefix + "0.module.attention.attention.")
    _export_mlp(cross["mlp"], out, prefix + "1.module.")


def export_perceiver_params(params: dict, prefix: str = "model.",
                            sequential: bool = False,
                            position_encoding=None
                            ) -> Dict[str, np.ndarray]:
    """The reverse migration: this framework's parameter pytree → a
    reference-format torch ``state_dict`` (numpy leaves; pass through
    ``torch.as_tensor`` to save). ``convert_perceiver_params`` of the
    result round-trips to the identical pytree.

    ``sequential=True`` emits the ``0.``/``1.`` child names of the
    reference's Sequential-based ``PerceiverIO`` (the classifier and
    ``run.py`` model layout, ``model.py:321-325``); the default named
    form matches ``PerceiverMLM``. For image models pass
    ``position_encoding`` (e.g. ``ImageInputAdapter.position_encoding()``)
    so the reference's persistent Fourier buffer
    (``adapter.py:43-51``) is present and ``load_state_dict`` works
    with ``strict=True``; without it, load with ``strict=False`` (the
    reference recomputes the buffer at construction)."""
    e_name, d_name = ("0", "1") if sequential else ("encoder", "decoder")
    out: Dict[str, np.ndarray] = {}
    enc = params["encoder"]
    ia = enc.get("input_adapter") or {}
    if "embed" in ia:
        out[f"{prefix}{e_name}.input_adapter.text_embedding.weight"] = \
            _a(ia["embed"])
        out[f"{prefix}{e_name}.input_adapter.pos_encoding"] = _a(ia["pos"])
    if position_encoding is not None:
        out[f"{prefix}{e_name}.input_adapter.position_encoding"] = \
            _a(position_encoding)
    out[f"{prefix}{e_name}.latent"] = _a(enc["latent"])
    for layer in ("layer_1", "layer_n"):
        if layer not in enc:
            continue
        lp = f"{prefix}{e_name}.{layer}."
        _export_cross(enc[layer]["cross"], out, lp + "0.")
        for i, self_layer in enumerate(_unstack(enc[layer]["selfs"])):
            sp = f"{lp}1.{i}."
            attn = self_layer["attn"]
            out[sp + "0.module.norm.weight"] = _a(attn["norm"]["scale"])
            out[sp + "0.module.norm.bias"] = _a(attn["norm"]["bias"])
            _export_mha(attn["mha"], out,
                        sp + "0.module.attention.attention.")
            _export_mlp(self_layer["mlp"], out, sp + "1.module.")
    dec = params["decoder"]
    out[f"{prefix}{d_name}.output"] = _a(dec["query"])
    _export_cross(dec["cross"], out, f"{prefix}{d_name}.cross_attention.")
    out[f"{prefix}{d_name}.output_adapter.linear.weight"] = \
        _t(dec["output_adapter"]["linear"]["w"])
    out[f"{prefix}{d_name}.output_adapter.linear.bias"] = \
        _a(dec["output_adapter"]["linear"]["b"])
    return out
