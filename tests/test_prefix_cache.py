"""PagePool-under-sharing + PrefixIndex invariants (ISSUE 18).

Property-style randomized tests over the refcounted arena: refcounts
never go negative (over-free raises instead), the reserved trash page
0 is never handed out, indexed, or published, copy-on-write's
``ensure_private_page`` guard rejects every shared page, and the
conservation law ``free + allocated == num_pages - 1`` holds after
every operation — with full teardown always reclaiming the arena
bit-for-bit (the no-refcount-leak law the chaos scenario asserts under
load).

The second half replays lookup/publish/evict/free interleavings under
the seeded InterleaveScheduler harness (tests/test_racecheck.py
pattern): a given seed reproduces the exact same schedule and the
exact same sharing outcome forever, and the guarded-proxy test proves
an unlocked touch of index state fails loudly instead of corrupting
refcounts one run in a thousand. No engine, no JAX — this file is
pure host-side allocator discipline.
"""

import random
from collections import Counter

import pytest

from perceiver_tpu.serving.decode import PagePool
from perceiver_tpu.serving.prefix_cache import (
    PrefixCacheConfig,
    PrefixIndex,
    ensure_private_page,
)
from perceiver_tpu.utils.concurrency import (
    InstrumentedLock,
    InterleaveScheduler,
    UnguardedAccessError,
    guarded,
)

PS = 4  # page size for every test in this file


def _ceil_pages(tokens):
    return -(-tokens // PS)


def _assert_invariants(pool, index=None):
    """The laws that must hold after EVERY pool/index operation."""
    # conservation: each non-reserved page is exactly one of free or
    # allocated; page 0 never escapes the allocator
    assert pool.free_pages + pool.allocated_pages == pool.num_pages - 1
    assert 0 not in pool._allocated
    # an allocated page always has at least one holder (refcounts can
    # never be observed at <= 0 — the zero-crossing recycles the page)
    for p in pool._allocated:
        assert pool.refcount(p) >= 1
    if index is not None:
        # the trash page is never indexed; every indexed page is a
        # live allocation (the index itself holds a reference)
        assert 0 not in index._by_page
        for p in index._by_page:
            assert pool.refcount(p) >= 1
        assert 0 <= index.evictable_pages() <= index.pages_indexed


# --- PagePool refcount properties -------------------------------------------


def test_pagepool_randomized_refcount_invariants():
    """Random alloc/incref/free against a Counter model: the pool's
    refcounts track the model exactly and never go negative."""
    rng = random.Random(0xA11C)
    pool = PagePool(num_pages=17, page_size=PS)
    held = []  # one entry per outstanding reference
    for _ in range(2000):
        op = rng.random()
        if op < 0.4 and pool.free_pages:
            held.extend(pool.alloc(rng.randint(1, pool.free_pages)))
        elif op < 0.7 and held:
            p = rng.choice(held)
            pool.incref([p])
            held.append(p)
        elif held:
            pool.free([held.pop(rng.randrange(len(held)))])
        _assert_invariants(pool)
        model = Counter(held)
        assert set(model) == pool._allocated
        for p, c in model.items():
            assert pool.refcount(p) == c
    pool.free(held)
    assert pool.allocated_pages == 0
    assert pool.free_pages == pool.num_pages - 1


def test_pagepool_over_free_and_foreign_free_raise():
    pool = PagePool(num_pages=9, page_size=PS)
    (page,) = pool.alloc(1)
    pool.incref([page])
    pool.free([page])
    pool.free([page])  # last holder — recycles
    with pytest.raises(ValueError, match="double-free or foreign"):
        pool.free([page])
    with pytest.raises(ValueError, match="double-free or foreign"):
        pool.free([0])  # the trash page is never allocated


def test_pagepool_incref_requires_allocation():
    pool = PagePool(num_pages=9, page_size=PS)
    with pytest.raises(ValueError, match="incref of unallocated"):
        pool.incref([3])
    with pytest.raises(ValueError, match="incref of unallocated"):
        pool.incref([0])
    (page,) = pool.alloc(1)
    pool.incref([page])
    assert pool.refcount(page) == 2


def test_ensure_private_page_is_the_cow_guard():
    pool = PagePool(num_pages=9, page_size=PS)
    (page,) = pool.alloc(1)
    assert ensure_private_page(pool, page) == page
    pool.incref([page])  # now shared — writing would corrupt a peer
    with pytest.raises(ValueError, match="copy-on-write violation"):
        ensure_private_page(pool, page)
    pool.free([page])
    assert ensure_private_page(pool, page) == page
    with pytest.raises(ValueError, match="trash page"):
        ensure_private_page(pool, 0)


# --- PrefixIndex properties -------------------------------------------------


def test_prefix_cache_config_validates():
    with pytest.raises(ValueError, match="max_pages"):
        PrefixCacheConfig(max_pages=-1)
    assert PrefixCacheConfig().max_pages is None
    assert PrefixCacheConfig(max_pages=0).max_pages == 0


def test_publish_refuses_trash_page():
    pool = PagePool(num_pages=9, page_size=PS)
    index = PrefixIndex(pool, PS)
    with pytest.raises(ValueError, match="trash page 0"):
        index.publish(list(range(PS)), [0])
    assert index.pages_indexed == 0


def test_contains_is_a_pure_query():
    pool = PagePool(num_pages=9, page_size=PS)
    index = PrefixIndex(pool, PS)
    prompt = list(range(PS)) + [7]
    pages = pool.alloc(1)
    index.publish(prompt, pages)
    rc = pool.refcount(pages[0])
    assert index.contains(prompt) == PS
    assert index.contains(prompt + [1, 2]) == PS
    assert index.contains([9] * (PS + 1)) == 0
    assert pool.refcount(pages[0]) == rc  # no incref, no LRU churn


def test_lookup_never_returns_the_whole_prompt():
    """The partial-last-page-is-private law: even a prompt whose every
    page is cached keeps >= 1 tail token for private chunk prefill."""
    pool = PagePool(num_pages=9, page_size=PS)
    index = PrefixIndex(pool, PS)
    prompt = list(range(2 * PS))
    pages = pool.alloc(2)
    index.publish(prompt, pages)
    # identical prompt again: only the first page may be served
    cached, shared = index.lookup(prompt)
    assert cached == PS and len(shared) == 1
    assert cached < len(prompt)
    pool.free(shared)
    pool.free(pages)
    index.clear()
    _assert_invariants(pool, index)


def test_max_pages_trims_after_publish():
    pool = PagePool(num_pages=17, page_size=PS)
    index = PrefixIndex(pool, PS, PrefixCacheConfig(max_pages=2))
    for tag in range(4):  # four distinct single-page prefixes
        prompt = [tag] * PS + [tag]
        pages = pool.alloc(1)
        index.publish(prompt, pages)
        pool.free(pages)  # stream finishes; index ref remains
        _assert_invariants(pool, index)
    assert index.pages_indexed == 2  # LRU-trimmed to the cap
    assert index.clear() == 2
    assert pool.free_pages == pool.num_pages - 1


def test_prefix_index_randomized_sharing_invariants():
    """The main property test: a random admission/publish/finish/evict
    /clear workload over a 3-symbol alphabet (so prefixes really
    collide) holds every invariant at every step, and full teardown
    reclaims the arena exactly."""
    for seed in (1, 7, 42):
        rng = random.Random(seed)
        pool = PagePool(num_pages=33, page_size=PS)
        index = PrefixIndex(pool, PS)
        streams = []  # (prompt, pages)
        for _ in range(600):
            op = rng.random()
            if op < 0.45:
                prompt = [rng.randrange(3)
                          for _ in range(rng.randint(1, 14))]
                cached, shared = index.lookup(prompt)
                assert cached % PS == 0 and cached < len(prompt)
                assert len(shared) == cached // PS
                for p in shared:  # index + this stream hold it
                    assert pool.refcount(p) >= 2
                    with pytest.raises(ValueError):
                        ensure_private_page(pool, p)
                need = _ceil_pages(len(prompt) - cached)
                budget = pool.free_pages + index.evictable_pages()
                if need > budget:  # admission deferred: undo the hold
                    if shared:
                        pool.free(shared)
                    continue
                if need > pool.free_pages:
                    index.evict(need - pool.free_pages)
                private = pool.alloc(need)
                for p in private:  # every writable page is private
                    assert ensure_private_page(pool, p) == p
                streams.append((prompt, shared + private))
            elif op < 0.65 and streams:
                prompt, pages = rng.choice(streams)
                index.publish(prompt, pages)  # idempotent re-publish ok
            elif op < 0.9 and streams:
                prompt, pages = streams.pop(rng.randrange(len(streams)))
                if rng.random() < 0.5:
                    index.publish(prompt, pages)
                pool.free(pages)  # uniform teardown decref
            elif op < 0.97:
                index.evict(rng.randint(1, 4))
            else:
                index.clear()
            _assert_invariants(pool, index)
        for _, pages in streams:
            pool.free(pages)
        index.clear()
        assert pool.allocated_pages == 0
        assert pool.free_pages == pool.num_pages - 1


# --- seeded interleavings ---------------------------------------------------


def _make_worker(name, pool, index, lock, prompts, log):
    """One simulated admission loop: lookup under the lock, publish,
    decode for a while (other workers interleave here), then the
    uniform teardown decref. Mirrors the engine's critical sections."""

    def run():
        for prompt in prompts:
            with lock:
                cached, shared = index.lookup(prompt)
                need = _ceil_pages(len(prompt) - cached)
                if need > pool.free_pages:
                    index.evict(need - pool.free_pages)
                if need > pool.free_pages:
                    if shared:
                        pool.free(shared)
                    log.append((name, tuple(prompt), "deferred"))
                    continue
                pages = shared + pool.alloc(need)
                log.append((name, tuple(prompt), cached))
            with lock:
                index.publish(prompt, pages)
            with lock:
                pool.free(pages)

    return run


def _interleaved_run(seed):
    pool = PagePool(num_pages=17, page_size=PS)
    sched = InterleaveScheduler(seed=seed)
    lock = InstrumentedLock(sched, name="engine._lock")
    index = PrefixIndex(pool, PS)
    log = []
    shared_prefix = [9] * (2 * PS)
    for w in range(3):  # all three race on the same 2-page prefix
        prompts = [shared_prefix + [w, t] for t in range(3)]
        sched.spawn(_make_worker(f"w{w}", pool, index, lock, prompts,
                                 log), name=f"w{w}")
    sched.run()
    _assert_invariants(pool, index)
    # every stream finished: only the index holds pages now
    before = pool.allocated_pages
    assert before == index.pages_indexed
    assert index.clear() == before
    assert pool.allocated_pages == 0
    assert pool.free_pages == pool.num_pages - 1
    return log, list(sched.trace)


def test_interleaved_sharing_invariants_across_seeds():
    """Nine workers' worth of contended lookup/publish/free schedules:
    whatever the interleaving, the arena laws hold and at least one
    late-arriving stream observes the shared prefix as a cache hit."""
    for seed in (3, 11, 29, 54):
        log, _trace = _interleaved_run(seed)
        admitted = [e for e in log if e[2] != "deferred"]
        assert admitted, log
        # the prefix is 2 pages; once published, hits serve 2*PS tokens
        assert any(e[2] == 2 * PS for e in admitted), log


def test_interleaved_sharing_replays_are_bitwise():
    """Seeded determinism: the same seed reproduces the exact same
    schedule, the same hit pattern, and the same trace — a failure
    under seed S is replayable forever."""
    for seed in (3, 29):
        log_a, trace_a = _interleaved_run(seed)
        log_b, trace_b = _interleaved_run(seed)
        assert log_a == log_b
        assert trace_a == trace_b


def test_unguarded_index_access_fails_loudly():
    """The dynamic half of the _GUARDED_BY declaration: wrap the index
    map in a guarded proxy and a lockless touch raises instead of
    racing the refcount bookkeeping."""
    sched = InterleaveScheduler(seed=5)
    lock = InstrumentedLock(sched, name="engine._lock")
    pool = PagePool(num_pages=9, page_size=PS)
    index = PrefixIndex(pool, PS)
    index._by_page = guarded(index._by_page, lock,
                             "PrefixIndex._by_page")
    outcomes = []

    def bad():
        try:
            outcomes.append(("bad", index.pages_indexed))
        except UnguardedAccessError as e:
            outcomes.append(("bad", type(e).__name__))

    def good():
        with lock:
            outcomes.append(("good", index.pages_indexed))

    sched.spawn(bad, name="bad")
    sched.spawn(good, name="good")
    sched.run()
    assert ("bad", "UnguardedAccessError") in outcomes
    assert ("good", 0) in outcomes


def test_guarded_declarations_match_engine_registry():
    """The index is externally guarded by the engine lock, exactly
    like the pool — and the engine's _GUARDED registry (what the
    racecheck guarded-attrs pass keys on) says so."""
    from perceiver_tpu.serving.decode import DecodeEngine

    assert PrefixIndex._GUARDED_BY == "DecodeEngine._lock"
    assert PagePool._GUARDED_BY == "DecodeEngine._lock"
    assert DecodeEngine._GUARDED["prefix_index"] == "_lock"
    assert DecodeEngine._GUARDED["pool"] == "_lock"
