"""Paged decode attention kernel vs. pure-jax reference.

The kernel runs in Pallas interpreter mode on the CPU test backend —
the identical kernel body that compiles on TPU (ops/paged_attention.py,
docs/SERVING.md "Autoregressive decode"). Properties pinned here:

- the kernel matches masked-softmax attention over each stream's own
  page walk, for full and partial last pages;
- **placement invariance**: the same logical stream scattered across
  scrambled physical pages is BITWISE identical to the contiguous
  placement — the property that makes host-side page recycling safe;
- zero-length streams return exactly zero (not NaN);
- table entries beyond a stream's used pages are ignored (clamped,
  predicated off), so the allocator never has to sanitize tails;
- bf16 inputs survive both kernel and reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.ops.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_reference,
)


def _dense_reference(q, k, v, length):
    """Straight masked attention over one stream's dense (T, H, D)."""
    qf = q.astype(np.float32)                      # (H, Nq, D)
    kf = k[:length].astype(np.float32)             # (t, H, D)
    vf = v[:length].astype(np.float32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = np.einsum("hnd,thd->hnt", qf, kf) * scale
    w = np.exp(logits - logits.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    return np.einsum("hnt,thd->hnd", w, vf)


def _make_case(rng, *, r=4, h=2, nq=8, d=16, num_pages=32, page_size=8,
               pps=4, lengths=(0, 3, 8, 29), dtype=np.float32):
    """Build a pool with each stream's tokens on randomly chosen
    pages, plus the dense per-stream views the oracle uses."""
    q = rng.standard_normal((r, h, nq, d)).astype(dtype)
    k_pages = rng.standard_normal(
        (num_pages, page_size, h, d)).astype(dtype)
    v_pages = rng.standard_normal(
        (num_pages, page_size, h, d)).astype(dtype)
    perm = rng.permutation(np.arange(1, num_pages))
    tables = np.zeros((r, pps), np.int32)
    taken = 0
    for i in range(r):
        tables[i] = perm[taken:taken + pps]
        taken += pps
    lengths = np.asarray(lengths, np.int32)
    dense_k = np.stack([
        k_pages[tables[i]].reshape(pps * page_size, h, d)
        for i in range(r)])
    dense_v = np.stack([
        v_pages[tables[i]].reshape(pps * page_size, h, d)
        for i in range(r)])
    return q, k_pages, v_pages, tables, lengths, dense_k, dense_v


def test_kernel_matches_dense_oracle_fp32():
    rng = np.random.default_rng(0)
    q, kp, vp, tables, lengths, dk, dv = _make_case(rng)
    out = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths)))
    for i, t in enumerate(lengths):
        if t == 0:
            np.testing.assert_array_equal(out[i], 0.0)
        else:
            np.testing.assert_allclose(
                out[i], _dense_reference(q[i], dk[i], dv[i], int(t)),
                rtol=2e-5, atol=2e-5)


def test_reference_matches_dense_oracle():
    rng = np.random.default_rng(1)
    q, kp, vp, tables, lengths, dk, dv = _make_case(rng)
    out = np.asarray(paged_decode_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths)))
    for i, t in enumerate(lengths):
        if t == 0:
            np.testing.assert_array_equal(out[i], 0.0)
        else:
            np.testing.assert_allclose(
                out[i], _dense_reference(q[i], dk[i], dv[i], int(t)),
                rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_kernel_matches_reference(dtype):
    rng = np.random.default_rng(2)
    q, kp, vp, tables, lengths, _, _ = _make_case(
        rng, lengths=(5, 1, 32, 17),
        dtype=np.float32)
    args = [jnp.asarray(a).astype(dtype) for a in (q, kp, vp)]
    got = paged_decode_attention(
        *args, jnp.asarray(tables), jnp.asarray(lengths))
    want = paged_decode_attention_reference(
        *args, jnp.asarray(tables), jnp.asarray(lengths))
    assert got.dtype == want.dtype
    tol = 2e-5 if dtype == np.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=tol, atol=tol)


def test_placement_invariance_bitwise():
    """Contiguous vs scrambled physical pages: bitwise identical.

    This is the contract host-side page recycling stands on — a
    stream's numerics depend only on its LOGICAL token order, never on
    which physical pages the allocator happened to hand out."""
    rng = np.random.default_rng(3)
    r, h, nq, d = 3, 2, 8, 16
    num_pages, page_size, pps = 64, 8, 5
    lengths = np.asarray([37, 12, 40], np.int32)
    q = rng.standard_normal((r, h, nq, d)).astype(np.float32)
    tokens_k = rng.standard_normal(
        (r, pps * page_size, h, d)).astype(np.float32)
    tokens_v = rng.standard_normal(
        (r, pps * page_size, h, d)).astype(np.float32)

    def place(order):
        kp = np.asarray(
            rng.standard_normal((num_pages, page_size, h, d)),
            np.float32)  # junk in unused pages must not matter
        vp = np.asarray(
            rng.standard_normal((num_pages, page_size, h, d)),
            np.float32)
        tables = np.zeros((r, pps), np.int32)
        for i in range(r):
            pages = order[i * pps:(i + 1) * pps]
            tables[i] = pages
            for j, p in enumerate(pages):
                kp[p] = tokens_k[i, j * page_size:(j + 1) * page_size]
                vp[p] = tokens_v[i, j * page_size:(j + 1) * page_size]
        return jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables)

    contiguous = np.arange(1, 1 + r * pps)
    scrambled = np.random.default_rng(7).permutation(
        np.arange(1, num_pages))[:r * pps]
    outs = []
    for order in (contiguous, scrambled):
        kp, vp, tables = place(order)
        outs.append(np.asarray(paged_decode_attention(
            jnp.asarray(q), kp, vp, tables, jnp.asarray(lengths))))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_table_tail_entries_ignored():
    """Entries past ceil(length / page_size) may be arbitrary garbage
    (even out of range — they are clamped)."""
    rng = np.random.default_rng(4)
    q, kp, vp, tables, lengths, _, _ = _make_case(
        rng, lengths=(9, 3, 16, 1))
    junk = np.array(tables)
    for i, t in enumerate(lengths):
        used = max(1, -(-int(t) // 8))
        junk[i, used:] = 10_000 + i  # out of range on purpose
    a = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths))
    b = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(junk), jnp.asarray(lengths))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_survives_jit():
    rng = np.random.default_rng(5)
    q, kp, vp, tables, lengths, _, _ = _make_case(rng)
    f = jax.jit(paged_decode_attention)
    got = np.asarray(f(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                       jnp.asarray(tables), jnp.asarray(lengths)))
    want = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


# --- ragged mode: per-row (kv_len, query_len), mixed prefill + decode ------

from perceiver_tpu.ops.paged_attention import (  # noqa: E402
    ragged_paged_attention,
    ragged_paged_attention_reference,
)


def _dense_ragged_reference(q, k, v, kv_len, q_len, causal):
    """Per-query-row oracle: query i of a causal row attends kv
    positions < kv_len - (q_len - 1 - i); non-causal rows see the
    whole cache. Padding rows and empty windows are exact zeros."""
    h, nq, d = q.shape
    out = np.zeros((h, nq, d), np.float32)
    for i in range(nq):
        if i >= q_len:
            continue
        limit = kv_len - (q_len - 1 - i) if causal else kv_len
        if limit <= 0:
            continue
        out[:, i:i + 1, :] = _dense_reference(
            q[:, i:i + 1, :], k, v, int(limit))
    return out


@pytest.mark.parametrize("causal", [False, True])
def test_ragged_mixed_rows_match_dense_oracle(causal):
    """One call, mixed traffic: chunked-prefill rows (q_len 8 / 5 / 3)
    and decode rows (q_len 1) — the unified serving step's shape."""
    rng = np.random.default_rng(10)
    q, kp, vp, tables, kv_lens, dk, dv = _make_case(
        rng, lengths=(29, 8, 17, 1), nq=8)
    q_lens = np.asarray([8, 5, 1, 1], np.int32)
    out = np.asarray(ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(kv_lens),
        jnp.asarray(q_lens), causal=causal))
    for i in range(len(kv_lens)):
        want = _dense_ragged_reference(
            q[i], dk[i], dv[i], int(kv_lens[i]), int(q_lens[i]), causal)
        np.testing.assert_allclose(out[i], want, rtol=2e-5, atol=2e-5)
        # padding query rows are exact zeros, not just small
        np.testing.assert_array_equal(out[i][:, int(q_lens[i]):, :], 0.0)


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
@pytest.mark.parametrize("causal", [False, True])
def test_ragged_kernel_matches_reference(dtype, causal):
    rng = np.random.default_rng(11)
    q, kp, vp, tables, kv_lens, _, _ = _make_case(
        rng, lengths=(32, 7, 12, 2), nq=8)
    q_lens = jnp.asarray([8, 4, 1, 2], jnp.int32)
    args = [jnp.asarray(a).astype(dtype) for a in (q, kp, vp)]
    got = ragged_paged_attention(
        *args, jnp.asarray(tables), jnp.asarray(kv_lens), q_lens,
        causal=causal)
    want = ragged_paged_attention_reference(
        *args, jnp.asarray(tables), jnp.asarray(kv_lens), q_lens,
        causal=causal)
    assert got.dtype == want.dtype
    tol = 2e-5 if dtype == np.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=tol, atol=tol)


def test_ragged_empty_causal_windows_are_exact_zero():
    """kv_len < q_len leaves the earliest chunk queries with empty
    windows (limit <= 0): exact zeros, never NaN — NEG_INF is finite
    by design and the wrapper zeroes those rows."""
    rng = np.random.default_rng(12)
    q, kp, vp, tables, kv_lens, dk, dv = _make_case(
        rng, lengths=(2, 0, 5, 3), nq=8)
    q_lens = np.asarray([5, 3, 8, 3], np.int32)  # rows 0/1 underfull
    out = np.asarray(ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(kv_lens),
        jnp.asarray(q_lens), causal=True))
    assert np.isfinite(out).all()
    for i in range(len(kv_lens)):
        want = _dense_ragged_reference(
            q[i], dk[i], dv[i], int(kv_lens[i]), int(q_lens[i]), True)
        np.testing.assert_allclose(out[i], want, rtol=2e-5, atol=2e-5)
    # row 1 has no cache at all: everything zero
    np.testing.assert_array_equal(out[1], 0.0)


def test_decode_delegate_is_ragged_noncausal():
    """paged_decode_attention must stay a thin delegate of the ragged
    path (all query rows live, non-causal) — bitwise identical."""
    rng = np.random.default_rng(13)
    q, kp, vp, tables, lengths, _, _ = _make_case(rng)
    a = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths))
    b = ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths),
        jnp.full((q.shape[0],), q.shape[2], jnp.int32), causal=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
