"""Output adapters: classification, semantic segmentation, text logits.

Parity targets: reference ``perceiver/adapter.py:136-173``.

- ``ClassificationOutputAdapter``: ``output_shape = (num_outputs,
  num_output_channels)`` with channels defaulting to ``num_classes``;
  Linear(C_out → classes), squeezing the query axis when there is a
  single output query (torch's ``squeeze(dim=1)`` is a no-op for
  ``num_outputs > 1``; here the squeeze is static on shape).
- ``SemanticSegOutputAdapter``: the reference version constructs a
  linear layer but returns its input unchanged — a defect
  (SURVEY.md §2.6.3). This rebuild applies the linear projection, i.e.
  per-pixel class logits, which is the evident intent.
- ``TextOutputAdapter``: classification adapter with
  ``num_classes = vocab_size`` and ``num_outputs = max_seq_len`` →
  per-position vocab logits.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from perceiver_tpu.ops.linear import linear_init, linear_apply
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY


@dataclasses.dataclass(frozen=True)
class ClassificationOutputAdapter:
    num_classes: int
    num_outputs: int = 1
    num_output_channels: Optional[int] = None

    def __post_init__(self):
        if self.num_output_channels is None:
            object.__setattr__(self, "num_output_channels", self.num_classes)

    @property
    def output_shape(self) -> Tuple[int, int]:
        return (self.num_outputs, self.num_output_channels)

    def init(self, key):
        return {"linear": linear_init(key, self.num_output_channels,
                                      self.num_classes)}

    def apply(self, params, x, *, policy: Policy = DEFAULT_POLICY):
        y = linear_apply(params["linear"], x, policy=policy)
        if self.num_outputs == 1:
            y = y.squeeze(axis=1)
        return y


@dataclasses.dataclass(frozen=True)
class SemanticSegOutputAdapter(ClassificationOutputAdapter):
    """Per-query (per-pixel) class logits; see module docstring re: the
    reference's identity-forward defect."""

    def apply(self, params, x, *, policy: Policy = DEFAULT_POLICY):
        return linear_apply(params["linear"], x, policy=policy)


def TextOutputAdapter(vocab_size: int, max_seq_len: int,
                      num_output_channels: Optional[int] = None
                      ) -> ClassificationOutputAdapter:
    """Factory matching reference ``adapter.py:166-173``."""
    return ClassificationOutputAdapter(
        num_classes=vocab_size, num_outputs=max_seq_len,
        num_output_channels=num_output_channels)
