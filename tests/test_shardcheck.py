"""Self-verification of shardcheck (ISSUE 10).

Same philosophy as test_graphcheck.py: every sharding-aware pass must
demonstrably FAIL on a seeded violation, because a gate that cannot
catch its target defect certifies trees it never checked. The
collective walker is exercised on synthetic optimized-HLO lines in
every replica-group syntax XLA prints (explicit, iota, iota+transpose,
source_target_pairs); the budget/replication/per-shard passes each get
a violating input, a clean twin, and — where applicable — an
allowlist round-trip. The slow end-to-end test lowers+compiles a tiny
dp2×tp2 MLM step and drives it through ``run_graph_checks`` against a
manifest pinned from its own measurement (clean) and an empty one
(fails), proving the wiring, not just the passes.
"""

import json

import pytest

from perceiver_tpu.analysis import (
    CANONICAL_TARGETS,
    FAST_TARGETS,
    ReplicationAllow,
    SHARDED_TARGETS,
    StepTarget,
    collective_budget,
    collective_inventory,
    hlo,
    lint_source,
    load_shard_budgets,
    lower_target,
    per_shard_hbm_budget,
    replication_check,
    run_graph_checks,
    run_shard_passes,
    write_shard_budgets,
)
from perceiver_tpu.analysis.shardcheck import DEFAULT_FLOOR_BYTES
from perceiver_tpu.analysis.targets import DP2_TP2, MeshSpec

# --- synthetic optimized HLO: one op per replica-group syntax ---------------
#
# mesh (2,2) = (data, model), iota device order [[0,1],[2,3]]:
#   data-axis groups  {0,2},{1,3}   model-axis groups {0,1},{2,3}

_HLO = """\
HloModule jit_train_step

ENTRY %main.42 {
  %all-reduce.1 = f32[256,256]{1,0} all-reduce(f32[256,256]{1,0} %x), channel_id=1, replica_groups={{0,2},{1,3}}, use_global_device_ids=true, to_apply=%add
  %all-gather.2 = bf16[64,128]{1,0} all-gather(bf16[64,64]{1,0} %y), channel_id=2, replica_groups=[2,2]<=[4], dimensions={1}
  %collective-permute.3 = f32[32]{0} collective-permute(f32[32]{0} %z), channel_id=3, source_target_pairs={{0,1},{1,0},{2,3},{3,2}}
  %all-reduce.4 = f32[8]{0} all-reduce(f32[8]{0} %w), channel_id=4, replica_groups={{0},{1},{2},{3}}, to_apply=%add
  %all-reduce-done.5 = f32[8]{0} all-reduce-done(f32[8]{0} %w2)
}
"""

_AR_BYTES = 256 * 256 * 4          # data axis
_AG_BYTES = 64 * 128 * 2           # model axis (result shape)
_CP_BYTES = 32 * 4                 # model axis (permute ring)


def _budget_entry(collectives, per_shard, mesh="data2_model2",
                  headroom=1.10):
    return {
        "mesh": mesh,
        "collectives": {
            axis: {"pinned_bytes": b, "budget_bytes": int(b * headroom)}
            for axis, b in collectives.items()},
        "per_shard": {"pinned_bytes": per_shard,
                      "budget_bytes": int(per_shard * headroom)},
    }


# --- collective walker ------------------------------------------------------


def test_iter_collectives_parses_every_group_syntax():
    cols = list(hlo.iter_collectives(_HLO))
    # the -done line must NOT parse as a collective
    assert [c["op"] for c in cols] == [
        "all-reduce", "all-gather", "collective-permute", "all-reduce"]
    assert cols[0]["bytes"] == _AR_BYTES
    assert cols[0]["groups"] == [(0, 2), (1, 3)]
    assert cols[1]["bytes"] == _AG_BYTES
    assert cols[1]["groups"] == [(0, 1), (2, 3)]
    assert cols[2]["groups"] == [(0, 1), (2, 3)]


def test_iota_transpose_groups():
    text = ("  %all-gather.9 = f32[16]{0} all-gather(f32[8]{0} %a), "
            "replica_groups=[2,2]<=[2,2]T(1,0), dimensions={0}\n")
    (col,) = hlo.iter_collectives(text)
    # iota(4).reshape(2,2).T.flatten() = [0,2,1,3] → groups {0,2},{1,3}
    assert col["groups"] == [(0, 2), (1, 3)]


def test_attribute_axis_on_dp2_tp2():
    shape, names = [2, 2], ["data", "model"]
    assert hlo.attribute_axis([(0, 2), (1, 3)], shape, names) == "data"
    assert hlo.attribute_axis([(0, 1), (2, 3)], shape, names) == "model"
    assert hlo.attribute_axis([(0, 1, 2, 3)], shape, names) \
        == "data+model"
    assert hlo.attribute_axis([(0, 3)], shape, names) == "other"


def test_collective_inventory_attributes_and_skips_singletons():
    inv = collective_inventory(_HLO, DP2_TP2)
    assert inv["collectives"] == {
        "data": _AR_BYTES, "model": _AG_BYTES + _CP_BYTES}
    assert inv["ops"]["data"] == {"all-reduce": 1}
    assert inv["ops"]["model"] == {"all-gather": 1,
                                   "collective-permute": 1}
    # the singleton-group all-reduce.4 moved no bytes and is absent


def test_sharding_factor():
    assert hlo.sharding_factor(None) == 1
    assert hlo.sharding_factor("{replicated}") == 1
    assert hlo.sharding_factor("{devices=[2,2]<=[4]}") == 4
    assert hlo.sharding_factor(
        "{devices=[2,1,2]<=[4] last_tile_dim_replicate}") == 2


# --- collective_budget ------------------------------------------------------


def test_collective_budget_clean_within_budget():
    budgets = {"t": _budget_entry(
        {"data": _AR_BYTES, "model": _AG_BYTES + _CP_BYTES},
        per_shard=1)}
    vs, inv = collective_budget(_HLO, DP2_TP2, where="t",
                                budgets=budgets)
    assert not vs
    assert inv["collectives"]["data"] == _AR_BYTES


def test_collective_budget_fails_over_budget():
    budgets = {"t": _budget_entry(
        {"data": _AR_BYTES // 100, "model": _AG_BYTES + _CP_BYTES},
        per_shard=1)}
    vs, _ = collective_budget(_HLO, DP2_TP2, where="t", budgets=budgets)
    assert len(vs) == 1 and vs[0].check == "collective_budget"
    assert "'data'" in vs[0].message and "exceeds" in vs[0].message


def test_collective_budget_fails_on_unbudgeted_axis():
    budgets = {"t": _budget_entry({"data": _AR_BYTES}, per_shard=1)}
    vs, _ = collective_budget(_HLO, DP2_TP2, where="t", budgets=budgets)
    assert len(vs) == 1
    assert "unbudgeted mesh axis 'model'" in vs[0].message


def test_collective_budget_fails_without_manifest_entry():
    vs, _ = collective_budget(_HLO, DP2_TP2, where="t", budgets={})
    assert len(vs) == 1 and "no collective budget" in vs[0].message


def test_collective_budget_fails_without_compiled_text():
    vs, inv = collective_budget(None, DP2_TP2, where="t", budgets={})
    assert len(vs) == 1 and "no compiled HLO" in vs[0].message
    assert inv == {}


def test_collective_budget_fails_on_mesh_mismatch():
    budgets = {"t": _budget_entry(
        {"data": _AR_BYTES, "model": _AG_BYTES + _CP_BYTES},
        per_shard=1, mesh="data4_model1")}
    vs, _ = collective_budget(_HLO, DP2_TP2, where="t", budgets=budgets)
    assert len(vs) == 1 and "data4_model1" in vs[0].message


# --- replication_check ------------------------------------------------------

# 8192x64xf32 = 2 MB (above the 1 MiB floor); 256x64xf32 = 64 KB below
_REPLICATED_MAIN = (
    'module @jit_step {\n'
    '  func.func public @main('
    '%arg0: tensor<8192x64xf32> {mhlo.sharding = "{replicated}"}, '
    '%arg1: tensor<8192x64xf32> {mhlo.sharding = '
    '"{devices=[2,2]<=[4]}"}, '
    '%arg2: tensor<256x64xf32> {mhlo.sharding = "{replicated}"}) '
    '-> (tensor<8192x64xf32> {mhlo.sharding = "{replicated}"}) {\n'
    '  }\n'
    '}\n')


def test_replication_check_fails_on_replicated_large_tensor():
    vs = replication_check(_REPLICATED_MAIN, where="t")
    # %arg0 and the result replicate 2 MB; %arg1 is sharded, %arg2 is
    # under the floor
    assert len(vs) == 2
    assert all(v.check == "replication_check" for v in vs)
    assert "arg tensor<8192x64xf32>" in vs[0].message
    assert "result tensor<8192x64xf32>" in vs[1].message


def test_replication_check_allowlist_roundtrip():
    allow = (ReplicationAllow(type="8192x64xf32", max_count=2,
                              reason="read-only table, by design"),)
    assert not replication_check(_REPLICATED_MAIN, where="t",
                                 allowlist=allow)
    # max_count is a budget, not a blanket: one allowance covers one
    # tensor, the second replication still fails
    tight = (ReplicationAllow(type="8192x64xf32", max_count=1,
                              reason="only the arg"),)
    vs = replication_check(_REPLICATED_MAIN, where="t", allowlist=tight)
    assert len(vs) == 1


def test_replication_check_floor_excludes_small_tensors():
    # with the floor dropped, the 64 KB %arg2 is caught too
    vs = replication_check(_REPLICATED_MAIN, where="t", floor_bytes=1)
    assert len(vs) == 3


def test_replication_check_catches_midgraph_reshard():
    text = _REPLICATED_MAIN.replace(
        "  }\n",
        '    %2 = stablehlo.custom_call @Sharding(%1) '
        '{mhlo.sharding = "{replicated}"} : '
        '(tensor<512x1024xf32>) -> tensor<512x1024xf32>\n  }\n')
    allow = (ReplicationAllow(type="8192x64xf32", max_count=2,
                              reason="boundary tensors excused"),)
    vs = replication_check(text, where="t", allowlist=allow)
    assert len(vs) == 1
    assert "mid-graph @Sharding tensor<512x1024xf32>" in vs[0].message


# --- per_shard_hbm_budget ---------------------------------------------------


def test_per_shard_budget_clean_and_over():
    budgets = {"t": _budget_entry({}, per_shard=1_000_000)}
    assert not per_shard_hbm_budget(4_000_000, DP2_TP2, where="t",
                                    budgets=budgets)
    vs = per_shard_hbm_budget(8_000_000, DP2_TP2, where="t",
                              budgets=budgets)
    assert len(vs) == 1 and vs[0].check == "per_shard_hbm_budget"
    assert "exceeds" in vs[0].message


def test_per_shard_budget_fails_without_pin_or_cost():
    vs = per_shard_hbm_budget(1.0, DP2_TP2, where="t", budgets={})
    assert len(vs) == 1 and "no per-shard byte budget" in vs[0].message
    budgets = {"t": _budget_entry({}, per_shard=1)}
    vs = per_shard_hbm_budget(None, DP2_TP2, where="t", budgets=budgets)
    assert len(vs) == 1 and "no cost analysis" in vs[0].message


# --- manifest round-trip ----------------------------------------------------


def test_write_load_shard_budgets_roundtrip(tmp_path):
    path = str(tmp_path / "shard_budgets.json")
    measured = {"t": {"mesh": "data2_model2",
                      "collectives": {"data": 1000, "model": 500},
                      "ops": {"data": {"all-reduce": 3}},
                      "per_shard": 2_000_000}}
    write_shard_budgets(measured, path=path, note="test")
    loaded = load_shard_budgets(path)
    entry = loaded["t"]
    assert entry["mesh"] == "data2_model2"
    assert entry["collectives"]["data"] == {
        "pinned_bytes": 1000, "budget_bytes": 1100}
    assert entry["per_shard"]["budget_bytes"] == 2_200_000
    assert entry["ops"] == {"data": {"all-reduce": 3}}
    # keep= copies existing pins through untouched (--pin-missing-shard)
    write_shard_budgets(
        {"u": {"mesh": "data2_model2", "collectives": {},
               "per_shard": 1}},
        path=path, note="test2", keep=loaded)
    again = load_shard_budgets(path)
    assert set(again) == {"t", "u"}
    assert again["t"] == entry
    # a deleted/corrupt manifest reads as empty, never as "clean"
    with open(path, "w") as f:
        f.write("not json")
    assert load_shard_budgets(path) == {}


# --- unsharded-pjit lint rule -----------------------------------------------

_UNSHARDED_SRC = '''
import jax
from functools import partial

@jax.jit
def bare(x):
    return x

@partial(jax.jit, donate_argnums=(0,))
def via_partial(x):
    return x

half = jax.jit(lambda x: x, in_shardings=None)
'''

_SHARDED_SRC = '''
import jax
from functools import partial

@partial(jax.jit, in_shardings=None, out_shardings=None,
         donate_argnums=(0,))
def step(x):
    return x

also = jax.jit(lambda x: x, in_shardings=None, out_shardings=None)
'''


def _pjit_violations(src, path):
    return [v for v in lint_source(src, path)
            if v.check == "unsharded-pjit"]


def test_unsharded_pjit_flags_all_three_forms():
    vs = _pjit_violations(_UNSHARDED_SRC,
                          "perceiver_tpu/parallel/fake.py")
    assert len(vs) == 3
    # the half-annotated call reports only what is missing
    assert any("out_shardings" in v.message
               and "in_shardings" not in v.message for v in vs)


def test_unsharded_pjit_scoped_to_spmd_modules():
    assert _pjit_violations(_UNSHARDED_SRC,
                            "perceiver_tpu/training/spmd.py")
    # same source outside the SPMD modules: propagation is the norm
    assert not _pjit_violations(_UNSHARDED_SRC,
                                "perceiver_tpu/models/fake.py")


def test_unsharded_pjit_clean_on_explicit_shardings():
    assert not _pjit_violations(_SHARDED_SRC,
                                "perceiver_tpu/parallel/fake.py")


def test_spmd_modules_lint_clean():
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("perceiver_tpu/training/spmd.py",
                "perceiver_tpu/parallel/sharding.py",
                "perceiver_tpu/parallel/mesh.py"):
        with open(os.path.join(root, rel)) as f:
            assert not _pjit_violations(f.read(), rel), rel


# --- registration + MeshSpec ------------------------------------------------


def test_sharded_targets_registered_and_pinned():
    names = {t.name for t in SHARDED_TARGETS}
    assert len(names) >= 2
    assert {t.kind for t in SHARDED_TARGETS} == {"train", "serve",
                                                 "decode"}
    # ride the default sweep (check.py --all), but not the fast tier —
    # mesh targets pay an XLA compile the warm-cache contract excludes
    assert names <= {t.name for t in CANONICAL_TARGETS}
    assert not names & {t.name for t in FAST_TARGETS}
    assert all(t.mesh is not None for t in SHARDED_TARGETS)
    # the shipped manifest pins every sharded target on its mesh
    budgets = load_shard_budgets()
    for t in SHARDED_TARGETS:
        assert t.name in budgets, t.name
        assert budgets[t.name]["mesh"] == t.mesh.descriptor
        assert budgets[t.name]["collectives"], t.name


def test_mesh_spec_properties_and_build():
    mesh = MeshSpec(axes=(("data", 2), ("model", 2)))
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape == (2, 2)
    assert mesh.n_devices == 4
    assert mesh.descriptor == "data2_model2"
    built = mesh.build()
    assert built.devices.shape == (2, 2)
    with pytest.raises(ValueError, match="xla_force_host_platform"):
        MeshSpec(axes=(("data", 64),)).build()


# --- end-to-end: a tiny sharded step through the real pipeline --------------


def _tiny_spmd_target():
    from perceiver_tpu.analysis.targets import (
        _MLM_OVERFLOW_CALLBACK,
        _build_mlm,
    )

    def build():
        return _build_mlm(batch=8, channels=16, seq_len=32, vocab=128,
                          loss_impl="packed")

    return StepTarget(name="tiny_mlm_spmd_dp2_tp2", build=build,
                      mesh=DP2_TP2,
                      transfer_allow=_MLM_OVERFLOW_CALLBACK)


def test_tiny_sharded_target_end_to_end(monkeypatch, tmp_path):
    """Lower+compile a tiny dp2×tp2 MLM train step, pin a manifest
    from its own measurement, and drive it through run_graph_checks:
    clean against its pins, failing against an empty manifest — the
    wiring proof, not just the passes. Slow-marked (one XLA compile)."""
    import perceiver_tpu.analysis.passes as passes_mod
    from perceiver_tpu.analysis import shardcheck

    target = _tiny_spmd_target()
    lowered = lower_target(target)
    assert lowered.compiled_text, "mesh target must carry compiled HLO"
    assert lowered.bytes_accessed

    inv = collective_inventory(lowered.compiled_text, target.mesh)
    # GSPMD must have inserted real collectives (at minimum the data-
    # axis gradient all-reduce) — an empty inventory means the step
    # silently stopped being SPMD
    assert inv["collectives"]

    path = str(tmp_path / "shard_budgets.json")
    write_shard_budgets({target.name: {
        "mesh": target.mesh.descriptor,
        "collectives": inv["collectives"],
        "ops": inv["ops"],
        "per_shard": lowered.bytes_accessed / target.mesh.n_devices,
    }}, path=path, note="test")
    budgets = load_shard_budgets(path)

    vs, _ = run_shard_passes(lowered, budgets=budgets)
    assert not vs, vs

    # seeded failures: an empty manifest and a zeroed budget both trip
    vs, _ = run_shard_passes(lowered, budgets={})
    assert {v.check for v in vs} == {"collective_budget",
                                    "per_shard_hbm_budget"}
    zeroed = json.loads(json.dumps(budgets))
    for axis in zeroed[target.name]["collectives"].values():
        axis["budget_bytes"] = 0
    zeroed[target.name]["per_shard"]["budget_bytes"] = 0
    vs, _ = run_shard_passes(lowered, budgets=zeroed)
    assert any(v.check == "collective_budget" and "exceeds"
               in v.message for v in vs)
    assert any(v.check == "per_shard_hbm_budget" for v in vs)

    # dropping the floor exposes the replicated small buffers (adamw
    # step counts etc.) the default floor rightly ignores
    assert replication_check(lowered.text, where=target.name,
                             floor_bytes=1)
    assert not replication_check(lowered.text, where=target.name,
                                 floor_bytes=DEFAULT_FLOOR_BYTES)

    # and the same lowering through the real driver: the three shard
    # passes run and gate
    monkeypatch.setattr(passes_mod, "lower_target",
                        lambda t, cache=None, **kw: lowered)
    monkeypatch.setattr(shardcheck, "load_shard_budgets",
                        lambda p=None: budgets)
    monkeypatch.setattr(
        passes_mod, "load_hbm_budgets",
        lambda p=None: {target.name: {
            "pinned_bytes": lowered.bytes_accessed,
            "budget_bytes": lowered.bytes_accessed * 1.05}})
    report = run_graph_checks([target], recompile=False)
    assert {"collective_budget", "replication_check",
            "per_shard_hbm_budget"} <= set(report.checks_run)
    assert report.ok, report.format()
    monkeypatch.setattr(shardcheck, "load_shard_budgets",
                        lambda p=None: {})
    assert not run_graph_checks([target], recompile=False).ok


# --- end-to-end: a tiny sharded DECODE step (ISSUE 14) ----------------------


def _tiny_decode_spmd_target(spec_k=0):
    import jax.numpy as jnp
    import numpy as np

    def build():
        from perceiver_tpu.serving.decode import DecodeGeometry
        from perceiver_tpu.tasks import MaskedLanguageModelTask

        # vocab 128 divides evenly over the model axis (tp2), streams
        # divide over data (dp2) — same divisibility rules as the
        # canonical decode_mixed_mlm_spmd target, at compile-cheap
        # shapes; mixed qlens exercise the unified prefill+decode step
        # (with spec_k, row 1 carries a k+1-lane verify window)
        task = MaskedLanguageModelTask(
            vocab_size=128, max_seq_len=32, num_latents=4,
            num_latent_channels=16, num_encoder_layers=2,
            num_encoder_self_attention_layers_per_block=1)
        rng = np.random.default_rng(0)
        return task, {
            "geometry": DecodeGeometry(max_streams=4, num_pages=9,
                                       page_size=4, max_seq_len=32,
                                       max_chunk=4, spec_k=spec_k),
            "tokens": jnp.asarray(rng.integers(3, 128, (4, 4)),
                                  jnp.int32),
            "qlens": jnp.asarray(
                [4, 1 + spec_k, 2, 1], jnp.int32),
            "attn_impl": "reference",
        }

    name = ("tiny_decode_spmd_dp2_tp2" if not spec_k
            else f"tiny_spec_decode_spmd_k{spec_k}_dp2_tp2")
    return StepTarget(name=name, build=build,
                      kind="decode", mesh=DP2_TP2)


def test_tiny_sharded_decode_target_end_to_end(tmp_path):
    """Lower+compile a tiny dp2×tp2 decode step, pin a manifest from
    its own measurement, and run the shard passes: clean against its
    pins, tripping against an emptied or zeroed manifest — the
    seeded-violation proof for the decode shard pin. The carry stays
    fully donated under explicit shardings (per-shard buffers alias in
    place), and the sub-floor KV pools may replicate freely."""
    from perceiver_tpu.analysis import donation_check

    target = _tiny_decode_spmd_target()
    lowered = lower_target(target)
    assert lowered.compiled_text, "mesh target must carry compiled HLO"
    assert lowered.expected_donated == 6  # k1 v1 kn vn lengths tables
    assert not donation_check(lowered.text, where=target.name,
                              expected_donated=lowered.expected_donated)
    # replicated pools sit below the 1 MiB floor by design
    assert not replication_check(lowered.text, where=target.name,
                                 floor_bytes=DEFAULT_FLOOR_BYTES)

    inv = collective_inventory(lowered.compiled_text, target.mesh)
    assert inv["collectives"], \
        "GSPMD inserted no collectives — the step stopped being SPMD"

    path = str(tmp_path / "shard_budgets.json")
    write_shard_budgets({target.name: {
        "mesh": target.mesh.descriptor,
        "collectives": inv["collectives"],
        "ops": inv["ops"],
        "per_shard": lowered.bytes_accessed / target.mesh.n_devices,
    }}, path=path, note="test")
    budgets = load_shard_budgets(path)

    vs, _ = run_shard_passes(lowered, budgets=budgets)
    assert not vs, vs
    # seeded failures: missing pin and zeroed budgets both trip
    vs, _ = run_shard_passes(lowered, budgets={})
    assert {v.check for v in vs} == {"collective_budget",
                                    "per_shard_hbm_budget"}
    zeroed = json.loads(json.dumps(budgets))
    for axis in zeroed[target.name]["collectives"].values():
        axis["budget_bytes"] = 0
    zeroed[target.name]["per_shard"]["budget_bytes"] = 0
    vs, _ = run_shard_passes(lowered, budgets=zeroed)
    assert any(v.check == "collective_budget" and "exceeds"
               in v.message for v in vs)
    assert any(v.check == "per_shard_hbm_budget" for v in vs)


# --- end-to-end: a tiny sharded SPECULATIVE decode step (ISSUE 19) ----------


def test_tiny_sharded_spec_decode_target_end_to_end(tmp_path):
    """The speculative verify step under dp2×tp2: window tiling folds
    the k+1 lanes into the kernel row axis, so GSPMD partitions the
    SAME program shape as plain decode — the carry stays fully donated
    (one paged cache per shard), collectives still appear, and its pin
    round-trips through shard_budgets with seeded violations tripping
    on an emptied or zeroed manifest."""
    from perceiver_tpu.analysis import donation_check

    target = _tiny_decode_spmd_target(spec_k=2)
    lowered = lower_target(target)
    assert lowered.compiled_text, "mesh target must carry compiled HLO"
    assert lowered.expected_donated == 6  # k1 v1 kn vn lengths tables
    assert not donation_check(lowered.text, where=target.name,
                              expected_donated=lowered.expected_donated)
    assert not replication_check(lowered.text, where=target.name,
                                 floor_bytes=DEFAULT_FLOOR_BYTES)

    inv = collective_inventory(lowered.compiled_text, target.mesh)
    assert inv["collectives"], \
        "GSPMD inserted no collectives — the step stopped being SPMD"

    path = str(tmp_path / "shard_budgets.json")
    write_shard_budgets({target.name: {
        "mesh": target.mesh.descriptor,
        "collectives": inv["collectives"],
        "ops": inv["ops"],
        "per_shard": lowered.bytes_accessed / target.mesh.n_devices,
    }}, path=path, note="test")
    budgets = load_shard_budgets(path)

    vs, _ = run_shard_passes(lowered, budgets=budgets)
    assert not vs, vs
    vs, _ = run_shard_passes(lowered, budgets={})
    assert {v.check for v in vs} == {"collective_budget",
                                    "per_shard_hbm_budget"}
    zeroed = json.loads(json.dumps(budgets))
    for axis in zeroed[target.name]["collectives"].values():
        axis["budget_bytes"] = 0
    zeroed[target.name]["per_shard"]["budget_bytes"] = 0
    vs, _ = run_shard_passes(lowered, budgets=zeroed)
    assert any(v.check == "collective_budget" and "exceeds"
               in v.message for v in vs)
    assert any(v.check == "per_shard_hbm_budget" for v in vs)
