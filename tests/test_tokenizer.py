"""Tokenizer tests, incl. parity with the shipped HF tokenizer JSON."""

import json
import os

import pytest

from perceiver_tpu.tokenizer import (
    PAD_TOKEN_ID,
    SPECIAL_TOKENS,
    WordPieceTokenizer,
    create_tokenizer,
    train_tokenizer,
)
from perceiver_tpu.tokenizer.wordpiece import Replace

SHIPPED = "/root/reference/.cache/imdb-tokenizer-10003.json"


def test_special_token_ids():
    # reference tokenizer.py:10-19
    from perceiver_tpu.tokenizer import (PAD_TOKEN, UNK_TOKEN, MASK_TOKEN,
                                         UNK_TOKEN_ID, MASK_TOKEN_ID)
    assert (PAD_TOKEN, PAD_TOKEN_ID) == ("[PAD]", 0)
    assert (UNK_TOKEN, UNK_TOKEN_ID) == ("[UNK]", 1)
    assert (MASK_TOKEN, MASK_TOKEN_ID) == ("[MASK]", 2)
    assert SPECIAL_TOKENS == ["[PAD]", "[UNK]", "[MASK]"]


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
class TestShippedTokenizerParity:
    def setup_method(self):
        self.tok = WordPieceTokenizer.from_file(SHIPPED)

    def test_loads_vocab(self):
        assert self.tok.get_vocab_size() == 10003
        assert self.tok.token_to_id("[PAD]") == 0
        assert self.tok.token_to_id("[UNK]") == 1
        assert self.tok.token_to_id("[MASK]") == 2

    def test_encode_known_words(self):
        enc = self.tok.encode("This is a great movie!")
        assert all(i != 1 for i in enc.ids)  # no UNK for common words
        assert self.tok.decode(enc.ids) == "this is a great movie!"

    def test_normalizer_chain_replace_br(self):
        # IMDB passes Replace('<br />', ' ') (data/imdb.py:101)
        enc1 = self.tok.encode("good<br />movie")
        enc2 = self.tok.encode("good movie")
        assert enc1.ids == enc2.ids

    def test_normalizer_accents_and_case(self):
        enc1 = self.tok.encode("Café CRÈME")
        enc2 = self.tok.encode("cafe creme")
        assert enc1.ids == enc2.ids

    def test_wordpiece_continuation(self):
        # unusual word must split into ## pieces, not UNK
        enc = self.tok.encode("unbelievableness")
        assert len(enc.tokens) > 1
        assert any(t.startswith("##") for t in enc.tokens)
        assert "".join(t.removeprefix("##") for t in enc.tokens) \
            == "unbelievableness"

    def test_padding_and_truncation(self):
        self.tok.enable_padding(pad_id=0, pad_token="[PAD]")
        self.tok.enable_truncation(8)
        encs = self.tok.encode_batch(["a very long sentence that truncates "
                                      "beyond eight tokens certainly",
                                      "short"])
        assert len(encs[0].ids) == 8 and len(encs[1].ids) == 8
        assert encs[1].ids[-1] == 0
        self.tok.no_padding()
        self.tok.no_truncation()

    def test_save_load_roundtrip(self, tmp_path):
        p = str(tmp_path / "tok.json")
        self.tok.save(p)
        tok2 = WordPieceTokenizer.from_file(p)
        assert tok2.get_vocab_size() == 10003
        s = "An absolutely wonderful film <br /> with great acting."
        assert tok2.encode(s).ids == self.tok.encode(s).ids

    def test_json_model_section_matches_shipped(self, tmp_path):
        p = str(tmp_path / "tok.json")
        self.tok.save(p)
        with open(SHIPPED) as f:
            ref = json.load(f)
        with open(p) as f:
            ours = json.load(f)
        assert ours["model"] == ref["model"]
        assert ours["normalizer"] == ref["normalizer"]
        assert ours["pre_tokenizer"] == ref["pre_tokenizer"]
        assert ours["added_tokens"] == ref["added_tokens"]


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
def test_parity_with_hf_tokenizers_if_available():
    """If the Rust HF library is importable, byte-level id parity."""
    hf = pytest.importorskip("tokenizers")
    ref = hf.Tokenizer.from_file(SHIPPED)
    ours = WordPieceTokenizer.from_file(SHIPPED)
    samples = [
        "This movie was absolutely fantastic! I loved every minute.",
        "Worst. Film. Ever. <br /><br />Don't waste your time...",
        "Café touché — naïve résumé's crème brûlée!?",
        "supercalifragilisticexpialidocious antidisestablishmentarianism",
        "numbers 123 456,789 and $9.99 (50% off)",
    ]
    for s in samples:
        ids = ref.encode(s).ids
        assert ours.encode(s).ids == ids, s
        assert ours.decode(ids) == ref.decode(ids), s


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
def test_special_tokens_matched_on_raw_text():
    """'[MASK]' in a raw string must map to id 2, surviving the
    lowercasing normalizer (HF added_tokens semantics; the reference's
    predict_masked_samples path depends on it, utils.py:27)."""
    tok = WordPieceTokenizer.from_file(SHIPPED)
    enc = tok.encode("I watched this [MASK] yesterday")
    assert 2 in enc.ids
    assert "[MASK]" in enc.tokens
    enc2 = tok.encode("[MASK][MASK] double")
    assert enc2.ids[:2] == [2, 2]


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
def test_native_encode_matches_python_engine():
    """The C++ core and the pure-Python engine must agree id-for-id."""
    tok_native = WordPieceTokenizer.from_file(SHIPPED)
    tok_py = WordPieceTokenizer.from_file(SHIPPED)
    tok_py._native_failed = True  # pin the Python path
    samples = [
        "An absolutely wonderful film with great acting.",
        "Café touché — naïve résumé!? [MASK] unbelievableness",
        "x" * 150,  # exceeds max_input_chars_per_word → [UNK]
        "edge-case:semi;colons and CJK 電影 characters",
    ]
    for s in samples:
        assert tok_native.encode(s).ids == tok_py.encode(s).ids, s
    if tok_native._native is None:
        pytest.skip("native library unavailable (g++ missing?)")


def test_native_trainer_matches_python_trainer():
    from perceiver_tpu.tokenizer.wordpiece import WordPieceTrainer
    try:
        from perceiver_tpu.tokenizer.native import native_train
    except (ImportError, OSError):
        pytest.skip("native library unavailable")
    corpus = ["the quick brown fox jumps over the lazy dog",
              "the lazy dog sleeps deeply",
              "quick quick fox runs far"] * 7
    tok = create_tokenizer()
    trainer = WordPieceTrainer(vocab_size=90)
    v_native = native_train(tok, corpus, 90,
                            list(trainer.special_tokens), 0)
    v_py = trainer._train_py(tok, corpus)
    assert v_native == v_py


def test_trainer_learns_vocab_and_roundtrips():
    corpus = ["the quick brown fox jumps over the lazy dog",
              "the lazy dog sleeps", "quick quick fox"] * 5
    tok = create_tokenizer()
    train_tokenizer(tok, corpus, vocab_size=60)
    assert tok.get_vocab_size() <= 60
    assert tok.token_to_id("[PAD]") == 0
    enc = tok.encode("the quick fox")
    assert 1 not in enc.ids  # fully covered by learned vocab
    assert tok.decode(enc.ids) == "the quick fox"


def test_trainer_with_replace_normalizer():
    corpus = ["hello<br />world"] * 3
    tok = create_tokenizer(Replace("<br />", " "))
    train_tokenizer(tok, corpus, vocab_size=40)
    enc = tok.encode("hello<br />world")
    assert tok.decode(enc.ids) == "hello world"


def test_trained_json_loads_in_hf_tokenizers(tmp_path):
    """Byte-compatibility in the hard direction: a tokenizer *we
    trained and saved* must load in the HF/Rust library and encode
    identically (so checkpoints/tokenizers made here are portable to
    reference-stack users)."""
    rust = pytest.importorskip("tokenizers")
    corpus = ["the quick brown fox jumps over the lazy dog",
              "Café naïve RÉSUMÉ!", "the lazy dog sleeps deeply"] * 5
    tok = create_tokenizer(Replace("<br />", " "))
    train_tokenizer(tok, corpus, vocab_size=120)
    path = str(tmp_path / "trained.json")
    tok.save(path)
    theirs = rust.Tokenizer.from_file(path)
    for s in ["the quick fox", "Café<br />dog!", "[MASK] the dog",
              "unseen wordpieces zzz"]:
        assert theirs.encode(s).ids == tok.encode(s).ids, s
        assert theirs.decode(tok.encode(s).ids) == tok.decode(
            tok.encode(s).ids), s


def test_trainer_parity_with_hf_wordpiece_trainer():
    """Train OUR trainer and HF's WordPieceTrainer on the same fixed
    corpus and bound the divergence (VERDICT r1 missing #3).

    HF's WordPieceTrainer wraps BpeTrainer (count-scored merges); our
    trainer implements the same algorithm, but HF breaks score ties
    using its internal hashmap iteration order, which is not
    reproducible from outside. So exact vocab identity is not
    achievable in general; this test quantifies and bounds:
    - vocab-set Jaccard similarity >= 0.75, and
    - identical token sequences on every corpus document (functional
      equivalence where it matters: the encodings that feed training).
    """
    hf = pytest.importorskip("tokenizers")
    from tokenizers.models import WordPiece as HFWordPiece
    from tokenizers.normalizers import (NFD as HFNFD,
                                        Lowercase as HFLower,
                                        Sequence as HFSeq,
                                        StripAccents as HFStrip)
    from tokenizers.pre_tokenizers import Whitespace as HFWhitespace
    from tokenizers.trainers import WordPieceTrainer as HFTrainer

    from perceiver_tpu.data.imdb import _synthetic_reviews

    texts, _ = _synthetic_reviews(2000, 3)
    vocab_size = 400

    theirs = hf.Tokenizer(HFWordPiece(unk_token="[UNK]"))
    theirs.normalizer = HFSeq([HFNFD(), HFLower(), HFStrip()])
    theirs.pre_tokenizer = HFWhitespace()
    theirs.train_from_iterator(
        texts, HFTrainer(vocab_size=vocab_size,
                         special_tokens=list(SPECIAL_TOKENS)))

    ours = create_tokenizer()
    train_tokenizer(ours, texts, vocab_size=vocab_size)

    hf_vocab = set(theirs.get_vocab())
    my_vocab = set(ours.to_json()["model"]["vocab"])
    assert len(hf_vocab) == len(my_vocab)  # both saturate identically
    jaccard = len(hf_vocab & my_vocab) / len(hf_vocab | my_vocab)
    assert jaccard >= 0.75, f"vocab Jaccard {jaccard:.3f}"

    for t in texts[:200]:
        hf_toks = [theirs.id_to_token(i) for i in theirs.encode(t).ids]
        my_toks = [ours.id_to_token(i) for i in ours.encode(t).ids]
        assert hf_toks == my_toks, t


@pytest.fixture(scope="module")
def batch_tok_path(tmp_path_factory):
    """Tokenizer JSON for the batch-encode tests: the shipped IMDB
    artifact when present, otherwise a tokenizer trained once on the
    synthetic review corpus and cached for the module. The batch tests
    assert ``encode_batch_padded`` parity against per-doc ``encode``
    on the SAME tokenizer — which vocab that is doesn't matter, and
    the serving path (which batch-encodes on the request thread pool,
    ``serving/api.py``) must hold this parity on trained-from-scratch
    tokenizers too."""
    if os.path.exists(SHIPPED):
        return SHIPPED
    from perceiver_tpu.data.imdb import _synthetic_reviews

    texts, _ = _synthetic_reviews(600, 0)
    tok = create_tokenizer(Replace("<br />", " "))
    train_tokenizer(tok, texts, vocab_size=300)
    path = str(tmp_path_factory.mktemp("tok") / "batch-tok.json")
    tok.save(path)
    return path


class TestBatchPaddedEncode:
    """encode_batch_padded: native threaded path vs per-doc encode."""

    TEXTS = [
        "This movie was [MASK] and I loved it!",
        "Cafe au lait, naive fiancee — clichéd résumé...",
        "",
        "UPPER lower MiXeD 123 #@!  " * 30,  # long doc: truncation
        "[MASK][MASK] double mask, and [UNK] literal",
    ]

    def _reference_rows(self, tok, max_len):
        import numpy as np
        rows = np.zeros((len(self.TEXTS), max_len), np.int32)
        lens = []
        for i, t in enumerate(self.TEXTS):
            ids = tok.encode(t).ids[:max_len]
            rows[i, :len(ids)] = ids
            lens.append(len(ids))
        return rows, lens

    def test_matches_per_doc_encode(self, batch_tok_path):
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        tok.no_truncation()
        max_len = 64
        ids, lengths = tok.encode_batch_padded(self.TEXTS, max_len)
        ref, ref_lens = self._reference_rows(tok, max_len)
        np.testing.assert_array_equal(lengths, ref_lens)
        for i, n in enumerate(ref_lens):
            np.testing.assert_array_equal(ids[i, :n], ref[i, :n])
            assert (ids[i, n:] == 0).all()  # PAD id 0 past length

    def test_python_fallback_identical(self, batch_tok_path):
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        native_ids, native_lens = tok.encode_batch_padded(self.TEXTS, 48)
        tok._native_failed = True  # force the pure-Python path
        py_ids, py_lens = tok.encode_batch_padded(self.TEXTS, 48)
        np.testing.assert_array_equal(native_ids, py_ids)
        np.testing.assert_array_equal(native_lens, py_lens)

    def test_many_docs_many_threads(self, batch_tok_path):
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        docs = [f"doc number {i}: some repeated filler text." * (i % 7)
                for i in range(257)]
        ids, lengths = tok.encode_batch_padded(docs, 32)
        assert ids.shape == (257, 32)
        # spot-check rows against single encodes
        for i in (0, 1, 100, 256):
            ref = tok.encode(docs[i]).ids[:32]
            np.testing.assert_array_equal(ids[i, :len(ref)], ref)
            assert lengths[i] == len(ref)

    def test_unsupported_chain_falls_back(self, batch_tok_path):
        """A non-ASCII Replace disables the raw C++ path but results
        stay identical to per-doc encode."""
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer
        from perceiver_tpu.tokenizer.wordpiece import Replace

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        tok.normalizers.insert(0, Replace("—", " "))
        assert tok._ascii_raw_chain() is None
        ids, lengths = tok.encode_batch_padded(self.TEXTS, 48)
        for i, t in enumerate(self.TEXTS):
            ref = tok.encode(t).ids[:48]
            np.testing.assert_array_equal(ids[i, :lengths[i]], ref)

    def test_c0_separator_whitespace_parity(self, batch_tok_path):
        """\\x1c-\\x1f are whitespace to Python's \\s — the native raw
        path must agree."""
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        texts = ["a\x1cb", "one\x1dtwo\x1ethree\x1ffour", "tab\tok"]
        ids, lengths = tok.encode_batch_padded(texts, 16)
        for i, t in enumerate(texts):
            ref = tok.encode(t).ids[:16]
            np.testing.assert_array_equal(ids[i, :lengths[i]], ref)

    def test_truncation_limit_respected(self, batch_tok_path):
        """enable_truncation below max_len caps every row identically
        on the native and fallback paths."""
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        tok.enable_truncation(5)
        texts = ["a long sentence with many words here",
                 "short café text with some accents okay"]
        ids, lengths = tok.encode_batch_padded(texts, 16)
        assert ids.shape == (2, 16)
        assert (lengths <= 5).all()
        for i, t in enumerate(texts):
            ref = tok.encode(t).ids  # encode() applies the same cap
            np.testing.assert_array_equal(ids[i, :lengths[i]], ref)
            assert (ids[i, lengths[i]:] == 0).all()

    def test_nul_byte_parity(self, batch_tok_path):
        """Embedded NUL bytes must not truncate native word encoding."""
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        texts = [",\x00,", "a\x00b word", "tail nul\x00"]
        ids, lengths = tok.encode_batch_padded(texts, 16)
        for i, t in enumerate(texts):
            ref = tok.encode(t).ids[:16]
            np.testing.assert_array_equal(ids[i, :lengths[i]], ref)

    def test_non_vocab_pad_id(self, batch_tok_path):
        """pad_id outside the vocab (e.g. an ignore sentinel) works on
        every path."""
        import numpy as np
        from perceiver_tpu.tokenizer import WordPieceTokenizer

        tok = WordPieceTokenizer.from_file(batch_tok_path)
        ids, lengths = tok.encode_batch_padded(
            ["short text", "café au lait"], 12, pad_id=-100)
        for i in range(2):
            assert (ids[i, lengths[i]:] == -100).all()
            assert (ids[i, :lengths[i]] >= 0).all()
