"""Unit tests for the tensor core (perceiver_tpu.ops)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.ops import (
    Policy,
    linear_init,
    linear_apply,
    layer_norm_init,
    layer_norm_apply,
    mlp_init,
    mlp_apply,
    mha_init,
    mha_apply,
    cross_attention_init,
    cross_attention_apply,
    self_attention_init,
    self_attention_apply,
)

FP32 = Policy.fp32()


def test_linear_shapes_and_init_bounds():
    p = linear_init(jax.random.key(0), 16, 32)
    assert p["w"].shape == (16, 32) and p["b"].shape == (32,)
    bound = 1 / np.sqrt(16)
    assert np.all(np.abs(p["w"]) <= bound)
    y = linear_apply(p, jnp.ones((2, 5, 16)), policy=FP32)
    assert y.shape == (2, 5, 32)


def test_layer_norm_matches_numpy():
    p = layer_norm_init(8)
    x = jax.random.normal(jax.random.key(1), (4, 8))
    y = layer_norm_apply(p, x, policy=FP32)
    xn = np.asarray(x)
    expected = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(
        xn.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), expected, atol=1e-5)


def test_mlp_hidden_width_equals_channels():
    # Reference model.py:20-26 — no 4x expansion.
    p = mlp_init(jax.random.key(0), 12)
    assert p["fc1"]["w"].shape == (12, 12)
    y = mlp_apply(p, jnp.ones((2, 3, 12)), policy=FP32)
    assert y.shape == (2, 3, 12)


def test_mha_init_matches_torch_fan_math():
    """torch xavier-inits the PACKED (3E, E) in_proj in the symmetric
    case — bound sqrt(6/4E) — but each matrix separately (bound from
    its own fans) in the asymmetric case (VERDICT r3 weak #5)."""
    import math

    e = 64
    p = mha_init(jax.random.key(0), q_dim=e, num_heads=8)
    packed_bound = math.sqrt(6.0 / (4 * e))
    for name in ("q", "k", "v"):
        w = p[name]["w"]
        assert float(jnp.abs(w).max()) <= packed_bound + 1e-6, name
        # and it genuinely fills the packed range (not the 2x-smaller
        # per-matrix bound misread as packed)
        assert float(jnp.abs(w).max()) > 0.8 * packed_bound, name

    pa = mha_init(jax.random.key(0), q_dim=e, num_heads=8, k_dim=32,
                  v_dim=48)
    for name, fan_in in (("q", e), ("k", 32), ("v", 48)):
        w = pa[name]["w"]
        sep_bound = math.sqrt(6.0 / (fan_in + e))
        assert float(jnp.abs(w).max()) <= sep_bound + 1e-6, name
        assert float(jnp.abs(w).max()) > 0.8 * sep_bound, name


def test_mha_bf16_backward_has_no_fp32_dots():
    """Under the bf16 policy EVERY attention matmul — including the
    QK backward pair fed by the fp32 softmax cotangent — must run with
    bf16 operands (the TPU executes fp32 dots at a fraction of the
    bf16 MXU rate; graph audit scripts/hlo_audit.py found the backward
    pair at ~9% of headline-step FLOPs before the _qk_dot fix)."""
    import re

    from perceiver_tpu.ops.policy import Policy

    p = mha_init(jax.random.key(0), q_dim=32, num_heads=4)
    q = jax.random.normal(jax.random.key(1), (2, 8, 32))
    kv = jax.random.normal(jax.random.key(2), (2, 16, 32))
    bf16 = Policy.bf16()

    def check_no_f32_dots(impl):
        def loss(params, q, kv):
            return mha_apply(params, q, kv, kv, num_heads=4, impl=impl,
                             policy=bf16).astype(jnp.float32).sum()

        text = jax.jit(jax.grad(loss)).lower(p, q, kv).as_text()
        bad = []
        for ln in text.splitlines():
            if "stablehlo.dot_general" not in ln:
                continue
            ops = re.search(r": \(tensor<([^>]+)>, tensor<([^>]+)>\)",
                            ln)
            assert ops is not None, ln
            if "f32" in ops.group(1) or "f32" in ops.group(2):
                bad.append(ln.strip()[:160])
        assert not bad, (impl, bad[:3])
        return loss

    loss = check_no_f32_dots("einsum")
    check_no_f32_dots("chunked")

    # and the bf16 grads stay close to the fp32-policy reference
    fp32 = Policy.fp32()

    def loss32(params, q, kv):
        return mha_apply(params, q, kv, kv, num_heads=4,
                         policy=fp32).sum()

    g16 = jax.grad(loss)(p, q, kv)
    g32 = jax.grad(loss32)(p, q, kv)
    for name in ("q", "k", "v"):
        a, b = g16[name]["w"], g32[name]["w"]
        denom = float(jnp.abs(b).max()) + 1e-9
        assert float(jnp.abs(a - b).max()) / denom < 5e-2, name


def test_mha_output_shape_asymmetric_kv():
    p = mha_init(jax.random.key(0), q_dim=32, num_heads=4, k_dim=131,
                 v_dim=131)
    q = jax.random.normal(jax.random.key(1), (2, 7, 32))
    kv = jax.random.normal(jax.random.key(2), (2, 50, 131))
    y = mha_apply(p, q, kv, kv, num_heads=4, policy=FP32)
    assert y.shape == (2, 7, 32)


def test_mha_key_padding_mask_blocks_positions():
    """Masked kv positions must not influence the output."""
    p = mha_init(jax.random.key(0), q_dim=16, num_heads=2)
    q = jax.random.normal(jax.random.key(1), (1, 3, 16))
    kv = jax.random.normal(jax.random.key(2), (1, 6, 16))
    mask = jnp.array([[False, False, False, True, True, True]])

    y1 = mha_apply(p, q, kv, kv, num_heads=2, key_padding_mask=mask,
                   policy=FP32)
    # Perturb the masked positions wildly; output must be unchanged.
    kv2 = kv.at[:, 3:].set(100.0)
    y2 = mha_apply(p, q, kv2, kv2, num_heads=2, key_padding_mask=mask,
                   policy=FP32)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-5)
    # And must differ from the unmasked result.
    y3 = mha_apply(p, q, kv, kv, num_heads=2, policy=FP32)
    assert not np.allclose(np.asarray(y1), np.asarray(y3), atol=1e-3)


def test_mha_additive_and_boolean_attn_mask_agree():
    p = mha_init(jax.random.key(0), q_dim=16, num_heads=2)
    x = jax.random.normal(jax.random.key(1), (2, 5, 16))
    bool_mask = jnp.triu(jnp.ones((5, 5), bool), k=1)
    add_mask = jnp.where(bool_mask, -1e30, 0.0)
    y1 = mha_apply(p, x, x, x, num_heads=2, attn_mask=bool_mask, policy=FP32)
    y2 = mha_apply(p, x, x, x, num_heads=2, attn_mask=add_mask, policy=FP32)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-6)


def test_mha_matches_torch_multihead_attention():
    """Numerical parity with torch nn.MultiheadAttention (the op the
    reference wraps, model.py:59-74), including asymmetric kdim/vdim
    and key_padding_mask."""
    torch = pytest.importorskip("torch")

    q_dim, kv_dim, heads, lq, lk, b = 32, 48, 4, 5, 11, 3
    tm = torch.nn.MultiheadAttention(embed_dim=q_dim, num_heads=heads,
                                     kdim=kv_dim, vdim=kv_dim,
                                     batch_first=True)
    tm.eval()

    params = {
        "q": {"w": jnp.asarray(tm.q_proj_weight.detach().numpy().T),
              "b": jnp.asarray(tm.in_proj_bias.detach().numpy()[:q_dim])},
        "k": {"w": jnp.asarray(tm.k_proj_weight.detach().numpy().T),
              "b": jnp.asarray(
                  tm.in_proj_bias.detach().numpy()[q_dim:2 * q_dim])},
        "v": {"w": jnp.asarray(tm.v_proj_weight.detach().numpy().T),
              "b": jnp.asarray(
                  tm.in_proj_bias.detach().numpy()[2 * q_dim:])},
        "out": {"w": jnp.asarray(tm.out_proj.weight.detach().numpy().T),
                "b": jnp.asarray(tm.out_proj.bias.detach().numpy())},
    }

    rng = np.random.default_rng(0)
    q = rng.standard_normal((b, lq, q_dim), dtype=np.float32)
    kv = rng.standard_normal((b, lk, kv_dim), dtype=np.float32)
    pad = np.zeros((b, lk), dtype=bool)
    pad[:, -3:] = True

    with torch.no_grad():
        expected, _ = tm(torch.from_numpy(q), torch.from_numpy(kv),
                         torch.from_numpy(kv),
                         key_padding_mask=torch.from_numpy(pad))

    got = mha_apply(params, jnp.asarray(q), jnp.asarray(kv), jnp.asarray(kv),
                    num_heads=heads, key_padding_mask=jnp.asarray(pad),
                    policy=FP32)
    np.testing.assert_allclose(np.asarray(got), expected.numpy(), atol=2e-5)


def test_cross_attention_prenorm_and_shapes():
    p = cross_attention_init(jax.random.key(0), num_q_channels=64,
                             num_kv_channels=131, num_heads=4)
    xq = jax.random.normal(jax.random.key(1), (2, 32, 64))
    xkv = jax.random.normal(jax.random.key(2), (2, 784, 131))
    y = cross_attention_apply(p, xq, xkv, num_heads=4, policy=FP32)
    assert y.shape == (2, 32, 64)


def test_self_attention_shapes():
    p = self_attention_init(jax.random.key(0), num_channels=64, num_heads=4)
    x = jax.random.normal(jax.random.key(1), (2, 32, 64))
    y = self_attention_apply(p, x, num_heads=4, policy=FP32)
    assert y.shape == (2, 32, 64)


def test_bf16_policy_close_to_fp32():
    p = mha_init(jax.random.key(0), q_dim=32, num_heads=4)
    x = jax.random.normal(jax.random.key(1), (2, 8, 32))
    y32 = mha_apply(p, x, x, x, num_heads=4, policy=FP32)
    ybf = mha_apply(p, x, x, x, num_heads=4, policy=Policy.bf16())
    assert ybf.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y32),
                               np.asarray(ybf, dtype=np.float32),
                               atol=0.1)


def test_packed_qkv_matches_separate_projections():
    """The self-attention packed in-proj (q is k is v) must equal the
    three-matmul path bit-for-bit up to dtype rounding."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from perceiver_tpu.ops.attention import mha_init, mha_apply
    from perceiver_tpu.ops.policy import Policy

    params = mha_init(jax.random.key(0), 32, 4)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 10, 32)),
                    jnp.float32)
    packed = mha_apply(params, x, x, x, num_heads=4, policy=Policy.fp32())
    separate = mha_apply(params, x, x + 0.0, x + 0.0, num_heads=4,
                         policy=Policy.fp32())
    np.testing.assert_allclose(np.asarray(packed), np.asarray(separate),
                               rtol=1e-6, atol=1e-6)
