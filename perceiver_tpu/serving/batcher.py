"""Thread-safe micro-batching queue with deadlines and load shedding.

Serving traffic arrives one request at a time, but the engine's
executables want bucket-shaped batches — the batcher sits between:
concurrent ``submit`` calls enqueue single requests, a worker thread
coalesces them into batches of up to ``max_batch`` (waiting at most
``max_delay_ms`` after the first request of a batch), and hands each
batch to the runner callable.

Overload semantics are explicit and typed, never an unbounded queue:

- a ``submit`` while the queue already holds ``max_depth`` requests is
  shed immediately with ``Overloaded("queue_full")``;
- a request whose per-request deadline (``timeout_ms``) expires while
  it waits in the queue is shed with ``Overloaded("deadline")`` at
  service time, *before* any compute is spent on it;
- runner exceptions fail only the requests in that batch — each
  request's future gets a *typed* error (``serving/errors.py``: typed
  exceptions pass through, anything else is wrapped in
  ``BatchError``), the ``serving_failed_batches_total`` counter
  ticks, and the worker loop is never harmed.

Under saturation the queue depth is therefore bounded by
``max_depth``, latency of *accepted* requests is bounded by their
deadline, and excess load degrades to typed shed results the caller
can turn into HTTP 429s — the standard TPU-serving answer to the
"compile a few buckets, keep them full" regime this subsystem
implements (see docs/SERVING.md).

The autoregressive decode path uses
:class:`ContinuousBatchScheduler` instead: one object owning both
stream admission (slots + page budget) and the per-step token budget
that co-schedules chunked prefill with in-flight decode rows
(docs/SERVING.md "Continuous batching"). ``AdmissionQueue`` and the
budget rule of ``TokenBudgetBatcher`` are thin compat facades over
it.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import warnings
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from perceiver_tpu.obs import trace as trace_mod
from perceiver_tpu.serving.errors import BatchError, ServingError, Unavailable
from perceiver_tpu.serving.metrics import MetricsRegistry
from perceiver_tpu.serving.tenancy import DEFAULT_TENANT, weighted_fair_shares


@dataclasses.dataclass(frozen=True)
class Overloaded:
    """Typed shed result: the request was NOT served.

    ``reason`` is ``"queue_full"`` (shed at submit) or ``"deadline"``
    (expired while queued). ``queue_depth`` is the depth observed when
    the decision was made — the caller's backpressure signal.
    """

    reason: str
    queue_depth: int


@dataclasses.dataclass
class _Pending:
    payload: object
    future: Future
    enqueued_at: float
    deadline: Optional[float]  # absolute monotonic seconds, or None
    ctx: Optional[trace_mod.TraceContext] = None
    taken_at: float = 0.0  # stamped when popped into a batch


class MicroBatcher:
    """Coalesce concurrent requests into runner-sized batches.

    ``runner(payloads)`` receives 1..max_batch payloads in submission
    order and returns one result per payload (same order). Results —
    or the runner's exception, or an ``Overloaded`` — resolve each
    request's future.
    """

    # lock discipline (gated by check.py --race): queue/closed/inflight
    # are shared between the client side and the worker; _not_empty is
    # a Condition over _lock, so its frames count as holding it
    _GUARDED = {
        "_queue": "_lock",
        "_closed": "_lock",
        "_inflight": "_lock",
    }

    def __init__(self, runner: Callable[[List[object]], Sequence[object]],
                 *, max_batch: int = 8, max_delay_ms: float = 2.0,
                 max_depth: int = 64,
                 metrics: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_batch < 1 or max_depth < 1:
            raise ValueError("max_batch and max_depth must be >= 1")
        self._runner = runner
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1000.0
        self.max_depth = max_depth
        self._clock = clock
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._queue: collections.deque = collections.deque()
        self._closed = False
        self._inflight = 0  # requests handed to the runner, unresolved

        m = metrics if metrics is not None else MetricsRegistry()
        self.metrics = m
        self._m_depth = m.gauge("serving_queue_depth",
                                "requests waiting in the batcher queue")
        self._m_shed = m.counter("serving_shed_total",
                                 "requests shed, by reason")
        self._m_latency = m.histogram(
            "serving_request_latency_seconds",
            "submit → result latency of served requests")
        self._m_batch = m.histogram(
            "serving_batch_size", "coalesced requests per runner call",
            buckets=tuple(float(x) for x in (1, 2, 4, 8, 16, 32, 64)))
        self._m_served = m.counter("serving_requests_total",
                                   "requests whose future resolved, "
                                   "by outcome")
        self._m_failed_batches = m.counter(
            "serving_failed_batches_total",
            "runner calls that raised (every request in the batch got "
            "a typed per-request error)")

        self._worker = threading.Thread(target=self._loop,
                                        name="micro-batcher", daemon=True)
        self._worker.start()

    # -- client side ------------------------------------------------------

    def submit(self, payload, *, timeout_ms: Optional[float] = None,
               trace: Optional[trace_mod.TraceContext] = None) -> Future:
        """Enqueue one request. The future resolves to the runner's
        result for it, an ``Overloaded``, or raises the runner's error.

        Each accepted request gets a trace context (the caller's, or a
        fresh one when tracing is enabled), exposed on the returned
        future as ``fut.trace_ctx`` so callers can look up their spans
        by ``trace_ctx.trace_id``.
        """
        now = self._clock()
        fut = Future()
        ctx = trace if trace is not None \
            else trace_mod.start_trace(origin="batcher")
        fut.trace_ctx = ctx
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._queue) >= self.max_depth:
                depth = len(self._queue)
                self._m_shed.labels(reason="queue_full").inc()  # graphcheck: ignore — micro-batch plane (rectangular serve path) predates tenancy; decode plane carries serving_tenant_shed_total
                self._m_served.labels(outcome="shed").inc()  # graphcheck: ignore — micro-batch plane; decode plane carries the tenant-split series
                fut.set_result(Overloaded("queue_full", depth))
                return fut
            deadline = (now + timeout_ms / 1000.0
                        if timeout_ms is not None else None)
            self._queue.append(_Pending(payload, fut, now, deadline,
                                        ctx=ctx))
            self._m_depth.set(len(self._queue))
            self._not_empty.notify()
        return fut

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def inflight(self) -> int:
        """Requests currently inside the runner (unresolved)."""
        with self._lock:
            return self._inflight

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted request has resolved — queue
        empty AND no batch inside the runner. This is the rolling
        update's cutover precondition (docs/SERVING.md "Fleet"): after
        a successful drain, no request can be served mid-param-swap.
        Returns False if ``timeout`` expires first."""
        deadline = (None if timeout is None
                    else self._clock() + timeout)
        with self._not_empty:
            while self._queue or self._inflight:
                if deadline is not None \
                        and self._clock() >= deadline:
                    return False
                self._not_empty.wait(0.05)
        return True

    def close(self, timeout: float = 5.0) -> None:
        """Stop the worker; queued requests drain first. Idempotent —
        a second close returns immediately. If the worker cannot drain
        within ``timeout`` (a wedged runner), every request still
        queued is failed with a typed ``Unavailable("shutting_down")``
        instead of leaving its caller blocked on a future that will
        never resolve."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
        self._worker.join(timeout)
        if not self._worker.is_alive():
            return
        # the worker missed its deadline: take the queue over (same
        # lock the worker pops under — no double delivery) and resolve
        # every stranded future with the typed shutdown error
        with self._lock:
            leftover = list(self._queue)
            self._queue.clear()
            self._m_depth.set(0)
        err = Unavailable("shutting_down")
        for p in leftover:
            self._m_served.labels(outcome="unavailable").inc()  # graphcheck: ignore — micro-batch plane; decode plane carries the tenant-split series
            p.future.set_exception(err)

    # -- worker side ------------------------------------------------------

    def _pop_taken_locked(self) -> _Pending:
        """Pop the queue head, stamping when it joined a batch (the
        queue_wait → batch_form span boundary)."""
        p = self._queue.popleft()
        p.taken_at = self._clock()
        return p

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block for the first request, then gather until ``max_batch``
        or ``max_delay`` past the first. None = closed and drained."""
        with self._not_empty:
            while not self._queue and not self._closed:
                self._not_empty.wait(0.1)
            if not self._queue:
                return None  # closed
            batch = [self._pop_taken_locked()]
            batch_deadline = self._clock() + self.max_delay
            while len(batch) < self.max_batch:
                if self._queue:
                    batch.append(self._pop_taken_locked())
                    continue
                remaining = batch_deadline - self._clock()
                if remaining <= 0 or self._closed:
                    break
                self._not_empty.wait(remaining)
            self._m_depth.set(len(self._queue))
            self._inflight = len(batch)
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._run_one(batch)
            finally:
                with self._lock:
                    self._inflight = 0
                    self._not_empty.notify_all()  # wake drain()ers

    def _run_one(self, batch: List[_Pending]) -> None:
        now = self._clock()
        live: List[_Pending] = []
        for p in batch:
            if p.deadline is not None and now > p.deadline:
                self._m_shed.labels(reason="deadline").inc()  # graphcheck: ignore — micro-batch plane; decode plane carries serving_tenant_shed_total
                self._m_served.labels(outcome="shed").inc()  # graphcheck: ignore — micro-batch plane; decode plane carries the tenant-split series
                p.future.set_result(
                    Overloaded("deadline", len(batch)))
            else:
                live.append(p)
        if not live:
            return
        run_start = self._clock()
        ctxs = [p.ctx for p in live if p.ctx is not None]
        for p in live:
            if p.ctx is not None:
                p.ctx.record("queue_wait", start=p.enqueued_at,
                             end=p.taken_at)
                p.ctx.record("batch_form", start=p.taken_at,
                             end=run_start, batch_size=len(live))
        try:
            # attach the member traces so engine/api regions executed
            # inside the runner attribute to every request in the batch
            if ctxs:
                with trace_mod.attach(ctxs):
                    results = self._runner([p.payload for p in live])
            else:
                results = self._runner([p.payload for p in live])
            if len(results) != len(live):
                raise RuntimeError(
                    f"runner returned {len(results)} results for "
                    f"{len(live)} requests")
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            self._m_failed_batches.inc()
            # batch-failure isolation: one typed error per request,
            # never a raw internal traceback or a dead worker
            err = e if isinstance(e, ServingError) else BatchError(
                f"batch of {len(live)} failed: {type(e).__name__}: "
                f"{e}", cause=e)
            outcome = ("unavailable" if isinstance(e, Unavailable)
                       else "error")
            for p in live:
                self._m_served.labels(outcome=outcome).inc()  # graphcheck: ignore — micro-batch plane; decode plane carries the tenant-split series
                p.future.set_exception(err)
            return
        done = self._clock()
        self._m_batch.observe(float(len(live)))
        for p, r in zip(live, results):
            self._m_latency.observe(done - p.enqueued_at)
            self._m_served.labels(outcome="ok").inc()  # graphcheck: ignore — micro-batch plane; decode plane carries the tenant-split series
            p.future.set_result(r)


@dataclasses.dataclass
class _Queued:
    item: object
    cost: int
    enqueued_at: float
    deadline: Optional[float]
    tenant: str = DEFAULT_TENANT


class ContinuousBatchScheduler:
    """The unified prefill+decode scheduler for the stepped decode
    engine: one FIFO admission queue AND one per-step token-budget
    chunk planner (docs/SERVING.md "Continuous batching").

    Admission side (long-lived entries, no worker thread, no futures
    — the decode engine's step loop is the consumer, so every method
    is safe to call under the engine lock): the engine ``offer``s
    each stream with its page cost and, once per step, ``take``s the
    longest admissible prefix — entries pop while slots remain and
    each head's cost fits the remaining page budget. Head blocking
    preserves submission order (no small-stream starvation of a large
    head: its pages free up as running streams finish). Expired heads
    shed; the caller resolves them with the typed
    ``Overloaded("deadline")`` just like the micro-batcher would.

    Budget side (:meth:`plan_chunks`): each step spends at most
    ``token_budget`` tokens across ALL resident rows. In-flight
    decode rows cost 1 each and are always scheduled — a generating
    stream never stalls behind a new prompt. What remains is handed
    out FIFO to prefilling rows in chunks of up to ``max_chunk``
    prompt tokens, so waiting prompts ride the SAME stepped
    executable as decode traffic instead of queuing behind a separate
    prefill engine (the Sarathi/vLLM chunked-prefill discipline; the
    r14→r17 TTFT fix). The budget is a per-step pacing target, not a
    hard wall: the FIFO-head prefill row always advances at least one
    token per step (the same no-livelock rule as
    :meth:`budget_admits`'s first-entry case).
    """

    _GUARDED = {"_queue": "_lock"}

    def __init__(self, *, max_depth: int = 64,
                 token_budget: Optional[int] = None,
                 max_chunk: int = 1,
                 metrics: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        if token_budget is not None and token_budget < 1:
            raise ValueError("token_budget must be >= 1")
        if max_chunk < 1:
            raise ValueError("max_chunk must be >= 1")
        self.max_depth = max_depth
        self.token_budget = token_budget
        self.max_chunk = int(max_chunk)
        self._clock = clock
        self._lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        m = metrics if metrics is not None else MetricsRegistry()
        self._m_depth = m.gauge(
            "serving_decode_queue_depth",
            "streams waiting for slot + page admission")

    # -- budget policy (pure; shared with TokenBudgetBatcher) -------------

    @staticmethod
    def budget_admits(spent: int, cost: int, budget: int) -> bool:
        """One more entry of ``cost`` fits ``budget`` after ``spent``
        — except the FIRST entry, which is always admitted so an
        oversized head can never wedge the queue."""
        return spent == 0 or spent + cost <= budget

    def plan_chunks(self, decode_rows: int,
                    prefill_remaining: Sequence[int],
                    prefill_tenants: Optional[Sequence[str]] = None,
                    tenant_weights: Optional[Dict[str, float]] = None,
                    ) -> List[int]:
        """Split one step's token budget: returns the prompt-token
        chunk for each prefilling row (FIFO order, aligned with
        ``prefill_remaining``). Decode rows pre-spend ``decode_rows``
        tokens; rows the leftover cannot reach get 0 (they idle this
        step), except the head row, which always gets >= 1. With
        ``prefill_tenants``, the leftover splits across tenants by
        weighted fair share first (see :meth:`plan_speculative`)."""
        _, chunks = self.plan_speculative(decode_rows, (),
                                          prefill_remaining,
                                          prefill_tenants,
                                          tenant_weights)
        return chunks

    def plan_speculative(self, decode_rows: int,
                         spec_requests: Sequence[int],
                         prefill_remaining: Sequence[int],
                         prefill_tenants: Optional[Sequence[str]] = None,
                         tenant_weights: Optional[Dict[str, float]] = None,
                         ) -> Tuple[List[int], List[int]]:
        """Speculative-aware budget split for one step.

        Every decode row (speculative or not) pre-spends 1 token —
        its guaranteed feedback lane. Each speculative row then
        *requests* up to ``spec_requests[i]`` extra drafted lanes;
        extras are granted FIFO from what the budget has left, so a
        saturated step degrades speculation toward plain decode
        instead of starving prefill completely. The remainder is
        handed to prefilling rows exactly as :meth:`plan_chunks`
        (which is the ``spec_requests=()`` special case). Returns
        ``(grants, chunks)`` aligned with the two input sequences.

        With ``prefill_tenants`` (one tenant per prefilling row), the
        leftover prefill budget first splits across the tenants
        actually waiting — proportional to ``tenant_weights``
        (:func:`~perceiver_tpu.serving.tenancy.weighted_fair_shares`,
        weight 1.0 when unlisted) — and each tenant's rows draw FIFO
        from their tenant's share. A second work-conserving pass hands
        any unclaimed share back out FIFO, so fair-share costs nothing
        when only one tenant is hungry, but a flood tenant's prompts
        can never consume a waiting neighbour's slice. The global
        head row still always advances >= 1 token (no-livelock).
        """
        budget = self.token_budget
        if budget is None:
            budget = (decode_rows + sum(int(k) for k in spec_requests)
                      + len(prefill_remaining) * self.max_chunk)
        left = max(0, budget - decode_rows)
        grants: List[int] = []
        for req in spec_requests:
            g = min(int(req), left)
            grants.append(g)
            left -= g
        caps: Optional[Dict[str, int]] = None
        if prefill_tenants is not None and prefill_remaining:
            if len(prefill_tenants) != len(prefill_remaining):
                raise ValueError(
                    f"{len(prefill_tenants)} tenants for "
                    f"{len(prefill_remaining)} prefill rows")
            weights = {
                t: (tenant_weights or {}).get(t, 1.0)
                for t in prefill_tenants
            }
            caps = weighted_fair_shares(left, weights)
        chunks: List[int] = []
        for i, rem in enumerate(prefill_remaining):
            c = min(int(rem), self.max_chunk, left)
            if caps is not None:
                c = min(c, caps[prefill_tenants[i]])
            if i == 0 and rem > 0:
                c = max(c, 1)
            chunks.append(c)
            if caps is not None:
                caps[prefill_tenants[i]] = max(
                    0, caps[prefill_tenants[i]] - c)
            left = max(0, left - c)
        if caps is not None and left > 0:
            # work-conserving second pass: shares nobody could use
            # (short prompts, absent tenants) go back out FIFO
            for i, rem in enumerate(prefill_remaining):
                extra = min(int(rem) - chunks[i],
                            self.max_chunk - chunks[i], left)
                if extra > 0:
                    chunks[i] += extra
                    left -= extra
                if left <= 0:
                    break
        return grants, chunks

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def offer(self, item, *, cost: int,
              deadline: Optional[float] = None,
              tenant: str = DEFAULT_TENANT) -> bool:
        """Enqueue one entry; False = queue full (caller sheds)."""
        with self._lock:
            if len(self._queue) >= self.max_depth:
                return False
            self._queue.append(_Queued(item, int(cost), self._clock(),
                                       deadline, tenant))
            self._m_depth.set(len(self._queue))
        return True

    def tenant_queued_cost(self) -> Dict[str, int]:
        """Summed queued cost per tenant (quota pre-admission input)."""
        with self._lock:
            out: Dict[str, int] = {}
            for e in self._queue:
                out[e.tenant] = out.get(e.tenant, 0) + e.cost
            return out

    def take(self, *, budget: int, slots: int,
             now: Optional[float] = None,
             tenant_budgets: Optional[Dict[str, int]] = None):
        """Pop the admissible FIFO prefix: entries admit while ``slots``
        remain and their cost fits the remaining ``budget``; expired
        heads shed along the way. Returns ``(admitted, shed)`` items.

        ``budget`` is whatever the caller can actually provide by
        admission time, not just what is free right now — the decode
        engine passes ``pool.free_pages +
        prefix_index.evictable_pages()`` (the kv-share seam: pages
        held only by the prefix index are reclaimed on demand, and a
        cached-prefix hit draws fewer pages than the conservative
        per-item cost, so charging full cost here stays safe).

        ``tenant_budgets`` maps a tenant to the pages it may still
        claim (absent tenant = unlimited; the dict is decremented in
        place as entries admit). An entry whose tenant is out of
        budget **defers** — it stays queued in order, and the scan
        moves past it — instead of head-blocking the whole queue, so
        one tenant's flood can never starve a neighbour's admission.
        Order within a tenant is still FIFO: once one of a tenant's
        entries defers, all its later entries defer this round too.
        """
        if now is None:
            now = self._clock()
        admitted, shed = [], []
        with self._lock:
            deferred: List[_Queued] = []
            over_quota: set = set()
            while self._queue:
                head = self._queue[0]
                # expired heads shed even when no slot/budget is free —
                # a caller polling take() under saturation must not sit
                # on dead requests until capacity happens to return
                if head.deadline is not None and now > head.deadline:
                    self._queue.popleft()
                    shed.append(head.item)
                    continue
                if slots <= 0 or head.cost > budget:
                    break
                if tenant_budgets is not None:
                    tb = tenant_budgets.get(head.tenant)
                    if head.tenant in over_quota \
                            or (tb is not None and head.cost > tb):
                        over_quota.add(head.tenant)
                        deferred.append(self._queue.popleft())
                        continue
                self._queue.popleft()
                admitted.append(head.item)
                budget -= head.cost
                slots -= 1
                if tenant_budgets is not None \
                        and head.tenant in tenant_budgets:
                    tenant_budgets[head.tenant] -= head.cost
            for e in reversed(deferred):
                self._queue.appendleft(e)
            self._m_depth.set(len(self._queue))
        return admitted, shed

    def remove(self, item) -> bool:
        """Drop one queued entry (stream cancellation)."""
        with self._lock:
            for e in self._queue:
                if e.item is item:
                    self._queue.remove(e)
                    self._m_depth.set(len(self._queue))
                    return True
        return False

    def drain_all(self):
        """Empty the queue, returning the items (engine shutdown)."""
        with self._lock:
            items = [e.item for e in self._queue]
            self._queue.clear()
            self._m_depth.set(0)
        return items


class AdmissionQueue(ContinuousBatchScheduler):
    """Deprecated alias: the admission half of
    :class:`ContinuousBatchScheduler`, kept importable so existing
    fleet specs and ``GenerationServer`` callers keep working. New
    code should construct ``ContinuousBatchScheduler`` directly (it
    also owns the per-step prefill chunk budget)."""

    def __init__(self, *, max_depth: int = 64,
                 metrics: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        warnings.warn(
            "AdmissionQueue is deprecated; construct "
            "ContinuousBatchScheduler directly (it also owns the "
            "per-step prefill chunk budget)",
            DeprecationWarning, stacklevel=2)
        super().__init__(max_depth=max_depth, metrics=metrics,
                         clock=clock)


class TokenBudgetBatcher(MicroBatcher):
    """Continuous batching by token budget instead of request count.

    Requests join the in-flight batch until adding the next queued
    request would exceed ``token_budget`` real tokens
    (``cost_fn(payload)`` tokens each) — so short requests stop waiting
    for request-count slots and long requests stop dragging padding
    along. ``max_requests`` caps the row axis (the packed bucket's
    request dimension). The first request of a batch is always taken
    even if it alone exceeds the budget: the engine's packed-bucket
    check is the authority on servable sizes and raises the typed
    error the caller should see.

    Deprecation note: the budget rule now lives on
    :class:`ContinuousBatchScheduler` (``budget_admits``) — this
    class is a thin facade over it that keeps the ``MicroBatcher``
    future/worker surface for the packed single-shot serve path. The
    decode path uses the unified scheduler directly
    (serving/decode.py).

    Everything else — deadline shedding, ``drain()``, ``close()``,
    batch-failure isolation, every metric — is inherited unchanged
    from ``MicroBatcher``.
    """

    # same discipline as the base class; the Condition-over-_lock
    # aliasing is declared explicitly here (tuple form) because
    # _not_empty is constructed in MicroBatcher.__init__ and the
    # static pass reads one class body at a time
    _GUARDED = {
        "_queue": ("_lock", "_not_empty"),
        "_closed": ("_lock", "_not_empty"),
        "_inflight": ("_lock", "_not_empty"),
    }

    def __init__(self, runner: Callable[[List[object]], Sequence[object]],
                 *, token_budget: int,
                 cost_fn: Callable[[object], int],
                 max_requests: int = 64, max_delay_ms: float = 2.0,
                 max_depth: int = 64,
                 metrics: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        if token_budget < 1:
            raise ValueError("token_budget must be >= 1")
        warnings.warn(
            "TokenBudgetBatcher is deprecated; the budget rule lives "
            "on ContinuousBatchScheduler (budget_admits) and the "
            "decode path uses the unified scheduler directly",
            DeprecationWarning, stacklevel=2)
        self.token_budget = token_budget
        self.cost_fn = cost_fn
        super().__init__(runner, max_batch=max_requests,
                         max_delay_ms=max_delay_ms, max_depth=max_depth,
                         metrics=metrics, clock=clock)

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block for the first request, then gather while the budget
        (and row cap) allow, until ``max_delay`` past the first. The
        head request that would overflow stays queued and seeds the
        next batch — submission order is preserved."""
        with self._not_empty:
            while not self._queue and not self._closed:
                self._not_empty.wait(0.1)
            if not self._queue:
                return None  # closed
            batch = [self._pop_taken_locked()]
            spent = self.cost_fn(batch[0].payload)
            batch_deadline = self._clock() + self.max_delay
            while len(batch) < self.max_batch:
                if self._queue:
                    cost = self.cost_fn(self._queue[0].payload)
                    if not ContinuousBatchScheduler.budget_admits(
                            spent, cost, self.token_budget):
                        break
                    batch.append(self._pop_taken_locked())
                    spent += cost
                    continue
                remaining = batch_deadline - self._clock()
                if remaining <= 0 or self._closed:
                    break
                self._not_empty.wait(remaining)
            self._m_depth.set(len(self._queue))
            self._inflight = len(batch)
            return batch
