"""One observability plane: request tracing, typed events, fleet
metrics aggregation, training telemetry, on-demand profiling.

See docs/OBSERVABILITY.md for the schemas, the endpoint map, and the
overhead budget.  Everything here is host-side and dependency-free:
tracing and events never touch jax, so they can never change an XLA
cache key or add a compile (the same contract as
``resilience/faults.py`` unarmed).
"""

from perceiver_tpu.obs.events import (
    SCHEMA,
    EventLog,
    default_log,
    emit,
    set_default_log,
    validate_event,
)
from perceiver_tpu.obs.trace import (
    PHASES,
    SpanCollector,
    TraceBuffer,
    TraceContext,
    attach,
    attached,
    default_buffer,
    enabled,
    from_wire,
    region,
    set_default_buffer,
    set_enabled,
    start_trace,
)

__all__ = [
    "PHASES",
    "SCHEMA",
    "EventLog",
    "SpanCollector",
    "TraceBuffer",
    "TraceContext",
    "attach",
    "attached",
    "default_buffer",
    "default_log",
    "emit",
    "enabled",
    "from_wire",
    "region",
    "set_default_buffer",
    "set_default_log",
    "set_enabled",
    "start_trace",
    "validate_event",
]
