#!/usr/bin/env python
"""AOT memory check for the big BASELINE configs (VERDICT r1 #6).

Compiles (compile ONLY — no execution) the full train step of:

1. the 224×224 / 512-latent classifier preset (BASELINE configs[3],
   v5e-8 target) at its per-chip batch shard,
2. the v5p-16 Perceiver-LM MLM preset (1024×512 latents, 12 self-attn
   layers/block, seq 2048; BASELINE configs[4]) at its per-chip shard,
3. (``bench``) the headline bench MLM config at batch 512 (the top
   ``bench.py`` ladder rung) and 1024 (a sweep/watcher point beyond
   the ladder) — predicts whether those fit HBM,

on whatever single device is available, and reports XLA's HBM usage
estimates (argument/output/temp/generated-code sizes). This validates
that remat + query chunking keep the per-chip footprint inside a
v5e/v5p chip's HBM before any pod time is spent.

Usage: python scripts/aot_memcheck.py [224 | lm | bench | all]
Env:   MEMCHECK_PLATFORM=cpu   (forces the CPU backend for smoke runs)
"""

import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _mem_analysis(compiled):
    try:
        m = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001
        return {"error": f"memory_analysis unavailable: {e}"}
    keys = (
        "argument_size_in_bytes", "output_size_in_bytes",
        "temp_size_in_bytes", "generated_code_size_in_bytes",
        "alias_size_in_bytes",
    )
    out = {}
    for k in keys:
        v = getattr(m, k, None)
        if v is not None:
            out[k.replace("_in_bytes", "_mb")] = round(v / 2**20, 1)
    # peak live ≈ args + temps (outputs alias donated args here)
    if "argument_size_mb" in out and "temp_size_mb" in out:
        out["approx_peak_mb"] = round(
            out["argument_size_mb"] + out["temp_size_mb"], 1)
    return out


def _topology_sharding():
    """When MEMCHECK_TOPOLOGY is set (e.g. ``v5e:2x2``), AOT-compile
    against that real TPU target via the local libtpu instead of the
    host backend — memory numbers then come from the actual TPU
    compiler, not a CPU-backend estimate (VERDICT r3 missing #4)."""
    name = os.environ.get("MEMCHECK_TOPOLOGY")
    if not name:
        return None
    import jax
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(name, platform="tpu")
    print(f"[memcheck] target topology {name}: "
          f"{topo.devices[0].device_kind}", file=sys.stderr, flush=True)
    return jax.sharding.SingleDeviceSharding(topo.devices[0])


def _compile_train_step(task, batch, label):
    import jax
    import optax

    from perceiver_tpu.ops.policy import Policy

    model = task.build()
    policy = Policy.bf16()
    params = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    tx = optax.adamw(1e-3)
    opt_state = jax.eval_shape(tx.init, params)
    topo_sh = _topology_sharding()
    if topo_sh is not None:
        retarget = lambda t: jax.tree.map(  # noqa: E731
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=topo_sh), t)
        params, opt_state = retarget(params), retarget(opt_state)
        batch = retarget({k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                          for k, v in batch.items()})

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch, rng):
        def loss_fn(p):
            loss, _ = task.loss_and_metrics(model, p, batch, rng=rng,
                                            deterministic=False,
                                            policy=policy)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    shapes = {k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                      sharding=getattr(v, "sharding",
                                                       None))
              for k, v in batch.items()}
    rng_sds = jax.ShapeDtypeStruct((), jax.random.key(0).dtype,
                                   sharding=topo_sh)
    print(f"[{label}] lowering ...", file=sys.stderr, flush=True)
    lowered = train_step.lower(params, opt_state, shapes, rng_sds)
    print(f"[{label}] compiling ...", file=sys.stderr, flush=True)
    compiled = lowered.compile()  # graphcheck: ignore — AOT memory diagnostic, compilation IS the measurement
    return _mem_analysis(compiled)


def check_224(per_chip_batch: int = 4):
    """224×224/512-latent classifier; v5e-8 runs dp8, so the per-chip
    shard is global_batch/8 (preset batch 32 → 4/chip)."""
    import jax.numpy as jnp

    from perceiver_tpu.tasks import ImageClassifierTask

    task = ImageClassifierTask(
        image_shape=(224, 224, 3), num_classes=1000,
        num_frequency_bands=64, num_latents=512, num_latent_channels=512,
        num_encoder_layers=6,
        num_encoder_self_attention_layers_per_block=6,
        num_encoder_cross_attention_heads=8,
        num_encoder_self_attention_heads=8,
        num_decoder_cross_attention_heads=8,
        remat=True, attention_impl="chunked", kv_chunk_size=4096)
    batch = {
        "image": jnp.zeros((per_chip_batch, 224, 224, 3), jnp.float32),
        "label": jnp.zeros((per_chip_batch,), jnp.int32),
    }
    return _compile_train_step(task, batch, "224")


def check_lm(per_chip_batch: int = 2):
    """v5p-16 Perceiver-LM preset per-chip shard: the mesh is dp4×tp4
    (scripts/configs/perceiver_lm_v5p16.yaml); tensor-parallel weight
    shards aren't modeled single-chip, so this is the CONSERVATIVE
    (replicated-weights) bound."""
    import jax.numpy as jnp

    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=32000, max_seq_len=2048,
        num_latents=1024, num_latent_channels=512,
        num_encoder_layers=2,
        num_encoder_self_attention_layers_per_block=12,
        num_encoder_cross_attention_heads=8,
        num_encoder_self_attention_heads=8,
        num_decoder_cross_attention_heads=8,
        remat=True, loss_impl="packed")
    batch = {
        "input_ids": jnp.zeros((per_chip_batch, 2048), jnp.int32),
        "pad_mask": jnp.zeros((per_chip_batch, 2048), bool),
    }
    return _compile_train_step(task, batch, "lm")


def check_mlm_bench(batch: int):
    """The headline bench config (bench.py: seq 512, vocab 10003,
    64×64 latents, packed CE) at a candidate batch size — predicts
    whether the big ladder rungs fit HBM before chip time is spent."""
    import jax.numpy as jnp

    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(vocab_size=10003, max_seq_len=512,
                                   loss_impl="packed")
    batch_arrs = {
        "input_ids": jnp.zeros((batch, 512), jnp.int32),
        "pad_mask": jnp.zeros((batch, 512), bool),
    }
    return _compile_train_step(task, batch_arrs, f"mlm_b{batch}")


def check_seg(batch: int = 2, side: int = 512):
    """The 512×512 / 262,144-output-query LArTPC segmentation config
    (``run.py:72-112``) — the decoder query-chunking memory stress."""
    import jax.numpy as jnp

    from perceiver_tpu.tasks import SegmentationTask

    task = SegmentationTask(image_shape=(side, side, 1),
                            query_chunk_size=min(16384, side * side))
    batch_arrs = {
        "image": jnp.zeros((batch, side, side, 1), jnp.float32),
        "label": jnp.zeros((batch, side, side), jnp.int32),
    }
    return _compile_train_step(task, batch_arrs, f"seg{side}_b{batch}")


def main():
    import jax

    want = os.environ.get("MEMCHECK_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)
    which = sys.argv[1] if len(sys.argv) > 1 else "all"

    out = {"device": str(jax.devices()[0]),
           "topology": os.environ.get("MEMCHECK_TOPOLOGY")}
    if which in ("224", "all"):
        out["classifier_224"] = check_224()
    if which in ("lm", "all"):
        out["perceiver_lm_v5p16_shard"] = check_lm()
    if which in ("seg", "all"):
        out["seg_512_262k_queries"] = check_seg()
    if which in ("bench", "all"):
        for b in (512, 1024):
            out[f"mlm_bench_b{b}"] = check_mlm_bench(b)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
