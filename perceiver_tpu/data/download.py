"""Best-effort dataset download (reference ``data/imdb.py:92-94`` /
torchvision MNIST semantics: fetch when absent, behind the same
datamodule surface).

Zero-egress environments are first-class: every fetch is wrapped, uses
a short connect timeout, retries transient failures a bounded number
of times with exponential backoff (optionally verifying an expected
sha256 before publishing), and returns False once the budget is spent
so callers fall back (to local files or synthetic data) instead of
crashing. ``PERCEIVER_TPU_OFFLINE=1`` skips attempts entirely.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import sys
import tarfile
import time


def offline() -> bool:
    return os.environ.get("PERCEIVER_TPU_OFFLINE", "") not in ("", "0")


# URLs that already exhausted their retries in this process — retried
# next process, but never within one (a firewalled host must not stall
# repeatedly on the same connect timeout during a single run)
_failed_urls: set = set()


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def fetch(url: str, dest: str, timeout: float = 15.0, retries: int = 3,
          backoff_s: float = 0.5, sha256: str = None) -> bool:
    """Download ``url`` to ``dest`` atomically. False only once every
    retry is exhausted (with the final error reported on stderr — a
    flaky mirror should look flaky, not silent).

    Transient failures — connect errors, truncated transfers, and
    checksum mismatches when ``sha256`` (the expected lowercase hex
    digest) is given — are retried up to ``retries`` times with
    exponential backoff. A digest mismatch also deletes the temp file,
    so a corrupted download can never be published. The temp name is
    per-process so concurrent callers (multi-host runs sharing a
    data_dir) never interleave writes; last finished rename wins, each
    with a complete, verified file."""
    if offline() or url in _failed_urls:
        return False
    tmp = f"{dest}.part.{os.getpid()}"
    last_err = None
    for attempt in range(max(int(retries), 1)):
        if attempt and backoff_s > 0:
            time.sleep(backoff_s * (2 ** (attempt - 1)))
        try:
            import urllib.request
            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            if sha256 is not None:
                got = _sha256_file(tmp)
                if got != sha256.lower():
                    raise IOError(
                        f"sha256 mismatch for {url}: got {got}, "
                        f"want {sha256.lower()}")
            os.replace(tmp, dest)
            return True
        except Exception as e:  # noqa: BLE001 — every failure retries
            last_err = e
            try:
                os.unlink(tmp)
            except OSError:
                pass  # already gone / never created
    _failed_urls.add(url)
    print(f"[download] giving up on {url} after {max(int(retries), 1)} "
          f"attempt(s): {type(last_err).__name__}: {last_err}",
          file=sys.stderr)
    return False


def extract_tgz(path: str, dest_dir: str) -> bool:
    """Extract a .tar.gz safely (no paths escaping ``dest_dir``).
    On failure the archive is deleted so the next run re-fetches
    instead of being stuck on a corrupt cached file."""
    try:
        with tarfile.open(path, "r:gz") as tf:
            try:
                tf.extractall(dest_dir, filter="data")
            except TypeError:
                # filter= landed in 3.10.12/3.11.4; older patch
                # releases get a conservative manual check instead:
                # no links at all (symlink members could redirect
                # later writes outside dest_dir) and no names
                # escaping dest_dir ("." itself is fine)
                base = os.path.realpath(dest_dir)
                for m in tf.getmembers():
                    if not (m.isfile() or m.isdir()):
                        # no links (could redirect later writes), no
                        # devices/FIFOs — what filter="data" rejects
                        raise ValueError(f"special tar member {m.name}")
                    target = os.path.realpath(
                        os.path.join(dest_dir, m.name))
                    if not (target == base or
                            target.startswith(base + os.sep)):
                        raise ValueError(f"unsafe tar member {m.name}")
                tf.extractall(dest_dir)
        return True
    except Exception:
        try:
            os.unlink(path)
        except OSError:
            pass
        return False
