"""PrefetchIterator: identical stream, exception propagation, epochs."""

import numpy as np
import pytest

from perceiver_tpu.data.core import ArrayDataset, BatchIterator
from perceiver_tpu.data.prefetch import PrefetchIterator


def _loader(n=23, bs=4, shuffle=True):
    ds = ArrayDataset(x=np.arange(n, dtype=np.int32),
                      y=np.arange(n, dtype=np.int32) * 2)
    return BatchIterator(ds, bs, shuffle=shuffle, seed=5)


def _collect(it):
    return [{k: v.copy() for k, v in b.items()} for b in it]


def test_same_batches_same_order():
    plain, wrapped = _collect(_loader()), _collect(PrefetchIterator(_loader()))
    assert len(plain) == len(wrapped)
    for a, b in zip(plain, wrapped):
        assert a.keys() == b.keys()
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


def test_len_and_set_epoch_proxy():
    inner = _loader()
    pf = PrefetchIterator(inner, depth=1)
    assert len(pf) == len(inner)
    first = _collect(pf)
    pf.set_epoch(1)
    assert inner.epoch == 1
    second = _collect(pf)
    # epoch-seeded shuffle must differ through the wrapper
    assert any(not np.array_equal(a["x"], b["x"])
               for a, b in zip(first, second))


def test_exception_propagates():
    def bad():
        yield {"x": np.zeros(2)}
        raise RuntimeError("boom")

    it = iter(PrefetchIterator(bad()))
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_early_exit_does_not_hang():
    for _ in range(3):
        for i, _batch in enumerate(PrefetchIterator(_loader(n=64), depth=1)):
            if i == 1:
                break  # producer blocked on put() must be drained


def test_early_exit_stops_producer():
    """Breaking out must not run the rest of the epoch dry."""
    import time

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield {"x": np.array([i])}

    it = iter(PrefetchIterator(gen(), depth=1))
    next(it), next(it)
    it.close()
    time.sleep(0.5)
    assert len(produced) < 10


def test_depth_validation():
    with pytest.raises(ValueError):
        PrefetchIterator(_loader(), depth=0)


class TestShardedLoader:
    """set_sharding: the DistributedSampler equivalent."""

    def _ds(self, n=37):
        return ArrayDataset(x=np.arange(n, dtype=np.int32))

    def test_shards_partition_the_epoch(self):
        loaders = []
        for s in range(3):
            it = BatchIterator(self._ds(), 4, shuffle=True, seed=9)
            it.set_sharding(3, s)
            loaders.append(it)
        seen = [np.concatenate([b["x"][b["valid"]] for b in it])
                for it in loaders]
        # equal per-shard sizes (37 // 3 = 12) and full disjointness
        assert all(len(s) == 12 for s in seen)
        allx = np.concatenate(seen)
        assert len(np.unique(allx)) == len(allx) == 36

    def test_same_shuffle_across_shards(self):
        """All shards must derive from the SAME epoch permutation."""
        a = BatchIterator(self._ds(), 4, shuffle=True, seed=9)
        a.set_sharding(2, 0)
        b = BatchIterator(self._ds(), 4, shuffle=True, seed=9)
        b.set_sharding(2, 1)
        a.set_epoch(5), b.set_epoch(5)
        xa = np.concatenate([x["x"][x["valid"]] for x in a])
        xb = np.concatenate([x["x"][x["valid"]] for x in b])
        assert len(np.intersect1d(xa, xb)) == 0

    def test_len_matches_iteration(self):
        it = BatchIterator(self._ds(40), 4)
        it.set_sharding(4, 1)
        assert len(it) == len(list(it)) == 3  # 40//4=10 rows, 3 batches

    def test_invalid_shard_rejected(self):
        it = BatchIterator(self._ds(), 4)
        with pytest.raises(ValueError):
            it.set_sharding(2, 2)

    def test_pad_remainder_covers_every_example(self):
        """Eval sharding: no example dropped, equal batch counts."""
        loaders = []
        for s in range(3):
            it = BatchIterator(self._ds(37), 4, shuffle=True, seed=9)
            it.set_sharding(3, s, pad_remainder=True)
            loaders.append(it)
        # every shard yields the same number of batches (lockstep
        # collectives) even though 37 = 3*12 + 1
        assert len({len(it) for it in loaders}) == 1
        assert all(len(list(it)) == len(it) for it in loaders)
        seen = [np.concatenate([b["x"][b["valid"]] for b in it])
                for it in loaders]
        allx = np.concatenate(seen)
        # exact cover: all 37 examples exactly once
        assert len(np.unique(allx)) == len(allx) == 37

    def test_pad_remainder_exact_multiple_unpadded(self):
        it = BatchIterator(self._ds(36), 4, shuffle=False)
        it.set_sharding(3, 1, pad_remainder=True)
        batches = list(it)
        assert len(batches) == len(it) == 3
        assert all(b["valid"].all() for b in batches)
