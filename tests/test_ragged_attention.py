"""Ragged (padding-free) attention kernels vs. pure-jax references.

Both kernels run in Pallas interpreter mode on the CPU test backend —
the identical kernel bodies that compile on TPU (see
ops/ragged_attention.py and docs/SERVING.md "Ragged serving").
The properties pinned here:

- the encoder kernel matches masked-softmax attention over each
  request's own token span, for aligned and unaligned offsets;
- zero-length rows return exactly zero (not NaN from an empty
  softmax);
- ``max_len`` only bounds the kv-block walk — numerics are unchanged
  as long as every request fits;
- the decoder kernel matches the block-diagonal latent mask and never
  leaks attention across requests;
- both survive jit and bf16 inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.ops.ragged_attention import (
    ragged_cross_attention,
    ragged_cross_attention_reference,
    ragged_decode_attention,
    ragged_decode_attention_reference,
)


def _pack(lengths):
    lengths = np.asarray(lengths, np.int32)
    offsets = np.zeros_like(lengths)
    offsets[1:] = np.cumsum(lengths)[:-1]
    return jnp.asarray(offsets), jnp.asarray(lengths)


def _cross_inputs(key, lengths, h=2, nq=4, d=8, t=None):
    r = len(lengths)
    t = int(np.sum(lengths)) if t is None else t
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (r, h, nq, d))
    k = jax.random.normal(kk, (h, t, d))
    v = jax.random.normal(kv, (h, t, d))
    offs, lens = _pack(lengths)
    return q, k, v, offs, lens


class TestRaggedCross:
    def test_matches_reference(self):
        q, k, v, offs, lens = _cross_inputs(jax.random.key(0),
                                            [40, 7, 81])
        out = ragged_cross_attention(q, k, v, offs, lens, block_k=128)
        ref = ragged_cross_attention_reference(q, k, v, offs, lens)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_unaligned_offsets_cross_block_edges(self):
        # spans straddle block_k boundaries at both ends
        q, k, v, offs, lens = _cross_inputs(jax.random.key(1),
                                            [100, 200, 60, 31],
                                            t=400)
        out = ragged_cross_attention(q, k, v, offs, lens, block_k=128)
        ref = ragged_cross_attention_reference(q, k, v, offs, lens)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_zero_length_rows_are_zero(self):
        # empty spans park at the packed tail (the engine's padding
        # convention) and must come back exactly zero, not NaN
        q, k, v, _, _ = _cross_inputs(jax.random.key(2), [30, 0, 12, 0],
                                      t=64)
        offs = jnp.asarray([0, 42, 30, 42], jnp.int32)
        lens = jnp.asarray([30, 0, 12, 0], jnp.int32)
        out = ragged_cross_attention(q, k, v, offs, lens, block_k=128)
        ref = ragged_cross_attention_reference(q, k, v, offs, lens)
        assert np.all(np.isfinite(np.asarray(out)))
        np.testing.assert_array_equal(np.asarray(out[1]), 0.0)
        np.testing.assert_array_equal(np.asarray(out[3]), 0.0)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_single_row_spans_whole_buffer(self):
        q, k, v, offs, lens = _cross_inputs(jax.random.key(3), [96])
        out = ragged_cross_attention(q, k, v, offs, lens, block_k=32)
        ref = ragged_cross_attention_reference(q, k, v, offs, lens)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_max_len_bound_preserves_numerics(self):
        # max_len trims the kv-block walk (the bytes win) but must not
        # change the result while every request fits under it
        q, k, v, offs, lens = _cross_inputs(jax.random.key(4),
                                            [64, 17, 33], t=256)
        full = ragged_cross_attention(q, k, v, offs, lens, block_k=64)
        bounded = ragged_cross_attention(q, k, v, offs, lens,
                                         block_k=64, max_len=64)
        np.testing.assert_allclose(bounded, full, atol=1e-6, rtol=1e-6)

    def test_under_jit(self):
        q, k, v, offs, lens = _cross_inputs(jax.random.key(5),
                                            [20, 44, 64])
        fn = jax.jit(lambda *a: ragged_cross_attention(*a, block_k=64))
        out = fn(q, k, v, offs, lens)
        ref = ragged_cross_attention_reference(q, k, v, offs, lens)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_bf16(self):
        q, k, v, offs, lens = _cross_inputs(jax.random.key(6),
                                            [40, 24, 64])
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        out = ragged_cross_attention(qb, kb, vb, offs, lens, block_k=64)
        ref = ragged_cross_attention_reference(q, k, v, offs, lens)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref,
                                   atol=2e-2, rtol=2e-2)

    def test_no_cross_request_leakage(self):
        # perturbing request 1's tokens must leave request 0's output
        # bit-identical — raggedness is isolation, not approximation
        q, k, v, offs, lens = _cross_inputs(jax.random.key(7), [32, 32])
        out_a = ragged_cross_attention(q, k, v, offs, lens, block_k=32)
        k2 = k.at[:, 32:, :].add(100.0)
        v2 = v.at[:, 32:, :].add(-7.0)
        out_b = ragged_cross_attention(q, k2, v2, offs, lens, block_k=32)
        np.testing.assert_array_equal(np.asarray(out_a[0]),
                                      np.asarray(out_b[0]))
        assert not np.allclose(np.asarray(out_a[1]),
                               np.asarray(out_b[1]))


class TestRaggedDecode:
    def _inputs(self, key, lengths, n=4, h=2, d=8):
        r = len(lengths)
        t = int(np.sum(lengths))
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (h, t, d))
        k = jax.random.normal(kk, (h, r * n, d))
        v = jax.random.normal(kv, (h, r * n, d))
        rows = jnp.asarray(np.repeat(np.arange(r), lengths), jnp.int32)
        return q, k, v, rows, n

    def test_matches_reference(self):
        q, k, v, rows, n = self._inputs(jax.random.key(10), [13, 40, 7])
        out = ragged_decode_attention(q, k, v, rows, latents_per_row=n,
                                      block_q=32)
        ref = ragged_decode_attention_reference(q, k, v, rows,
                                                latents_per_row=n)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_under_jit(self):
        q, k, v, rows, n = self._inputs(jax.random.key(11), [25, 39])
        fn = jax.jit(lambda *a: ragged_decode_attention(
            *a, latents_per_row=n, block_q=16))
        out = fn(q, k, v, rows)
        ref = ragged_decode_attention_reference(q, k, v, rows,
                                                latents_per_row=n)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_no_cross_request_leakage(self):
        q, k, v, rows, n = self._inputs(jax.random.key(12), [16, 16])
        out_a = ragged_decode_attention(q, k, v, rows, latents_per_row=n)
        # blow up request 1's latents; request 0's tokens can't see them
        k2 = k.at[:, n:, :].add(50.0)
        v2 = v.at[:, n:, :].add(9.0)
        out_b = ragged_decode_attention(q, k2, v2, rows,
                                        latents_per_row=n)
        np.testing.assert_array_equal(np.asarray(out_a[:, :16]),
                                      np.asarray(out_b[:, :16]))
        assert not np.allclose(np.asarray(out_a[:, 16:]),
                               np.asarray(out_b[:, 16:]))

    def test_bf16(self):
        q, k, v, rows, n = self._inputs(jax.random.key(13), [30, 18, 16])
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        out = ragged_decode_attention(qb, kb, vb, rows,
                                      latents_per_row=n)
        ref = ragged_decode_attention_reference(q, k, v, rows,
                                                latents_per_row=n)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref,
                                   atol=2e-2, rtol=2e-2)

    @pytest.mark.parametrize("lengths", [[1], [5, 1, 1, 9]])
    def test_tiny_rows(self, lengths):
        q, k, v, rows, n = self._inputs(jax.random.key(14), lengths)
        out = ragged_decode_attention(q, k, v, rows, latents_per_row=n,
                                      block_q=16)
        ref = ragged_decode_attention_reference(q, k, v, rows,
                                                latents_per_row=n)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
