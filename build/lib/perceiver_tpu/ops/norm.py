"""LayerNorm as pure init/apply functions.

Statistics are computed in fp32 regardless of the compute dtype —
bf16 mean/variance accumulation loses precision the MXU gains nothing
from, and XLA fuses the fp32 reduce into surrounding ops anyway.
"""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp

from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY


def layer_norm_init(dim: int, dtype=jnp.float32):
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layer_norm_apply(params, x, eps: float = 1e-5,
                     policy: Policy = DEFAULT_POLICY):
    xf = x.astype(policy.norm_dtype)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = (y * params["scale"].astype(policy.norm_dtype)
         + params["bias"].astype(policy.norm_dtype))
    return y.astype(policy.compute_dtype)
