#!/usr/bin/env python
"""Per-kernel attention microbenchmark.

Times the cross-attention implementations (einsum / chunked / flash)
at the shapes that dominate each BASELINE.md config's encoder — the
latent ← input step, the framework's hot op — forward and
forward+backward. Use on a real chip to pick ``--model.attention_impl``
and ``kv_chunk_size``; on CPU it validates the harness (flash runs the
Pallas kernel in interpreter mode and is expected to be slow there).

Usage: python scripts/bench_kernels.py [impl ...]
       impls: einsum chunked flash flash_std flash_t
       (flash_std/flash_t pin the flash block layout; plain flash
       auto-picks by head dim)
Env:   BENCH_PLATFORM=cpu   KERNEL_SHAPES=mlm,seg   KERNEL_REPS=20
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# (name, batch, n_q, n_kv, channels, heads) — cross-attention shapes
# of the BASELINE configs. "seg" is the 262k-kv shape (32 latents ←
# 512×512 input tokens, D=16) and "seg_dec" its decoder twin (262k
# output queries ← 32 latents); "mlm2048"/"lm2048" are the seq-2048
# A/B pair at production D=16 and wide D=64 — together the harvest
# set for the flash-vs-chunked verdict (VERDICT r5 item 6).
_SHAPES = {
    "mnist": (128, 32, 784, 128, 4),
    "mlm": (64, 64, 512, 64, 4),
    "imagenet": (8, 512, 50176, 512, 4),
    "seg": (4, 32, 262144, 64, 4),
    "seg_dec": (1, 262144, 32, 64, 4),
    "lm2048": (4, 1024, 2048, 512, 8),
    "mlm2048": (16, 64, 2048, 64, 4),
}


def main():
    impls = sys.argv[1:] or ["einsum", "chunked", "flash"]
    reps = int(os.environ.get("KERNEL_REPS", "20"))
    # default harvest set = the shapes the flash-vs-chunked verdict
    # needs (262k-kv + both seq-2048 widths), heaviest last so a short
    # tunnel window still collects the small shapes
    names = [s for s in os.environ.get(
        "KERNEL_SHAPES",
        "mnist,mlm,lm2048,mlm2048,seg,seg_dec").split(",") if s]

    import jax
    import jax.numpy as jnp

    want = os.environ.get("BENCH_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)

    from perceiver_tpu.ops.attention import (
        cross_attention_init,
        cross_attention_apply,
    )
    from perceiver_tpu.utils.timing import fence

    print(f"device: {jax.devices()[0]}", flush=True)
    for name in names:
        b, nq, nkv, c, h = _SHAPES[name]
        params = cross_attention_init(jax.random.key(0), c, c, h)
        q = jnp.zeros((b, nq, c), jnp.bfloat16)
        kv = jax.random.normal(jax.random.key(1), (b, nkv, c),
                               jnp.bfloat16)
        caller_layout = os.environ.get("PERCEIVER_TPU_FLASH_LAYOUT")
        for impl in impls:
            # pseudo-impls flash_std / flash_t pin the flash kernel's
            # block layout (auto picks by head dim) for on-chip A/B;
            # plain impls keep the caller's own env pin, if any
            layout = {"flash_std": "standard", "flash_t": "transposed"
                      }.get(impl, caller_layout)
            real_impl = "flash" if impl.startswith("flash") else impl
            if layout:
                os.environ["PERCEIVER_TPU_FLASH_LAYOUT"] = layout
            else:
                os.environ.pop("PERCEIVER_TPU_FLASH_LAYOUT", None)

            def fwd(p, q, kv):
                return cross_attention_apply(
                    p, q, kv, num_heads=h, impl=real_impl).sum()

            grad = jax.jit(jax.grad(fwd))
            fj = jax.jit(fwd)
            try:
                # fence(), not block_until_ready: the axon tunnel
                # acks block_until_ready before the chip finishes
                # (utils/timing.py), which would time dispatch latency
                # instead of the kernels
                fence(fj(params, q, kv))  # compile + first run
                t0 = time.perf_counter()
                for _ in range(reps):
                    out = fj(params, q, kv)
                fence(out)
                f_ms = (time.perf_counter() - t0) / reps * 1e3

                fence(grad(params, q, kv))  # compile + first run
                t0 = time.perf_counter()
                for _ in range(reps):
                    g = grad(params, q, kv)
                fence(g)
                fb_ms = (time.perf_counter() - t0) / reps * 1e3
                print(f"{name:9s} (B{b} q{nq} kv{nkv} c{c}) "
                      f"{impl:7s} fwd {f_ms:8.2f} ms   "
                      f"fwd+bwd {fb_ms:8.2f} ms", flush=True)
            except Exception as e:  # noqa: BLE001 — report and move on
                print(f"{name:9s} {impl:7s} FAILED: "
                      f"{type(e).__name__}: {str(e)[:120]}", flush=True)


if __name__ == "__main__":
    main()
