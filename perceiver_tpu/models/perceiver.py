"""Perceiver IO models as pure init/apply dataclasses.

Parity targets (reference ``perceiver/model.py``):

- ``PerceiverEncoder`` (``model.py:119-189``): input adapter → learned
  latent array (trunc-N(0,0.02) clamped ±2) broadcast over batch →
  ``layer_1`` (unshared) then ``layer_n`` applied ``num_layers - 1``
  times with **shared weights**. Each perceiver layer is a
  cross-attention layer (latent ← input, with key-padding mask) followed
  by a block of self-attention layers (no mask). Returns
  ``(x_latent, pad_mask)`` — the tuple contract the decoder consumes.
- ``PerceiverDecoder`` (``model.py:192-237``): learned output query
  array of shape ``output_adapter.output_shape``, one cross-attention
  layer (query ← latent, no mask — matching ``model.py:236``), then the
  output adapter. Supports query chunking for huge output arrays (the
  262k-query segmentation config) — exact, since output queries only
  interact with the latent kv, never with each other.
- ``PerceiverIO`` (``model.py:321-325``): encoder ∘ decoder.
- ``PerceiverMLM`` (``model.py:296-318``): masking → encoder → decoder →
  logits sliced to the input length. The reference version crashes
  (encoder tuple fed to the decoder as a single arg, SURVEY.md §2.6.1);
  here the plumbing is explicit and correct.

TPU-first design notes:

- The weight-shared ``layer_n`` recurrence and the per-block
  self-attention stack both run under ``lax.scan`` — each layer body is
  traced and compiled once regardless of depth, and the stacked
  parameter pytrees give XLA one big fused HBM layout per block.
- All residual/attention dropout uses explicitly threaded PRNG keys
  (scan carries a per-iteration key), so training steps stay pure and
  reproducible under ``jit`` and ``shard_map``.
- Latent and output-query broadcasts are ``jnp.broadcast_to`` views —
  no materialized per-batch copies in HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from perceiver_tpu.models.masking import TextMasking
from perceiver_tpu.ops.attention import (
    ATTENTION_IMPLS,
    DECODER_ATTENTION_IMPLS,
    cross_attention_init,
    cross_attention_apply,
    cross_attention_kv,
    self_attention_init,
    self_attention_apply,
)
from perceiver_tpu.ops.dropout import dropout
from perceiver_tpu.ops.initializers import trunc_normal_clamped
from perceiver_tpu.ops.mlp import mlp_init, mlp_apply
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY


def _rng_or_dummy(rng, deterministic: bool = True):
    """Dummy key for deterministic paths (scan still needs a key array).

    Raises when randomness is actually required but no rng was given —
    a silent constant key would reuse the same dropout/masking pattern
    every step and quietly degrade training.
    """
    if rng is None and not deterministic:
        raise ValueError(
            "deterministic=False requires an explicit `rng` key")
    return rng if rng is not None else jax.random.key(0)


# --- layer composers (reference model.py:29-44) ------------------------------


def cross_attention_layer_init(key, num_q_channels, num_kv_channels,
                               num_heads, widening_factor=1):
    ka, km = jax.random.split(key)
    return {
        "attn": cross_attention_init(ka, num_q_channels, num_kv_channels,
                                     num_heads),
        "mlp": mlp_init(km, num_q_channels, widening_factor),
    }


def cross_attention_layer_apply(params, x_q, x_kv, *, num_heads,
                                key_padding_mask=None, attn_mask=None,
                                dropout_rate=0.0, rng=None,
                                deterministic=True,
                                policy: Policy = DEFAULT_POLICY,
                                impl=None, kv_chunk_size=1024, spmd=None,
                                kv_heads=None):
    """Residual(CrossAttention) then Residual(mlp) (model.py:29-33).

    ``kv_heads`` carries the pre-normed, pre-projected kv from
    ``cross_attention_kv`` — the encoder hoists it out of the layer
    scan because the kv tokens (and the shared layer weights) are
    loop-invariant there."""
    k_attn, k_r1, k_r2 = jax.random.split(_rng_or_dummy(rng, deterministic), 3)
    y = cross_attention_apply(
        params["attn"], x_q, x_kv, num_heads=num_heads,
        key_padding_mask=key_padding_mask, attn_mask=attn_mask,
        dropout_rate=dropout_rate, rng=k_attn, deterministic=deterministic,
        policy=policy, impl=impl, kv_chunk_size=kv_chunk_size, spmd=spmd,
        kv_heads=kv_heads)
    x = x_q + dropout(y, dropout_rate, rng=k_r1, deterministic=deterministic)
    y = mlp_apply(params["mlp"], x, policy=policy)
    return x + dropout(y, dropout_rate, rng=k_r2, deterministic=deterministic)


def self_attention_layer_init(key, num_channels, num_heads,
                              widening_factor=1):
    ka, km = jax.random.split(key)
    return {
        "attn": self_attention_init(ka, num_channels, num_heads),
        "mlp": mlp_init(km, num_channels, widening_factor),
    }


def self_attention_layer_apply(params, x, *, num_heads,
                               key_padding_mask=None, attn_mask=None,
                               dropout_rate=0.0, rng=None, deterministic=True,
                               policy: Policy = DEFAULT_POLICY):
    k_attn, k_r1, k_r2 = jax.random.split(_rng_or_dummy(rng, deterministic), 3)
    y = self_attention_apply(
        params["attn"], x, num_heads=num_heads,
        key_padding_mask=key_padding_mask, attn_mask=attn_mask,
        dropout_rate=dropout_rate, rng=k_attn, deterministic=deterministic,
        policy=policy)
    x = x + dropout(y, dropout_rate, rng=k_r1, deterministic=deterministic)
    y = mlp_apply(params["mlp"], x, policy=policy)
    return x + dropout(y, dropout_rate, rng=k_r2, deterministic=deterministic)


def self_attention_block_init(key, num_layers, num_channels, num_heads,
                              widening_factor=1):
    """Stacked parameters for ``num_layers`` self-attention layers.

    Leaves carry a leading ``num_layers`` axis so the block applies
    under a single ``lax.scan`` (one compiled layer body).
    """
    keys = jax.random.split(key, num_layers)
    return jax.vmap(
        lambda k: self_attention_layer_init(k, num_channels, num_heads,
                                            widening_factor))(keys)


def self_attention_block_apply(stacked, x, *, num_heads, dropout_rate=0.0,
                               rng=None, deterministic=True,
                               policy: Policy = DEFAULT_POLICY):
    num_layers = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    keys = jax.random.split(_rng_or_dummy(rng, deterministic), num_layers)

    def body(carry, layer_in):
        layer_params, k = layer_in
        out = self_attention_layer_apply(
            layer_params, carry, num_heads=num_heads,
            dropout_rate=dropout_rate, rng=k, deterministic=deterministic,
            policy=policy)
        return out, None

    x, _ = jax.lax.scan(body, x, (stacked, keys))
    return x


# --- encoder -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PerceiverEncoder:
    """Generic Perceiver IO encoder (reference model.py:119-189)."""

    input_adapter: object
    latent_shape: Tuple[int, int]  # (N latents, C latent channels)
    num_layers: int
    num_cross_attention_heads: int = 4
    num_self_attention_heads: int = 4
    num_self_attention_layers_per_block: int = 2
    dropout: float = 0.0
    widening_factor: int = 1
    # Cross-attention kernel for the latent ← input step, the long-kv
    # hot op: None/"einsum", "chunked" (lax.scan online softmax), or
    # "flash" (fused Pallas TPU kernel). Self-attention over the small
    # latent array always uses the einsum path.
    attention_impl: Optional[str] = None
    kv_chunk_size: int = 1024
    # For the shard_map sequence-parallel attention impls ("seqpar",
    # "ring", "ulysses"): (mesh, seq_axis, batch_axis) describing how
    # the input token axis is laid out across devices. None for the
    # single-device / pure-GSPMD paths.
    spmd: Optional[tuple] = None
    # Rematerialize each perceiver layer (cross-attn + self-attn block)
    # on the backward pass: activations inside a layer are recomputed
    # instead of stored, trading FLOPs for HBM — the lever that fits
    # the seq-2048 / 12-block configs (BASELINE.md configs[4]).
    remat: bool = False

    def __post_init__(self):
        # fail at model build, not deep inside a jit trace
        if self.attention_impl not in ATTENTION_IMPLS:
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}; "
                f"expected one of {ATTENTION_IMPLS}")

    def _layer_init(self, key):
        kc, ks = jax.random.split(key)
        return {
            "cross": cross_attention_layer_init(
                kc, self.latent_shape[1],
                self.input_adapter.num_input_channels,
                self.num_cross_attention_heads, self.widening_factor),
            "selfs": self_attention_block_init(
                ks, self.num_self_attention_layers_per_block,
                self.latent_shape[1], self.num_self_attention_heads,
                self.widening_factor),
        }

    def init(self, key):
        k_adapter, k_latent, k1, kn = jax.random.split(key, 4)
        params = {
            "input_adapter": self.input_adapter.init(k_adapter),
            "latent": trunc_normal_clamped(k_latent, self.latent_shape),
            "layer_1": self._layer_init(k1),
        }
        if self.num_layers > 1:
            params["layer_n"] = self._layer_init(kn)
        return params

    def _layer_apply(self, params, latent, kv_heads, pad_mask, attn_mask,
                     rng, deterministic, policy):
        k_cross, k_selfs = jax.random.split(_rng_or_dummy(rng))
        latent = cross_attention_layer_apply(
            params["cross"], latent, None,
            num_heads=self.num_cross_attention_heads,
            key_padding_mask=pad_mask, attn_mask=attn_mask,
            dropout_rate=self.dropout, rng=k_cross,
            deterministic=deterministic, policy=policy,
            impl=self.attention_impl, kv_chunk_size=self.kv_chunk_size,
            spmd=self.spmd, kv_heads=kv_heads)
        return self_attention_block_apply(
            params["selfs"], latent,
            num_heads=self.num_self_attention_heads,
            dropout_rate=self.dropout, rng=k_selfs,
            deterministic=deterministic, policy=policy)

    def apply(self, params, x, pad_mask=None, attn_mask=None, *, rng=None,
              deterministic: bool = True, policy: Policy = DEFAULT_POLICY):
        """Returns ``(x_latent, pad_mask)`` (reference model.py:189)."""
        b = x.shape[0]
        x = self.input_adapter.apply(params["input_adapter"], x,
                                     policy=policy)
        latent = jnp.broadcast_to(
            policy.cast_param(params["latent"])[None],
            (b, *self.latent_shape))

        k1, kn = jax.random.split(_rng_or_dummy(rng, deterministic))

        def layer_kv(layer_params):
            # hoisted loop-invariant kv: the cross-attention norms and
            # projects the SAME input tokens with the SAME (shared)
            # weights in every scan iteration — compute once per
            # distinct parameter set, close over it in the scan body
            return cross_attention_kv(
                layer_params["cross"]["attn"], x,
                num_heads=self.num_cross_attention_heads, policy=policy)

        def one_layer(layer_params, kv_heads, latent, k):
            return self._layer_apply(layer_params, latent, kv_heads,
                                     pad_mask, attn_mask, k,
                                     deterministic, policy)

        if self.remat:
            one_layer = jax.checkpoint(one_layer)

        latent = one_layer(params["layer_1"], layer_kv(params["layer_1"]),
                           latent, k1)
        if self.num_layers > 1:
            # Weight-shared recurrence (model.py:186-187): one compiled
            # body, scanned num_layers-1 times over per-iteration keys.
            keys = jax.random.split(kn, self.num_layers - 1)
            layer_n = params["layer_n"]
            kv_n = layer_kv(layer_n)

            def body(carry, k):
                # explicit compute-dtype carry: the latent rides the
                # scan in bf16 under the default policy (fp32 master
                # values live only in params/optimizer state)
                return one_layer(layer_n, kv_n,
                                 policy.cast_compute(carry), k), None

            latent, _ = jax.lax.scan(body, latent, keys)
        return latent, pad_mask


# --- decoder -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PerceiverDecoder:
    """Generic Perceiver IO decoder (reference model.py:192-237)."""

    output_adapter: object
    latent_shape: Tuple[int, int]
    num_cross_attention_heads: int = 4
    dropout: float = 0.0
    widening_factor: int = 1
    # Chunk the K output queries through cross-attention + mlp in slices
    # of this size (None = no chunking). Exact: queries never attend to
    # each other. Needed for the 262k-query segmentation config where
    # the full (B, K, N) attention-weight tensor would blow HBM.
    query_chunk_size: Optional[int] = None
    # Attention kernel for the output-query ← latent cross-attention
    # (see PerceiverEncoder.attention_impl). "flash" blocks over the
    # query axis in-kernel, an alternative to query_chunk_size for the
    # 262k-query config.
    attention_impl: Optional[str] = None
    kv_chunk_size: int = 1024

    def __post_init__(self):
        if self.attention_impl not in DECODER_ATTENTION_IMPLS:
            raise ValueError(
                f"unknown decoder attention_impl "
                f"{self.attention_impl!r}; expected one of "
                f"{DECODER_ATTENTION_IMPLS} (the SPMD impls shard the "
                "encoder token axis and do not apply to output queries)")

    def init(self, key):
        k_out, k_query, k_cross = jax.random.split(key, 3)
        return {
            "output_adapter": self.output_adapter.init(k_out),
            "query": trunc_normal_clamped(k_query,
                                          self.output_adapter.output_shape),
            "cross": cross_attention_layer_init(
                k_cross, self.output_adapter.output_shape[-1],
                self.latent_shape[1], self.num_cross_attention_heads,
                self.widening_factor),
        }

    def apply(self, params, x, pad_mask=None, *, rng=None,
              deterministic: bool = True, policy: Policy = DEFAULT_POLICY,
              return_hidden: bool = False, query_positions=None):
        """``pad_mask`` is accepted for the encoder-tuple contract but —
        matching the reference (model.py:229,236) — not applied in the
        decoder cross-attention (the latent kv has no padding).

        ``return_hidden=True`` skips the output adapter and returns the
        pre-projection ``(B, K, C)`` query states — the hook for fused
        projection+loss kernels (``perceiver_tpu.ops.fused_ce``).

        ``query_positions`` (B, Q) int32 decodes ONLY those rows of the
        learned query array (per example). Output queries never attend
        to each other, so the selected rows are computed exactly as in
        the full decode — the masked-position-only MLM loss path uses
        this to shrink every decoder-side tensor from seq_len to the
        ~mask_p·seq_len positions the loss actually reads. Requires
        ``return_hidden=True`` (the output adapter's position-wise
        ``output_shape`` contract assumes the full query array)."""
        del pad_mask
        b, *d = x.shape
        if tuple(d) != tuple(self.latent_shape):
            raise ValueError(
                f"Latent shape {tuple(d)} different from required shape "
                f"{tuple(self.latent_shape)}")

        if query_positions is not None:
            if not return_hidden:
                raise ValueError(
                    "query_positions requires return_hidden=True")
            query = jnp.take(policy.cast_param(params["query"]),
                             query_positions, axis=0)
        else:
            query = jnp.broadcast_to(
                policy.cast_param(params["query"])[None],
                (b, *self.output_adapter.output_shape))

        def run(q, k):
            return cross_attention_layer_apply(
                params["cross"], q, x,
                num_heads=self.num_cross_attention_heads,
                dropout_rate=self.dropout, rng=k,
                deterministic=deterministic, policy=policy,
                impl=self.attention_impl, kv_chunk_size=self.kv_chunk_size)

        num_q = query.shape[1]
        cs = self.query_chunk_size
        if cs is not None and num_q > cs:
            if num_q % cs != 0:
                raise ValueError(
                    f"query_chunk_size {cs} must divide num queries {num_q}")
            n_chunks = num_q // cs
            chunks = query.reshape(b, n_chunks, cs, -1).swapaxes(0, 1)
            keys = jax.random.split(_rng_or_dummy(rng, deterministic), n_chunks)
            out = jax.lax.map(lambda qk: run(qk[0], qk[1]), (chunks, keys))
            out = out.swapaxes(0, 1).reshape(b, num_q, -1)
        else:
            out = run(query, _rng_or_dummy(rng, deterministic))
        if return_hidden:
            return out
        return self.output_adapter.apply(params["output_adapter"], out,
                                         policy=policy)


# --- composed models ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PerceiverIO:
    """Encoder ∘ decoder (reference model.py:321-325)."""

    encoder: PerceiverEncoder
    decoder: PerceiverDecoder

    def init(self, key):
        ke, kd = jax.random.split(key)
        return {"encoder": self.encoder.init(ke),
                "decoder": self.decoder.init(kd)}

    def apply(self, params, x, pad_mask=None, *, rng=None,
              deterministic: bool = True, policy: Policy = DEFAULT_POLICY):
        ke, kd = jax.random.split(_rng_or_dummy(rng, deterministic))
        latent, pad_mask = self.encoder.apply(
            params["encoder"], x, pad_mask, rng=ke,
            deterministic=deterministic, policy=policy)
        return self.decoder.apply(
            params["decoder"], latent, pad_mask, rng=kd,
            deterministic=deterministic, policy=policy)


def _pack_masked_positions(labels, capacity: int):
    """Left-pack each example's masked positions into (B, capacity).

    labels: (B, L) with ``IGNORE_INDEX`` at unmasked positions (the
    ``TextMasking`` contract). Returns ``(positions, labels_q,
    dropped)``: positions (B, capacity) int32 into the L axis (slot j
    holds the j-th masked position of that row; unused slots point at
    position 0 with labels_q == IGNORE so downstream weights vanish),
    labels_q (B, capacity) the labels at those positions, and dropped
    — the scalar count of masked positions past ``capacity`` (loss
    bias when nonzero; callers surface it exactly like the packed-CE
    overflow). The per-row scatter is the batched twin of
    ``ops.fused_ce.pack_positions``."""
    from perceiver_tpu.models.masking import IGNORE_INDEX

    b, l = labels.shape
    sel = labels != IGNORE_INDEX
    slot = jnp.cumsum(sel.astype(jnp.int32), axis=1) - 1
    count = slot[:, -1] + 1
    dropped = jnp.maximum(count - capacity, 0).sum()
    # unmasked and overflow positions land on a dump slot sliced off
    slot = jnp.where(sel & (slot < capacity), slot, capacity)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    pos = jnp.broadcast_to(jnp.arange(l, dtype=jnp.int32)[None, :], (b, l))
    positions = jnp.zeros((b, capacity + 1), jnp.int32)
    positions = positions.at[rows, slot].set(pos)[:, :capacity]
    labels_q = jnp.full((b, capacity + 1), IGNORE_INDEX, labels.dtype)
    labels_q = labels_q.at[rows, slot].set(labels)[:, :capacity]
    return positions, labels_q, dropped


@dataclasses.dataclass(frozen=True)
class PerceiverMLM:
    """Masked-language model (reference model.py:296-318, plumbing fixed)."""

    encoder: PerceiverEncoder
    decoder: PerceiverDecoder
    masking: TextMasking

    def init(self, key):
        ke, kd = jax.random.split(key)
        return {"encoder": self.encoder.init(ke),
                "decoder": self.decoder.init(kd)}

    def apply(self, params, x_input, pad_mask=None, *, masking: bool = True,
              rng=None, deterministic: bool = True,
              policy: Policy = DEFAULT_POLICY, return_hidden: bool = False,
              query_capacity: Optional[int] = None):
        """Returns ``(logits, labels)``; ``labels`` is None when
        ``masking=False`` (inference path, reference utils.py:30).

        ``return_hidden=True`` returns pre-vocab-projection decoder
        states ``(B, l, C)`` instead of logits (fused-loss hook; the
        vocab projection then happens inside the loss, see
        ``perceiver_tpu.ops.fused_ce``).

        ``query_capacity`` (static int Q, requires masking and
        return_hidden) switches to the masked-position-only decode:
        each example's ≤Q masked positions are packed left into a
        (B, Q) position buffer and ONLY those decoder queries are
        computed — exact, because output queries never attend to each
        other, and the loss reads nothing else. Returns
        ``(hidden (B,Q,C), labels (B,Q) IGNORE-padded, dropped)`` where
        ``dropped`` counts masked positions past Q (loss bias when
        nonzero — surface it like the packed-CE overflow). Every
        decoder-side tensor shrinks seq_len → Q ≈ mask_p·seq_len, the
        single largest HBM cut on the flagship MLM step."""
        l = x_input.shape[1]
        if masking and rng is None:
            # a silent constant key would mask the same positions in
            # every batch — val_loss would be computed on one fixed,
            # position-correlated 15% subset
            raise ValueError("masking=True requires an explicit `rng` key")
        if query_capacity is not None and not (masking and return_hidden):
            raise ValueError(
                "query_capacity requires masking=True and "
                "return_hidden=True (it selects masked positions and "
                "bypasses the output adapter)")
        k_mask, k_enc, k_dec = jax.random.split(
            _rng_or_dummy(rng, deterministic), 3)

        if masking:
            x_masked, labels = self.masking.apply(k_mask, x_input, pad_mask)
        else:
            x_masked, labels = x_input, None

        latent, _ = self.encoder.apply(
            params["encoder"], x_masked, pad_mask, rng=k_enc,
            deterministic=deterministic, policy=policy)
        if query_capacity is not None:
            positions, labels_q, dropped = _pack_masked_positions(
                labels, query_capacity)
            hidden = self.decoder.apply(
                params["decoder"], latent, rng=k_dec,
                deterministic=deterministic, policy=policy,
                return_hidden=True, query_positions=positions)
            return hidden, labels_q, dropped
        out = self.decoder.apply(
            params["decoder"], latent, rng=k_dec,
            deterministic=deterministic, policy=policy,
            return_hidden=return_hidden)[:, :l, :]
        return out, labels
