"""Background-thread batch prefetching with a supervised producer.

The reference keeps its accelerator fed with torch ``DataLoader``
worker processes (``data/imdb.py:112-126`` sets ``num_workers=3``,
``data/mnist.py:15``). The JAX equivalent needs no worker *processes* —
batch assembly is NumPy slicing over preloaded arrays (C under the
hood) and the jitted step dispatches asynchronously — but the host
loop must not assemble batch N+1 *after* blocking on step N. A single
daemon thread with a small bounded queue decouples the two: the device
runs the current step while the host builds the next batches.

Failure contract (docs/RESILIENCE.md): a production input pipeline's
worker dying must not kill a multi-day run. When the producer raises
(or, with ``stall_timeout_s`` set, goes silent), the supervisor
restarts it with exponential backoff — re-iterating the inner loader
and discarding the batches already delivered, so the stream resumes
at the exact position with no duplicates and no gaps (the inner
loader's iteration order is deterministic per epoch). Restarts are
bounded by the ``max_restarts`` poison-pill budget; once spent, the
original exception is re-raised at the consumer's ``next()`` exactly
like in-line iteration — persistent failures stay loud. The default
budget is 0 (the historical die-on-first-error behavior); the trainer
passes its configured budget. Inner iterables that cannot be
re-iterated (bare generators) are never restarted.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Iterator, Optional

import numpy as np

from perceiver_tpu.resilience import faults

_SENTINEL = object()


class LoaderStalled(RuntimeError):
    """The producer delivered nothing for ``stall_timeout_s`` seconds."""


class PrefetchIterator:
    """Wrap a batch iterable so iteration overlaps with consumption.

    ``depth`` bounds host memory: at most ``depth`` assembled batches
    exist beyond the one being consumed. ``max_restarts`` /
    ``backoff_s`` / ``stall_timeout_s`` configure the producer
    supervisor (see module docstring). Proxies ``len``, ``set_epoch``
    and ``set_sharding`` so it can stand in for a ``BatchIterator``
    (``perceiver_tpu.data.core``) anywhere, including epoch-seeded
    shuffling and per-process multi-host sharding.
    """

    def __init__(self, inner, depth: int = 2, max_restarts: int = 0,
                 backoff_s: float = 0.05,
                 stall_timeout_s: Optional[float] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if max_restarts < 0 or backoff_s < 0:
            raise ValueError("max_restarts and backoff_s must be >= 0")
        if stall_timeout_s is not None and stall_timeout_s <= 0:
            raise ValueError("stall_timeout_s must be positive or None")
        self.inner = inner
        self.depth = depth
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.stall_timeout_s = stall_timeout_s
        # a bare iterator/generator consumes itself: re-iterating it
        # would silently drop the rest of the epoch, so never restart
        self._restartable = not hasattr(inner, "__next__")
        self.restarts = 0  # total producer restarts (observability)

    def __len__(self) -> int:
        return len(self.inner)

    def set_epoch(self, epoch: int):
        if hasattr(self.inner, "set_epoch"):
            self.inner.set_epoch(epoch)

    def set_sharding(self, num_shards: int, shard_index: int,
                     pad_remainder: bool = False):
        """Proxy per-process sharding so a prefetched loader composes
        with multi-host runs (``distributed/bootstrap.py``): the
        producer then iterates only this process's disjoint shard, and
        a supervised restart re-derives the same strided slice — the
        no-dups/no-gaps restart guarantee holds per shard, hence
        globally."""
        if not hasattr(self.inner, "set_sharding"):
            raise ValueError(
                f"inner loader {type(self.inner).__name__} is not "
                f"process-shardable (no set_sharding)")
        self.inner.set_sharding(num_shards, shard_index, pad_remainder)

    # -- producer ---------------------------------------------------------

    def _produce(self, q: "queue.Queue", stop: threading.Event,
                 skip: int) -> None:
        """Iterate the inner loader, discarding the first ``skip``
        batches (restart reposition), and feed the bounded queue.
        Ends with a ``(_SENTINEL, exc_or_None)`` marker."""

        def put(item) -> bool:
            """False once the consumer has gone away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for i, batch in enumerate(self.inner):
                if i < skip:
                    continue
                # chaos seams fire once per *delivered* batch, so a
                # restart replays the same deterministic schedule
                faults.maybe_stall("loader.stall")
                faults.maybe_raise("loader.exception")
                if not put(batch):
                    return  # consumer exited early: stop, don't
                    # run the rest of the epoch dry
        except BaseException as e:  # handed to the supervisor
            put((_SENTINEL, e))
            return
        put((_SENTINEL, None))

    # -- consumer / supervisor -------------------------------------------

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        delivered = 0
        restarts_left = self.max_restarts
        backoff = self.backoff_s
        while True:
            q: "queue.Queue" = queue.Queue(maxsize=self.depth)
            stop = threading.Event()
            t = threading.Thread(target=self._produce,
                                 args=(q, stop, delivered), daemon=True)
            t.start()
            failure: Optional[BaseException] = None
            finished = False
            last_progress = time.monotonic()
            try:
                while True:
                    try:
                        item = q.get(timeout=0.2)
                    except queue.Empty:
                        if self.stall_timeout_s is not None \
                                and time.monotonic() - last_progress \
                                > self.stall_timeout_s:
                            failure = LoaderStalled(
                                f"loader produced nothing for "
                                f"{self.stall_timeout_s}s")
                            break
                        continue
                    last_progress = time.monotonic()
                    if isinstance(item, tuple) and len(item) == 2 \
                            and item[0] is _SENTINEL:
                        failure = item[1]
                        finished = failure is None
                        break
                    yield item
                    delivered += 1
            finally:
                # covers early consumer exit (break / preemption /
                # GeneratorExit) too: halt the producer after at most
                # its in-flight batch
                stop.set()
                t.join(timeout=0.2 if failure is not None else 5.0)
            if finished:
                return
            if not self._restartable or restarts_left <= 0 \
                    or isinstance(failure, (KeyboardInterrupt,
                                            SystemExit)):
                raise failure
            restarts_left -= 1
            self.restarts += 1
            if backoff > 0:
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
