"""Canonical lowering targets: one jitted train step per task, at the
shapes the benchmarks and runbooks actually pin.

Each target rebuilds, from scratch, the exact step ``bench.py`` times
(forward + backward + AdamW, params and optimizer state donated) and
lowers it on the CPU backend — StableHLO lowering is platform-
independent, so the dtype/transfer/donation properties gated here are
the ones the chip will see. The targets also define the per-config
allowlists: every exception is written down next to the config it
covers, with a reason (the allowlist is the audit trail, not an
escape hatch).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Optional, Tuple

from perceiver_tpu.analysis.report import (
    DtypeAllow,
    ReplicationAllow,
    TransferAllow,
)

# The packed-CE overflow warning (tasks/mlm.py) lowers to one host
# callback on backends that support them; on the axon TPU runtime the
# host_callbacks_supported() gate removes it entirely, so the CPU-side
# lowering legitimately carries up to one callback custom call per
# traced loss (primal only — debug_print has no transpose).
_MLM_OVERFLOW_CALLBACK = (
    TransferAllow(
        marker="xla_python_cpu_callback", max_count=1,
        reason="packed-CE overflow warning (tasks/mlm.py) — "
               "observability-only debug print, removed on the TPU "
               "runtime by host_callbacks_supported()"),
    TransferAllow(
        marker="xla_ffi_python_cpu_callback", max_count=1,
        reason="same warning under the FFI callback lowering newer "
               "jax versions emit"),
)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh for a sharded target: ordered (axis, size)
    pairs, outermost first — ``(("data", 2), ("model", 2))`` is the
    dp2×tp2 layout ``parallel/mesh.make_mesh`` builds. Declarative so
    targets stay import-cheap (no jax at module import) and the
    descriptor can key caches/manifests without building devices."""

    axes: Tuple[Tuple[str, int], ...]

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.axes)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(n for _, n in self.axes)

    @property
    def n_devices(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    @property
    def descriptor(self) -> str:
        """Stable string identity: ``"data2_model2"`` — the manifest
        key suffix and the lowering-cache key extra."""
        return "_".join(f"{name}{n}" for name, n in self.axes)

    def build(self):
        """Mesh over the first ``n_devices`` devices in iota order —
        the same layout ``parallel/mesh.make_mesh`` produces, and the
        order the collective-attribution pass assumes. On CPU, run
        under ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
        (conftest.py and scripts/check.py both force it)."""
        import jax
        import numpy as np

        devices = jax.devices()
        if len(devices) < self.n_devices:
            raise ValueError(
                f"mesh {self.descriptor} needs {self.n_devices} devices, "
                f"backend has {len(devices)}; on CPU set XLA_FLAGS="
                "--xla_force_host_platform_device_count=8")
        arr = np.array(devices[:self.n_devices]).reshape(self.shape)
        return jax.sharding.Mesh(arr, self.axis_names)


@dataclasses.dataclass(frozen=True)
class StepTarget:
    """One canonical (task config, input shapes) pair to lower and gate.

    ``build`` returns a fresh ``(task, batch)`` every call — the
    recompile-budget pass relies on independent rebuilds producing
    byte-identical step signatures.

    ``kind`` selects what gets lowered: ``"train"`` is the full
    forward + backward + AdamW step (``make_train_step``); ``"serve"``
    is the task's serve graph (``serving/graphs.py``) at its bucket
    shapes — the exact executable ``ServingEngine`` AOT-compiles, so
    the gates certify the graph production dispatches.

    ``mesh`` turns the target SPMD: the step is built with explicit
    shardings over ``mesh.build()`` (``training/spmd.py`` /
    ``serving/graphs.serve_graph_shardings``) and additionally
    compiled, because GSPMD inserts collectives during SPMD
    partitioning — the shardcheck passes parse the optimized HLO.
    """

    name: str
    build: Callable[[], Tuple[object, dict]]
    # headline targets additionally assert bf16_flop_fraction == 1.0
    headline: bool = False
    transfer_allow: Tuple[TransferAllow, ...] = ()
    dtype_allow: Tuple[DtypeAllow, ...] = ()
    kind: str = "train"
    mesh: Optional[MeshSpec] = None
    replication_allow: Tuple[ReplicationAllow, ...] = ()
    # the name of another canonical target this one MUST share its
    # step signature with — a positive gate, not an allowlist entry:
    # the recompile_budget pass asserts the two fingerprints are
    # EQUAL (and excludes the twin from the distinct-targets collapse
    # check). Used by the multi-tenant decode round, whose whole claim
    # is that tenancy never mints a new compile key.
    signature_twin: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class LoweredStep:
    """A lowered target: the StableHLO text plus the donation contract
    derived from the live arguments."""

    target: StepTarget
    text: str
    # leaves of (params, opt_state) — every one must be donated AND
    # aliased onto an output by lowering
    expected_donated: int
    # None when the step was reconstructed from a cache record —
    # Python's salted str hashing makes task hashes incomparable
    # across processes, so cached steps opt out of that check
    task_hash: Optional[int]
    # XLA HLO-cost-analysis "bytes accessed" of the lowered module
    # (scan/while bodies counted once) — the hbm_budget pass's metric.
    # None when the backend exposes no lowering-time cost analysis.
    bytes_accessed: Optional[float] = None
    # True when served from a persistent lowering record (a previous
    # process's lowering of the same source tree) instead of a fresh
    # trace — see perceiver_tpu/cache
    cached: bool = False
    # optimized-HLO text of the compiled executable — mesh targets
    # only (GSPMD collectives exist nowhere else). None when the
    # target is unsharded or the caller asked to skip compilation.
    compiled_text: Optional[str] = None


def cost_bytes_accessed(lowered) -> Optional[float]:
    """``bytes accessed`` from a ``jax.stages.Lowered`` cost analysis,
    or None where unavailable (e.g. the axon TPU plugin, which only
    exposes post-compile analysis)."""
    try:
        cost = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not cost:
        return None
    value = cost.get("bytes accessed")
    return float(value) if value is not None else None


def make_train_step(task, batch):
    """The canonical single-optimizer-step jit: forward + backward +
    AdamW with (params, opt_state) donated — the step every benchmark
    and the trainer's hot loop run. Returns ``(jitted_fn, args)``."""
    import jax
    import optax

    from perceiver_tpu.ops.policy import Policy

    model = task.build()
    policy = Policy.bf16()
    params = model.init(jax.random.key(0))
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch_i, key):
        def loss_fn(p):
            loss, _ = task.loss_and_metrics(
                model, p, batch_i, rng=key, deterministic=False,
                policy=policy)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return train_step, (params, opt_state, batch, jax.random.key(1))


def make_serve_step(task, batch):
    """The canonical serve-graph jit for a task: the same function —
    with the same donation layout — that ``ServingEngine`` AOT-compiles
    per bucket. Returns ``(jitted_fn, args, expected_donated)``; only
    the donated request buffers (which alias outputs by construction,
    see serving/graphs.py) count toward ``expected_donated``."""
    import jax

    from perceiver_tpu.serving.graphs import build_serve_graph

    graph = build_serve_graph(task)
    params = graph.init_params()
    args = (params,) + tuple(batch[spec.name] for spec in graph.inputs)
    jitted = jax.jit(graph.fn, donate_argnums=graph.donate_argnums)
    donated_args = tuple(args[i] for i in graph.donate_argnums)
    expected = len(jax.tree_util.tree_leaves(donated_args))
    return jitted, args, expected


def make_packed_serve_step(task, batch):
    """The packed ragged serve-graph jit for a task — the executable
    ``ServingEngine.dispatch_packed`` AOT-compiles per token-budget
    bucket. Returns ``(jitted_fn, args, expected_donated)``: the MLM
    packed graph donates ``packed_ids`` (aliases ``filled_ids``)."""
    import jax

    from perceiver_tpu.serving.graphs import build_packed_serve_graph

    graph = build_packed_serve_graph(task)
    params = graph.init_params()
    args = (params,) + tuple(batch[spec.name] for spec in graph.inputs)
    jitted = jax.jit(graph.fn, donate_argnums=graph.donate_argnums)
    donated_args = tuple(args[i] for i in graph.donate_argnums)
    expected = len(jax.tree_util.tree_leaves(donated_args))
    return jitted, args, expected


def make_decode_step(task, batch):
    """The unified prefill+decode step jit — the exact executable
    ``DecodeEngine`` AOT-compiles once per pool geometry and then runs
    for every step of every stream (serving/decode.py). ``batch``
    carries the ``DecodeGeometry`` plus one MIXED-phase round of
    per-slot ``tokens`` (streams × max_chunk lanes) and ``qlens``
    (chunked-prefill rows feed >1 token, decode rows feed 1) — the
    gates certify the single signature both phases share. Returns
    ``(jitted_fn, args, expected_donated)``: the whole carry (KV pools,
    lengths, page tables) is donated — every leaf aliases an output, so
    the step's HBM high-water mark is ONE copy of the paged cache."""
    import jax

    from perceiver_tpu.serving.decode import build_decode_graph

    graph = build_decode_graph(task.build(), batch["geometry"],
                               attn_impl=batch.get("attn_impl", "pallas"))
    params = graph.init_params()
    carry = graph.init_carry()
    args = (params, carry, batch["tokens"], batch["qlens"])
    jitted = jax.jit(graph.fn, donate_argnums=graph.donate_argnums)
    expected = len(jax.tree_util.tree_leaves(carry))
    return jitted, args, expected


def make_sharded_decode_step(task, batch, mesh):
    """The sharded decode step: params tensor-parallel (``model``),
    per-stream rows (tokens/qlens/lengths/page tables) batch-sharded
    over ``data``, and the KV pools replicated — each pool is a shared
    arena indexed by data-local page tables, and at canonical geometry
    it sits far below the replication floor (the replication pass still
    audits it). Lowers the ``"reference"`` attention path: GSPMD
    partitions gathers/einsums, not Pallas calls. Donation survives
    sharding — carry leaves and the outputs they alias carry identical
    specs. Returns ``(jitted_fn, args, expected_donated)``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from perceiver_tpu.parallel.sharding import param_sharding
    from perceiver_tpu.serving.decode import build_decode_graph

    graph = build_decode_graph(task.build(), batch["geometry"],
                               attn_impl=batch.get("attn_impl",
                                                   "reference"))
    params = graph.init_params()
    carry = graph.init_carry()
    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("data"))
    carry_sh = {
        "kv": {name: rep for name in carry["kv"]},
        "lengths": row,
        "page_tables": NamedSharding(mesh, P("data", None)),
    }
    args = (params, carry, batch["tokens"], batch["qlens"])
    # tokens are (streams, max_chunk): rows shard on data, the chunk
    # lanes stay local to the row's device
    tok_sh = NamedSharding(mesh, P("data", None))
    jitted = jax.jit(
        graph.fn, donate_argnums=graph.donate_argnums,
        in_shardings=(param_sharding(params, mesh), carry_sh, tok_sh,
                      row),
        out_shardings=(carry_sh,
                       {name: row for name in graph.output_names}))
    expected = len(jax.tree_util.tree_leaves(carry))
    return jitted, args, expected


def make_sharded_serve_step(task, batch, mesh):
    """The sharded serve-graph jit: the same graph + donation layout
    as ``make_serve_step``, under explicit GSPMD shardings (params
    tensor-parallel on ``model``, request/response batch axes on
    ``data``). Returns ``(jitted_fn, args, expected_donated)``."""
    import jax

    from perceiver_tpu.serving.graphs import (
        build_serve_graph,
        serve_graph_shardings,
    )

    graph = build_serve_graph(task)
    params = graph.init_params()
    p_sh, in_sh, out_sh = serve_graph_shardings(graph, params, mesh)
    args = (params,) + tuple(batch[spec.name] for spec in graph.inputs)
    jitted = jax.jit(graph.fn, donate_argnums=graph.donate_argnums,
                     in_shardings=(p_sh,) + in_sh, out_shardings=out_sh)
    donated_args = tuple(args[i] for i in graph.donate_argnums)
    expected = len(jax.tree_util.tree_leaves(donated_args))
    return jitted, args, expected


def lower_target(target: StepTarget, cache=None,
                 want_compiled: bool = True) -> LoweredStep:
    """Build the target's task + batch, lower its step (train or
    serve), and package the properties the graph passes gate on.

    ``cache`` (a ``perceiver_tpu.cache.ExecutableCache``) consults the
    persistent lowering records first: the key binds the target name
    to the jax/jaxlib versions, the backend topology, and a content
    hash of the whole source tree, so a hit is exactly the text a
    fresh trace of this code would produce — and any code edit is a
    miss. Fresh lowerings are stored back for the next process.

    Mesh targets are also XLA-compiled (collectives exist only in
    optimized HLO); the compiled text rides in the lowering record so
    warm ``check.py`` runs stay compile-free. ``want_compiled=False``
    skips that compile for callers that only need StableHLO (the
    recompile-stability re-lowering)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    key = None
    if cache is not None:
        extra = (target.mesh.descriptor,) if target.mesh else ()
        key = cache.lowering_key(target.name, extra=extra)
        record = cache.load_lowering(key)
        # a record stored by a want_compiled=False lowering of a mesh
        # target has no compiled text — useless to the collective
        # passes, so fall through to a fresh lowering
        usable = record is not None and not (
            target.mesh and want_compiled
            and not record.get("compiled_text"))
        if usable:
            return LoweredStep(
                target=target, text=record["text"],
                expected_donated=int(record["expected_donated"]),
                task_hash=None,
                bytes_accessed=record.get("bytes_accessed"),
                cached=True,
                compiled_text=record.get("compiled_text"))
    task, batch = target.build()
    mesh = target.mesh.build() if target.mesh else None
    if mesh is not None and target.kind == "train":
        from perceiver_tpu.training.spmd import make_sharded_train_step

        step, args = make_sharded_train_step(task, batch, mesh)
        params, opt_state = args[0], args[1]
        expected = len(jax.tree_util.tree_leaves((params, opt_state)))
    elif mesh is not None and target.kind == "serve":
        step, args, expected = make_sharded_serve_step(task, batch, mesh)
    elif mesh is not None and target.kind == "decode":
        step, args, expected = make_sharded_decode_step(task, batch, mesh)
    elif target.kind == "serve":
        step, args, expected = make_serve_step(task, batch)
    elif target.kind == "packed_serve":
        step, args, expected = make_packed_serve_step(task, batch)
    elif target.kind == "decode":
        step, args, expected = make_decode_step(task, batch)
    else:
        step, args = make_train_step(task, batch)
        params, opt_state = args[0], args[1]
        expected = len(jax.tree_util.tree_leaves((params, opt_state)))
    lowered = step.lower(*args)
    compiled_text = None
    if mesh is not None and want_compiled:
        from perceiver_tpu.cache import compile_lowered

        compiled_text = compile_lowered(lowered).as_text()
    result = LoweredStep(target=target, text=lowered.as_text(),
                         expected_donated=expected, task_hash=hash(task),
                         bytes_accessed=cost_bytes_accessed(lowered),
                         compiled_text=compiled_text)
    # a compile-less mesh lowering must not overwrite (or seed) a
    # record — warm runs would then miss compiled text forever
    if cache is not None and not (target.mesh and compiled_text is None):
        from perceiver_tpu.analysis import hlo

        cache.store_lowering(key, {
            "target": target.name,
            "text": result.text,
            "expected_donated": result.expected_donated,
            "bytes_accessed": result.bytes_accessed,
            "fingerprint": hlo.module_fingerprint(result.text),
            "text_hash": hlo.text_hash(result.text),
            **({"compiled_text": compiled_text, "mesh": target.mesh.descriptor}
               if target.mesh else {}),
        })
    return result


# --------------------------------------------------------------------------
# Canonical configs. Shapes mirror bench.py's pinned/headline rungs and
# the runbook configs; vocab/seq match the BASELINE MLM recipe.

def _build_mlm(batch: int = 512, channels: int = 64, seq_len: int = 512,
               vocab: int = 10003, loss_impl: str = "packed"):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=vocab, max_seq_len=seq_len, loss_impl=loss_impl,
        num_latent_channels=channels)
    rng = np.random.default_rng(0)
    data = {
        "input_ids": jnp.asarray(
            rng.integers(3, vocab, (batch, seq_len)), jnp.int32),
        "pad_mask": jnp.zeros((batch, seq_len), bool),
    }
    return task, data


def _build_text_clf(batch: int = 64, seq_len: int = 512,
                    vocab: int = 10003):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.tasks import TextClassifierTask

    task = TextClassifierTask(vocab_size=vocab, max_seq_len=seq_len)
    rng = np.random.default_rng(0)
    data = {
        "input_ids": jnp.asarray(
            rng.integers(3, vocab, (batch, seq_len)), jnp.int32),
        "pad_mask": jnp.zeros((batch, seq_len), bool),
        "label": jnp.asarray(rng.integers(0, 2, (batch,)), jnp.int32),
    }
    return task, data


def _build_img_clf(batch: int = 512):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.tasks import ImageClassifierTask

    task = ImageClassifierTask(
        image_shape=(28, 28, 1), num_classes=10, num_frequency_bands=32,
        num_latents=32, num_latent_channels=128, num_encoder_layers=3,
        num_encoder_self_attention_layers_per_block=3,
        num_decoder_cross_attention_heads=1)
    rng = np.random.default_rng(0)
    data = {
        "image": jnp.asarray(
            rng.normal(0, 1, (batch, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (batch,)), jnp.int32),
    }
    return task, data


def _build_seg(batch: int = 1, side: int = 512):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.tasks import SegmentationTask

    task = SegmentationTask(image_shape=(side, side, 1),
                            query_chunk_size=min(16384, side * side))
    rng = np.random.default_rng(0)
    data = {
        "image": jnp.asarray(
            rng.random((batch, side, side, 1)) *
            (rng.random((batch, side, side, 1)) < 0.01), jnp.float32),
        "label": jnp.asarray(
            rng.integers(0, 3, (batch, side, side)), jnp.int32),
    }
    return task, data


# --------------------------------------------------------------------------
# Serving targets: the serve graph of each task at its largest default
# engine bucket (serving/engine.py defaults: batch ≤ 32, seq ≤ 512 for
# the canonical text recipe) — the shapes steady-state traffic pads
# into, so the budget/dtype/transfer/donation/recompile gates certify
# the executable production actually dispatches. Forward-only, so all
# four lower in seconds.

def _serve_batch_mlm(batch: int = 32, seq_len: int = 512,
                     vocab: int = 10003, channels: int = 64):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.tasks import MaskedLanguageModelTask
    from perceiver_tpu.tokenizer import MASK_TOKEN_ID

    task = MaskedLanguageModelTask(
        vocab_size=vocab, max_seq_len=seq_len,
        num_latent_channels=channels)
    rng = np.random.default_rng(0)
    ids = rng.integers(3, vocab, (batch, seq_len))
    ids[:, ::7] = MASK_TOKEN_ID  # representative fill-mask density
    return task, {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "pad_mask": jnp.zeros((batch, seq_len), bool),
    }


def _serve_batch_text_clf(batch: int = 32, seq_len: int = 512,
                          vocab: int = 10003):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.tasks import TextClassifierTask

    task = TextClassifierTask(vocab_size=vocab, max_seq_len=seq_len)
    rng = np.random.default_rng(0)
    return task, {
        "input_ids": jnp.asarray(
            rng.integers(3, vocab, (batch, seq_len)), jnp.int32),
        "pad_mask": jnp.zeros((batch, seq_len), bool),
    }


def _serve_batch_img_clf(batch: int = 32):
    import jax.numpy as jnp
    import numpy as np

    task, _ = _build_img_clf(batch=batch)
    rng = np.random.default_rng(0)
    return task, {
        "image": jnp.asarray(rng.normal(0, 1, (batch, 28, 28, 1)),
                             jnp.float32),
    }


def _serve_batch_seg(batch: int = 1, side: int = 512):
    import jax.numpy as jnp
    import numpy as np

    task, _ = _build_seg(batch=batch, side=side)
    rng = np.random.default_rng(0)
    img = (rng.random((batch, side, side))
           * (rng.random((batch, side, side)) < 0.01))
    return task, {"image": jnp.asarray(img, jnp.float32)}


SERVING_TARGETS = (
    # headline: the serve graph is pure forward under Policy.bf16 —
    # every dot FLOP must run on bf16 operands, same bar as the
    # headline train step
    StepTarget(name="serve_mlm_b32_s512", build=_serve_batch_mlm,
               kind="serve", headline=True),
    StepTarget(name="serve_text_clf_b32_s512",
               build=_serve_batch_text_clf, kind="serve"),
    StepTarget(name="serve_img_clf_b32", build=_serve_batch_img_clf,
               kind="serve"),
    StepTarget(name="serve_seg_512x512_b1", build=_serve_batch_seg,
               kind="serve"),
)


# Packed (ragged) serving targets: the mixed-length headline workload
# — the same 32 requests serve_mlm_b32_s512 pads to a (32, 512)
# rectangle, packed into one 8192-token buffer (7680 real tokens,
# lengths cycling 64/128/256/512). The hbm_budget pin on these targets
# IS the merge gate for the padding-free claim: the packed executable
# must stay ≥ 25% below the rectangular equivalent's pinned bytes
# (tests/test_graphcheck.py).

def _packed_serve_lengths(rows: int):
    import numpy as np

    return np.array([(64, 128, 256, 512)[i % 4] for i in range(rows)],
                    np.int32)


def _packed_serve_batch(rows: int, tokens: int, vocab: int,
                        mask_every: int = 0):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.tokenizer import MASK_TOKEN_ID, PAD_TOKEN_ID

    lens = _packed_serve_lengths(rows)
    total = int(lens.sum())
    if total > tokens:
        raise ValueError(f"lengths sum {total} exceeds bucket {tokens}")
    rng = np.random.default_rng(0)
    ids = rng.integers(3, vocab, (tokens,))
    if mask_every:
        ids[::mask_every] = MASK_TOKEN_ID
    ids[total:] = PAD_TOKEN_ID
    offs = np.zeros(rows, np.int32)
    offs[1:] = np.cumsum(lens)[:-1]
    return {
        "packed_ids": jnp.asarray(ids, jnp.int32),
        "row_offsets": jnp.asarray(offs, jnp.int32),
        "lengths": jnp.asarray(lens, jnp.int32),
    }


def _serve_batch_mlm_packed(tokens: int = 8192, rows: int = 32,
                            vocab: int = 10003, channels: int = 64):
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=vocab, max_seq_len=512, num_latent_channels=channels)
    # same representative fill-mask density as _serve_batch_mlm
    return task, _packed_serve_batch(rows, tokens, vocab, mask_every=7)


def _serve_batch_text_clf_packed(tokens: int = 8192, rows: int = 32,
                                 vocab: int = 10003):
    from perceiver_tpu.tasks import TextClassifierTask

    task = TextClassifierTask(vocab_size=vocab, max_seq_len=512)
    return task, _packed_serve_batch(rows, tokens, vocab)


PACKED_SERVING_TARGETS = (
    StepTarget(name="serve_mlm_packed_t8192_r32",
               build=_serve_batch_mlm_packed, kind="packed_serve"),
    StepTarget(name="serve_text_clf_packed_t8192_r32",
               build=_serve_batch_text_clf_packed, kind="packed_serve"),
)


# --------------------------------------------------------------------------
# Decode targets: ONE stepped executable per pool geometry — the
# unified step DecodeEngine runs for chunked prefill AND decode. The
# canonical geometry is 8 slots over a 64-page × 16-token shared KV
# pool with 8 chunk lanes, at the BASELINE MLM recipe shapes. The
# batch is deliberately MIXED-phase (half the rows prefill a full
# chunk, half decode one token) so the gates certify the signature
# both phases share. The hbm_budget pin on this target IS the O(1)
# memory gate for the paged-decode claim: the step's bytes accessed
# are geometry-bound (pools + params), independent of how many tokens
# any stream has generated — a regression that makes cost grow with
# sequence position would move the pin.

def _decode_batch_mlm(vocab: int = 10003, seq: int = 512,
                      channels: int = 64, streams: int = 8,
                      num_pages: int = 64, page_size: int = 16,
                      max_chunk: int = 8, attn_impl: str = "pallas",
                      spec_k: int = 0):
    import jax.numpy as jnp
    import numpy as np

    from perceiver_tpu.serving.decode import DecodeGeometry
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=vocab, max_seq_len=seq, num_latent_channels=channels)
    rng = np.random.default_rng(0)
    if spec_k:
        # all three row phases of a speculative engine in one batch:
        # prefill chunk / k+1-lane verify window / plain decode
        pattern = (max_chunk, spec_k + 1, 1)
        qlens = np.array([pattern[i % 3] for i in range(streams)],
                         np.int32)
    else:
        # alternate prefill (full chunk) and decode (1 token) rows
        qlens = np.array([max_chunk if i % 2 == 0 else 1
                          for i in range(streams)], np.int32)
    return task, {
        "geometry": DecodeGeometry(
            max_streams=streams, num_pages=num_pages,
            page_size=page_size, max_seq_len=seq, max_chunk=max_chunk,
            spec_k=spec_k),
        "tokens": jnp.asarray(
            rng.integers(3, vocab, (streams, max_chunk)), jnp.int32),
        "qlens": jnp.asarray(qlens),
        "attn_impl": attn_impl,
    }


def _decode_batch_mlm_spmd():
    # reference attention: GSPMD partitions gathers, not Pallas calls;
    # vocab/seq follow the SPMD serve rung (_SPMD_MLM) so the model
    # axis divides the vocab projection evenly
    return _decode_batch_mlm(vocab=8192, seq=256, num_pages=48,
                             attn_impl="reference")


def _multitenant_qlens(streams: int, max_chunk: int):
    """The per-slot qlens a mixed-TENANT round actually feeds: three
    tenants (weights 2/1/1) share the step's token budget through the
    same ``weighted_fair_shares`` split the continuous batcher's
    per-tenant planner uses (``serving/batcher.py take(tenant_budgets=
    ...)``) — each tenant prefills full chunks until its fair share is
    spent, then its remaining rows decode one token. Deterministic by
    construction (no RNG), so the target re-lowers byte-identically."""
    import numpy as np

    from perceiver_tpu.serving.tenancy import weighted_fair_shares

    owners = ["a" if i < streams // 2 else
              "b" if i < 3 * streams // 4 else "c"
              for i in range(streams)]
    budget = streams * max_chunk // 2
    remaining = weighted_fair_shares(
        budget, {"a": 2.0, "b": 1.0, "c": 1.0})
    qlens = []
    for tenant in owners:
        q = max(1, min(max_chunk, remaining[tenant]))
        remaining[tenant] = max(0, remaining[tenant] - q)
        qlens.append(q)
    return np.array(qlens, np.int32)


def _decode_batch_mlm_multitenant(vocab: int = 10003, seq: int = 512,
                                  num_pages: int = 64,
                                  attn_impl: str = "pallas"):
    """The canonical MULTI-TENANT decode round: same geometry as
    ``decode_mixed_mlm_r8_p64x16_q8``, but the qlens are the
    fair-share plan of three tenants sharing the step (see
    ``_multitenant_qlens``). Tenancy is host-side state only — quota
    ledgers, fair-share planning, and shed decisions all happen before
    tokens reach the device — so this target MUST lower to the
    byte-identical module of its single-tenant twin
    (tests/test_graphcheck.py pins the fingerprint equality). The
    pinned hbm budget is therefore the same O(1) gate: admitting a
    tenant costs zero compiles and zero step-cost growth."""
    task, batch = _decode_batch_mlm(vocab=vocab, seq=seq,
                                    num_pages=num_pages,
                                    attn_impl=attn_impl)
    import jax.numpy as jnp

    geometry = batch["geometry"]
    batch["qlens"] = jnp.asarray(
        _multitenant_qlens(geometry.max_streams, geometry.max_chunk))
    return task, batch


def _decode_batch_mlm_multitenant_spmd():
    return _decode_batch_mlm_multitenant(vocab=8192, seq=256,
                                         num_pages=48,
                                         attn_impl="reference")


def _decode_batch_mlm_spec():
    # the speculative verify executable: k=4 drafted lanes + feedback
    # fold 5 latent-rebuild windows per stream into the kernel row
    # axis — the hbm pin certifies the widened step stays
    # geometry-bound (same pools, W× latents only)
    return _decode_batch_mlm(spec_k=4)


def _decode_batch_mlm_spec_spmd():
    return _decode_batch_mlm(vocab=8192, seq=256, num_pages=48,
                             attn_impl="reference", spec_k=4)


DECODE_TARGETS = (
    StepTarget(name="decode_mixed_mlm_r8_p64x16_q8",
               build=_decode_batch_mlm, kind="decode"),
    StepTarget(name="decode_spec_mlm_r8_p64x16_q8_k4",
               build=_decode_batch_mlm_spec, kind="decode"),
    StepTarget(name="decode_multitenant_mlm_r8_p64x16_q8",
               build=_decode_batch_mlm_multitenant, kind="decode",
               signature_twin="decode_mixed_mlm_r8_p64x16_q8"),
)


# --------------------------------------------------------------------------
# Sharded (SPMD) targets: the first mesh rung — dp2×tp2 over 4 CPU
# devices (virtual via --xla_force_host_platform_device_count; the
# same specs place on a v4-8 slice unchanged). Shapes shrink from the
# headline rung so lower+compile stays seconds, and vocab drops to
# 8192 so the model axis divides the vocab projection evenly (the odd
# 10003 vocab would fall back to replication — exactly what the
# replication pass exists to flag).

DP2_TP2 = MeshSpec(axes=(("data", 2), ("model", 2)))

_SPMD_MLM = dict(batch=32, channels=64, seq_len=256, vocab=8192)


def _build_mlm_spmd():
    return _build_mlm(loss_impl="packed", **_SPMD_MLM)


def _serve_batch_mlm_spmd():
    return _serve_batch_mlm(**_SPMD_MLM)


# the input embedding table (vocab×C fp32) is replicated by design:
# the sharding rules keep embeddings whole on every device (read-only
# per step, gathered by token id), and only its ZeRO moments shard
_SPMD_MLM_EMBED_ALLOW = (
    ReplicationAllow(
        type="8192x64xf32", max_count=2,
        reason="input-embedding table (and its aliased output copy) — "
               "replicated by design per parallel/sharding.py; its "
               "optimizer moments ARE data-sharded (ZeRO)"),
)

SHARDED_TARGETS = (
    StepTarget(name="mlm_spmd_b32_s256_dp2_tp2", build=_build_mlm_spmd,
               mesh=DP2_TP2, transfer_allow=_MLM_OVERFLOW_CALLBACK,
               replication_allow=_SPMD_MLM_EMBED_ALLOW),
    StepTarget(name="serve_mlm_spmd_b32_s256_dp2_tp2",
               build=_serve_batch_mlm_spmd, kind="serve", mesh=DP2_TP2,
               replication_allow=_SPMD_MLM_EMBED_ALLOW),
    StepTarget(name="decode_mixed_mlm_spmd_r8_p48x16_q8_dp2_tp2",
               build=_decode_batch_mlm_spmd, kind="decode",
               mesh=DP2_TP2,
               replication_allow=_SPMD_MLM_EMBED_ALLOW,
               # the reference paged-attention path upcasts q/k/v to
               # fp32 (ops/paged_attention.py) to match the Pallas
               # kernel's fp32 online-softmax accumulator bit-for-bit
               # in tests — two QK^T and two PV dots per step (layer_1
               # + the scanned layer_n), ~9% of step dot-FLOPs each
               dtype_allow=(
                   DtypeAllow(
                       dtype="f32", max_count=4,
                       reason="reference paged-attention fp32 "
                              "accumulation — parity twin of the "
                              "Pallas kernel's fp32 online-softmax "
                              "accumulator; production decode lowers "
                              "the bf16 Pallas kernel instead"),)),
    StepTarget(name="decode_multitenant_mlm_spmd_r8_p48x16_q8_dp2_tp2",
               build=_decode_batch_mlm_multitenant_spmd, kind="decode",
               signature_twin="decode_mixed_mlm_spmd_r8_p48x16_q8_dp2_tp2",
               mesh=DP2_TP2,
               replication_allow=_SPMD_MLM_EMBED_ALLOW,
               # same reference-path fp32 parity twin as the other
               # spmd decode targets — the multi-tenant qlens plan is
               # host-side data, so the lowered dots are unchanged
               dtype_allow=(
                   DtypeAllow(
                       dtype="f32", max_count=4,
                       reason="reference paged-attention fp32 "
                              "accumulation — parity twin of the "
                              "Pallas kernel's fp32 online-softmax "
                              "accumulator; production decode lowers "
                              "the bf16 Pallas kernel instead"),)),
    StepTarget(name="decode_spec_mlm_spmd_r8_p48x16_q8_k4_dp2_tp2",
               build=_decode_batch_mlm_spec_spmd, kind="decode",
               mesh=DP2_TP2,
               replication_allow=_SPMD_MLM_EMBED_ALLOW,
               # window tiling folds the k+1 verify lanes into the row
               # axis of the SAME attention dots, so the fp32 count is
               # unchanged from the non-speculative twin
               dtype_allow=(
                   DtypeAllow(
                       dtype="f32", max_count=4,
                       reason="reference paged-attention fp32 "
                              "accumulation — parity twin of the "
                              "Pallas kernel's fp32 online-softmax "
                              "accumulator; production decode lowers "
                              "the bf16 Pallas kernel instead"),)),
)


# The headline MLM rung (bench.py _LADDER[0]: B=512/C=64/packed) plus
# one target per remaining task at its canonical shapes, plus the
# serving targets. "fast" targets keep tracing under a few seconds for
# the tier-1 subset; --all adds the expensive ones (the 262k-query
# segmentation train step — its forward-only serve twin stays fast).
CANONICAL_TARGETS = (
    StepTarget(name="mlm_b512_c64_packed", build=_build_mlm,
               headline=True, transfer_allow=_MLM_OVERFLOW_CALLBACK),
    StepTarget(name="text_clf_b64", build=_build_text_clf),
    StepTarget(name="img_clf_b512", build=_build_img_clf),
    StepTarget(name="seg_512x512_b1", build=_build_seg),
) + (SERVING_TARGETS + PACKED_SERVING_TARGETS + DECODE_TARGETS
     + SHARDED_TARGETS)

# --fast also drops the mesh targets: they are the only targets that
# must be XLA-COMPILED (collectives appear post-partitioning), and the
# fast tier exists to keep the tier-1 wall clock bounded. --all and
# --graph still run them, which is where the shardcheck gates live.
FAST_TARGETS = tuple(t for t in CANONICAL_TARGETS
                     if t.name != "seg_512x512_b1" and t.mesh is None)
