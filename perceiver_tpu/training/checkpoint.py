"""Checkpoint save/restore on orbax (SURVEY §5 checkpoint/resume).

Covers the reference's three mechanisms:

1. Best-k retention monitored on ``val_loss`` (Lightning
   ``ModelCheckpoint``, ``trainer.yaml:10-14``) with hparams embedded —
   ``CheckpointHook``.
2. Cross-task transfer restore (``lightning.py:144-149``):
   ``restore_params(path)`` loads a checkpoint's params pytree so a
   task can graft the encoder subtree or the whole model.
3. Manual one-shot save/load (``run.py:278-281``): ``save_params``.

Orbax writes are async-capable and multi-host-safe (each host writes
its shard), which is the TPU-native answer to preemption: frequent
cheap checkpoints instead of elastic recovery (the reference has none
either, SURVEY §5 failure detection).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from perceiver_tpu.training.state import TrainState


def _abs(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


class CheckpointHook:
    """val_loss-monitored best-k checkpointing of the full TrainState."""

    def __init__(self, directory: str, max_to_keep: int = 1,
                 monitor: str = "val_loss", mode: str = "min",
                 hparams: Optional[dict] = None):
        self.directory = _abs(directory)
        self.monitor = monitor
        best_fn = (lambda m: m[monitor]) if monitor else None
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=best_fn,
                best_mode=mode,
                enable_async_checkpointing=True))
        if hparams is not None:
            os.makedirs(self.directory, exist_ok=True)
            with open(os.path.join(self.directory, "hparams.json"),
                      "w") as f:
                json.dump(hparams, f, indent=2, default=str)

    def save(self, step: int, state: TrainState, metrics: dict):
        metrics = {k: float(v) for k, v in metrics.items()}
        self._mgr.save(step, args=ocp.args.StandardSave(
            {"params": state.params, "opt_state": state.opt_state,
             "rng": jax.random.key_data(state.rng), "step": state.step}),
            metrics=metrics)

    def restore_latest(self, template_state: TrainState
                       ) -> Optional[TrainState]:
        step = self._mgr.latest_step()
        if step is None:
            return None
        return self.restore(step, template_state)

    def restore_params_and_step(self, template_state: TrainState
                                ) -> Optional[TrainState]:
        """Partial resume for a checkpoint whose optimizer state no
        longer matches the current optimizer/scheduler config (e.g.
        the schedule was changed between runs): restore params + rng +
        step, keep the template's freshly initialized opt_state."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        got = _partial_restore(
            os.path.join(self.directory, str(step), "default"),
            {"params": template_state.params,
             "rng": jax.random.key_data(template_state.rng),
             "step": template_state.step})
        return TrainState(params=got["params"],
                          opt_state=template_state.opt_state,
                          rng=jax.random.wrap_key_data(got["rng"]),
                          step=got["step"])

    def restore(self, step: int, template_state: TrainState) -> TrainState:
        template = {
            "params": template_state.params,
            "opt_state": template_state.opt_state,
            "rng": jax.random.key_data(template_state.rng),
            "step": template_state.step,
        }
        got = self._mgr.restore(step,
                                args=ocp.args.StandardRestore(template))
        return TrainState(params=got["params"],
                          opt_state=got["opt_state"],
                          rng=jax.random.wrap_key_data(got["rng"]),
                          step=got["step"])

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def save_params(path: str, params: Any, hparams: Optional[dict] = None):
    """One-shot params save (the ``run.py:278-281`` analogue).
    Overwrites like ``torch.save`` — a rerun into the same directory
    must not crash at the end of training."""
    path = _abs(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "params"), params, force=True)
    if hparams is not None:
        with open(os.path.join(path, "hparams.json"), "w") as f:
            json.dump(hparams, f, indent=2, default=str)


def _partial_restore(path: str, item: dict) -> dict:
    """Typed partial restore of selected subtrees from a checkpoint
    step's ``default`` item dir (a save may hold more than the caller
    wants — or can type — e.g. an opt_state from a different optimizer
    config).

    Orbax's native ``partial_restore`` kwarg only exists from the 0.9
    line; this image ships 0.7, where the supported spelling of "drop
    checkpoint subtrees absent from my template" is an empty
    ``transforms`` dict (fallback-to-item semantics). Try the modern
    kwarg first so an orbax upgrade keeps working, then degrade."""
    with ocp.PyTreeCheckpointer() as ckptr:
        restore_args = ocp.checkpoint_utils.construct_restore_args(item)
        try:
            args = ocp.args.PyTreeRestore(
                item=item, restore_args=restore_args,
                partial_restore=True)
        except TypeError:
            args = ocp.args.PyTreeRestore(
                item=item, restore_args=restore_args, transforms={})
        return ckptr.restore(path, args=args)


def restore_params(path: str, template: Any = None) -> Any:
    """Load a params pytree from either a ``save_params`` directory or a
    ``CheckpointHook`` step directory (transfer-learning source,
    ``lightning.py:144-149``). ``template`` (a params pytree) pins
    shapes/dtypes for a safe typed restore; without it orbax falls back
    to the on-disk metadata."""
    path = _abs(path)
    # (checkpoint dir, template shape): save_params stores the bare
    # params tree; CheckpointHook steps store {params, opt_state, ...}
    # — only params is restored from those (partial restore)
    candidates = [(os.path.join(path, "params"), False)]
    if os.path.isdir(path):
        # CheckpointHook layout: <dir>/<step>/default/... → pick best/latest
        steps = sorted(int(d) for d in os.listdir(path) if d.isdigit())
        candidates += [(os.path.join(path, str(s), "default"), True)
                       for s in reversed(steps)]
    for c, wrapped in candidates:
        if not os.path.isdir(c):
            continue
        if template is not None and wrapped:
            # hook layout stores {params, opt_state, rng, step}; only
            # params is wanted (and only its template is available)
            got = _partial_restore(c, {"params": template})
        else:
            with ocp.StandardCheckpointer() as ckptr:
                got = ckptr.restore(c, template)
        return got.get("params", got) if isinstance(got, dict) \
            else got
    raise FileNotFoundError(f"No checkpoint found under {path}")
