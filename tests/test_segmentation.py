"""LArTPC segmentation: label remap, occupancy filter, weighted loss,
end-to-end standalone app smoke run (SURVEY §3.4 parity)."""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.data.lartpc import (
    load_lartpc,
    load_npz_events,
    min_pixels_for,
    remap_labels,
    synthetic_events,
)
from perceiver_tpu.ops.policy import Policy
from perceiver_tpu.tasks.segmentation import SegmentationTask

FP32 = Policy.fp32()


def test_remap_labels_reference_semantics():
    # run.py:62-65: >=0 shifted up, negatives → 0, {2}→1, {>=3}→2
    raw = np.array([-1, 0, 1, 2, 3, 4])
    np.testing.assert_array_equal(remap_labels(raw), [0, 1, 1, 2, 2, 2])


def test_synthetic_events_classes_and_filter():
    ds = synthetic_events(4, size=64, seed=0)
    labels = ds.fields["label"]
    images = ds.fields["image"]
    assert set(np.unique(labels)) <= {0, 1, 2}
    # nonzero pixels are exactly the non-background pixels
    np.testing.assert_array_equal(images > 0, labels > 0)
    assert min_pixels_for(512) == 2621  # run.py:125
    assert min_pixels_for(64) == 2621 * 64 * 64 // (512 * 512)


def test_load_npz_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    img = rng.uniform(0, 10, (3, 16, 16)).astype(np.float32)
    raw = rng.integers(-1, 5, (3, 16, 16))
    path = tmp_path / "events.npz"
    np.savez(path, image=img, label=raw)
    ds = load_npz_events([str(path)])
    np.testing.assert_array_equal(ds.fields["label"], remap_labels(raw))
    assert ds.fields["image"].dtype == np.float32


def test_load_lartpc_synthetic_applies_filter():
    ds = load_lartpc(None, size=32, num_synthetic=6, seed=1)
    mp = min_pixels_for(32)
    assert all((img > 0).sum() > mp for img in ds.fields["image"])


@pytest.fixture(scope="module")
def tiny_task():
    task = SegmentationTask(
        image_shape=(16, 16, 1), num_latents=8, num_latent_channels=16,
        num_encoder_layers=2,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=2,
        num_encoder_self_attention_heads=2)
    model = task.build()
    params = model.init(jax.random.key(0))
    return task, model, params


def test_segmentation_forward_shape(tiny_task):
    task, model, params = tiny_task
    images = jnp.asarray(
        np.random.default_rng(0).uniform(0, 5, (2, 16, 16)), jnp.float32)
    logits = task.forward(model, params, images, policy=FP32)
    assert logits.shape == (2, 256, 3)


def test_query_chunking_is_exact(tiny_task):
    task, model, params = tiny_task
    chunked_task = SegmentationTask(
        image_shape=(16, 16, 1), num_latents=8, num_latent_channels=16,
        num_encoder_layers=2,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=2,
        num_encoder_self_attention_heads=2, query_chunk_size=64)
    chunked = chunked_task.build()
    images = jnp.asarray(
        np.random.default_rng(1).uniform(0, 5, (1, 16, 16)), jnp.float32)
    a = task.forward(model, params, images, policy=FP32)
    b = chunked_task.forward(chunked, params, images, policy=FP32)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


def test_weighted_loss_ignores_background(tiny_task):
    task, model, params = tiny_task
    # all-background labels → weight sum ~0 → loss 0, acc masked out
    images = jnp.ones((1, 16, 16), jnp.float32)
    batch = {"image": images,
             "label": jnp.zeros((1, 16, 16), jnp.int32)}
    loss, metrics = task.loss_and_metrics(model, params, batch,
                                          policy=FP32)
    assert float(loss) == pytest.approx(0.0, abs=1e-6)

    # non-background labels contribute; loss ≈ -log p averaged with
    # torch's summed-weight normalization
    batch2 = {"image": images,
              "label": jnp.ones((1, 16, 16), jnp.int32)}
    loss2, m2 = task.loss_and_metrics(model, params, batch2, policy=FP32)
    assert float(loss2) > 0
    assert 0.0 <= float(m2["acc1"]) <= 1.0


def test_run_script_end_to_end(tmp_path, monkeypatch):
    """The full standalone loop on synthetic 32×32 events — the
    reference's only exercise path for this app was actually running
    it (SURVEY §4)."""
    import run as run_mod

    monkeypatch.setattr(
        sys, "argv",
        ["run.py", "--size", "32", "--num-synthetic", "8",
         "--epochs", "1", "--batch-size", "2", "--val-events", "2",
         "--precision", "32",
         "--logdir", str(tmp_path / "logs"),
         "--ckpt-dir", str(tmp_path / "ckpt")])
    run_mod.main()
    ckpts = list((tmp_path / "ckpt").glob("model_*"))
    assert ckpts, "final checkpoint not written"
    events = list((tmp_path / "logs").glob("events.out.tfevents.*"))
    assert events, "TensorBoard event file not written"


def test_load_lartpc_rejects_empty_file_list():
    with pytest.raises(ValueError, match="Empty file list"):
        load_lartpc([], size=32)


def test_run_script_val_events_zero(tmp_path, monkeypatch):
    """--val-events 0 must train on everything and skip validation,
    not invert the split."""
    import run as run_mod

    monkeypatch.setattr(
        sys, "argv",
        ["run.py", "--size", "32", "--num-synthetic", "8",
         "--epochs", "1", "--batch-size", "2", "--val-events", "0",
         "--precision", "32",
         "--logdir", str(tmp_path / "logs"),
         "--ckpt-dir", str(tmp_path / "ckpt")])
    run_mod.main()
    assert list((tmp_path / "ckpt").glob("model_*"))


def test_uresnet_task_loss_and_state():
    from perceiver_tpu.tasks.segmentation import UResNetSegmentationTask

    task = UResNetSegmentationTask(image_shape=(32, 32, 1), inplanes=4)
    model = task.build()
    params, state = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = {"image": jnp.asarray(rng.uniform(0, 5, (2, 32, 32)),
                                  jnp.float32),
             "label": jnp.asarray(rng.integers(0, 3, (2, 32, 32)),
                                  jnp.int32)}
    loss, metrics, new_state = task.loss_and_metrics(
        model, (params, state), batch, train=True, policy=FP32)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert set(metrics) >= {"loss", "acc", "acc1", "acc2"}
    # BN state moved in train mode
    assert not np.allclose(
        np.asarray(state["stem1"]["bn"]["mean"]),
        np.asarray(new_state["stem1"]["bn"]["mean"]))


def test_run_script_uresnet_end_to_end(tmp_path, monkeypatch):
    """--model uresnet: the dense U-ResNet path trains, threads BN
    state, and checkpoints."""
    import run as run_mod

    monkeypatch.setattr(
        sys, "argv",
        ["run.py", "--size", "32", "--num-synthetic", "8",
         "--model", "uresnet", "--inplanes", "4",
         "--epochs", "1", "--batch-size", "2", "--val-events", "2",
         "--precision", "32",
         "--logdir", str(tmp_path / "logs"),
         "--ckpt-dir", str(tmp_path / "ckpt")])
    run_mod.main()
    assert list((tmp_path / "ckpt").glob("model_*"))
