#!/usr/bin/env python
"""Chaos harness: run the fault matrix against a tiny preset and prove
every defense (docs/RESILIENCE.md).

Each scenario arms one deterministic fault (``resilience/faults.py``)
in a FRESH subprocess (the ``PERCEIVER_FAULTS`` env seam — exactly how
a chaos job arms a production binary) and asserts the run still
reaches its target: training hits its target step with
verified-checkpoint resume where resumes are involved, and serving
answers every request with a result or a *typed* error — zero
unhandled exceptions, zero silent data loss. ``kill_save`` goes one
step further and SIGKILLs a training victim mid-checkpoint-save in a
grand-child process (crash-only checkpointing).

Emits one ``bench.py``-format JSON line per scenario::

    {"metric": "chaos_serve_dispatch", "value": 1.0, "unit":
     "survived", "vs_baseline": null, "detail": {"faults_fired": ...,
     "recovery_s": ..., ...}}

plus a ``chaos_matrix`` summary line; exits non-zero iff any scenario
failed. ``--fast`` runs the tier-1 subset
(``tests/test_chaos.py`` mirrors the ``check.py`` subprocess-gate
pattern); ``--fleet``/``--fleet-fast`` run the multi-process fleet
matrix, and ``--dist``/``--dist-fast`` the multi-host matrix
(process-group training recovery, coordinator loss, group-replica
failover, two-phase cutover kill)::

    JAX_PLATFORMS=cpu python scripts/chaos.py --fast
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The determinism gates (race_*, prefix_evict_under_load) assert
# bitwise token equality between replayed schedules and a serial
# reference. A persistent XLA compilation cache inherited from the
# host (bench.py exports one) deserializes executables compiled under
# a DIFFERENT flag environment, which shifts near-tied logits on the
# degenerate scenario models — drop it before jax initializes so
# every chaos process compiles its own executables from scratch.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

TARGET_STEP = 6


def _tiny_image_task():
    from perceiver_tpu.tasks import ImageClassifierTask

    return ImageClassifierTask(
        image_shape=(28, 28, 1), num_classes=10, num_frequency_bands=4,
        num_latents=4, num_latent_channels=16, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_decoder_cross_attention_heads=1)


def _make_trainer(tmp: str, tag: str, **overrides):
    from perceiver_tpu.data import MNISTDataModule
    from perceiver_tpu.training import Trainer, TrainerConfig

    dm = MNISTDataModule(data_dir=os.path.join(tmp, "data"),
                         batch_size=16, synthetic_train_size=96,
                         synthetic_test_size=32)
    cfg = dict(max_steps=TARGET_STEP, max_epochs=8,
               num_sanity_val_steps=0, log_every_n_steps=1,
               default_root_dir=os.path.join(tmp, f"logs_{tag}"),
               enable_checkpointing=False, prefetch_batches=0)
    cfg.update(overrides)
    return Trainer(_tiny_image_task(), dm, TrainerConfig(**cfg),
                   optimizer_init={"class_path": "AdamW",
                                   "init_args": {"lr": 1e-3}})


def _finite(state) -> bool:
    import jax
    import numpy as np

    return all(bool(np.isfinite(np.asarray(leaf)).all())
               for leaf in jax.tree.leaves(state.params)
               if np.issubdtype(np.asarray(leaf).dtype, np.floating))


# --- scenarios (run in a fresh subprocess each) ------------------------------


def scenario_loader_crash(tmp: str) -> dict:
    """Prefetch producer raises twice; the supervisor restarts it with
    backoff and the run still reaches its target step."""
    trainer = _make_trainer(tmp, "loader", prefetch_batches=2)
    state = trainer.fit()
    assert int(state.step) == TARGET_STEP, int(state.step)
    assert _finite(state)
    return {"target_step": TARGET_STEP, "reached": int(state.step)}


def scenario_nan_skip(tmp: str) -> dict:
    """Two isolated non-finite steps are skipped (no parameter update,
    counter metric) and training completes with finite params."""
    trainer = _make_trainer(tmp, "nan", nonfinite_policy="skip",
                            nonfinite_streak=3)
    state = trainer.fit()
    assert int(state.step) == TARGET_STEP, int(state.step)
    assert trainer._guard.skipped_total == 2, trainer._guard.skipped_total
    assert trainer._guard.rewinds == 0
    assert _finite(state)
    from perceiver_tpu.obs import events as events_mod

    skip_events = events_mod.default_log().events("guard_skip")
    assert len(skip_events) == 2, skip_events  # one typed event per skip
    return {"target_step": TARGET_STEP, "reached": int(state.step),
            "skipped_steps": trainer._guard.skipped_total,
            "skip_events": len(skip_events)}


def scenario_nan_rewind(tmp: str) -> dict:
    """A streak of bad steps triggers restore of the verified anchor
    checkpoint + deterministic data rewind; the fault window expires
    during the replay and the run completes."""
    trainer = _make_trainer(tmp, "rewind", max_steps=8,
                            nonfinite_policy="skip", nonfinite_streak=3,
                            nonfinite_max_rewinds=2)
    state = trainer.fit()
    assert int(state.step) == 8, int(state.step)
    assert trainer._guard.rewinds >= 1
    assert _finite(state)
    return {"target_step": 8, "reached": int(state.step),
            "rewinds": trainer._guard.rewinds,
            "skipped_steps": trainer._guard.skipped_total}


def _checkpointed_run(tmp: str, tag: str, max_steps: int):
    trainer = _make_trainer(tmp, tag, max_steps=max_steps,
                            enable_checkpointing=True, save_top_k=2)
    state = trainer.fit()
    return trainer, state


def scenario_truncated_ckpt(tmp: str) -> dict:
    """The newest checkpoint's blob is truncated after its manifest was
    sealed (bit rot); resume detects the mismatch, falls back to the
    newest VERIFIED step, and still reaches the target."""
    import warnings

    from perceiver_tpu.training.checkpoint import CheckpointHook

    trainer, _ = _checkpointed_run(tmp, "trunc", max_steps=10)
    ckpt_dir = os.path.join(trainer.log_dir, "checkpoints")
    hook = CheckpointHook(ckpt_dir, monitor="")
    steps = hook._steps()
    assert len(steps) >= 2, steps
    statuses = {s: hook.verify(s) for s in steps}
    assert statuses[steps[0]] == "corrupt", statuses  # fault landed
    assert statuses[steps[1]] == "verified", statuses

    resume = _make_trainer(tmp, "trunc_resume", max_steps=12,
                           resume_from_checkpoint=ckpt_dir)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        state = resume.fit()
    assert any("manifest" in str(w.message) for w in caught), \
        [str(w.message) for w in caught]
    assert int(state.step) == 12, int(state.step)
    return {"steps": {str(k): v for k, v in statuses.items()},
            "resumed_from": steps[1], "reached": int(state.step)}


def scenario_kill_save(tmp: str) -> dict:
    """SIGKILL a training victim mid-checkpoint-save (grand-child
    process, crash-only); resume from what survived — the newest step
    that is committed and not provably corrupt — and reach the target.
    """
    env = dict(os.environ,
               PERCEIVER_FAULTS="ckpt.kill_during_save@at=1",
               PERCEIVER_TPU_OFFLINE="1")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--scenario",
         "kill_save_victim", "--tmp", tmp],
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stdout, proc.stderr)

    from perceiver_tpu.training.checkpoint import CheckpointHook
    log_root = os.path.join(tmp, "logs_killvictim", "default")
    versions = sorted(os.listdir(log_root))
    ckpt_dir = os.path.join(log_root, versions[-1], "checkpoints")
    hook = CheckpointHook(ckpt_dir, monitor="")
    steps = hook._steps()
    assert steps, "victim died before any checkpoint committed"
    survivor = hook._newest_restorable_step()
    assert survivor is not None and hook.verify(survivor) != "corrupt"

    resume = _make_trainer(tmp, "kill_resume", max_steps=survivor + 3,
                           resume_from_checkpoint=ckpt_dir)
    state = resume.fit()
    assert int(state.step) == survivor + 3, int(state.step)
    assert _finite(state)
    return {"victim_rc": proc.returncode, "committed_steps": steps,
            "resumed_from": survivor, "reached": int(state.step)}


def scenario_kill_save_victim(tmp: str) -> dict:
    """(grand-child) train with checkpointing until the armed
    kill-during-save fault SIGKILLs this process."""
    _checkpointed_run(tmp, "killvictim", max_steps=25)
    raise AssertionError("victim survived its kill fault")


def scenario_preempt(tmp: str) -> dict:
    """An injected preemption notice saves full state to
    checkpoints-preempt (manifest-sealed) and stops cleanly; resume
    picks it up and reaches the target."""
    from perceiver_tpu.training.checkpoint import CheckpointHook

    trainer = _make_trainer(tmp, "preempt", max_steps=20)
    trainer.fit()
    stopped_at = trainer.global_step
    assert 0 < stopped_at < 20, stopped_at
    preempt_dir = os.path.join(trainer.log_dir, "checkpoints-preempt")
    hook = CheckpointHook(preempt_dir, monitor="")
    assert hook.verify(stopped_at) == "verified"

    resume = _make_trainer(tmp, "preempt_resume",
                           max_steps=stopped_at + 3,
                           resume_from_checkpoint=preempt_dir)
    state = resume.fit()
    assert int(state.step) == stopped_at + 3, int(state.step)
    return {"preempted_at": stopped_at, "reached": int(state.step)}


def scenario_serve_dispatch(tmp: str) -> dict:
    """Serve-dispatch failures: the batch fails with per-request typed
    errors, the bucket's breaker opens (requests get typed Unavailable
    without hanging), a half-open probe recovers it, and health walks
    READY → UNAVAILABLE → READY. Zero unhandled exceptions."""
    import numpy as np

    from perceiver_tpu.serving import (
        BatchError,
        HealthState,
        MicroBatcher,
        ServingEngine,
        Unavailable,
        materialize,
    )
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=128, max_seq_len=32, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")
    engine = ServingEngine(task, batch_buckets=(1,), seq_buckets=(16,),
                           breaker_failure_threshold=2,
                           breaker_reset_s=0.25)
    assert engine.health.state is HealthState.READY

    def runner(payloads):
        res = engine.dispatch(payloads[0])
        return [materialize(res, engine.graph)]

    batcher = MicroBatcher(runner, max_batch=1, max_delay_ms=0.5,
                           metrics=engine.metrics)
    rng = np.random.default_rng(0)
    arrays = {"input_ids": rng.integers(3, 128, (1, 16)).astype(np.int32),
              "pad_mask": np.zeros((1, 16), bool)}

    counts = {"ok": 0, "batch_error": 0, "unavailable": 0}
    states_seen = {engine.health.state}
    first_failure_t = None
    recovered_t = None
    deadline = time.monotonic() + 30.0
    try:
        while time.monotonic() < deadline:
            try:
                out = batcher.submit(dict(arrays)).result(timeout=30)
                assert "topk_ids" in out
                counts["ok"] += 1
                if first_failure_t is not None and recovered_t is None:
                    recovered_t = time.monotonic()
                if recovered_t is not None and counts["ok"] >= 3:
                    break
            except Unavailable:
                counts["unavailable"] += 1
                if first_failure_t is None:
                    first_failure_t = time.monotonic()
                time.sleep(0.05)
            except BatchError:
                counts["batch_error"] += 1
                if first_failure_t is None:
                    first_failure_t = time.monotonic()
            states_seen.add(engine.health.state)
    finally:
        batcher.close()
    states_seen.add(engine.health.state)

    assert counts["batch_error"] >= 2, counts      # injected failures
    assert counts["unavailable"] >= 1, counts      # breaker opened
    assert recovered_t is not None, counts         # ...and recovered
    assert engine.health.state is HealthState.READY
    assert HealthState.UNAVAILABLE in states_seen  # sole bucket open
    m = engine.metrics
    assert m.get("serving_failed_batches_total").value >= 2
    assert m.get("serving_unavailable_total").value >= 1
    return {"requests": counts,
            "recovery_s": round(recovered_t - first_failure_t, 4),
            "health_states": sorted(s.name for s in states_seen),
            "failed_batches":
                m.get("serving_failed_batches_total").value}


# --- fleet scenarios (docs/SERVING.md "Fleet") -------------------------------
#
# Each builds a real multi-process fleet (router + supervisor +
# replica subprocesses) inside the scenario child, runs concurrent
# traffic through it while one fault lands, and asserts ZERO dropped
# requests: every submitted request resolves with a result or a typed
# ServingError — never a hang, never a raw traceback.

_FLEET_TASK_KWARGS = dict(
    vocab_size=110, max_seq_len=32, num_latents=4,
    num_latent_channels=8, num_encoder_layers=1,
    num_encoder_self_attention_layers_per_block=1,
    num_encoder_cross_attention_heads=1,
    num_encoder_self_attention_heads=1,
    num_decoder_cross_attention_heads=1, loss_impl="dense")


def _fleet_store(tmp: str, versions=("v1", "v2")):
    """Publish fresh-init params versions into a sealed store."""
    from perceiver_tpu.serving.graphs import build_serve_graph
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    from perceiver_tpu.training.checkpoint import ParamsVersionStore

    graph = build_serve_graph(
        MaskedLanguageModelTask(**_FLEET_TASK_KWARGS))
    store = ParamsVersionStore(os.path.join(tmp, "store"))
    for seed, version in enumerate(versions):
        store.publish(version, graph.init_params(seed),
                      set_current=(seed == 0))
    return store


def _fleet_spec(store) -> dict:
    return {"task_class": "MaskedLanguageModelTask",
            "task_kwargs": _FLEET_TASK_KWARGS,
            "batch_buckets": [4], "seq_buckets": [16],
            "store_dir": store.directory, "version": "v1", "seed": 0}


def _start_fleet(tmp: str, store, *, replicas: int,
                 per_replica_env=None, dispatch_timeout_s: float = 15.0,
                 max_restarts: int = 3, group_size: int = 1):
    from perceiver_tpu.fleet import Fleet

    # replicas share one persistent exec cache: the first spin-up
    # compiles and stores, the rest deserialize (zero-compile)
    os.environ.setdefault("PERCEIVER_EXEC_CACHE",
                          os.path.join(tmp, "exec_cache"))
    spec = _fleet_spec(store)
    if group_size > 1:
        # each fleet replica becomes a process GROUP of this many
        # members (distributed/serving_group.py); per_replica_env keys
        # of the form "r0.m1" then arm a fault on ONE member
        spec["group_size"] = group_size
    return Fleet(spec, os.path.join(tmp, "fleet"),
                 replicas=replicas, max_restarts=max_restarts,
                 dispatch_timeout_s=dispatch_timeout_s,
                 per_replica_env=per_replica_env)


def _fleet_traffic(fleet, *, threads: int, requests: int,
                   interval_s: float = 0.01):
    """Drive concurrent traffic; account for every single request.

    Returns (counts, dropped): ``dropped`` collects anything outside
    the typed contract — a non-ServingError exception, or a typed
    Unavailable carrying no retry_after hint when the fleet claims
    saturation. Zero dropped is every fleet scenario's core assertion.
    """
    import threading as _threading

    import numpy as np

    from perceiver_tpu.serving.errors import ServingError, Unavailable

    counts = {"ok": 0, "unavailable": 0}
    dropped = []
    lock = _threading.Lock()

    def worker(seed: int):
        rng = np.random.default_rng(seed)
        for i in range(requests):
            arrays = {
                "input_ids": rng.integers(
                    3, 110, (2, 16)).astype(np.int32),
                "pad_mask": np.zeros((2, 16), bool)}
            try:
                out = fleet.submit(arrays)
                assert "outputs" in out and "topk_ids" in out["outputs"]
                with lock:
                    counts["ok"] += 1
            except Unavailable as e:
                with lock:
                    if e.retry_after_s > 0:
                        counts["unavailable"] += 1
                    else:
                        dropped.append(f"no retry_after: {e}")
            except ServingError:
                with lock:
                    counts["unavailable"] += 1
            except Exception as e:  # noqa: BLE001 — the dropped bucket
                with lock:
                    dropped.append(f"{type(e).__name__}: {e}")
            time.sleep(interval_s)

    pool = [_threading.Thread(target=worker, args=(s,), daemon=True)
            for s in range(threads)]
    for t in pool:
        t.start()
    for t in pool:
        t.join(300)
    total = counts["ok"] + counts["unavailable"] + len(dropped)
    assert total == threads * requests, (total, threads * requests)
    return counts, dropped


def scenario_fleet_kill_replica(tmp: str) -> dict:
    """kill -9 a replica mid-traffic (the ``replica.crash`` fault
    SIGKILLs it mid-dispatch): the in-flight request transparently
    fails over to a sibling, the supervisor restarts the dead replica
    with backoff, and every request resolves — zero dropped."""
    store = _fleet_store(tmp, versions=("v1",))
    crash_env = {"PERCEIVER_FAULTS": "replica.crash@at=5"}
    fleet = _start_fleet(tmp, store, replicas=3,
                         per_replica_env={"r0": crash_env},
                         dispatch_timeout_s=8.0)
    try:
        counts, dropped = _fleet_traffic(fleet, threads=4, requests=25)
        # the crash counter ticks before the respawn finishes; wait
        # for the replacement to actually rejoin the router
        deadline = time.monotonic() + 60
        while (fleet.supervisor.restarts_of("r0") < 1
               or fleet.size() < 3) and time.monotonic() < deadline:
            time.sleep(0.1)
        crashes = fleet.supervisor.restarts_of("r0")
        retries = fleet.router.metrics.get("fleet_retries_total").value
        size = fleet.size()
        from perceiver_tpu.obs import events as events_mod

        deaths = events_mod.default_log().events("replica_death")
        respawns = events_mod.default_log().events("replica_respawn")
    finally:
        fleet.close()
    assert not dropped, dropped
    assert counts["ok"] >= 90, counts     # the fleet kept serving
    assert crashes >= 1, "victim never crashed"
    assert retries >= 1, "no request failed over"
    assert size == 3, size                # supervisor restarted the slot
    # the typed event log saw the death AND the recovery — the same
    # stream an operator would tail (docs/OBSERVABILITY.md)
    assert any(e["replica"] == "r0" for e in deaths), deaths
    assert any(e["replica"] == "r0" for e in respawns), respawns
    return {"requests": counts, "dropped": len(dropped),
            "replica_crashes": crashes, "router_retries": retries,
            "fleet_size_after": size,
            "death_events": len(deaths), "respawn_events": len(respawns),
            "faults_fired": {"replica.crash": crashes}}


def scenario_fleet_stall(tmp: str) -> dict:
    """A replica's dispatch path stalls (``replica.stall``): the
    router's recv deadline converts the hang into retry-on-sibling,
    repeated deadline hits eject the replica (breaker opens), and a
    half-open traffic probe readmits it once the stall clears. Zero
    dropped, zero hung requests."""
    store = _fleet_store(tmp, versions=("v1",))
    stall_env = {"PERCEIVER_FAULTS": "replica.stall@at=3,count=3,value=4"}
    fleet = _start_fleet(tmp, store, replicas=3,
                         per_replica_env={"r0": stall_env},
                         dispatch_timeout_s=1.5)
    try:
        counts, dropped = _fleet_traffic(fleet, threads=4, requests=25)
        m = fleet.router.metrics
        ejections = m.get("fleet_ejections_total").value
        retries = m.get("fleet_retries_total").value
        status = fleet.statuses().get("r0", {})
        from perceiver_tpu.obs import events as events_mod

        ejection_events = events_mod.default_log().events("fleet_ejection")
    finally:
        fleet.close()
    assert not dropped, dropped
    assert counts["ok"] >= 90, counts
    assert ejections >= 1, "stalled replica was never ejected"
    assert retries >= 3, retries
    fired = status.get("faults_fired", {})
    assert fired.get("replica.stall") == 3, fired
    # the breaker transition surfaced as a typed event, not just a
    # counter — chaos asserts on the operator-facing stream
    assert any(e["replica"] == "r0" for e in ejection_events), \
        ejection_events
    return {"requests": counts, "dropped": len(dropped),
            "ejections": ejections, "router_retries": retries,
            "ejection_events": len(ejection_events),
            "faults_fired": fired}


def scenario_fleet_rollout_corrupt(tmp: str) -> dict:
    """Mid-rollout checkpoint corruption: after the first replica cut
    over to v2, the v2 blobs rot (truncated post-seal). The next
    replica's verified load fails typed, the rollout auto-rolls the
    updated replica back to v1, CURRENT never moves, and traffic never
    drops a request."""
    from perceiver_tpu.fleet import RolloutAborted
    from perceiver_tpu.training.checkpoint import (
        CheckpointIntegrityError,
        verify_step,
    )

    store = _fleet_store(tmp, versions=("v1", "v2"))
    fleet = _start_fleet(tmp, store, replicas=3)
    corrupted = []

    def corrupt_v2_once(rid):
        if corrupted:
            return
        vdir = store.path("v2")
        blobs = [(os.path.getsize(os.path.join(r, f)),
                  os.path.join(r, f))
                 for r, _, fs in os.walk(vdir) for f in fs
                 if "manifest" not in f]
        _, victim = max(blobs)
        with open(victim, "r+b") as f:
            f.truncate(max(os.path.getsize(victim) // 2, 1))
        corrupted.append(rid)

    try:
        import threading as _threading

        background = {"counts": None, "dropped": None}

        def traffic():
            background["counts"], background["dropped"] = \
                _fleet_traffic(fleet, threads=2, requests=40,
                               interval_s=0.02)

        t = _threading.Thread(target=traffic, daemon=True)
        t.start()
        aborted = None
        try:
            fleet.rolling_update("v2",
                                 on_replica_updated=corrupt_v2_once)
        except RolloutAborted as e:
            aborted = e
        t.join(300)
        versions = {rid: s.get("version")
                    for rid, s in fleet.statuses().items()}
        from perceiver_tpu.obs import events as events_mod

        rollout_events = events_mod.default_log().events("rollout_step")
    finally:
        fleet.close()
    assert aborted is not None, "corrupt rollout was not aborted"
    # the abort left a typed rollback trail in the event log
    assert any(e["stage"] == "rollback" for e in rollout_events), \
        rollout_events
    assert isinstance(aborted.cause, CheckpointIntegrityError), \
        aborted.cause
    assert aborted.rolled_back and not aborted.rollback_failed, (
        aborted.rolled_back, aborted.rollback_failed)
    assert set(versions.values()) == {"v1"}, versions
    assert store.current() == "v1"
    assert verify_step(store.path("v2")) == "corrupt"
    counts, dropped = background["counts"], background["dropped"]
    assert counts is not None and not dropped, dropped
    return {"requests": counts, "dropped": len(dropped),
            "rolled_back": aborted.rolled_back,
            "replica_versions": versions,
            "current_after": store.current(),
            "faults_fired": {"ckpt.bitrot(v2)": 1}}


def scenario_fleet_rollout(tmp: str) -> dict:
    """The clean zero-downtime rolling update across 3 replicas: the
    exec cache is pre-warmed, so every replica spin-up performs ZERO
    XLA compiles (per-replica jax.monitoring listener count over RPC);
    under concurrent traffic the v1→v2 cutover completes with zero
    failed requests (router retries absorb the per-replica drain
    windows)."""
    os.environ["PERCEIVER_EXEC_CACHE"] = os.path.join(tmp, "exec_cache")
    store = _fleet_store(tmp, versions=("v1", "v2"))

    # warm the persistent cache in-process with the same spec the
    # replicas will use: their AOT warmup then deserializes
    from perceiver_tpu.serving.engine import ServingEngine
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    warm = ServingEngine(MaskedLanguageModelTask(**_FLEET_TASK_KWARGS),
                         store.load("v1", None),
                         batch_buckets=(4,), seq_buckets=(16,))
    assert warm.compile_count <= 1  # at most the one cold compile

    fleet = _start_fleet(tmp, store, replicas=3)
    try:
        compiles = {rid: s.get("compile_events")
                    for rid, s in fleet.statuses().items()}
        assert len(compiles) == 3, compiles

        import threading as _threading

        background = {}

        def traffic():
            background["counts"], background["dropped"] = \
                _fleet_traffic(fleet, threads=3, requests=40,
                               interval_s=0.02)

        t = _threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(0.3)  # let traffic establish before the rollout
        summary = fleet.rolling_update("v2")
        t.join(300)
        versions = {rid: s.get("version")
                    for rid, s in fleet.statuses().items()}
        from perceiver_tpu.obs import events as events_mod

        rollout_events = events_mod.default_log().events("rollout_step")
    finally:
        fleet.close()
    counts, dropped = background["counts"], background["dropped"]
    assert not dropped, dropped
    # every replica's cutover left the full drain→cutover→undrain
    # trail in the typed event log
    for rid in versions:
        stages = [e["stage"] for e in rollout_events
                  if e["replica"] == rid and e["version"] == "v2"]
        assert stages == ["drain", "cutover", "undrain"], (rid, stages)
    # zero FAILED requests: with siblings always available, retries
    # absorb every drain window — nothing surfaces even as typed errors
    assert counts["unavailable"] == 0, counts
    assert counts["ok"] == 120, counts
    assert summary["updated"] == 3, summary
    assert set(versions.values()) == {"v2"}, versions
    assert store.current() == "v2"
    # the PR-4 unlock, fleet-wide: replica spin-up compiled NOTHING
    assert all(c == 0 for c in compiles.values()), compiles
    return {"requests": counts, "dropped": len(dropped),
            "rollout": summary, "replica_versions": versions,
            "spin_up_xla_compiles": compiles,
            "faults_fired": {}}


# --- multi-host scenarios (docs/RESILIENCE.md / SERVING.md "Multi-host") ----
#
# The dist matrix proves the fault-tolerant multi-host story end to
# end on one machine: process-group training recovery with a
# bitwise-identical stitched loss curve, coordinator loss as a typed
# timebox (never a hang), and sharded group replicas that survive
# losing one host — both under traffic and mid-cutover. Cross-process
# COLLECTIVES are not required (the CPU-backend probe in
# tests/conftest.py gates those); cluster *formation* is pure gRPC and
# runs everywhere, which is exactly what dist_coordinator_loss spans.


def _worker_argv(spec_path: str):
    """argv factory for ``perceiver_tpu.distributed.worker`` members,
    in the shape ``GroupSupervisor`` expects."""

    def spawn_argv(rank, nproc, coordinator, generation):
        return [sys.executable, "-m", "perceiver_tpu.distributed.worker",
                "--spec", spec_path, "--rank", str(rank),
                "--nproc", str(nproc), "--coordinator", coordinator,
                "--generation", str(generation)]

    return spawn_argv


def _telemetry_losses(workdir: str, generation: int) -> dict:
    """step -> loss float from one generation's telemetry JSONL (JSON
    round-trips the float bits, so == below means bitwise equal)."""
    path = os.path.join(workdir, "telemetry", f"g{generation}",
                        "telemetry.jsonl")
    losses = {}
    with open(path) as f:
        for line in f:
            ev = json.loads(line)
            if ev.get("type") == "train_step":
                losses[int(ev["step"])] = ev["loss"]
    return losses


def scenario_dist_coordinator_loss(tmp: str) -> dict:
    """Coordinator dead at bootstrap: every member exits with the TYPED
    rendezvous timeout (exit 77 + ``rendezvous_timeout`` event) inside
    the timebox instead of wedging forever in the gRPC retry loop; a
    clean retry against a live coordinator then forms a real 2-process
    cluster (rendezvous needs no collectives, so this half runs on any
    CPU backend)."""
    from perceiver_tpu.distributed.group import free_port
    from perceiver_tpu.distributed.worker import RENDEZVOUS_EXIT

    workdir = os.path.join(tmp, "coord")
    events_dir = os.path.join(tmp, "events")
    os.makedirs(workdir, exist_ok=True)
    os.makedirs(events_dir, exist_ok=True)
    spec_path = os.path.join(workdir, "spec.json")
    timeout_s = 4.0
    with open(spec_path, "w") as f:
        json.dump({"mode": "bootstrap_only", "workdir": workdir,
                   "rendezvous_timeout_s": timeout_s}, f)
    env = dict(os.environ, PERCEIVER_TPU_OFFLINE="1",
               PERCEIVER_EVENT_LOG=events_dir)
    env.pop("PERCEIVER_FAULTS", None)
    argv = _worker_argv(spec_path)

    def spawn(ranks, nproc, coordinator, generation):
        return [subprocess.Popen(
            argv(rank, nproc, coordinator, generation), env=env,
            cwd=_REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True) for rank in ranks]

    # phase 1 — the COORDINATOR host (rank 0, which would serve the
    # rendezvous endpoint) is dead: the surviving members dial an
    # address nobody will ever listen on and must fail TYPED within
    # the timebox, never hang in the gRPC retry loop
    dead = f"127.0.0.1:{free_port()}"
    t0 = time.monotonic()
    procs = spawn((1, 2), 3, dead, 0)
    outs = [p.communicate(timeout=240)[0] for p in procs]
    phase1_s = time.monotonic() - t0
    codes = [p.returncode for p in procs]
    assert codes == [RENDEZVOUS_EXIT] * 2, (codes, outs)
    assert all("RENDEZVOUS_TIMEOUT" in o for o in outs), outs
    assert phase1_s < 180, phase1_s  # timeboxed, not a hang
    timeout_events = []
    for name in sorted(os.listdir(events_dir)):
        with open(os.path.join(events_dir, name)) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("type") == "rendezvous_timeout":
                    timeout_events.append(ev)
    assert len(timeout_events) >= 2, timeout_events
    assert all(e["coordinator"] == dead for e in timeout_events), \
        timeout_events

    # phase 2 — clean retry against a LIVE coordinator (rank 0 hosts
    # the coordinator service): the same binary, a fresh generation,
    # and the cluster actually forms
    live = f"127.0.0.1:{free_port()}"
    procs = spawn((0, 1), 2, live, 1)
    outs2 = [p.communicate(timeout=240)[0] for p in procs]
    assert [p.returncode for p in procs] == [0, 0], outs2
    results = []
    for rank in range(2):
        with open(os.path.join(workdir,
                               f"result.g1.r{rank}.json")) as f:
            results.append(json.load(f))
    assert all(r["process_count"] == 2 for r in results), results
    return {"phase1_exit_codes": codes,
            "phase1_wall_s": round(phase1_s, 2),
            "rendezvous_timeout_events": len(timeout_events),
            "retry_process_count": results[0]["process_count"],
            "faults_fired": {"coordinator.dead": 1}}


def scenario_dist_kill_train_host(tmp: str) -> dict:
    """SIGKILL the training host at the dispatch boundary mid-epoch
    (``train.kill``): the group supervisor tears the group down and
    re-forms it as generation 1, which restores the newest
    sha256-verified anchor generation 0 left and replays the
    epoch-seeded stream to that exact position — the stitched per-step
    loss trace is BITWISE-identical to an uninterrupted control run."""
    from perceiver_tpu.distributed.group import GroupSupervisor
    from perceiver_tpu.obs import events as events_mod
    from perceiver_tpu.training.checkpoint import CheckpointHook

    # control and victim generations share one compiled-step cache
    os.environ.setdefault("PERCEIVER_EXEC_CACHE",
                          os.path.join(tmp, "exec_cache"))

    def write_spec(workdir):
        os.makedirs(workdir, exist_ok=True)
        spec_path = os.path.join(workdir, "spec.json")
        with open(spec_path, "w") as f:
            json.dump({"mode": "train", "workdir": workdir,
                       "max_steps": TARGET_STEP,
                       "guard_anchor_every_n_steps": 2,
                       "seed": 42}, f)
        return spec_path

    # control: one uninterrupted run -> the reference loss trace
    control_dir = os.path.join(tmp, "control")
    env = dict(os.environ, PERCEIVER_TPU_OFFLINE="1")
    env.pop("PERCEIVER_FAULTS", None)
    argv = _worker_argv(write_spec(control_dir))
    proc = subprocess.run(argv(0, 1, "127.0.0.1:0", 0), env=env,
                          cwd=_REPO, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, (proc.stdout[-3000:],
                                  proc.stderr[-3000:])
    control = _telemetry_losses(control_dir, 0)
    assert sorted(control) == list(range(1, TARGET_STEP + 1)), control

    # victim: the same job under the group supervisor, with the kill
    # armed in generation 0 ONLY (the member_env seam) so the
    # re-formed generation runs clean
    victim_dir = os.path.join(tmp, "victim")
    sup = GroupSupervisor(
        _worker_argv(write_spec(victim_dir)), 1, workdir=victim_dir,
        member_env=lambda rank, gen: (
            {"PERCEIVER_FAULTS": "train.kill@at=4"} if gen == 0
            else {}),
        name="train-pg")
    try:
        reforms = sup.run(timeout_s=600.0)
    finally:
        sup.close()
    assert reforms == 1, reforms

    g0 = _telemetry_losses(victim_dir, 0)
    g1 = _telemetry_losses(victim_dir, 1)
    anchors_g0 = os.path.join(victim_dir, "anchors", "g0")
    anchor = CheckpointHook(anchors_g0,
                            monitor="").newest_restorable_step()
    assert anchor is not None and anchor >= 1, anchor
    with open(os.path.join(victim_dir, "result.g1.r0.json")) as f:
        result = json.load(f)
    assert result["final_step"] == TARGET_STEP, result
    # generation 1 resumed from EXACTLY the newest verified anchor of
    # generation 0 and logged the consecutive remainder of the run
    assert result["resumed_from"] == anchors_g0, result
    assert sorted(g1) == list(range(anchor + 1, TARGET_STEP + 1)), \
        (anchor, sorted(g1))
    assert sorted(set(g0) | set(g1)) == \
        list(range(1, TARGET_STEP + 1)), (sorted(g0), sorted(g1))
    # the stitched trace matches the control BITWISE: every step either
    # generation logged carries the exact float the uninterrupted run
    # produced (anchor restore + epoch-seeded replay, no drift)
    stitched = dict(g0)
    stitched.update(g1)
    mismatches = {s: (stitched[s], control[s]) for s in stitched
                  if stitched[s] != control[s]}
    assert not mismatches, mismatches
    log = events_mod.default_log()
    leaves = [e for e in log.events("host_leave")
              if e["group"] == "train-pg"]
    reform_events = [e for e in log.events("group_reform")
                     if e["group"] == "train-pg"]
    assert leaves and leaves[0]["exit_code"] != 0, leaves
    assert reform_events and reform_events[0]["generation"] == 1, \
        reform_events
    return {"control_steps": len(control), "killed_after_step": anchor,
            "g0_steps": sorted(g0), "g1_steps": sorted(g1),
            "resumed_from_step": anchor, "reforms": reforms,
            "bitwise_identical": True,
            "faults_fired": {"train.kill": 1}}


def scenario_dist_kill_serve_host(tmp: str) -> dict:
    """kill -9 ONE host of a 2-member sharded replica group mid-
    traffic: the group declares itself dead as a whole (survivors of a
    torn collective can't serve), the fleet supervisor re-forms it as
    a fresh generation, and the router fails traffic over to the
    sibling group throughout — zero dropped requests."""
    store = _fleet_store(tmp, versions=("v1",))
    crash_env = {"PERCEIVER_FAULTS": "replica.crash@at=5"}
    fleet = _start_fleet(tmp, store, replicas=2, group_size=2,
                         per_replica_env={"r0.m0": crash_env},
                         dispatch_timeout_s=8.0)
    try:
        counts, dropped = _fleet_traffic(fleet, threads=4, requests=25)
        # wait for the replacement GROUP to rejoin the router
        deadline = time.monotonic() + 120
        while (fleet.supervisor.restarts_of("r0") < 1
               or fleet.size() < 2) and time.monotonic() < deadline:
            time.sleep(0.1)
        restarts = fleet.supervisor.restarts_of("r0")
        size = fleet.size()
        statuses = fleet.statuses()
        from perceiver_tpu.obs import events as events_mod

        log = events_mod.default_log()
        deaths = log.events("replica_death")
        respawns = log.events("replica_respawn")
        leaves = [e for e in log.events("host_leave")
                  if e["group"] == "r0"]
        joins = [e for e in log.events("host_join")
                 if e["group"] == "r0"]
        reforms = [e for e in log.events("group_reform")
                   if e["group"] == "r0"]
    finally:
        fleet.close()
    assert not dropped, dropped
    assert counts["ok"] >= 90, counts     # the fleet kept serving
    assert restarts >= 1, "victim group never died"
    assert size == 2, size                # the slot was re-formed
    # the replacement is a FULL group again, not a zombie quorum
    assert statuses.get("r0", {}).get("group_size") == 2, statuses
    assert any(e["replica"] == "r0" for e in deaths), deaths
    assert any(e["replica"] == "r0" for e in respawns), respawns
    assert leaves, "no host_leave for the killed member"
    assert len(joins) >= 4, joins         # 2 at spawn + 2 at re-form
    assert reforms and reforms[0]["generation"] >= 1, reforms
    return {"requests": counts, "dropped": len(dropped),
            "group_restarts": restarts, "fleet_size_after": size,
            "host_leave_events": len(leaves),
            "host_join_events": len(joins),
            "group_reform_events": len(reforms),
            "faults_fired": {"replica.crash": restarts}}


def scenario_dist_cutover_kill(tmp: str) -> dict:
    """SIGKILL a group member BETWEEN stage and swap of the two-phase
    cutover (``replica.commit_crash`` fires at commit entry): the
    already-committed member is rolled back to the previous version,
    the rollout aborts typed, the store's CURRENT pointer never moves,
    the supervisor re-forms the group on the old version, and the
    concurrent traffic never drops a request — no client ever observes
    torn params."""
    from perceiver_tpu.distributed.serving_group import GroupCutoverError
    from perceiver_tpu.fleet import RolloutAborted

    store = _fleet_store(tmp, versions=("v1", "v2"))
    crash_env = {"PERCEIVER_FAULTS": "replica.commit_crash@at=0"}
    fleet = _start_fleet(tmp, store, replicas=2, group_size=2,
                         per_replica_env={"r0.m1": crash_env},
                         dispatch_timeout_s=8.0)
    try:
        import threading as _threading

        background = {"counts": None, "dropped": None}

        def traffic():
            background["counts"], background["dropped"] = \
                _fleet_traffic(fleet, threads=2, requests=40,
                               interval_s=0.02)

        t = _threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(0.3)  # let traffic establish before the rollout
        aborted = None
        try:
            fleet.rolling_update("v2")
        except RolloutAborted as e:
            aborted = e
        t.join(300)
        # the supervisor re-forms r0; wait for the whole fleet to
        # converge back onto the OLD version
        deadline = time.monotonic() + 120
        versions = {}
        while time.monotonic() < deadline:
            versions = {rid: s.get("version")
                        for rid, s in fleet.statuses().items()}
            if len(versions) == 2 and set(versions.values()) == {"v1"}:
                break
            time.sleep(0.2)
        from perceiver_tpu.obs import events as events_mod

        log = events_mod.default_log()
        staged = {e["replica"] for e in log.events("cutover_stage")
                  if e["version"] == "v2"
                  and e["replica"].startswith("r0.")}
        acked = {e["replica"] for e in log.events("cutover_ack")
                 if e["version"] == "v2"
                 and e["replica"].startswith("r0.")}
        rollbacks = log.events("cutover_rollback")
        reforms = [e for e in log.events("group_reform")
                   if e["group"] == "r0"]
    finally:
        fleet.close()
    counts, dropped = background["counts"], background["dropped"]
    assert aborted is not None, "cutover kill did not abort the rollout"
    assert isinstance(aborted.cause, GroupCutoverError), aborted.cause
    assert store.current() == "v1"        # CURRENT never moved
    assert set(versions.values()) == {"v1"}, versions
    assert counts is not None and not dropped, dropped
    # two-phase ordering: BOTH members staged before any commit...
    assert staged == {"r0.m0", "r0.m1"}, staged
    # ...m0 committed and acked v2; m1 died at commit entry, so its
    # ack never appears and the group handle rolled the commit back
    assert acked == {"r0.m0"}, acked
    assert any(e["replica"] == "r0" and e["version"] == "v1"
               for e in rollbacks), rollbacks
    assert reforms, "killed group was never re-formed"
    return {"requests": counts, "dropped": len(dropped),
            "current_after": store.current(),
            "replica_versions": versions,
            "staged_members": sorted(staged),
            "acked_members": sorted(acked),
            "rollback_events": len(rollbacks),
            "group_reform_events": len(reforms),
            "rolled_back": aborted.cause.rolled_back,
            "rollback_failed": aborted.cause.rollback_failed,
            "faults_fired": {"replica.commit_crash": 1}}


def scenario_race_admission(tmp: str) -> dict:
    """Decode admission (``serving.batcher.AdmissionQueue``, the
    continuous-batching front door) driven through adversarial seeded
    interleavings: two producer threads offer streams while the
    step-loop consumer takes budget-gated prefixes, with the queue's
    lock swapped for an ``InstrumentedLock`` (every acquisition is a
    scheduler yield point) and the deque wrapped in a ``guarded()``
    proxy that raises the instant any access happens off-lock.
    Asserts conservation — every offered stream ends up admitted,
    shed, rejected, or still queued, exactly once — and that each
    seed replays bitwise-identically (the racecheck runtime-harness
    contract, docs/ANALYSIS.md "Racecheck")."""
    import itertools

    from perceiver_tpu.serving.batcher import AdmissionQueue
    from perceiver_tpu.utils.concurrency import (
        InstrumentedLock,
        InterleaveScheduler,
        guarded,
    )

    def run_once(seed: int):
        sched = InterleaveScheduler(seed=seed)
        # deterministic clock: admission/shedding decisions depend only
        # on the seeded schedule, never on wall time
        ticks = itertools.count()
        q = AdmissionQueue(max_depth=8,
                           clock=lambda: next(ticks) * 1e-3)
        lock = InstrumentedLock(sched, name="admission._lock")
        q._lock = lock
        q._queue = guarded(q._queue, lock, label="admission deque")

        offered, rejected = [], []
        admitted, shed = [], []

        def producer(base: int):
            def run():
                for i in range(6):
                    item = f"s{base}-{i}"
                    # every third stream carries an already-expired
                    # deadline so the shed path interleaves too
                    deadline = 0.0 if i % 3 == 2 else None
                    if q.offer(item, cost=1 + (i % 3),
                               deadline=deadline):
                        offered.append(item)
                    else:
                        rejected.append(item)
            return run

        def consumer():
            for _ in range(48):
                a, s = q.take(budget=4, slots=2)
                admitted.extend(a)
                shed.extend(s)
                if (len(offered) + len(rejected) == 12
                        and q.depth == 0):
                    return

        sched.spawn(producer(0), name="producer-0")
        sched.spawn(producer(1), name="producer-1")
        sched.spawn(consumer, name="step-loop")
        sched.run()
        leftover = q.drain_all()
        return (tuple(admitted), tuple(shed), tuple(rejected),
                tuple(leftover), tuple(sched.trace))

    seeds = [4, 7, 1234]
    totals = {"admitted": 0, "shed": 0, "rejected": 0, "leftover": 0}
    for seed in seeds:
        first = run_once(seed)
        admitted, shed, rejected, leftover, _trace = first
        everything = list(admitted) + list(shed) + list(rejected) \
            + list(leftover)
        expect = {f"s{b}-{i}" for b in (0, 1) for i in range(6)}
        assert sorted(everything) == sorted(expect), (
            f"seed {seed}: streams lost or duplicated: {everything}")
        # bitwise-reproducible: the same seed replays the same
        # interleaving, outcomes and all
        assert run_once(seed) == first, f"seed {seed} not deterministic"
        totals["admitted"] += len(admitted)
        totals["shed"] += len(shed)
        totals["rejected"] += len(rejected)
        totals["leftover"] += len(leftover)
    # The injected fault here is the scheduler itself: one adversarial
    # interleaving per seed, each replayed once to prove determinism.
    return {"seeds": seeds, "streams_per_seed": 12,
            "deterministic_replays": len(seeds), **totals,
            "faults_fired": {"race.interleave": len(seeds)}}


def scenario_race_mixed_prefill(tmp: str) -> dict:
    """The unified prefill+decode scheduler
    (``serving.batcher.ContinuousBatchScheduler``) under adversarial
    seeded interleavings: producers offer streams while the step-loop
    consumer alternates ``take`` (slot+page admission) with
    ``plan_chunks`` over the rows it owns — the mixed-phase hot path
    of the chunked-prefill decode engine. Asserts conservation (every
    offered stream admitted, shed, rejected, or left queued exactly
    once), the per-step budget invariant (non-head prefill chunks
    never exceed the leftover budget after decode rows; the FIFO head
    always advances >= 1 token; no chunk exceeds ``max_chunk`` or the
    remaining prompt), completion (every admitted prompt prefills to
    zero remaining and then decodes), and seed-deterministic replay
    (the racecheck runtime-harness contract)."""
    import itertools

    from perceiver_tpu.serving.batcher import ContinuousBatchScheduler
    from perceiver_tpu.utils.concurrency import (
        InstrumentedLock,
        InterleaveScheduler,
        guarded,
    )

    BUDGET, MAX_CHUNK = 4, 3

    def run_once(seed: int):
        sched = InterleaveScheduler(seed=seed)
        ticks = itertools.count()
        q = ContinuousBatchScheduler(max_depth=8, token_budget=BUDGET,
                                     max_chunk=MAX_CHUNK,
                                     clock=lambda: next(ticks) * 1e-3)
        lock = InstrumentedLock(sched, name="scheduler._lock")
        q._lock = lock
        q._queue = guarded(q._queue, lock, label="scheduler deque")

        offered, rejected = [], []
        admitted, shed = [], []
        # consumer-owned mixed-phase state: item -> remaining prompt
        prefill, decoding = {}, {}
        planned_steps = [0]

        def producer(base: int):
            def run():
                for i in range(6):
                    item = f"s{base}-{i}"
                    deadline = 0.0 if i % 3 == 2 else None
                    if q.offer(item, cost=1 + (i % 3),
                               deadline=deadline):
                        offered.append(item)
                    else:
                        rejected.append(item)
            return run

        def consumer():
            for _ in range(64):
                a, s = q.take(budget=4, slots=3 - len(prefill)
                              - len(decoding))
                admitted.extend(a)
                shed.extend(s)
                for item in a:
                    # deterministic prompt length from the stream id
                    prefill[item] = 2 + (int(item[-1]) % 4)
                order = sorted(prefill)  # FIFO by id (deterministic)
                rems = [prefill[i] for i in order]
                plan = q.plan_chunks(len(decoding), rems)
                planned_steps[0] += 1
                # --- the budget invariant, asserted EVERY step ---
                left = max(0, BUDGET - len(decoding))
                assert all(c <= MAX_CHUNK for c in plan), plan
                assert all(c <= r for c, r in zip(plan, rems)), plan
                assert sum(plan[1:]) <= left, (plan, left)
                assert sum(plan) <= left + 1, (plan, left)
                if rems:
                    assert plan[0] >= 1, plan  # head anti-starvation
                for item, c in zip(order, plan):
                    prefill[item] -= c
                    if prefill[item] == 0:
                        del prefill[item]
                        decoding[item] = 2  # decode a couple of steps
                for item in [d for d, n in decoding.items() if n == 0]:
                    del decoding[item]
                for item in decoding:
                    decoding[item] -= 1
                if (len(offered) + len(rejected) == 12
                        and q.depth == 0 and not prefill
                        and not decoding):
                    return

        sched.spawn(producer(0), name="producer-0")
        sched.spawn(producer(1), name="producer-1")
        sched.spawn(consumer, name="step-loop")
        sched.run()
        leftover = q.drain_all()
        assert not prefill, f"prompts stuck mid-prefill: {prefill}"
        return (tuple(admitted), tuple(shed), tuple(rejected),
                tuple(leftover), planned_steps[0],
                tuple(sched.trace))

    seeds = [3, 11, 4321]
    totals = {"admitted": 0, "shed": 0, "rejected": 0, "leftover": 0,
              "planned_steps": 0}
    for seed in seeds:
        first = run_once(seed)
        admitted, shed, rejected, leftover, steps, _trace = first
        everything = list(admitted) + list(shed) + list(rejected) \
            + list(leftover)
        expect = {f"s{b}-{i}" for b in (0, 1) for i in range(6)}
        assert sorted(everything) == sorted(expect), (
            f"seed {seed}: streams lost or duplicated: {everything}")
        assert run_once(seed) == first, f"seed {seed} not deterministic"
        totals["admitted"] += len(admitted)
        totals["shed"] += len(shed)
        totals["rejected"] += len(rejected)
        totals["leftover"] += len(leftover)
        totals["planned_steps"] += steps
    return {"seeds": seeds, "streams_per_seed": 12,
            "token_budget": BUDGET, "max_chunk": MAX_CHUNK,
            "deterministic_replays": len(seeds), **totals,
            "faults_fired": {"race.interleave": len(seeds)}}


def scenario_prefix_evict_under_load(tmp: str) -> dict:
    """Prefix-cache eviction under adversarial page pressure
    (``serving.prefix_cache``): flooder streams with unique prefixes
    publish fresh chains into a tight arena that can only admit by
    LRU-evicting index-only pages, while shared-prefix clients stream
    prompts that should keep hitting the shared chain.

    Two phases, following the race_* scenario pattern (the token
    oracle must not depend on wall-clock thread timing):

    1. **Deterministic token-exactness.** A manually stepped engine is
       driven by seeded admission schedules interleaving shared-prefix
       clients with flooders; every client completion — across hit,
       miss, and post-eviction re-prefill states — must be
       bit-identical to a cold-prefill reference engine with caching
       disabled, and each seed's full completion log must replay
       bitwise-identically.
    2. **Free-threaded liveness.** Real client/flooder threads hammer
       an auto-stepping engine; asserts zero dropped requests (every
       submission resolves to a complete ``DecodeResult``, never a
       shed) and no refcount leak: at drain the index accounts for
       every allocated page, and flushing returns the arena to fully
       free."""
    import threading

    import numpy as np

    from perceiver_tpu.serving.decode import (
        DecodeEngine,
        DecodeGeometry,
        DecodeResult,
    )
    from perceiver_tpu.serving.prefix_cache import PrefixCacheConfig
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=48, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")
    # tight arena: 3 slots x 4 pages per stream = 12 of 16 allocatable
    # pages in flight, so published chains (2-3 pages each) force LRU
    # eviction within a few flooder admissions
    geometry = DecodeGeometry(max_streams=3, num_pages=17, page_size=4,
                              max_seq_len=48, max_chunk=4)
    engine = DecodeEngine(task, geometry=geometry, auto_step=False,
                          max_queue=64,
                          prefix_cache=PrefixCacheConfig())
    params = engine.params
    reference = DecodeEngine(task, params=params,
                             geometry=geometry, auto_step=True,
                             max_queue=64)

    rng = np.random.default_rng(7)
    shared = rng.integers(3, 100, size=8)          # 2 full pages
    tails = [rng.integers(3, 100, size=3) for _ in range(3)]
    client_prompts = [np.concatenate([shared, t]).astype(np.int32)
                      for t in tails]
    MAX_NEW = 6

    # cold-prefill references, caching disabled — the oracle the
    # cached path must match bit-for-bit
    expect = {}
    for p in client_prompts:
        r = reference.submit(p, max_new_tokens=MAX_NEW).result(120.0)
        assert isinstance(r, DecodeResult) and r.finished == "complete"
        expect[p.tobytes()] = list(r.tokens)
    reference.close()

    # -- phase 1: deterministic token-exactness under eviction churn --
    # Seeded schedules drive the manually stepped engine: shared-
    # prefix clients and unique-prefix flooders admitted in shuffled
    # order with a random number of engine steps between submissions,
    # so warm admissions land mid-decode, mid-flood, and after their
    # chain was evicted and republished.
    seeds = [0, 7]
    hits = exact = 0

    def run_once(seed: int):
        nonlocal hits, exact
        srng = np.random.default_rng(seed)
        frng = np.random.default_rng(10_000 + seed)
        kinds = ["c"] * 12 + ["f"] * 10
        srng.shuffle(kinds)
        handles, ci = [], 0
        for kind in kinds:
            if kind == "c":
                p = client_prompts[ci % len(client_prompts)]
                ci += 1
            else:
                p = frng.integers(3, 100, size=11).astype(np.int32)
            handles.append((kind, p.tobytes(),
                            engine.submit(p, max_new_tokens=MAX_NEW)))
            for _ in range(int(srng.integers(0, 4))):
                engine.step()
        engine.run_until_idle()
        log = []
        for kind, key, h in handles:
            r = h.result(1.0)
            assert isinstance(r, DecodeResult), f"dropped request: {r}"
            assert r.finished == "complete" and len(r.tokens) == MAX_NEW
            if kind == "c":
                assert r.tokens == expect[key], (
                    f"seed {seed}: cache state leaked into tokens: "
                    f"{r.tokens} != {expect[key]} "
                    f"(cached_tokens={r.cached_tokens})")
                exact += 1
                hits += r.cached_tokens > 0
            log.append((kind, tuple(r.tokens), r.cached_tokens))
        # reset cache state so each run starts from an empty index —
        # the schedule, not leftover trie state, is the input
        engine.flush_prefix_cache()
        assert engine.pool.free_pages == geometry.allocatable_pages, (
            f"arena not reclaimable after seed {seed}: "
            f"{engine.pool.free_pages} free of "
            f"{geometry.allocatable_pages}")
        return log

    for seed in seeds:
        first = run_once(seed)
        assert run_once(seed) == first, f"seed {seed} not deterministic"
    det_stats = engine.prefix_cache_stats()
    assert det_stats["evicted_pages"] >= 1, \
        "flood never forced an eviction — pressure too low to test"
    engine.close()

    # -- phase 2: free-threaded liveness (structural invariants only;
    # token equality lives in phase 1 where the schedule is replayable)
    engine = DecodeEngine(task, params=params,
                          geometry=geometry, auto_step=True,
                          max_queue=64, prefix_cache=PrefixCacheConfig())
    results, errors = [], []
    res_lock = threading.Lock()

    def client(worker: int):
        def run():
            try:
                for i in range(6):
                    p = client_prompts[(worker + i) % len(client_prompts)]
                    r = engine.submit(
                        p, max_new_tokens=MAX_NEW).result(120.0)
                    with res_lock:
                        results.append(("client", r))
            except BaseException as e:  # noqa: BLE001 — surfaced below
                with res_lock:
                    errors.append(e)
        return run

    def flooder():
        frng = np.random.default_rng(1234)
        try:
            for _ in range(10):
                p = frng.integers(3, 100, size=11).astype(np.int32)
                r = engine.submit(
                    p, max_new_tokens=MAX_NEW).result(120.0)
                with res_lock:
                    results.append(("flood", r))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            with res_lock:
                errors.append(e)

    threads = [threading.Thread(target=client(w), name=f"client-{w}")
               for w in range(2)]
    threads.append(threading.Thread(target=flooder, name="flooder"))
    for t in threads:
        t.start()
    for t in threads:
        t.join(300.0)
        assert not t.is_alive(), f"{t.name} hung"
    assert not errors, f"client errors: {errors!r}"

    assert engine.drain(60.0), "engine failed to drain"
    stats = engine.prefix_cache_stats()
    dropped = sum(1 for _, r in results
                  if not isinstance(r, DecodeResult))
    for kind, r in results:
        assert isinstance(r, DecodeResult), f"dropped request: {r}"
        assert r.finished == "complete" and len(r.tokens) == MAX_NEW
        if kind == "client":
            hits += r.cached_tokens > 0
    assert len(results) == 22, f"expected 22 completions: {len(results)}"
    assert hits >= 1, "shared-prefix clients never hit the cache"
    # refcount-leak check: every allocated page is accounted to the
    # index, and dropping the index returns the arena to fully free
    assert engine.pool.allocated_pages == stats["pages_indexed"], (
        f"leaked pages: {engine.pool.allocated_pages} allocated vs "
        f"{stats['pages_indexed']} indexed")
    engine.flush_prefix_cache()
    assert engine.pool.free_pages == geometry.allocatable_pages, (
        f"arena not reclaimable: {engine.pool.free_pages} free of "
        f"{geometry.allocatable_pages}")
    engine.close()
    evicted = det_stats["evicted_pages"] + stats["evicted_pages"]
    return {"clients": 2, "client_requests": exact,
            "flood_requests": 10, "dropped": dropped,
            "client_hits": hits,
            "seeds": seeds, "deterministic_replays": len(seeds),
            "evicted_pages": evicted,
            "hit_tokens": (det_stats["hit_tokens"]
                           + stats["hit_tokens"]),
            "leak_free": True, "token_exact": True,
            "faults_fired": {"prefix.evict_pressure": evicted}}


def scenario_spec_reject_storm(tmp: str) -> dict:
    """Speculative decoding under adversarial 0%-acceptance
    (``serving.speculative``): the draft is a shrunk model with
    randomly initialized weights (``draft_seed`` only — never trained),
    so virtually every drafted token is rejected and every verify step
    rolls the target KV *and* the draft KV back by the full window.
    ``fallback_acceptance=0.0`` pins speculation ON, so the storm never
    de-escalates into plain decode — the rollback path runs for every
    stream on every step.

    Two phases, following the race_*/prefix_evict pattern:

    1. **Deterministic token-exactness.** A manually stepped
       speculative engine driven by seeded admission schedules must
       complete every request bit-identical to a plain (spec_k=0)
       reference engine sharing the same target params — the rejection
       rule's contract that speculation changes latency, never output,
       held at its worst case. Each seed's completion log replays
       bitwise-identically, and after every run BOTH arenas (target
       and draft) must be fully free.
    2. **Free-threaded liveness.** Client threads hammer an
       auto-stepping speculative engine; zero dropped requests, every
       completion still token-exact, and both arenas fully reclaimed
       at drain — a rejected window must never strand a page."""
    import threading
    from dataclasses import replace as _dc_replace

    import numpy as np

    from perceiver_tpu.serving.decode import (
        DecodeEngine,
        DecodeGeometry,
        DecodeResult,
    )
    from perceiver_tpu.serving.speculative import (
        SpeculativeConfig,
        shrink_task,
    )
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=32, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")
    geometry = DecodeGeometry(max_streams=3, num_pages=17, page_size=4,
                              max_seq_len=32, max_chunk=4, spec_k=3)
    spec_cfg = SpeculativeConfig(draft_task=shrink_task(task),
                                 draft_seed=1234,
                                 fallback_acceptance=0.0)
    engine = DecodeEngine(task, geometry=geometry, auto_step=False,
                          max_queue=64, speculative=spec_cfg)
    params = engine.params
    reference = DecodeEngine(task, params=params,
                             geometry=_dc_replace(geometry, spec_k=0),
                             auto_step=True, max_queue=64)

    rng = np.random.default_rng(11)
    prompts = [rng.integers(3, 100, size=n).astype(np.int32)
               for n in (5, 9, 11, 7)]
    MAX_NEW = 6

    expect = {}
    for p in prompts:
        r = reference.submit(p, max_new_tokens=MAX_NEW).result(120.0)
        assert isinstance(r, DecodeResult) and r.finished == "complete"
        expect[p.tobytes()] = list(r.tokens)
    reference.close()

    def _arenas_free(eng):
        assert eng.pool.free_pages == geometry.allocatable_pages, (
            f"target arena leaked: {eng.pool.free_pages} free of "
            f"{geometry.allocatable_pages}")
        assert (eng.draft_pool.free_pages
                == geometry.allocatable_pages), (
            f"draft arena leaked: {eng.draft_pool.free_pages} free of "
            f"{geometry.allocatable_pages}")

    # -- phase 1: deterministic token-exactness under total rejection --
    seeds = [0, 11]
    exact = 0

    def run_once(seed: int):
        nonlocal exact
        srng = np.random.default_rng(seed)
        handles = []
        for i in range(10):
            p = prompts[i % len(prompts)]
            handles.append((p.tobytes(),
                            engine.submit(p, max_new_tokens=MAX_NEW)))
            for _ in range(int(srng.integers(0, 4))):
                engine.step()
        engine.run_until_idle()
        log = []
        for key, h in handles:
            r = h.result(1.0)
            assert isinstance(r, DecodeResult), f"dropped request: {r}"
            assert r.finished == "complete" and len(r.tokens) == MAX_NEW
            assert r.tokens == expect[key], (
                f"seed {seed}: rejection rollback leaked into tokens: "
                f"{r.tokens} != {expect[key]}")
            exact += 1
            log.append(tuple(r.tokens))
        _arenas_free(engine)
        return log

    for seed in seeds:
        first = run_once(seed)
        assert run_once(seed) == first, f"seed {seed} not deterministic"
    det_stats = engine.speculative_stats()
    assert det_stats["drafted_tokens"] > 0, "draft never proposed"
    assert det_stats["acceptance_rate"] <= 0.2, (
        f"storm not adversarial: acceptance "
        f"{det_stats['acceptance_rate']}")
    assert det_stats["fallbacks"] == 0, \
        "fallback fired despite fallback_acceptance=0.0"
    engine.close()
    rejected = int(det_stats["drafted_tokens"]
                   - det_stats["accepted_tokens"])
    assert rejected >= 1, "no rejection ever rolled back a window"

    # -- phase 2: free-threaded liveness under the same storm --
    engine = DecodeEngine(task, params=params, geometry=geometry,
                          auto_step=True, max_queue=64,
                          speculative=spec_cfg)
    results, errors = [], []
    res_lock = threading.Lock()

    def client(worker: int):
        def run():
            try:
                for i in range(5):
                    p = prompts[(worker + i) % len(prompts)]
                    r = engine.submit(
                        p, max_new_tokens=MAX_NEW).result(120.0)
                    with res_lock:
                        results.append((p.tobytes(), r))
            except BaseException as e:  # noqa: BLE001 — surfaced below
                with res_lock:
                    errors.append(e)
        return run

    threads = [threading.Thread(target=client(w), name=f"client-{w}")
               for w in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300.0)
        assert not t.is_alive(), f"{t.name} hung"
    assert not errors, f"client errors: {errors!r}"

    assert engine.drain(60.0), "engine failed to drain"
    dropped = sum(1 for _, r in results
                  if not isinstance(r, DecodeResult))
    for key, r in results:
        assert isinstance(r, DecodeResult), f"dropped request: {r}"
        assert r.finished == "complete" and len(r.tokens) == MAX_NEW
        # greedy decode is schedule-independent, so exactness holds
        # under free threading too (no cache state to interleave)
        assert r.tokens == expect[key], (
            f"threaded storm leaked into tokens: {r.tokens} != "
            f"{expect[key]}")
    assert len(results) == 15, f"expected 15 completions: {len(results)}"
    _arenas_free(engine)
    live_stats = engine.speculative_stats()
    engine.close()
    rejected += int(live_stats["drafted_tokens"]
                    - live_stats["accepted_tokens"])
    return {"clients": 3, "requests": exact + len(results),
            "dropped": dropped,
            "seeds": seeds, "deterministic_replays": len(seeds),
            "drafted_tokens": int(det_stats["drafted_tokens"]
                                  + live_stats["drafted_tokens"]),
            "rejected_tokens": rejected,
            "acceptance_rate": round(live_stats["acceptance_rate"], 4),
            "leak_free": True, "token_exact": True,
            "faults_fired": {"spec.reject_storm": rejected}}


def scenario_noisy_neighbor(tmp: str) -> dict:
    """Multi-tenant isolation under a quota-busting flood
    (``serving.decode`` + ``serving.tenancy``): a best-effort "flood"
    tenant hammers the shared decode arena with far more work than its
    page quota admits while a standard-priority "victim" tenant runs
    its normal request pattern on the same engine. The isolation
    contract (docs/SERVING.md "Multi-tenancy"): the flood is shed with
    typed ``Unavailable("tenant_quota")`` *before any compute*, and
    the victim's latency stays within a pinned ratio of its solo
    baseline — quota enforcement plus weighted fair-share planning,
    never engine-wide backpressure, absorb the neighbor.

    The engine is manually stepped, so "latency" is *steps* — a
    deterministic clock. Per seed, the victim's submit/step schedule
    is driven by one RNG and the flood's burst sizes by a second, so
    the victim's schedule is bit-identical across the solo and flooded
    runs and the comparison is exact. Asserts, per seed:

    - **zero dropped victim requests**: every victim stream completes
      with the full token count, token-exact vs the solo run (greedy
      decode — interference can move latency, never content);
    - **pinned latency ratio**: flooded victim TTFT (p95, in steps)
      and per-token decode gap (p99) each stay ≤ 2x the solo baseline;
    - **typed flood shed, observably per-tenant**: the flood sees
      ``Unavailable("tenant_quota")`` at submit, the engine's
      ``serving_tenant_shed_total{tenant="flood"}`` counter and
      ``tenant_shed`` events record it, and the victim's shed count
      stays zero — the Prometheus text is the proof artifact;
    - **zero post-warmup compiles** (jax.monitoring) across both
      phases — tenancy is host-side state only;
    - **bitwise seeded replay**: the flooded run's full observable log
      (TTFTs, gaps, tokens, shed counts) replays identically."""
    from jax import monitoring as jax_monitoring
    from jax._src import monitoring as _monitoring_impl
    import numpy as np

    from perceiver_tpu.obs import events as events_mod
    from perceiver_tpu.serving.decode import (
        DecodeEngine,
        DecodeGeometry,
        DecodeResult,
    )
    from perceiver_tpu.serving.errors import Unavailable
    from perceiver_tpu.serving.tenancy import (
        PRIORITY_BEST_EFFORT,
        TenantRegistry,
        TenantSpec,
    )
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=32, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")
    geometry = DecodeGeometry(max_streams=4, num_pages=21, page_size=4,
                              max_seq_len=32, max_chunk=4)
    # victim: standard priority, uncapped pages, 3x fair-share weight.
    # flood: best-effort, page quota sized for ONE in-flight request —
    # every extra burst request must shed at submit, before compute.
    tenancy = TenantRegistry([
        TenantSpec(tenant="victim", weight=3.0),
        TenantSpec(tenant="flood", priority=PRIORITY_BEST_EFFORT,
                   weight=1.0, max_pages=4),
    ])

    rng = np.random.default_rng(5)
    prompts = [rng.integers(3, 100, size=n).astype(np.int32)
               for n in (5, 9, 11, 7)]
    MAX_NEW, N_VICTIM = 6, 6
    RATIO = 2.0  # the pinned noisy-neighbor budget

    compiles = []

    def _compile_listener(name, **kwargs):
        if "compile" in name:
            compiles.append(name)

    shared_params = [None]

    def run_phase(seed: int, flood: bool):
        engine = DecodeEngine(task, params=shared_params[0],
                              geometry=geometry, tenancy=tenancy,
                              auto_step=False, max_queue=32)
        if shared_params[0] is None:
            shared_params[0] = engine.params
        engine.step()  # idle warmup — compiles counted only after this
        jax_monitoring.register_event_listener(_compile_listener)
        try:
            step_no = [0]
            vrng = np.random.default_rng(seed)        # victim schedule
            frng = np.random.default_rng(seed + 1000)  # flood bursts
            victim, flood_handles, flood_shed = [], [], [0]

            def submit_victim(prompt):
                rec = {"submit": step_no[0], "token_steps": []}

                def on_token(_tok, rec=rec):
                    rec["token_steps"].append(step_no[0])

                rec["handle"] = engine.submit(
                    prompt, max_new_tokens=MAX_NEW, on_token=on_token,
                    tenant="victim")
                victim.append((prompt.tobytes(), rec))

            def submit_flood_burst():
                for _ in range(int(frng.integers(2, 5))):
                    try:
                        flood_handles.append(engine.submit(
                            prompts[0], max_new_tokens=MAX_NEW,
                            tenant="flood"))
                    except Unavailable as e:
                        assert e.reason == "tenant_quota", e.reason
                        flood_shed[0] += 1

            def step_once():
                step_no[0] += 1
                return engine.step()

            for i in range(N_VICTIM):
                if flood:
                    submit_flood_burst()
                submit_victim(prompts[i % len(prompts)])
                for _ in range(int(vrng.integers(2, 6))):
                    step_once()
            guard = 0
            while step_once():
                guard += 1
                assert guard < 5000, "engine never went idle"

            ttfts, gaps, tokens = [], [], []
            for key, rec in victim:
                r = rec["handle"].result(1.0)
                assert isinstance(r, DecodeResult), \
                    f"victim request dropped: {r!r}"
                assert r.finished == "complete" \
                    and len(r.tokens) == MAX_NEW, (r.finished, r.tokens)
                steps = rec["token_steps"]
                ttfts.append(steps[0] - rec["submit"])
                gaps.extend(b - a for a, b in zip(steps, steps[1:]))
                tokens.append((key, tuple(r.tokens)))
            for h in flood_handles:
                h.result(1.0)  # admitted flood work completes or sheds
            victim_shed = engine._m_tenant_shed.value_of(
                tenant="victim", reason="tenant_quota")
            flood_metric = engine._m_tenant_shed.value_of(
                tenant="flood", reason="tenant_quota")
            prom_text = engine.metrics.render()
            return {"ttfts": tuple(sorted(ttfts)),
                    "gaps": tuple(sorted(gaps)),
                    "tokens": tuple(tokens),
                    "flood_shed": flood_shed[0],
                    "flood_shed_metric": flood_metric,
                    "victim_shed_metric": victim_shed,
                    "prom_text": prom_text}
        finally:
            _monitoring_impl._unregister_event_listener_by_callback(
                _compile_listener)
            engine.close()

    def p(xs, q):
        return xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.999))]

    # one seed = three full engine phases (solo, flooded, bitwise
    # replay) — the isolation + replay assertions are per-seed, and
    # this scenario rides the tier-1 fast matrix, so wall time matters
    seeds = [7]
    shed_events_before = len(
        events_mod.default_log().events("tenant_shed"))
    totals = {"victim_requests": 0, "flood_shed": 0,
              "ttft_ratio_max": 0.0, "gap_ratio_max": 0.0}
    for seed in seeds:
        solo = run_phase(seed, flood=False)
        noisy = run_phase(seed, flood=True)
        # victim content is interference-proof
        assert noisy["tokens"] == solo["tokens"], (
            f"seed {seed}: flood changed victim tokens")
        # pinned latency budget: TTFT p95 and decode-gap p99, in steps
        ttft_ratio = p(noisy["ttfts"], 0.95) / max(1, p(solo["ttfts"],
                                                        0.95))
        gap_ratio = p(noisy["gaps"], 0.99) / max(1, p(solo["gaps"],
                                                      0.99))
        assert ttft_ratio <= RATIO, (
            f"seed {seed}: victim TTFT p95 {ttft_ratio:.2f}x solo "
            f"(budget {RATIO}x): {noisy['ttfts']} vs {solo['ttfts']}")
        assert gap_ratio <= RATIO, (
            f"seed {seed}: victim decode-gap p99 {gap_ratio:.2f}x solo "
            f"(budget {RATIO}x): {noisy['gaps']} vs {solo['gaps']}")
        # the flood was actually adversarial, and observably shed
        assert noisy["flood_shed"] >= 1, "flood never hit its quota"
        assert noisy["flood_shed_metric"] >= noisy["flood_shed"], (
            "per-tenant shed counter missed submissions")
        assert noisy["victim_shed_metric"] == 0, (
            "victim was quota-shed — isolation broken")
        assert ('serving_tenant_shed_total{reason="tenant_quota",'
                'tenant="flood"}') in noisy["prom_text"], (
            "per-tenant shed series missing from the Prometheus text")
        # bitwise seeded replay of the full flooded run
        replay = run_phase(seed, flood=True)
        for k in ("ttfts", "gaps", "tokens", "flood_shed"):
            assert replay[k] == noisy[k], (
                f"seed {seed}: {k} not deterministic")
        totals["victim_requests"] += len(noisy["tokens"])
        totals["flood_shed"] += noisy["flood_shed"]
        totals["ttft_ratio_max"] = max(totals["ttft_ratio_max"],
                                       round(ttft_ratio, 3))
        totals["gap_ratio_max"] = max(totals["gap_ratio_max"],
                                      round(gap_ratio, 3))
    shed_events = len(events_mod.default_log().events("tenant_shed")) \
        - shed_events_before
    assert shed_events >= totals["flood_shed"], \
        "tenant_shed events missing"
    assert compiles == [], f"post-warmup XLA compiles: {compiles}"
    return {"seeds": seeds, "deterministic_replays": len(seeds),
            "pinned_ratio": RATIO, "victim_dropped": 0,
            "post_warmup_compiles": 0,
            "tenant_shed_events": shed_events, **totals,
            "faults_fired": {"tenant.flood": totals["flood_shed"]}}


# scenario name -> (fault plan armed via PERCEIVER_FAULTS, fn)
_SCENARIOS = {
    "loader_crash": ("loader.exception@at=1,count=2",
                     scenario_loader_crash),
    "nan_skip": ("train.nonfinite@at=2,count=2", scenario_nan_skip),
    "nan_rewind": ("train.nonfinite@at=3,count=5", scenario_nan_rewind),
    "truncated_ckpt": ("ckpt.truncate@at=1", scenario_truncated_ckpt),
    "kill_save": (None, scenario_kill_save),
    "kill_save_victim": (None, scenario_kill_save_victim),  # internal
    "preempt": ("train.preempt@at=3", scenario_preempt),
    "serve_dispatch": ("serve.dispatch@at=1,count=4",
                       scenario_serve_dispatch),
    # race_* arm no fault plan: the "fault" is the adversarial thread
    # interleaving itself (racecheck runtime harness)
    "race_admission": (None, scenario_race_admission),
    "race_mixed_prefill": (None, scenario_race_mixed_prefill),
    # the "fault" is page pressure: a unique-prefix flood that can
    # only admit by evicting the prefix index's LRU chains
    "prefix_evict_under_load": (None, scenario_prefix_evict_under_load),
    # the "fault" is a never-trained draft: ~0% acceptance forces the
    # speculative rollback path on every verify step
    "spec_reject_storm": (None, scenario_spec_reject_storm),
    # the "fault" is a quota-busting best-effort tenant flooding the
    # shared decode arena — isolation, not backpressure, absorbs it
    "noisy_neighbor": (None, scenario_noisy_neighbor),
    # fleet scenarios arm faults per-REPLICA (supervisor env overrides)
    # rather than in the scenario child, so the plan column stays None
    "fleet_kill_replica": (None, scenario_fleet_kill_replica),
    "fleet_stall": (None, scenario_fleet_stall),
    "fleet_rollout_corrupt": (None, scenario_fleet_rollout_corrupt),
    "fleet_rollout": (None, scenario_fleet_rollout),
    # dist scenarios likewise arm faults per-member (group supervisor /
    # fleet per_replica_env seams), never in the scenario child itself
    "dist_coordinator_loss": (None, scenario_dist_coordinator_loss),
    "dist_kill_train_host": (None, scenario_dist_kill_train_host),
    "dist_kill_serve_host": (None, scenario_dist_kill_serve_host),
    "dist_cutover_kill": (None, scenario_dist_cutover_kill),
}
_MATRIX = ["loader_crash", "nan_skip", "nan_rewind", "truncated_ckpt",
           "kill_save", "preempt", "serve_dispatch", "race_admission",
           "race_mixed_prefill", "prefix_evict_under_load",
           "spec_reject_storm", "noisy_neighbor"]
_FAST = ["nan_skip", "serve_dispatch", "race_admission",
         "race_mixed_prefill", "prefix_evict_under_load",
         "spec_reject_storm", "noisy_neighbor"]
_FLEET_MATRIX = ["fleet_kill_replica", "fleet_stall",
                 "fleet_rollout_corrupt", "fleet_rollout"]
_FLEET_FAST = ["fleet_kill_replica"]
_DIST_MATRIX = ["dist_coordinator_loss", "dist_kill_train_host",
                "dist_kill_serve_host", "dist_cutover_kill"]
_DIST_FAST = ["dist_cutover_kill"]


def _run_child(name: str, tmp: str) -> dict:
    plan, _ = _SCENARIOS[name]
    env = dict(os.environ, PERCEIVER_TPU_OFFLINE="1")
    env.pop("PERCEIVER_FAULTS", None)
    if plan:
        env["PERCEIVER_FAULTS"] = plan
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--scenario", name,
         "--tmp", tmp],
        env=env, capture_output=True, text=True, cwd=_REPO, timeout=900)
    if proc.returncode != 0:
        return {"survived": False,
                "error": proc.stderr.strip().splitlines()[-12:]}
    detail = json.loads(proc.stdout.strip().splitlines()[-1])
    detail["survived"] = True
    return detail


def main() -> int:
    ap = argparse.ArgumentParser(description="fault-matrix chaos runner")
    ap.add_argument("--fast", action="store_true",
                    help=f"tier-1 subset {_FAST} instead of the full "
                         "matrix")
    ap.add_argument("--fleet", action="store_true",
                    help=f"the fleet matrix {_FLEET_MATRIX} (multi-"
                         "process router/rollout/failover scenarios)")
    ap.add_argument("--fleet-fast", action="store_true",
                    help=f"tier-1 fleet subset {_FLEET_FAST}")
    ap.add_argument("--dist", action="store_true",
                    help=f"the multi-host matrix {_DIST_MATRIX} "
                         "(process-group training recovery, "
                         "coordinator loss, group-replica failover, "
                         "two-phase cutover kill)")
    ap.add_argument("--dist-fast", action="store_true",
                    help=f"tier-1 multi-host subset {_DIST_FAST}")
    ap.add_argument("--only", nargs="*", default=None,
                    help="run just these scenarios")
    ap.add_argument("--out", default=None,
                    help="also append the result lines to this path")
    ap.add_argument("--scenario", default=None, choices=sorted(_SCENARIOS),
                    help=argparse.SUPPRESS)  # internal: child mode
    ap.add_argument("--tmp", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.scenario:
        # child mode: the fault plan (if any) was armed from the env at
        # import; run one scenario and emit its JSON detail
        from perceiver_tpu.resilience import faults

        detail = _SCENARIOS[args.scenario][1](args.tmp)
        # fleet scenarios report fired counts gathered from their
        # replica processes; don't clobber them with this process's
        detail.setdefault("faults_fired", faults.counts())
        print(json.dumps(detail, default=str), flush=True)
        return 0

    if args.fleet:
        names = _FLEET_MATRIX
    elif args.fleet_fast:
        names = _FLEET_FAST
    elif args.dist:
        names = _DIST_MATRIX
    elif args.dist_fast:
        names = _DIST_FAST
    else:
        names = args.only or (_FAST if args.fast else _MATRIX)
    unknown = [n for n in names
               if n not in _SCENARIOS or n == "kill_save_victim"]
    if unknown:
        ap.error(f"unknown scenario(s) {unknown}")
    results, ok = [], True
    for name in names:
        if name.startswith("race_"):
            default = "adversarial interleaving (seeded scheduler)"
        elif name == "prefix_evict_under_load":
            default = "page pressure (unique-prefix flood)"
        else:
            default = "kill -9 (grand-child)"
        fault = _SCENARIOS[name][0] or default
        print(f"[chaos] {name}: injecting {fault} ...",
              file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        with tempfile.TemporaryDirectory(prefix=f"chaos-{name}-") as tmp:
            detail = _run_child(name, tmp)
        detail["wall_s"] = round(time.perf_counter() - t0, 2)
        survived = detail.pop("survived")
        ok = ok and survived
        line = {"metric": f"chaos_{name}",
                "value": 1.0 if survived else 0.0, "unit": "survived",
                "vs_baseline": None, "detail": detail}
        results.append(line)
        print(json.dumps(line), flush=True)
    summary = {"metric": "chaos_matrix",
               "value": round(sum(r["value"] for r in results)
                              / max(len(results), 1), 3),
               "unit": "fraction_survived", "vs_baseline": None,
               "detail": {"scenarios": len(results),
                          "fast": bool(args.fast)}}
    results.append(summary)
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "a") as f:
            for line in results:
                f.write(json.dumps(line) + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
