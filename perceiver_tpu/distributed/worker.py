"""Group-member entrypoint: one training (or rendezvous) process.

``python -m perceiver_tpu.distributed.worker --spec spec.json --rank R
--nproc N --coordinator H:P --generation G`` is what
:class:`~perceiver_tpu.distributed.group.GroupSupervisor` spawns per
member. The spec file is the job description; rank / coordinator /
generation are the supervisor's per-spawn slot assignment.

Two modes (``spec["mode"]``):

- ``bootstrap_only`` — rendezvous with the coordinator, assert the
  group formed (``jax.process_count() == nproc``), exit 0. No
  collectives are issued, so this runs on CPU backends whose cluster
  formation works but whose cross-process computations don't (the
  probe in ``tests/conftest.py``) — it is the chaos harness's
  coordinator-loss scenario.
- ``train`` — run the tiny-preset trainer with the full resilience
  stack armed: sha256-verified anchors every
  ``guard_anchor_every_n_steps`` into the generation's anchor
  directory, and on generation > 0 resume from the NEWEST anchor any
  previous generation left (``resume_step_replay`` repositions the
  epoch-seeded stream at the restored step, so the resumed loss curve
  is bitwise-identical to an uninterrupted run — the
  ``dist_kill_train_host`` chaos assertion).

Exit codes: 0 success; 77 typed rendezvous timeout (the supervisor
and the chaos harness match on it); anything else is a crash the
supervisor answers with a group re-form.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

RENDEZVOUS_EXIT = 77


def _newest_anchor_dir(anchors_root: str, generation: int) -> str:
    """Newest previous generation's anchor dir that holds at least one
    committed step ('' if none) — the resume source after a re-form."""
    best = ""
    for g in range(generation):
        d = os.path.join(anchors_root, f"g{g}")
        if os.path.isdir(d) and any(s.isdigit() for s in os.listdir(d)):
            best = d
    return best


def _run_train(spec: dict, args, workdir: str) -> dict:
    from perceiver_tpu.data import MNISTDataModule
    from perceiver_tpu.training import Trainer, TrainerConfig
    from perceiver_tpu.tasks import ImageClassifierTask

    task = ImageClassifierTask(
        image_shape=(28, 28, 1), num_classes=10, num_frequency_bands=4,
        num_latents=4, num_latent_channels=16, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_decoder_cross_attention_heads=1)
    dm = MNISTDataModule(
        data_dir=os.path.join(workdir, "data"),
        batch_size=int(spec.get("batch_size", 16)),
        synthetic_train_size=int(spec.get("train_size", 96)),
        synthetic_test_size=32)
    anchors_root = os.path.join(workdir, "anchors")
    resume = _newest_anchor_dir(anchors_root, args.generation)
    cfg = TrainerConfig(
        max_steps=int(spec.get("max_steps", 6)), max_epochs=8,
        num_sanity_val_steps=0, log_every_n_steps=1,
        default_root_dir=os.path.join(workdir,
                                      f"logs_g{args.generation}"),
        enable_checkpointing=False,
        prefetch_batches=int(spec.get("prefetch_batches", 0)),
        nonfinite_policy="skip",
        guard_anchor_every_n_steps=int(
            spec.get("guard_anchor_every_n_steps", 2)),
        guard_anchor_dir=os.path.join(anchors_root,
                                      f"g{args.generation}"),
        resume_from_checkpoint=resume or None,
        resume_step_replay=True,
        telemetry_dir=os.path.join(workdir, "telemetry",
                                   f"g{args.generation}"),
        seed=int(spec.get("seed", 42)))
    trainer = Trainer(task, dm, cfg,
                      optimizer_init={"class_path": "AdamW",
                                      "init_args": {"lr": 1e-3}})
    state = trainer.fit()
    return {"final_step": int(state.step),
            "resumed_from": resume,
            "generation": args.generation}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--spec", required=True)
    parser.add_argument("--rank", type=int, required=True)
    parser.add_argument("--nproc", type=int, required=True)
    parser.add_argument("--coordinator", required=True)
    parser.add_argument("--generation", type=int, default=0)
    args = parser.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    # zero-egress default: synthetic datasets, never a download stall
    os.environ.setdefault("PERCEIVER_TPU_OFFLINE", "1")

    from perceiver_tpu.distributed import bootstrap

    config = bootstrap.DistributedConfig(
        coordinator_address=args.coordinator,
        num_processes=args.nproc, process_id=args.rank,
        rendezvous_timeout_s=float(
            spec.get("rendezvous_timeout_s", 60.0)))
    try:
        bootstrap.initialize(config)
    except bootstrap.RendezvousTimeout as e:
        print(f"RENDEZVOUS_TIMEOUT {e}", file=sys.stderr, flush=True)
        # hard exit: the abandoned rendezvous thread's gRPC client
        # LOG(FATAL)s (SIGABRT) when its own deadline expires during
        # interpreter teardown, clobbering the typed exit code — skip
        # teardown entirely (the timeout event is already on disk)
        os._exit(RENDEZVOUS_EXIT)

    import jax

    workdir = spec.get("workdir") or os.path.dirname(
        os.path.abspath(args.spec))
    if spec.get("mode") == "bootstrap_only":
        # cluster must actually have formed — process_count is served
        # by the coordinator, no collective involved
        assert jax.process_count() == args.nproc, \
            (jax.process_count(), args.nproc)
        result = {"process_count": jax.process_count(),
                  "process_id": jax.process_index()}
    else:
        result = _run_train(spec, args, workdir)
    out = os.path.join(
        workdir, f"result.g{args.generation}.r{args.rank}.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(f"DONE rank={args.rank} {json.dumps(result)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
