"""Best-effort dataset download (reference ``data/imdb.py:92-94`` /
torchvision MNIST semantics: fetch when absent, behind the same
datamodule surface).

Zero-egress environments are first-class: every fetch is wrapped, uses
a short connect timeout, and returns False on any failure so callers
fall back (to local files or synthetic data) instead of crashing.
``PERCEIVER_TPU_OFFLINE=1`` skips attempts entirely.
"""

from __future__ import annotations

import os
import shutil
import tarfile


def offline() -> bool:
    return os.environ.get("PERCEIVER_TPU_OFFLINE", "") not in ("", "0")


def fetch(url: str, dest: str, timeout: float = 15.0) -> bool:
    """Download ``url`` to ``dest`` atomically. False on any failure.
    The temp name is per-process so concurrent callers (multi-host
    runs sharing a data_dir) never interleave writes; last finished
    rename wins, each with a complete file."""
    if offline():
        return False
    tmp = f"{dest}.part.{os.getpid()}"
    try:
        import urllib.request
        with urllib.request.urlopen(url, timeout=timeout) as r, \
                open(tmp, "wb") as f:
            shutil.copyfileobj(r, f)
        os.replace(tmp, dest)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def extract_tgz(path: str, dest_dir: str) -> bool:
    """Extract a .tar.gz safely (no paths escaping ``dest_dir``).
    On failure the archive is deleted so the next run re-fetches
    instead of being stuck on a corrupt cached file."""
    try:
        with tarfile.open(path, "r:gz") as tf:
            tf.extractall(dest_dir, filter="data")
        return True
    except Exception:
        try:
            os.unlink(path)
        except OSError:
            pass
        return False
