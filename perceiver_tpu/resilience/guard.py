"""Non-finite step guard: one detection path, three policies
(docs/RESILIENCE.md).

A non-finite loss means the gradients — and after ``apply_updates``
the parameters — are garbage; without a guard one bad batch poisons
the run permanently and every later checkpoint silently (the
``trainer.py`` failure mode this module removes). The guard has two
halves that share one detection signal, the per-step loss:

* **Device half** (``wrap_train_step``/``wrap_train_step_multi``):
  the train step is wrapped so params and optimizer state only
  advance when the step's loss is finite — a bad step consumes its
  batch and advances rng/step but applies no update. The wrappers
  also thread every step's loss out of the dispatch (shape ``(K,)``
  under ``steps_per_execution``), so the host sees *which* step in a
  scanned block went bad, not just the block mean. Only armed
  configurations compile these wrappers; with the guard off the
  trainer jits the pristine step functions and the lowered graphs are
  byte-identical to before.

* **Host half** (:class:`StepGuard`): consumes the per-step losses
  after each dispatch and applies the policy —

  - ``halt``: raise :class:`NonFiniteLossError` naming the first bad
    step (the ``terminate_on_nan`` semantics, now exact inside
    multi-step blocks);
  - ``skip``: count isolated bad steps (the device half already
    skipped their updates); on ``streak_to_rewind`` consecutive bad
    steps, request a rewind — the trainer restores the last-good
    anchor checkpoint and replays the data stream deterministically.
    After ``max_rewinds`` rewinds the guard halts: persistent
    non-finite losses are a bug, not weather.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_tpu.obs import events as events_mod

OFF = "off"
HALT = "halt"
SKIP = "skip"
POLICIES = (OFF, HALT, SKIP)

#: observe() results
OK = "ok"
REWIND = "rewind"


class NonFiniteLossError(FloatingPointError):
    """Typed halt: the run must not continue training on garbage."""

    def __init__(self, step: int, detail: str = "terminate_on_nan"):
        super().__init__(f"Non-finite loss at step {step} ({detail})")
        self.step = step


def wrap_train_step(train_step):
    """Guarded single step: apply ``train_step`` but keep the previous
    params/opt_state when the step's loss is non-finite (rng and the
    step counter still advance — the batch was consumed). Returns
    ``(state, metrics, losses)`` with ``losses`` shape ``(1,)``."""

    def guarded(state, batch):
        new_state, metrics = train_step(state, batch)
        ok = jnp.isfinite(metrics["loss"])

        def sel(new, old):
            return jnp.where(ok, new, old)

        merged = dataclasses.replace(
            new_state,
            params=jax.tree.map(sel, new_state.params, state.params),
            opt_state=jax.tree.map(sel, new_state.opt_state,
                                   state.opt_state))
        return merged, metrics, metrics["loss"][None]

    return guarded


def wrap_train_step_multi(train_step):
    """Guarded K-step scan: each inner step individually guarded, the
    per-step losses threaded out so the host can attribute a bad step
    inside the block. Returns ``(state, mean_metrics, losses)`` with
    ``losses`` shape ``(K,)``."""
    single = wrap_train_step(train_step)

    def scan_body(state, batch):
        state, metrics, _ = single(state, batch)
        return state, metrics

    def guarded_multi(state, stacked):
        state, metrics = jax.lax.scan(scan_body, state, stacked)
        return (state, jax.tree.map(lambda m: m.mean(0), metrics),
                metrics["loss"])

    return guarded_multi


class StepGuard:
    """Host-side policy over per-step losses (see module docstring)."""

    def __init__(self, policy: str, streak_to_rewind: int = 3,
                 max_rewinds: int = 2):
        if policy not in (HALT, SKIP):
            raise ValueError(f"guard policy {policy!r} not in "
                             f"{(HALT, SKIP)}")
        if streak_to_rewind < 1 or max_rewinds < 0:
            raise ValueError("streak_to_rewind >= 1 and "
                             "max_rewinds >= 0 required")
        self.policy = policy
        self.streak_to_rewind = streak_to_rewind
        self.max_rewinds = max_rewinds
        self.skipped_total = 0
        self.rewinds = 0
        self._streak = 0

    def observe(self, losses, first_step: int) -> str:
        """Apply the policy to one dispatch's per-step losses.
        ``first_step`` is the global step *before* the dispatch, so
        step numbers in errors/metrics are exact. Returns ``OK`` or
        ``REWIND``; raises :class:`NonFiniteLossError` on halt or an
        exhausted rewind budget."""
        losses = np.atleast_1d(np.asarray(losses))
        for i, value in enumerate(losses):
            step = first_step + i + 1
            if np.isfinite(value):
                self._streak = 0
                continue
            if self.policy == HALT:
                raise NonFiniteLossError(step)
            self.skipped_total += 1
            events_mod.emit("guard_skip", step=step)
            self._streak += 1
            if self._streak >= self.streak_to_rewind:
                if self.rewinds >= self.max_rewinds:
                    raise NonFiniteLossError(
                        step,
                        detail=f"{self._streak} consecutive bad steps "
                               f"after {self.rewinds} rewind(s) — "
                               "rewind budget exhausted")
                self.rewinds += 1
                self._streak = 0
                events_mod.emit("guard_rewind", step=step)
                return REWIND
        return OK
