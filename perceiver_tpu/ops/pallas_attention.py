"""Fused flash-attention Pallas kernel for TPU.

Single-pass online-softmax attention (FlashAttention recurrence) as a
Pallas TPU kernel: for each query block, key/value blocks stream
HBM → VMEM along the innermost grid dimension while running max ``m``,
normalizer ``l``, and unnormalized output ``acc`` live in VMEM scratch.
The (Lq, Lk) logit matrix never hits HBM — softmax, masking, and both
matmuls fuse in one kernel, so HBM traffic is O(Lq·D + Lk·D) instead
of O(Lq·Lk).

This is the hot-op kernel for the encoder cross-attention at large
input length M (reference ``model.py:150-160``): the 512×512 LArTPC
config cross-attends 32 latents against M = 262,144 inputs
(``run.py:79``), and the seq-2048 MLM config (BASELINE.md configs[4])
streams 2048 kv tokens per layer.

Grid layout: ``(B, H, num_q_blocks, num_kv_blocks)`` — the kv axis is
innermost because TPU grids execute sequentially, which is what makes
carrying (m, l, acc) across kv steps in scratch legal.

Two block layouts, selected by head dim:

- standard (``D > 32``): blocks are (L, D) with D padded to 128 lanes.
- transposed (``D <= 32``): blocks are (D, L) — every 64-channel/
  4-head BASELINE config has head dim 16, which the standard layout
  would pad 8x in the lane axis; putting the huge kv axis on lanes and
  the skinny head dim on sublanes (padded only to 16) cuts kv HBM
  traffic ~8x. The (B,H,L,D) -> (B,H,D,L) relayout happens outside the
  kernel, where XLA fuses it into the producing projection matmuls.

Masking is an additive fp32 key bias ``(B, Lk)`` (``NEG_INF`` at
padding), matching the einsum path's ``key_padding_mask`` semantics.
Attention-weight dropout is not supported here (the reference default
is dropout 0.0, ``lightning.py:40``); the einsum path covers the
dropout>0 case.

Backward pass: ``jax.custom_vjp`` whose reverse recomputes attention
with the blockwise-scan implementation
(``perceiver_tpu.ops.chunked_attention``) — exact, and memory-bounded
like the forward.

On non-TPU backends the kernel runs in Pallas interpreter mode, so
tests exercise the identical code path on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from perceiver_tpu.ops.tiling import round_up as _round_up

from perceiver_tpu.ops.chunked_attention import NEG_INF, chunked_attention
from perceiver_tpu.ops.online_softmax import (
    online_softmax_finish,
    online_softmax_init,
    online_softmax_update,
)


def _flash_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, scale: float, nk: int):
    ib = pl.program_id(0)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _():
        online_softmax_init(m_ref, l_ref, acc_ref)

    q = q_ref[0, 0]  # (block_q, Dp)
    k = k_ref[0, 0]  # (block_k, Dp)
    v = v_ref[0, 0]  # (block_k, Dp)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # (block_q, block_k)
    # bias block spans the whole batch (Mosaic requires the sublane dim
    # be 8-divisible or full); select this program's row dynamically
    s = s + bias_ref[pl.ds(ib, 1), :]

    online_softmax_update(s, v, m_ref, l_ref, acc_ref)

    @pl.when(ik == nk - 1)
    def _():
        o_ref[0, 0] = online_softmax_finish(
            m_ref, l_ref, acc_ref).astype(o_ref.dtype)


def _flash_forward(q, k, v, bias, scale: float,
                   block_q: int, block_k: int, interpret: bool):
    b, h, lq, d = q.shape
    lk = k.shape[2]

    # Pad to hardware-friendly tiles. Zero-padding D leaves logits and
    # outputs unchanged; padded kv columns are killed by NEG_INF bias;
    # padded query rows are sliced off after.
    dp = _round_up(d, 128)
    # 16-sublane rounding covers the strictest dtype tile (bf16 needs
    # sublane multiples of 16; fp32 needs 8 — 16 satisfies both), e.g.
    # the 1-query classification decoder under impl="flash"
    block_q = min(block_q, _round_up(lq, 16))
    block_k = _round_up(min(block_k, _round_up(lk, 128)), 128)
    lq_p = _round_up(lq, block_q)
    lk_p = _round_up(lk, block_k)

    q = jnp.pad(q, ((0, 0), (0, 0), (0, lq_p - lq), (0, dp - d)))
    k = jnp.pad(k, ((0, 0), (0, 0), (0, lk_p - lk), (0, dp - d)))
    v = jnp.pad(v, ((0, 0), (0, 0), (0, lk_p - lk), (0, dp - d)))
    if bias is None:
        bias = jnp.zeros((b, lk), jnp.float32)
    bias = jnp.pad(bias.astype(jnp.float32), ((0, 0), (0, lk_p - lk)),
                   constant_values=NEG_INF)

    nq, nk = lq_p // block_q, lk_p // block_k
    grid = (b, h, nq, nk)

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dp),
                         lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, block_k, dp),
                         lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, block_k, dp),
                         lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
            pl.BlockSpec((b, block_k),
                         lambda ib, ih, iq, ik: (0, ik)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, dp),
                               lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, lq_p, dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),   # normalizer l
            pltpu.VMEM((block_q, dp), jnp.float32),    # unnormalized acc
        ],
        interpret=interpret,
    )(q, k, v, bias)
    return out[:, :, :lq, :d]


def _flash_kernel_t(q_ref, k_ref, v_ref, bias_ref, o_ref,
                    m_ref, l_ref, acc_ref, *, scale: float, nk: int):
    """Transposed-layout kernel: q/k/v/o are (..., D, L) so the HUGE
    kv axis is the 128-lane minor dim and the skinny head dim (16 for
    every 64-channel/4-head BASELINE config) rides the sublane axis
    unpadded — 8x less HBM traffic than padding D up to 128 lanes."""
    ib = pl.program_id(0)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    qt = q_ref[0, 0]  # (Dp, block_q)
    kt = k_ref[0, 0]  # (Dp, block_k)
    vt = v_ref[0, 0]  # (Dp, block_k)

    s = jax.lax.dot_general(
        qt, kt, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # (block_q, block_k)
    s = s + bias_ref[pl.ds(ib, 1), :]

    m_prev = m_ref[:, :1]                                # (block_q, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)

    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
    # acc wants q on the LANE axis; softmax stats have q on SUBLANE.
    # Cross the orientations with one tile-aligned (block_q, 128) →
    # (128, block_q) transpose per kv step (a standard Mosaic relayout;
    # both dims are tile multiples, unlike a (block_q, 1) vector); its
    # rows are all identical, so row 0 broadcasts to any Dp.
    alpha_t = jax.lax.transpose(
        jnp.broadcast_to(alpha, (alpha.shape[0], 128)), (1, 0))
    acc_ref[:] = (acc_ref[:]
                  * jnp.broadcast_to(alpha_t[:1], acc_ref.shape)
                  + jax.lax.dot_general(
                      vt, p.astype(vt.dtype), (((1,), (1,)), ((), ())),
                      preferred_element_type=jnp.float32))  # (Dp, block_q)

    @pl.when(ik == nk - 1)
    def _():
        l_t = jax.lax.transpose(l_ref[:], (1, 0))        # (128, block_q)
        o_ref[0, 0] = (acc_ref[:] /
                       jnp.maximum(jnp.broadcast_to(l_t[:1],
                                                    acc_ref.shape),
                                   1e-30)).astype(o_ref.dtype)


def _flash_forward_t(q, k, v, bias, scale: float,
                     block_q: int, block_k: int, interpret: bool):
    """Forward via the transposed kernel. Takes standard (B, H, L, D)
    arrays; the (D, L) relayout happens outside the kernel where XLA
    fuses it into the producing projection matmuls."""
    b, h, lq, d = q.shape
    lk = k.shape[2]

    # sublane-pad D to the strictest tile (16 covers bf16 and fp32);
    # lane-pad both L axes to their block sizes. Both L blocks are the
    # MINOR dim of their arrays here, so Mosaic requires them to be
    # 128-multiples — round the user's block_q UP (the standard layout
    # only needs sublane-rounding for it).
    dp = _round_up(d, 16)
    block_q = _round_up(min(block_q, _round_up(lq, 128)), 128)
    block_k = _round_up(min(block_k, _round_up(lk, 128)), 128)
    lq_p = _round_up(lq, block_q)
    lk_p = _round_up(lk, block_k)

    qt = jnp.pad(q.swapaxes(2, 3), ((0, 0), (0, 0), (0, dp - d),
                                    (0, lq_p - lq)))
    kt = jnp.pad(k.swapaxes(2, 3), ((0, 0), (0, 0), (0, dp - d),
                                    (0, lk_p - lk)))
    vt = jnp.pad(v.swapaxes(2, 3), ((0, 0), (0, 0), (0, dp - d),
                                    (0, lk_p - lk)))
    if bias is None:
        bias = jnp.zeros((b, lk), jnp.float32)
    bias = jnp.pad(bias.astype(jnp.float32), ((0, 0), (0, lk_p - lk)),
                   constant_values=NEG_INF)

    nq, nk = lq_p // block_q, lk_p // block_k
    grid = (b, h, nq, nk)

    out = pl.pallas_call(
        functools.partial(_flash_kernel_t, scale=scale, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, dp, block_q),
                         lambda ib, ih, iq, ik: (ib, ih, 0, iq)),
            pl.BlockSpec((1, 1, dp, block_k),
                         lambda ib, ih, iq, ik: (ib, ih, 0, ik)),
            pl.BlockSpec((1, 1, dp, block_k),
                         lambda ib, ih, iq, ik: (ib, ih, 0, ik)),
            pl.BlockSpec((b, block_k),
                         lambda ib, ih, iq, ik: (0, ik)),
        ],
        out_specs=pl.BlockSpec((1, 1, dp, block_q),
                               lambda ib, ih, iq, ik: (ib, ih, 0, iq)),
        out_shape=jax.ShapeDtypeStruct((b, h, dp, lq_p), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),   # normalizer l
            pltpu.VMEM((dp, block_q), jnp.float32),    # acc, q on lanes
        ],
        interpret=interpret,
    )(qt, kt, vt, bias)
    return out[:, :, :d, :lq].swapaxes(2, 3)


# D at or below this uses the transposed kernel: the padding ratio
# 128/D makes the standard layout waste >=4x HBM bandwidth on kv
_SKINNY_D = 32


def _pick_layout(d: int) -> str:
    """'transposed' or 'standard'; PERCEIVER_TPU_FLASH_LAYOUT overrides
    the D-based auto choice (for on-chip A/B benchmarking)."""
    import os
    env = os.environ.get("PERCEIVER_TPU_FLASH_LAYOUT", "auto")
    if env in ("standard", "transposed"):
        return env
    if env != "auto":
        # a typo'd override would silently measure the auto layout in
        # both arms of a chip-time A/B — reject like any other config
        raise ValueError(
            f"PERCEIVER_TPU_FLASH_LAYOUT={env!r}; expected 'auto', "
            "'standard', or 'transposed'")
    return "transposed" if d <= _SKINNY_D else "standard"


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, bias, scale, block_q, block_k, interpret):
    return _flash_forward_any(q, k, v, bias, scale, block_q, block_k,
                              interpret)


def _flash_forward_any(q, k, v, bias, scale, block_q, block_k, interpret):
    if _pick_layout(q.shape[-1]) == "transposed":
        return _flash_forward_t(q, k, v, bias, scale, block_q, block_k,
                                interpret)
    return _flash_forward(q, k, v, bias, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, bias, scale, block_q, block_k, interpret):
    out = _flash_forward_any(q, k, v, bias, scale, block_q, block_k,
                             interpret)
    return out, (q, k, v, bias)


def _flash_bwd(scale, block_q, block_k, interpret, res, g):
    q, k, v, bias = res
    # Exact recompute through the blockwise scan — backward stays
    # memory-bounded on BOTH axes: kv streams through the scan
    # (rematerialized), and the query axis is blocked like the forward
    # kernel grid (matters for the 262k-query decoder config).
    if bias is None:
        _, vjp = jax.vjp(
            lambda a, b_, c: chunked_attention(
                a, b_, c, scale=scale, chunk_size=block_k,
                q_chunk_size=block_q * 8),
            q, k, v)
        return (*vjp(g), None)
    # bias is differentiable (a learned additive key bias trains the
    # same under impl="flash" as under "chunked"/"einsum")
    _, vjp = jax.vjp(
        lambda a, b_, c, bi: chunked_attention(
            a, b_, c, bias=bi, scale=scale, chunk_size=block_k,
            q_chunk_size=block_q * 8),
        q, k, v, bias)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, bias: Optional[jax.Array] = None,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 512,
                    interpret: Optional[bool] = None):
    """Fused attention. q: (B, H, Lq, D); k, v: (B, H, Lk, D);
    bias: optional (B, Lk) additive key bias (NEG_INF at padding).
    Returns (B, H, Lq, D) in q's dtype."""
    from perceiver_tpu.utils.platform import (
        assume_tpu_target,
        is_tpu_platform,
    )
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        # see pallas_ce: plugin TPU backends ("axon") must not fall
        # into interpreter mode on the real chip
        interpret = not (is_tpu_platform(jax.default_backend())
                         or assume_tpu_target())
    return _flash(q, k, v, bias, float(scale), int(block_q), int(block_k),
                  bool(interpret))
