#!/bin/bash
# Probe the axon TPU backend every ~4 min with a hard timeout; append
# a timestamped status line per attempt. Exits when the backend is up.
LOG=${1:-/root/repo/logs/tpu_probe.log}
mkdir -p "$(dirname "$LOG")"
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 120 python -c "import jax; d=jax.devices(); print('OK', len(d), d[0].platform)" 2>&1 | tail -1)
  echo "$ts $out" >> "$LOG"
  # require the axon/tpu platform explicitly: jax can fall back to the
  # CPU backend and still print OK when the tunnel is down
  if [[ "$out" == OK* && ( "$out" == *axon* || "$out" == *tpu* ) ]]; then
    echo "$ts TPU BACKEND UP" >> "$LOG"
    exit 0
  fi
  sleep 240
done
