"""Training telemetry: per-step JSONL + the serving registry types.

The trainer already syncs its metrics to host at the logging boundary
(``crossed_log`` in ``training/trainer.py``) — the :class:`Telemetry`
sink rides that boundary, so telemetry adds ZERO extra device syncs:
it receives already-host floats and writes one JSONL line per logged
step plus ``training_*`` series in a
:class:`~perceiver_tpu.serving.metrics.MetricsRegistry` (same types as
serving, so the exposition conformance tests and the lint conventions
cover both planes with one rule set).

Profiling: :func:`install_signal_profiler` arms SIGUSR1 so a running
trainer can be told to capture ``jax.profiler`` traces without a
restart (first signal starts, second stops — or the bounded-duration
watchdog stops it); the serving side gets the same capability over
HTTP (``/profile?seconds=N`` in :mod:`perceiver_tpu.obs.server`).
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Optional

from perceiver_tpu.obs.events import EventLog
from perceiver_tpu.serving.metrics import MetricsRegistry

__all__ = ["Telemetry", "install_signal_profiler"]


class Telemetry:
    """Per-step training telemetry sink (JSONL + metrics registry)."""

    def __init__(self, out_dir: str, *,
                 registry: Optional[MetricsRegistry] = None,
                 max_bytes: int = 4 << 20, max_backups: int = 3):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.path = os.path.join(out_dir, "telemetry.jsonl")
        self._log = EventLog(self.path, max_bytes=max_bytes,
                             max_backups=max_backups)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        m = self.registry
        self._m_steps = m.counter(
            "training_steps_total", "optimizer steps completed")
        self._m_loss = m.gauge(
            "training_loss", "last logged training loss")
        self._m_steps_per_sec = m.gauge(
            "training_steps_per_second", "optimizer steps per second")
        self._m_samples_per_sec = m.gauge(
            "training_samples_per_second", "training throughput")
        self._m_tokens_per_sec = m.gauge(
            "training_tokens_per_second", "token throughput")
        self._m_guard_skips = m.counter(
            "training_guard_skips_total", "non-finite steps skipped")
        self._m_rewinds = m.counter(
            "training_guard_rewinds_total",
            "rewinds to a verified anchor")
        self._m_seals = m.counter(
            "training_checkpoint_seals_total",
            "sha256-sealed checkpoints written")
        self._m_preempts = m.counter(
            "training_preempt_checkpoints_total",
            "preemption checkpoints written")

    def step(self, step: int, loss: float, *, steps_delta: int = 1,
             steps_per_sec: Optional[float] = None,
             samples_per_sec: Optional[float] = None,
             tokens_per_sec: Optional[float] = None, **extra) -> dict:
        """Record one logged step (values must already be host floats —
        never pass device arrays; the trainer syncs first)."""
        self._m_steps.inc(steps_delta)
        self._m_loss.set(loss)
        fields = {"step": int(step), "loss": float(loss)}
        if steps_per_sec is not None:
            self._m_steps_per_sec.set(steps_per_sec)
            fields["steps_per_sec"] = round(float(steps_per_sec), 4)
        if samples_per_sec is not None:
            self._m_samples_per_sec.set(samples_per_sec)
            fields["samples_per_sec"] = round(float(samples_per_sec), 4)
        if tokens_per_sec is not None:
            self._m_tokens_per_sec.set(tokens_per_sec)
            fields["tokens_per_sec"] = round(float(tokens_per_sec), 4)
        for k, v in extra.items():
            try:
                fields[k] = float(v)
            except (TypeError, ValueError):
                fields[k] = v
        return self._log.emit("train_step", **fields)

    def guard_skip(self, step: int, **fields) -> None:
        self._m_guard_skips.inc()
        self._log.emit("guard_skip", step=int(step), **fields)

    def guard_rewind(self, step: int, **fields) -> None:
        self._m_rewinds.inc()
        self._log.emit("guard_rewind", step=int(step), **fields)

    def checkpoint_seal(self, path: str) -> None:
        self._m_seals.inc()
        self._log.emit("checkpoint_seal", path=str(path))

    def preempt_checkpoint(self, step: int) -> None:
        self._m_preempts.inc()
        self._log.emit("preempt_checkpoint", step=int(step))

    def events(self, etype: Optional[str] = None):
        return self._log.events(etype)


def install_signal_profiler(profile_dir: str, *,
                            signum: int = signal.SIGUSR1,
                            max_seconds: float = 60.0,
                            event_log: Optional[EventLog] = None):
    """Arm ``signum`` to toggle a ``jax.profiler`` capture into
    ``profile_dir``.  Returns an ``uninstall()`` callable, or ``None``
    when handlers can't be installed (non-main thread).

    First signal starts the capture; a second signal — or a
    ``max_seconds`` watchdog — stops it, so a forgotten capture cannot
    fill the disk.
    """
    os.makedirs(profile_dir, exist_ok=True)
    state = {"active": False}
    lock = threading.Lock()

    def _stop(reason: str) -> None:
        with lock:
            if not state["active"]:
                return
            state["active"] = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # profiler drift — the toggle must survive
            return
        if event_log is not None:
            event_log.emit("profile_capture", dir=profile_dir,
                           reason=reason)

    def _handler(signo, frame):
        with lock:
            starting = not state["active"]
            state["active"] = starting
        if starting:
            try:
                import jax

                jax.profiler.start_trace(profile_dir)
            except Exception:  # profiler drift — the toggle must survive
                with lock:
                    state["active"] = False
                return
            threading.Timer(max_seconds,
                            lambda: _stop("watchdog")).start()
        else:
            with lock:  # _stop re-checks; restore for its guard
                state["active"] = True
            _stop("signal")

    try:
        prev = signal.signal(signum, _handler)
    except ValueError:  # not the main thread — profiling stays manual
        return None

    def uninstall() -> None:
        _stop("uninstall")
        signal.signal(signum, prev)

    return uninstall
