#!/usr/bin/env python
"""Bag-of-words control for the coherence corpus (VERDICT r2 #4).

The transfer-wins claim in QUALITY_r03_coherence.json rests on the
coherence labels NOT being solvable by surface lexical statistics (the
round-2 API-vs-prose labels were, which let scratch beat transfer).
This probe trains a hashed bag-of-words logistic regression — the
strongest pure-keyword model — on the corpus; at-chance accuracy is
the certificate that the label needs language understanding.

Usage: python scripts/bow_probe.py [--data .cache_coh]  → one JSON line
"""

import argparse
import glob
import json
import os
import re
import sys
import zlib

import numpy as np

D = 2 ** 15  # hashed vocab dim


def load(root: str, split: str):
    texts, y = [], []
    for label, yy in (("neg", 0), ("pos", 1)):
        for p in sorted(glob.glob(os.path.join(root, "aclImdb", split,
                                               label, "*.txt"))):
            with open(p, encoding="utf-8") as f:
                texts.append(f.read())
            y.append(yy)
    return texts, np.asarray(y)


def featurize(texts):
    m = np.zeros((len(texts), D), np.float32)
    for i, t in enumerate(texts):
        for w in re.findall(r"[a-z]+", t.lower()):
            # crc32: process-stable (python's hash() is salted)
            m[i, zlib.crc32(w.encode()) % D] += 1.0
        n = m[i].sum()
        if n:
            m[i] /= n
    return m


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=".cache_coh")
    ap.add_argument("--limit-train", type=int, default=0,
                    help="subset the train set to N examples "
                         "(balanced, seed 0) — the few-shot control")
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    xtr, ytr = load(args.data, "train")
    xte, yte = load(args.data, "test")
    if not len(ytr) or not len(yte):
        sys.exit(f"no corpus at {args.data}/aclImdb — an empty probe "
                 "result would be a meaningless certificate")
    if args.limit_train and args.limit_train < len(ytr):
        rng = np.random.default_rng(0)
        keep = np.concatenate([
            rng.permutation(np.flatnonzero(ytr == c))[:args.limit_train // 2]
            for c in (0, 1)])
        xtr = [xtr[i] for i in keep]
        ytr = ytr[keep]
    ftr, fte = featurize(xtr), featurize(xte)

    rng = np.random.default_rng(0)
    w = np.zeros(D, np.float32)
    b = 0.0
    idx = np.arange(len(ytr))
    for _ in range(args.epochs):
        rng.shuffle(idx)
        for s in range(0, len(idx), 64):
            j = idx[s:s + 64]
            p = 1.0 / (1.0 + np.exp(-(ftr[j] @ w + b)))
            g = p - ytr[j]
            w -= args.lr * (ftr[j].T @ g) / len(j)
            b -= args.lr * g.mean()

    out = {
        "probe": "hashed-BoW logistic regression",
        "dim": D,
        "data": args.data,
        "n_train": len(ytr),
        "n_test": len(yte),
        "train_acc": round(float((((ftr @ w + b) > 0) == ytr).mean()), 4),
        "test_acc": round(float((((fte @ w + b) > 0) == yte).mean()), 4),
        "chance": 0.5,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
