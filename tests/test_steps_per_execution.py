"""steps_per_execution: scanned multi-step dispatch vs single-step.

The scanned path must be a pure batching of the classic loop: same
number of optimizer steps, same rng chain (train_step splits
``state.rng`` per step whether driven by Python or ``lax.scan``), and
therefore numerically matching parameters.
"""

import dataclasses

import jax
import numpy as np

from perceiver_tpu.data import MNISTDataModule
from perceiver_tpu.training import Trainer, TrainerConfig

from tests.test_training import ADAMW, small_image_task


def _run(tmp_path, spe, tag, max_steps=-1, max_epochs=1):
    dm = MNISTDataModule(data_dir=str(tmp_path / "nope"), batch_size=16,
                         synthetic_train_size=96, synthetic_test_size=32)
    trainer = Trainer(
        small_image_task(), dm,
        TrainerConfig(max_epochs=max_epochs, max_steps=max_steps,
                      steps_per_execution=spe,
                      default_root_dir=str(tmp_path / f"logs_{tag}"),
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      log_every_n_steps=2, prefetch_batches=0),
        optimizer_init=ADAMW)
    state = trainer.fit()
    return trainer, state


def test_matches_single_step(tmp_path):
    t1, s1 = _run(tmp_path, 1, "s1")
    # 96 synthetic samples minus the val split = 5 train batches:
    # one full group of 3, then 2 trailing single steps
    t3, s3 = _run(tmp_path, 3, "s3")
    assert t1.global_step == t3.global_step == 5
    assert int(s1.step) == int(s3.step) == 5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        s1.params, s3.params)


def test_trailing_partial_group(tmp_path):
    """5 train batches with spe=4: one full group + 1 single step."""
    t, s = _run(tmp_path, 4, "s4")
    assert t.global_step == 5
    assert int(s.step) == 5


def test_max_steps_not_overshot(tmp_path):
    t, s = _run(tmp_path, 4, "cap", max_steps=5, max_epochs=3)
    assert t.global_step == 5
    assert int(s.step) == 5


def test_resume_at_max_steps_trains_zero_steps(tmp_path):
    """Resuming a run already at max_steps must not overtrain."""
    import os

    from perceiver_tpu.data import MNISTDataModule
    from perceiver_tpu.training import Trainer, TrainerConfig

    dm = MNISTDataModule(data_dir=str(tmp_path / "nope"), batch_size=16,
                         synthetic_train_size=96, synthetic_test_size=32)
    root = str(tmp_path / "logs_resume")
    cfg = TrainerConfig(max_steps=3, max_epochs=5,
                        default_root_dir=root, num_sanity_val_steps=0,
                        prefetch_batches=0)
    t1 = Trainer(small_image_task(), dm, cfg, optimizer_init=ADAMW)
    s1 = t1.fit()
    assert int(s1.step) == 3
    ckpt = os.path.join(t1.log_dir, "checkpoints")

    cfg2 = dataclasses.replace(cfg, resume_from_checkpoint=ckpt)
    t2 = Trainer(small_image_task(), dm, cfg2, optimizer_init=ADAMW)
    s2 = t2.fit()
    assert int(s2.step) == 3  # not 4: zero extra optimizer steps


def test_on_virtual_mesh(tmp_path):
    from perceiver_tpu.parallel import make_mesh
    dm = MNISTDataModule(data_dir=str(tmp_path / "nope"), batch_size=16,
                         synthetic_train_size=64, synthetic_test_size=32)
    trainer = Trainer(
        small_image_task(), dm,
        TrainerConfig(max_epochs=1, steps_per_execution=2,
                      default_root_dir=str(tmp_path / "logs_mesh"),
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      prefetch_batches=0),
        optimizer_init=ADAMW, mesh=make_mesh(8))
    state = trainer.fit()
    assert trainer.global_step == 3
    assert np.isfinite(
        float(jax.tree.leaves(state.params)[0].sum()))
