"""Tokenizer tests, incl. parity with the shipped HF tokenizer JSON."""

import json
import os

import pytest

from perceiver_tpu.tokenizer import (
    PAD_TOKEN_ID,
    SPECIAL_TOKENS,
    WordPieceTokenizer,
    create_tokenizer,
    train_tokenizer,
)
from perceiver_tpu.tokenizer.wordpiece import Replace

SHIPPED = "/root/reference/.cache/imdb-tokenizer-10003.json"


def test_special_token_ids():
    # reference tokenizer.py:10-19
    from perceiver_tpu.tokenizer import (PAD_TOKEN, UNK_TOKEN, MASK_TOKEN,
                                         UNK_TOKEN_ID, MASK_TOKEN_ID)
    assert (PAD_TOKEN, PAD_TOKEN_ID) == ("[PAD]", 0)
    assert (UNK_TOKEN, UNK_TOKEN_ID) == ("[UNK]", 1)
    assert (MASK_TOKEN, MASK_TOKEN_ID) == ("[MASK]", 2)
    assert SPECIAL_TOKENS == ["[PAD]", "[UNK]", "[MASK]"]


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
class TestShippedTokenizerParity:
    def setup_method(self):
        self.tok = WordPieceTokenizer.from_file(SHIPPED)

    def test_loads_vocab(self):
        assert self.tok.get_vocab_size() == 10003
        assert self.tok.token_to_id("[PAD]") == 0
        assert self.tok.token_to_id("[UNK]") == 1
        assert self.tok.token_to_id("[MASK]") == 2

    def test_encode_known_words(self):
        enc = self.tok.encode("This is a great movie!")
        assert all(i != 1 for i in enc.ids)  # no UNK for common words
        assert self.tok.decode(enc.ids) == "this is a great movie!"

    def test_normalizer_chain_replace_br(self):
        # IMDB passes Replace('<br />', ' ') (data/imdb.py:101)
        enc1 = self.tok.encode("good<br />movie")
        enc2 = self.tok.encode("good movie")
        assert enc1.ids == enc2.ids

    def test_normalizer_accents_and_case(self):
        enc1 = self.tok.encode("Café CRÈME")
        enc2 = self.tok.encode("cafe creme")
        assert enc1.ids == enc2.ids

    def test_wordpiece_continuation(self):
        # unusual word must split into ## pieces, not UNK
        enc = self.tok.encode("unbelievableness")
        assert len(enc.tokens) > 1
        assert any(t.startswith("##") for t in enc.tokens)
        assert "".join(t.removeprefix("##") for t in enc.tokens) \
            == "unbelievableness"

    def test_padding_and_truncation(self):
        self.tok.enable_padding(pad_id=0, pad_token="[PAD]")
        self.tok.enable_truncation(8)
        encs = self.tok.encode_batch(["a very long sentence that truncates "
                                      "beyond eight tokens certainly",
                                      "short"])
        assert len(encs[0].ids) == 8 and len(encs[1].ids) == 8
        assert encs[1].ids[-1] == 0
        self.tok.no_padding()
        self.tok.no_truncation()

    def test_save_load_roundtrip(self, tmp_path):
        p = str(tmp_path / "tok.json")
        self.tok.save(p)
        tok2 = WordPieceTokenizer.from_file(p)
        assert tok2.get_vocab_size() == 10003
        s = "An absolutely wonderful film <br /> with great acting."
        assert tok2.encode(s).ids == self.tok.encode(s).ids

    def test_json_model_section_matches_shipped(self, tmp_path):
        p = str(tmp_path / "tok.json")
        self.tok.save(p)
        with open(SHIPPED) as f:
            ref = json.load(f)
        with open(p) as f:
            ours = json.load(f)
        assert ours["model"] == ref["model"]
        assert ours["normalizer"] == ref["normalizer"]
        assert ours["pre_tokenizer"] == ref["pre_tokenizer"]
        assert ours["added_tokens"] == ref["added_tokens"]


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
def test_parity_with_hf_tokenizers_if_available():
    """If the Rust HF library is importable, byte-level id parity."""
    hf = pytest.importorskip("tokenizers")
    ref = hf.Tokenizer.from_file(SHIPPED)
    ours = WordPieceTokenizer.from_file(SHIPPED)
    samples = [
        "This movie was absolutely fantastic! I loved every minute.",
        "Worst. Film. Ever. <br /><br />Don't waste your time...",
        "Café touché — naïve résumé's crème brûlée!?",
        "supercalifragilisticexpialidocious antidisestablishmentarianism",
        "numbers 123 456,789 and $9.99 (50% off)",
    ]
    for s in samples:
        ids = ref.encode(s).ids
        assert ours.encode(s).ids == ids, s
        assert ours.decode(ids) == ref.decode(ids), s


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
def test_special_tokens_matched_on_raw_text():
    """'[MASK]' in a raw string must map to id 2, surviving the
    lowercasing normalizer (HF added_tokens semantics; the reference's
    predict_masked_samples path depends on it, utils.py:27)."""
    tok = WordPieceTokenizer.from_file(SHIPPED)
    enc = tok.encode("I watched this [MASK] yesterday")
    assert 2 in enc.ids
    assert "[MASK]" in enc.tokens
    enc2 = tok.encode("[MASK][MASK] double")
    assert enc2.ids[:2] == [2, 2]


@pytest.mark.skipif(not os.path.exists(SHIPPED),
                    reason="shipped tokenizer not present")
def test_native_encode_matches_python_engine():
    """The C++ core and the pure-Python engine must agree id-for-id."""
    tok_native = WordPieceTokenizer.from_file(SHIPPED)
    tok_py = WordPieceTokenizer.from_file(SHIPPED)
    tok_py._native_failed = True  # pin the Python path
    samples = [
        "An absolutely wonderful film with great acting.",
        "Café touché — naïve résumé!? [MASK] unbelievableness",
        "x" * 150,  # exceeds max_input_chars_per_word → [UNK]
        "edge-case:semi;colons and CJK 電影 characters",
    ]
    for s in samples:
        assert tok_native.encode(s).ids == tok_py.encode(s).ids, s
    if tok_native._native is None:
        pytest.skip("native library unavailable (g++ missing?)")


def test_native_trainer_matches_python_trainer():
    from perceiver_tpu.tokenizer.wordpiece import WordPieceTrainer
    try:
        from perceiver_tpu.tokenizer.native import native_train
    except (ImportError, OSError):
        pytest.skip("native library unavailable")
    corpus = ["the quick brown fox jumps over the lazy dog",
              "the lazy dog sleeps deeply",
              "quick quick fox runs far"] * 7
    tok = create_tokenizer()
    trainer = WordPieceTrainer(vocab_size=90)
    v_native = native_train(tok, corpus, 90,
                            list(trainer.special_tokens), 0)
    v_py = trainer._train_py(tok, corpus)
    assert v_native == v_py


def test_trainer_learns_vocab_and_roundtrips():
    corpus = ["the quick brown fox jumps over the lazy dog",
              "the lazy dog sleeps", "quick quick fox"] * 5
    tok = create_tokenizer()
    train_tokenizer(tok, corpus, vocab_size=60)
    assert tok.get_vocab_size() <= 60
    assert tok.token_to_id("[PAD]") == 0
    enc = tok.encode("the quick fox")
    assert 1 not in enc.ids  # fully covered by learned vocab
    assert tok.decode(enc.ids) == "the quick fox"


def test_trainer_with_replace_normalizer():
    corpus = ["hello<br />world"] * 3
    tok = create_tokenizer(Replace("<br />", " "))
    train_tokenizer(tok, corpus, vocab_size=40)
    enc = tok.encode("hello<br />world")
    assert tok.decode(enc.ids) == "hello world"
