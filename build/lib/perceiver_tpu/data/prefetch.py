"""Background-thread batch prefetching.

The reference keeps its accelerator fed with torch ``DataLoader``
worker processes (``data/imdb.py:112-126`` sets ``num_workers=3``,
``data/mnist.py:15``). The JAX equivalent needs no worker *processes* —
batch assembly is NumPy slicing over preloaded arrays (C under the
hood) and the jitted step dispatches asynchronously — but the host
loop must not assemble batch N+1 *after* blocking on step N. A single
daemon thread with a small bounded queue decouples the two: the device
runs the current step while the host builds the next batches.

Exceptions raised inside the producer surface on the consumer side at
the point of ``next()``, matching in-line iteration semantics.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator

import numpy as np

_SENTINEL = object()


class PrefetchIterator:
    """Wrap a batch iterable so iteration overlaps with consumption.

    ``depth`` bounds host memory: at most ``depth`` assembled batches
    exist beyond the one being consumed. Proxies ``len`` and
    ``set_epoch`` so it can stand in for a ``BatchIterator``
    (``perceiver_tpu.data.core``) anywhere, including epoch-seeded
    shuffling.
    """

    def __init__(self, inner, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.inner = inner
        self.depth = depth

    def __len__(self) -> int:
        return len(self.inner)

    def set_epoch(self, epoch: int):
        if hasattr(self.inner, "set_epoch"):
            self.inner.set_epoch(epoch)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def put(item) -> bool:
            """False once the consumer has gone away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for batch in self.inner:
                    if not put(batch):
                        return  # consumer exited early: stop, don't
                        # run the rest of the epoch dry
            except BaseException as e:  # re-raised on the consumer side
                put((_SENTINEL, e))
                return
            put((_SENTINEL, None))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] is _SENTINEL:
                    err = item[1]
                    if err is not None:
                        raise err
                    return
                yield item
        finally:
            # Early consumer exit (break / preemption): signal the
            # producer to halt after at most its in-flight batch.
            stop.set()
            t.join(timeout=5.0)
