#!/usr/bin/env python
"""Re-label the harvested corpus with a coherence task that surface
keywords cannot solve (VERDICT r2 #4).

Round 2's API-vs-prose labels were keyword-derivable, so a scratch
classifier beat the MLM-transfer recipe on the end task. This script
rebuilds the classification corpus as *passage coherence*:

  pos = two consecutive sentence-aligned halves of ONE document
  neg = first half of doc A + second half of doc B (A != B), spliced
        at sentence boundaries, with A and B drawn from the SAME
        style class (API-ish vs prose) of the source harvest

By construction the two classes have identical lexical and style
statistics — every sentence in a negative is a real sentence from the
same doc pool, and splices never cross style classes — so a bag-of-
words shortcut is useless. What separates the classes is whether the
second half *continues* the first: topical and discourse coherence,
exactly what MLM pretraining (reference recipe, README.md:78) teaches
an encoder and what a few-hundred-step scratch run cannot learn.

Reads the harvest at ``--src`` (``harvest_text.py`` output layout,
``aclImdb/{train,test}/{pos,neg}``), writes the same layout to
``--out``, and copies the cached tokenizer json from ``--src`` so the
classifier shares the MLM run's vocabulary (prepare_data only trains a
tokenizer when the json is missing).

Halves target ``--half-chars`` characters (default 700) so the splice
boundary lands well inside the model's 512-token window.
"""

import argparse
import glob
import os
import random
import re
import shutil
import sys

_SENT = re.compile(r"(?<=[.!?])\s+")


def halves(text: str, half_chars: int):
    """Split into two consecutive sentence-aligned chunks of roughly
    half_chars each, or None if the doc can't fill both halves."""
    sents = [s.strip() for s in _SENT.split(text) if s.strip()]
    head, head_len, i = [], 0, 0
    while i < len(sents) and head_len < half_chars:
        head.append(sents[i])
        head_len += len(sents[i]) + 1
        i += 1
    tail, tail_len = [], 0
    while i < len(sents) and tail_len < half_chars:
        tail.append(sents[i])
        tail_len += len(sents[i]) + 1
        i += 1
    if head_len < half_chars or tail_len < half_chars:
        return None
    return " ".join(head), " ".join(tail)


def build_split(style_files: dict, out_split_dir: str, half_chars: int,
                seed: int) -> dict:
    rng = random.Random(seed)
    n_pos = n_neg = n_short = 0
    for label in ("neg", "pos"):
        os.makedirs(os.path.join(out_split_dir, label), exist_ok=True)
    out_i = 0
    # style classes are processed independently so no splice crosses
    # API-ish/prose — style mixture must not become a label shortcut
    for style in ("neg", "pos"):
        files = sorted(style_files[style])
        rng.shuffle(files)
        pairs = []
        for path in files:
            with open(path, encoding="utf-8") as f:
                hv = halves(f.read(), half_chars)
            if hv is None:
                n_short += 1
                continue
            pairs.append(hv)
        # alternate exactly: two docs -> either 2 coherent or 2 spliced
        for j in range(0, len(pairs) - 1, 2):
            (h1, t1), (h2, t2) = pairs[j], pairs[j + 1]
            if (j // 2) % 2 == 0:
                examples = [(f"{h1} {t1}", 1), (f"{h2} {t2}", 1)]
            else:
                examples = [(f"{h1} {t2}", 0), (f"{h2} {t1}", 0)]
            for text, y in examples:
                out = os.path.join(out_split_dir, ("neg", "pos")[y],
                                   f"{out_i}_{5 + y * 5}.txt")
                with open(out, "w", encoding="utf-8") as f:
                    f.write(text)
                out_i += 1
                n_pos += y
                n_neg += 1 - y
    return {"pos": n_pos, "neg": n_neg, "too_short": n_short}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", default=".cache")
    ap.add_argument("--out", default=".cache_coh")
    ap.add_argument("--half-chars", type=int, default=700)
    ap.add_argument("--extra-test-src", default=None,
                    help="second harvest root whose aclImdb/test docs "
                         "AUGMENT the test split (VERDICT r3 weak #3: "
                         "val>=500). Must contain only text the MLM "
                         "run never pretrained on — use "
                         "make_unseen_pool.py, NOT train-split docs "
                         "(encoder-side val contamination would "
                         "inflate the transfer arms)")
    args = ap.parse_args()

    src_root = os.path.join(args.src, "aclImdb")
    if not os.path.isdir(src_root):
        sys.exit(f"no harvest at {src_root} — run harvest_text.py first")
    shutil.rmtree(os.path.join(args.out, "aclImdb"), ignore_errors=True)
    os.makedirs(args.out, exist_ok=True)
    splits = {
        split: {style: sorted(glob.glob(os.path.join(
            src_root, split, style, "*.txt")))
            for style in ("neg", "pos")}
        for split in ("train", "test")
    }
    if args.extra_test_src:
        n_extra = 0
        for style in ("neg", "pos"):
            extra = sorted(glob.glob(os.path.join(
                args.extra_test_src, "aclImdb", "test", style,
                "*.txt")))
            n_extra += len(extra)
            splits["test"][style] = splits["test"][style] + extra
        # the unseen pool is usually single-style (balance-dropping
        # removes the majority class) — that's fine, splices never
        # cross styles — but an empty pool means a wrong path
        if not n_extra:
            sys.exit(f"--extra-test-src has no docs under "
                     f"{args.extra_test_src}/aclImdb/test")
    for seed, split in enumerate(("train", "test")):
        stats = build_split(splits[split],
                            os.path.join(args.out, "aclImdb", split),
                            args.half_chars, seed=seed)
        print(f"{split}: {stats}", flush=True)
    # share the MLM run's vocabulary — transfer requires identical ids
    copied = 0
    for tok in glob.glob(os.path.join(args.src, "imdb-tokenizer-*.json")):
        shutil.copy(tok, args.out)
        copied += 1
    print(f"copied {copied} tokenizer json(s) from {args.src}", flush=True)


if __name__ == "__main__":
    main()
