"""Host-side data modules (NumPy pipelines feeding device batches)."""

from perceiver_tpu.data.core import ArrayDataset, BatchIterator  # noqa: F401
from perceiver_tpu.data.images import SyntheticImageDataModule  # noqa: F401
from perceiver_tpu.data.mnist import MNISTDataModule  # noqa: F401
from perceiver_tpu.data.imdb import IMDBDataModule, Collator  # noqa: F401
from perceiver_tpu.data.lartpc import load_lartpc, synthetic_events  # noqa: F401
