"""Worker for the true multi-process distributed test.

Run as: python tests/dist_worker.py <pid> <nproc> <port> <out.json> \
            <data_dir> [model_parallel]

Initializes ``jax.distributed`` over the CPU backend (Gloo
collectives), then trains a tiny MLM through the REAL Trainer path:
per-host dataset sharding (``set_sharding``), cross-process global
batch assembly (``make_array_from_process_local_data``), GSPMD
gradient all-reduce, the multi-host prepare_data barrier, and the
multi-host eval aggregation. With ``model_parallel > 1`` (each process
forced to several virtual devices by the caller's XLA_FLAGS), the mesh
gains a tensor-parallel axis that stays host-internal while the dp
gradient all-reduce crosses processes — the standard multi-host layout
(dp over DCN, tp over ICI) in miniature.
Writes this process's final metrics to ``out.json`` — the test asserts
both processes produced IDENTICAL metrics (collective consistency) and
that training stepped.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pid, nproc, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                                  sys.argv[3], sys.argv[4])
    model_parallel = int(sys.argv[6]) if len(sys.argv) > 6 else 1
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc

    from perceiver_tpu.data import IMDBDataModule
    from perceiver_tpu.parallel import make_mesh
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    from perceiver_tpu.training import Trainer, TrainerConfig

    mesh = make_mesh(model_parallel=model_parallel)
    # smallest config that still exercises every distributed code
    # path: the test asserts collective consistency and stepping, not
    # model capacity, and the 2-process compile+trace cost is paid
    # twice per parametrization (test-suite budget, VERDICT r5 item 8)
    task = MaskedLanguageModelTask(
        vocab_size=96, max_seq_len=16, num_latents=4,
        num_latent_channels=16, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=2,
        num_encoder_self_attention_heads=2,
        num_decoder_cross_attention_heads=2, loss_impl="dense")
    dm = IMDBDataModule(data_dir=sys.argv[5], vocab_size=96,
                        max_seq_len=16, batch_size=4,
                        synthetic_train_size=16, synthetic_test_size=8)
    # SAME experiment dir on both processes: exercises the broadcast
    # version pick, the rank-0-only TB writer, and orbax's collective
    # multi-host checkpoint save into the shared directory
    cfg = TrainerConfig(max_steps=3, max_epochs=1, accelerator="cpu",
                        log_every_n_steps=1, num_sanity_val_steps=0,
                        enable_checkpointing=True, save_top_k=1,
                        precision="32",
                        default_root_dir=os.path.join(sys.argv[5], "logs"),
                        experiment=f"dist_tp{model_parallel}")
    trainer = Trainer(task, dm, cfg, mesh=mesh)
    state = trainer.fit()
    val = trainer.validate(state)
    ckpt_dir = os.path.join(trainer.log_dir, "checkpoints")
    assert os.path.isdir(ckpt_dir) and any(
        d.isdigit() for d in os.listdir(ckpt_dir)), \
        f"collective checkpoint missing in {ckpt_dir}"

    with open(out_path, "w") as f:
        json.dump({"global_step": trainer.global_step,
                   "process_count": jax.process_count(),
                   **{k: float(v) for k, v in val.items()}}, f)
    print(f"proc {pid} done: {val}", flush=True)


if __name__ == "__main__":
    main()
