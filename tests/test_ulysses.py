"""Ulysses all-to-all attention vs dense reference (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from perceiver_tpu.ops.chunked_attention import pad_mask_to_bias
from perceiver_tpu.parallel.ulysses import make_ulysses_attention

from tests.test_ring_attention import dense_attention, _mesh, _qkv


class TestUlyssesAttention:
    def test_matches_dense(self):
        rng = np.random.default_rng(10)
        q, k, v = _qkv(rng, 2, 8, 64, 64, 8)
        f = make_ulysses_attention(_mesh(), "data")
        out = f(q, k, v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(dense_attention(q, k, v)),
                                   rtol=2e-5, atol=2e-5)

    def test_with_pad_mask(self):
        rng = np.random.default_rng(11)
        q, k, v = _qkv(rng, 2, 8, 32, 32, 8)
        pad = jnp.asarray(rng.random((2, 32)) < 0.3)
        bias = pad_mask_to_bias(pad)
        f = make_ulysses_attention(_mesh(), "data")
        out = f(q, k, v, bias)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(dense_attention(q, k, v, bias)),
            rtol=2e-5, atol=2e-5)

    def test_batch_and_seq_axes(self):
        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("data", "seq"))
        rng = np.random.default_rng(12)
        q, k, v = _qkv(rng, 4, 4, 32, 32, 8)
        f = make_ulysses_attention(mesh, "seq", batch_axis="data")
        out = f(q, k, v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(dense_attention(q, k, v)),
                                   rtol=2e-5, atol=2e-5)

    def test_grad_flows(self):
        rng = np.random.default_rng(13)
        q, k, v = _qkv(rng, 1, 8, 16, 16, 8)
        f = make_ulysses_attention(_mesh(), "data")
        g = jax.grad(lambda q, k, v: f(q, k, v).sum(), argnums=(0, 1, 2))(
            q, k, v)
        gd = jax.grad(
            lambda q, k, v: dense_attention(q, k, v).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_head_divisibility_enforced(self):
        rng = np.random.default_rng(14)
        q, k, v = _qkv(rng, 1, 4, 16, 16, 8)  # 4 heads on 8 devices
        f = make_ulysses_attention(_mesh(), "data")
        with pytest.raises(ValueError, match="divisible"):
            f(q, k, v)

    def test_agrees_with_ring(self):
        from perceiver_tpu.parallel.ring_attention import make_ring_attention
        rng = np.random.default_rng(15)
        q, k, v = _qkv(rng, 2, 8, 64, 64, 8)
        pad = jnp.asarray(rng.random((2, 64)) < 0.2)
        bias = pad_mask_to_bias(pad)
        mesh = _mesh()
        out_u = make_ulysses_attention(mesh, "data")(q, k, v, bias)
        out_r = make_ring_attention(mesh, "data")(q, k, v, bias)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                                   rtol=2e-5, atol=2e-5)
