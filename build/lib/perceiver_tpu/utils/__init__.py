"""Host-side utilities: TB-compatible logging, config, freezing."""
