"""Multi-tenant isolation primitives (ISSUE 20): the tenant registry
and its quota arithmetic, per-tenant admission in the unified
scheduler, the decode engine's page-quota ledger, the fleet RPC error
envelope, and demand-proportional replica allocation.

The noisy-neighbor *behaviour* gates live in scripts/chaos.py
(noisy_neighbor) and scripts/bench_decode.py (--tenants); this module
pins the host-side mechanisms those gates are built from, including
seeded InterleaveScheduler races proving the scheduler's per-tenant
page budgets are conserved under adversarial interleavings.
"""

import dataclasses

import numpy as np
import pytest

from perceiver_tpu.serving.batcher import ContinuousBatchScheduler
from perceiver_tpu.serving.errors import (
    SHED_REASONS,
    Unavailable,
    known_reason,
)
from perceiver_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    PRIORITY_BEST_EFFORT,
    PRIORITY_STANDARD,
    TenantRegistry,
    TenantSpec,
    weighted_fair_shares,
)


# --- TenantSpec validation ---------------------------------------------------

def test_tenant_spec_rejects_invalid_fields():
    with pytest.raises(ValueError):
        TenantSpec(tenant="")
    with pytest.raises(ValueError):
        TenantSpec(tenant="a", priority=-1)
    with pytest.raises(ValueError):
        TenantSpec(tenant="a", weight=0.0)
    with pytest.raises(ValueError):
        TenantSpec(tenant="a", max_pages=0)
    with pytest.raises(ValueError):
        TenantSpec(tenant="a", max_inflight=0)
    with pytest.raises(ValueError):
        TenantSpec(tenant="a", rate_per_s=0.0)
    with pytest.raises(ValueError):
        TenantSpec(tenant="a", burst=0)


def test_tenant_spec_is_frozen_with_open_defaults():
    spec = TenantSpec(tenant="gold")
    assert spec.priority == PRIORITY_STANDARD
    assert spec.weight == 1.0
    # None caps = unlimited: a single-tenant deployment needs no knobs
    assert spec.max_pages is None and spec.max_inflight is None
    assert spec.rate_per_s is None and spec.model is None
    with pytest.raises(dataclasses.FrozenInstanceError):
        spec.weight = 2.0


# --- registry fallback + identity --------------------------------------------

def test_registry_unknown_tenant_falls_back_to_default_spec():
    # no default registered: unknown names get an uncapped spec but
    # KEEP their identity (metrics/events still attribute correctly)
    reg = TenantRegistry()
    spec = reg.get("ghost")
    assert spec.tenant == "ghost" and spec.max_pages is None
    assert reg.get(None).tenant == DEFAULT_TENANT

    # a registered default spec donates its caps to unregistered
    # names — identity still stays the caller's
    reg = TenantRegistry([
        TenantSpec(tenant=DEFAULT_TENANT, max_pages=8, weight=2.0),
        TenantSpec(tenant="bronze", priority=PRIORITY_BEST_EFFORT,
                   max_pages=2),
    ])
    ghost = reg.get("ghost")
    assert ghost.tenant == "ghost"
    assert ghost.max_pages == 8 and ghost.weight == 2.0
    assert reg.get("bronze").max_pages == 2
    assert reg.tenants() == ["bronze", DEFAULT_TENANT]


def test_registry_register_replaces_spec():
    reg = TenantRegistry([TenantSpec(tenant="a", max_pages=2)])
    reg.register(TenantSpec(tenant="a", max_pages=5))
    assert reg.get("a").max_pages == 5


# --- weighted fair shares ----------------------------------------------------

def test_weighted_fair_shares_proportional_and_conserving():
    shares = weighted_fair_shares(8, {"a": 3.0, "b": 1.0})
    assert shares == {"a": 6, "b": 2}
    assert sum(shares.values()) == 8
    # deterministic: identical inputs always agree
    assert shares == weighted_fair_shares(8, {"a": 3.0, "b": 1.0})


def test_weighted_fair_shares_largest_remainder_ties_break_by_key():
    # exact shares 2.5/2.5 — the single leftover unit goes to the
    # lexicographically first key, same answer every run
    assert weighted_fair_shares(5, {"a": 1.0, "b": 1.0}) \
        == {"a": 3, "b": 2}


def test_weighted_fair_shares_floor_of_one():
    # a 100:1 weight ratio must not shut the small tenant out while
    # units remain — a zero share is starvation by arithmetic
    shares = weighted_fair_shares(10, {"whale": 100.0, "shrimp": 1.0})
    assert shares == {"whale": 9, "shrimp": 1}


def test_weighted_fair_shares_edges():
    assert weighted_fair_shares(0, {"a": 1.0}) == {"a": 0}
    assert weighted_fair_shares(5, {}) == {}
    with pytest.raises(ValueError):
        weighted_fair_shares(5, {"a": 0.0})


# --- token-bucket rate admission ---------------------------------------------

def test_registry_token_bucket_admits_burst_then_sheds_with_hint():
    reg = TenantRegistry([
        TenantSpec(tenant="r", rate_per_s=2.0, burst=2),
        TenantSpec(tenant="free"),
    ])
    # burst admits, then the bucket is dry with an exact refill hint
    assert reg.admit("r", now=0.0) == (True, 0.0)
    assert reg.admit("r", now=0.0) == (True, 0.0)
    ok, retry = reg.admit("r", now=0.0)
    assert not ok and retry == pytest.approx(0.5)
    # half a second refills exactly one token at 2/s
    assert reg.admit("r", now=0.5) == (True, 0.0)
    ok, retry = reg.admit("r", now=0.5)
    assert not ok and retry == pytest.approx(0.5)
    # unlimited tenants never consult a bucket
    for _ in range(10):
        assert reg.admit("free", now=0.0) == (True, 0.0)


def test_registry_register_resets_rate_bucket():
    reg = TenantRegistry([TenantSpec(tenant="r", rate_per_s=1.0,
                                     burst=1)])
    assert reg.admit("r", now=0.0)[0]
    assert not reg.admit("r", now=0.0)[0]
    reg.register(TenantSpec(tenant="r", rate_per_s=1.0, burst=1))
    assert reg.admit("r", now=0.0)[0]


# --- scheduler: per-tenant budgets in take() ---------------------------------

def _offer_all(q, entries):
    for tenant, i, cost in entries:
        assert q.offer((tenant, i), cost=cost, tenant=tenant)


def test_take_defers_over_quota_tenant_without_head_blocking():
    q = ContinuousBatchScheduler(max_depth=16, clock=lambda: 0.0)
    # flood's entries sit at the HEAD of the queue; with its budget
    # exhausted they defer in place and the victim admits past them
    _offer_all(q, [("flood", 0, 2), ("flood", 1, 2),
                   ("victim", 0, 2), ("victim", 1, 2)])
    budgets = {"flood": 0}
    admitted, shed = q.take(budget=8, slots=4, tenant_budgets=budgets)
    assert admitted == [("victim", 0), ("victim", 1)]
    assert shed == []
    # deferred entries stayed queued, in order, for the next round
    assert q.depth == 2
    budgets = {"flood": 4}
    admitted, _ = q.take(budget=8, slots=4, tenant_budgets=budgets)
    assert admitted == [("flood", 0), ("flood", 1)]
    assert budgets["flood"] == 0


def test_take_fifo_within_tenant_once_deferred():
    q = ContinuousBatchScheduler(max_depth=16, clock=lambda: 0.0)
    # flood has budget for its SECOND entry (cost 1) but not its
    # first (cost 3) — admitting it would reorder the tenant's queue,
    # so once one entry defers, all its later entries defer too
    _offer_all(q, [("flood", 0, 3), ("flood", 1, 1), ("victim", 0, 1)])
    admitted, _ = q.take(budget=8, slots=4,
                         tenant_budgets={"flood": 2})
    assert admitted == [("victim", 0)]
    admitted, _ = q.take(budget=8, slots=4,
                         tenant_budgets={"flood": 4})
    assert admitted == [("flood", 0), ("flood", 1)]


def test_take_absent_tenant_budget_means_unlimited():
    q = ContinuousBatchScheduler(max_depth=16, clock=lambda: 0.0)
    _offer_all(q, [("victim", 0, 3), ("victim", 1, 3)])
    admitted, _ = q.take(budget=8, slots=4, tenant_budgets={"flood": 0})
    assert admitted == [("victim", 0), ("victim", 1)]


# --- scheduler: weighted fair-share chunk planning ---------------------------

def test_plan_chunks_splits_leftover_by_tenant_weight():
    q = ContinuousBatchScheduler(token_budget=8, max_chunk=4)
    # 2 decode rows pre-spend 2; the leftover 6 splits a:4 / b:2, and
    # a's second row gets nothing once a's share is spent — b's slice
    # survives a's greed
    chunks = q.plan_chunks(2, [10, 10, 10],
                           prefill_tenants=["a", "a", "b"],
                           tenant_weights={"a": 2.0, "b": 1.0})
    assert chunks == [4, 0, 2]


def test_plan_chunks_fair_share_is_work_conserving():
    q = ContinuousBatchScheduler(token_budget=8, max_chunk=8)
    # a only needs 2 of its 4-token share; the unclaimed 2 go back
    # out FIFO instead of idling the step
    chunks = q.plan_chunks(0, [2, 10],
                           prefill_tenants=["a", "b"],
                           tenant_weights={"a": 1.0, "b": 1.0})
    assert chunks == [2, 6]
    assert sum(chunks) == 8


def test_plan_chunks_head_row_always_advances():
    q = ContinuousBatchScheduler(token_budget=1, max_chunk=4)
    # decode spends the whole budget; the FIFO-head prefill row still
    # gets its no-livelock token even under fair-share caps
    assert q.plan_chunks(1, [5], prefill_tenants=["flood"],
                         tenant_weights={"flood": 1.0}) == [1]


def test_plan_speculative_grants_before_tenant_shares():
    q = ContinuousBatchScheduler(token_budget=6, max_chunk=4)
    grants, chunks = q.plan_speculative(
        1, [3, 5], [4], prefill_tenants=["a"],
        tenant_weights={"a": 1.0})
    # decode 1 + grants 3, 2 exhaust the budget; the head prefill row
    # still advances its guaranteed token
    assert grants == [3, 2]
    assert chunks == [1]


# --- seeded races: quota conservation under adversarial interleavings --------

def test_take_quota_conservation_under_seeded_races():
    """Two producer tenants and a consumer race offer()/take() under
    seeded InterleaveScheduler schedules. Invariants, every seed:
    the flood tenant's admitted page cost never exceeds its budget,
    nothing is lost or duplicated (admitted + queued == offered), and
    order within each tenant is FIFO. Each seed replays bitwise."""
    from perceiver_tpu.utils.concurrency import InterleaveScheduler

    N, COST, FLOOD_BUDGET = 6, 2, 4

    def run_once(seed):
        sched = InterleaveScheduler(seed=seed)
        q = ContinuousBatchScheduler(max_depth=32, clock=lambda: 0.0)
        admitted = []
        budgets = {"flood": FLOOD_BUDGET}  # persists across take()s

        def producer(tenant):
            def fn():
                for i in range(N):
                    assert q.offer((tenant, i), cost=COST,
                                   tenant=tenant)
                    sched.point(f"offer:{tenant}")
            return fn

        def consumer():
            for _ in range(2 * N):
                got, shed = q.take(budget=2 * COST, slots=2,
                                   tenant_budgets=budgets)
                assert shed == []  # no deadlines in this harness
                admitted.extend(got)
                sched.point("take")

        sched.spawn(producer("victim"), name="victim")
        sched.spawn(producer("flood"), name="flood")
        sched.spawn(consumer, name="engine")
        sched.run()
        # post-race drain: whatever the racing consumer missed
        while True:
            got, _ = q.take(budget=2 * COST, slots=2,
                            tenant_budgets=budgets)
            if not got:
                break
            admitted.extend(got)
        return admitted, q.depth, budgets["flood"], tuple(sched.trace)

    for seed in (3, 11, 4321):
        admitted, depth, flood_left, trace = run_once(seed)
        flood_taken = [i for t, i in admitted if t == "flood"]
        victim_taken = [i for t, i in admitted if t == "victim"]
        # quota conservation: the flood can never admit past its
        # budget no matter how the threads interleave
        assert len(flood_taken) * COST <= FLOOD_BUDGET
        assert flood_left == FLOOD_BUDGET - len(flood_taken) * COST
        # nothing lost, nothing duplicated
        assert len(admitted) + depth == 2 * N
        assert depth == N - len(flood_taken)  # only flood defers
        # FIFO within each tenant
        assert victim_taken == list(range(N))
        assert flood_taken == list(range(len(flood_taken)))
        # bitwise seeded replay: same seed, same interleaving, same
        # admission order
        assert run_once(seed) == (admitted, depth, flood_left, trace)


# --- decode engine: page-quota shed + ledger conservation --------------------

def test_decode_engine_quota_shed_and_ledger_conservation():
    """A capped tenant's second concurrent request sheds typed at
    submit — before a slot, a page, or a device token is spent — and
    after drain the per-tenant page ledger returns to zero with the
    pool fully free (charge/credit conservation)."""
    from perceiver_tpu.obs import events as events_mod
    from perceiver_tpu.serving.decode import (
        DecodeEngine,
        DecodeGeometry,
        DecodeResult,
    )
    from perceiver_tpu.serving.engine import RequestTooLarge
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=32, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")
    geometry = DecodeGeometry(max_streams=2, num_pages=9, page_size=4,
                              max_seq_len=16, max_chunk=4)
    tenancy = TenantRegistry([
        TenantSpec(tenant="bronze", priority=PRIORITY_BEST_EFFORT,
                   max_pages=2),
    ])
    engine = DecodeEngine(task, geometry=geometry, tenancy=tenancy,
                          auto_step=False, max_queue=8)
    try:
        prompt = np.arange(3, 8, dtype=np.int32)  # 5 tokens, 2 pages

        # a request that can NEVER fit the quota is a sizing error,
        # not a transient shed
        with pytest.raises(RequestTooLarge):
            engine.submit(np.arange(3, 11, dtype=np.int32),
                          max_new_tokens=4, tenant="bronze")

        shed_before = len(events_mod.default_log().events("tenant_shed"))
        h_bronze = engine.submit(prompt, max_new_tokens=3,
                                 tenant="bronze")
        # held + queued already fill the 2-page quota: the second
        # request sheds typed, with the tenant attributed
        with pytest.raises(Unavailable) as exc:
            engine.submit(prompt, max_new_tokens=3, tenant="bronze")
        assert exc.value.reason == "tenant_quota"
        assert exc.value.tenant == "bronze"
        assert exc.value.retry_after_s == \
            SHED_REASONS["tenant_quota"]
        # an uncapped tenant is untouched by bronze's quota
        h_gold = engine.submit(prompt, max_new_tokens=3, tenant="gold")

        engine.run_until_idle()
        for handle in (h_bronze, h_gold):
            r = handle.result(1.0)
            assert isinstance(r, DecodeResult), r
            assert r.finished == "complete" and len(r.tokens) == 3

        # ledger conservation: every page charged at admission was
        # credited back at finish, and the pool is whole again
        assert all(v == 0 for v in engine._tenant_pages.values())
        assert engine.pool.free_pages == geometry.allocatable_pages
        # the shed is observable per tenant: counter + typed event
        assert engine._m_tenant_shed.value_of(
            tenant="bronze", reason="tenant_quota") == 1
        assert engine._m_tenant_tokens.value_of(tenant="gold") == 3
        shed_events = events_mod.default_log().events("tenant_shed")
        assert len(shed_events) == shed_before + 1
        assert shed_events[-1]["tenant"] == "bronze"
        assert shed_events[-1]["reason"] == "tenant_quota"
    finally:
        engine.close()


# --- fleet: RPC envelope + demand-proportional allocation --------------------

def test_unavailable_tenant_survives_rpc_envelope_round_trip():
    from perceiver_tpu.fleet.rpc import (
        error_envelope,
        raise_remote_error,
    )

    env = error_envelope(Unavailable("tenant_quota", tenant="bronze",
                                     retry_after_s=0.25))
    assert env == {"type": "Unavailable", "reason": "tenant_quota",
                   "bucket": None, "retry_after_s": 0.25,
                   "tenant": "bronze"}
    with pytest.raises(Unavailable) as exc:
        raise_remote_error(env)
    assert exc.value.reason == "tenant_quota"
    assert exc.value.tenant == "bronze"
    assert exc.value.retry_after_s == 0.25


def test_shed_reason_vocabulary_is_closed():
    assert known_reason("tenant_quota")
    # decode-plane sheds cross the fleet boundary prefixed
    assert known_reason("decode_queue_full")
    assert not known_reason("made_up_reason")
    # every vocabulary entry carries a retry hint
    assert all(isinstance(v, float) for v in SHED_REASONS.values())


def test_allocate_replicas_proportional_to_demand():
    from perceiver_tpu.fleet.autoscaler import allocate_replicas

    assert allocate_replicas({"a": 3.0, "b": 1.0}, 4) \
        == {"a": 3, "b": 1}
    # an idle fleet balances instead of collapsing onto one tenant
    assert allocate_replicas({"a": 0.0, "b": 0.0}, 4) \
        == {"a": 2, "b": 2}
    assert allocate_replicas({}, 4) == {}
    alloc = allocate_replicas({"a": 5.0, "b": 2.0, "c": 0.1}, 7)
    assert sum(alloc.values()) == 7
    assert alloc["c"] >= 1  # floor-of-one reaches the autoscaler too
    with pytest.raises(ValueError):
        allocate_replicas({"a": 1.0}, -1)
