"""AOT bucketed-batch inference engine.

The repo's previous inference entry (``utils/predict.py``) re-created
a lambda per call — a fresh jit cache key, i.e. a full XLA recompile
per request — and round-tripped every intermediate through the host.
TPU serving stacks instead compile a *small, closed set* of padded
shape buckets ahead of time and coalesce traffic into them (PAPERS:
Gemma-on-TPU serving; ragged paged attention): compilation happens
once at startup, dispatch is a dictionary lookup plus a pad, and the
steady state performs **zero** XLA compiles.

``ServingEngine`` implements that contract:

- one AOT executable per (batch-bucket, seq-bucket), built with
  ``jax.jit(...).lower(...).compile()`` at startup (``warmup``);
- params restored once (``training/checkpoint.restore_params``) and
  kept device-resident; ``update_params`` swaps weights without any
  recompile (same shapes → same executables);
- requests dispatch to the smallest fitting bucket, padded with inert
  values (PAD tokens / masked key positions / zero pixels);
- the MLM graph donates its request buffers (they alias the
  ``filled_ids``/``is_masked`` outputs — see ``serving/graphs.py``);
- degrade-don't-die: each bucket carries a circuit breaker — repeated
  dispatch failures open it and requests get a typed ``Unavailable``
  (with a retry-after hint) instead of piling onto a dead executable;
  a half-open probe recovers it. Engine health/readiness is an
  explicit state machine exported via metrics (``serving/health.py``,
  docs/RESILIENCE.md).

Host-sync discipline: ``dispatch`` never synchronizes on device
values — no ``.item()``/``.tolist()``/``block_until_ready``/
``device_get``/``np.asarray`` on results (enforced by the
``serving-host-sync`` lint rule over this file). Materializing
outputs — and therefore timing a request's completion — belongs to
the consumer (``serving/api.py`` / the micro-batcher), which keeps
dispatches pipelined exactly as the trainer pipelines train steps.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from perceiver_tpu.cache import ExecutableCache, aot_compile, default_cache
from perceiver_tpu.obs import events as events_mod
from perceiver_tpu.obs import trace as trace_mod
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY
from perceiver_tpu.resilience import faults
from perceiver_tpu.resilience.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
)
from perceiver_tpu.serving.errors import Unavailable
from perceiver_tpu.serving.graphs import (
    PackedServeGraph,
    ServeGraph,
    build_packed_serve_graph,
    build_serve_graph,
)
from perceiver_tpu.serving.health import HealthMonitor, HealthState
from perceiver_tpu.serving.metrics import MetricsRegistry

# occupancy/waste are fractions in [0, 1] — linear buckets, not the
# latency defaults
_RATIO_BUCKETS = tuple(i / 10 for i in range(1, 11))

# serving_breaker_state gauge encoding (docs/SERVING.md "Fleet")
_BREAKER_STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class RequestTooLarge(ValueError):
    """Request exceeds every configured bucket on some axis."""


def resolve_exec_cache(exec_cache) -> Optional[ExecutableCache]:
    """The engines' shared persistent-compile-cache knob: ``None``
    resolves the process default (the ``PERCEIVER_EXEC_CACHE`` env
    dir), a ``str`` opens that directory, ``False`` disables caching
    even when the env var is set, and an ``ExecutableCache`` passes
    through. Used by :class:`ServingEngine` and the decode engine
    (``serving/decode.py``) so both read the same configuration."""
    if exec_cache is None:
        return default_cache()
    if exec_cache is False:
        return None
    if isinstance(exec_cache, str):
        return default_cache(exec_cache)
    return exec_cache


@dataclasses.dataclass(frozen=True)
class ServeResult:
    """One dispatched bucket call, still on device.

    ``outputs`` hold bucket-shaped device arrays; ``batch``/``length``
    say which slice is real. Nothing here has synchronized — slicing
    to host happens in ``serving.api.materialize``.
    """

    outputs: Dict[str, object]
    batch: int
    length: Optional[int]
    bucket: Tuple[int, Optional[int]]
    # per-request true lengths (host int array), when the caller knows
    # them — they drive the true-waste metrics and let materialize
    # slice each row to its real span instead of the batch width
    lengths: Optional[object] = None


@dataclasses.dataclass(frozen=True)
class PackedServeResult:
    """One packed (ragged) dispatch, still on device.

    ``outputs`` are token-budget-bucket shaped; ``row_offsets`` /
    ``lengths`` (host int arrays, ``batch`` real rows) say which spans
    of the packed token axis are real."""

    outputs: Dict[str, object]
    batch: int
    lengths: object
    row_offsets: object
    bucket: Tuple[object, int, int]  # ("packed", tokens, rows)


class ServingEngine:
    """Checkpoint-loaded, AOT-compiled, bucketed forward executor."""

    # lock discipline (gated by check.py --race). Deliberately NOT
    # declared: _params/_params_src — update_params swaps each with a
    # single reference assignment (atomic under the GIL, pinned by the
    # torn-pytree stress test), so readers never see a torn tree and
    # the hot path takes no lock.
    _GUARDED = {
        "_exe": "_exe_lock",
        "_breakers": "_breaker_lock",
    }

    def __init__(self, task=None, params=None, *,
                 graph: Optional[ServeGraph] = None,
                 checkpoint: Optional[str] = None,
                 batch_buckets: Sequence[int] = (1, 8, 32),
                 seq_buckets: Optional[Sequence[int]] = (128, 512, 2048),
                 policy: Policy = DEFAULT_POLICY,
                 top_k: int = 3,
                 metrics: Optional[MetricsRegistry] = None,
                 allow_unlisted_buckets: bool = False,
                 warmup: bool = True,
                 exec_cache=None,
                 seed: int = 0,
                 packed_buckets: Optional[Sequence[Tuple[int, int]]] = None,
                 packed_graph: Optional[PackedServeGraph] = None,
                 breaker_failure_threshold: int = 5,
                 breaker_reset_s: float = 30.0,
                 breaker_clock=time.monotonic):
        self.exec_cache: Optional[ExecutableCache] = \
            resolve_exec_cache(exec_cache)
        self.task = task
        if graph is None:
            if task is None:
                raise ValueError("pass a task config or a ServeGraph")
            graph = build_serve_graph(task, policy=policy, top_k=top_k)
        self.graph: ServeGraph = graph
        self.policy = policy
        self.batch_buckets = tuple(sorted(set(int(b) for b in batch_buckets)))
        if self.batch_buckets and self.batch_buckets[0] < 1:
            raise ValueError(f"invalid batch_buckets {batch_buckets!r}")
        if not self.batch_buckets and not allow_unlisted_buckets:
            raise ValueError(
                "empty batch_buckets requires allow_unlisted_buckets "
                "(exact-shape lazy compiles)")
        if self.graph.seq_bucketable:
            if seq_buckets:
                self.seq_buckets = tuple(sorted(set(int(s)
                                                    for s in seq_buckets)))
                too_big = [s for s in self.seq_buckets
                           if s > self.graph.max_seq_len]
                if too_big:
                    raise ValueError(
                        f"seq_buckets {too_big} exceed the model's "
                        f"max_seq_len {self.graph.max_seq_len}")
            elif allow_unlisted_buckets:
                self.seq_buckets = ()
            else:
                raise ValueError(
                    f"task kind {self.graph.kind!r} buckets over the "
                    "sequence axis; pass seq_buckets")
        else:
            self.seq_buckets = (None,)
        # packed (ragged) dispatch mode: fixed (token-budget, max-rows)
        # buckets over the concatenated token axis — seq-bucketable
        # tasks only, negotiated per task; rectangles stay the fallback
        self.packed_graph = packed_graph
        if packed_buckets:
            if self.packed_graph is None:
                if task is None:
                    raise ValueError(
                        "packed_buckets needs a task config or an "
                        "explicit packed_graph")
                if not self.graph.seq_bucketable:
                    raise ValueError(
                        f"task kind {self.graph.kind!r} has fixed-shape "
                        "inputs; packed mode applies to seq-bucketable "
                        "tasks only")
                self.packed_graph = build_packed_serve_graph(
                    task, policy=policy, top_k=top_k)
            self.packed_buckets = tuple(sorted(
                set((int(t), int(r)) for t, r in packed_buckets)))
            bad = [tb for tb in self.packed_buckets
                   if tb[0] < 1 or tb[1] < 1 or tb[0] < tb[1]]
            if bad:
                raise ValueError(
                    f"invalid packed_buckets {bad}: need tokens >= "
                    "rows >= 1 (every real row holds >= 1 token)")
        else:
            self.packed_buckets = ()
        self.allow_unlisted_buckets = allow_unlisted_buckets
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._init_metrics()
        # degrade-don't-die: one circuit breaker per bucket, plus the
        # health/readiness machine both export (docs/RESILIENCE.md)
        self.health = HealthMonitor(self.metrics)
        self._breaker_failure_threshold = breaker_failure_threshold
        self._breaker_reset_s = breaker_reset_s
        self._breaker_clock = breaker_clock
        self._breakers: Dict[Tuple[int, Optional[int]], CircuitBreaker] = {}
        self._breaker_lock = threading.Lock()

        if params is None and checkpoint is not None:
            from perceiver_tpu.training.checkpoint import restore_params
            params = restore_params(checkpoint,
                                    template=self.graph.init_params(seed))
        elif params is None:
            # fresh-init weights: load tests and offline benches; a
            # production engine passes params or checkpoint
            params = self.graph.init_params(seed)
        import jax
        self._params_src = params
        self._params = jax.device_put(params)
        self._exe = {}
        self._exe_lock = threading.Lock()
        if warmup:
            self.warmup()
        # lazy-bucket engines are serveable immediately; warmed engines
        # become ready once every configured bucket compiled
        self.health.set(HealthState.READY)

    @classmethod
    def from_graph(cls, graph: ServeGraph, params, *,
                   batch_buckets: Sequence[int] = (),
                   seq_buckets: Sequence[int] = (),
                   policy: Policy = DEFAULT_POLICY,
                   metrics: Optional[MetricsRegistry] = None,
                   warmup: bool = False,
                   exec_cache=None,
                   allow_unlisted_buckets: bool = True,
                   breaker_failure_threshold: int = 5,
                   breaker_reset_s: float = 30.0,
                   breaker_clock=time.monotonic) -> "ServingEngine":
        """Engine over a prebuilt serve graph + live params — the
        compat path for callers holding a model instead of a task
        config. Defaults to exact-shape lazy buckets: the first call
        at a new shape compiles once, repeats are cache hits."""
        return cls(None, params, graph=graph,
                   batch_buckets=batch_buckets, seq_buckets=seq_buckets,
                   policy=policy, metrics=metrics, warmup=warmup,
                   exec_cache=exec_cache,
                   allow_unlisted_buckets=allow_unlisted_buckets,
                   breaker_failure_threshold=breaker_failure_threshold,
                   breaker_reset_s=breaker_reset_s,
                   breaker_clock=breaker_clock)

    # -- metrics ----------------------------------------------------------

    def _init_metrics(self):
        m = self.metrics
        self._m_dispatch = m.counter(
            "serving_bucket_dispatch_total",
            "dispatches per (batch, seq) bucket")
        self._m_compile = m.counter(
            "serving_compile_total",
            "AOT bucket compiles, by phase (warmup|lazy)")
        self._m_hits = m.counter(
            "serving_compile_cache_hits_total",
            "dispatches served by an already-compiled bucket")
        self._m_occupancy = m.histogram(
            "serving_batch_occupancy",
            "real rows / bucket batch per dispatch",
            buckets=_RATIO_BUCKETS)
        self._m_waste = m.histogram(
            "serving_padding_waste_fraction",
            "padded elements / bucket elements per dispatch",
            buckets=_RATIO_BUCKETS)
        self._m_padded_tokens = m.counter(
            "serving_padded_tokens_total",
            "absolute pad tokens dispatched, by mode (rect|packed) — "
            "waste attributable in tokens, not just fractions")
        self._m_buckets = m.gauge(
            "serving_compiled_buckets", "compiled bucket executables")
        self._m_exec_hits = m.counter(
            "serving_exec_cache_hits_total",
            "bucket executables deserialized from the persistent "
            "compile cache (zero-compile warm starts)")
        self._m_exec_misses = m.counter(
            "serving_exec_cache_misses_total",
            "bucket executables the persistent cache could not serve "
            "(fresh compile performed and stored)")
        self._m_exec_bytes = m.counter(
            "serving_exec_cache_bytes_total",
            "serialized executable bytes, by direction (read|written)")
        self._m_dispatch_fail = m.counter(
            "serving_dispatch_failures_total",
            "dispatch executions that raised, per bucket")
        self._m_breaker_transitions = m.counter(
            "serving_breaker_transitions_total",
            "circuit-breaker state changes, labeled bucket/to")
        self._m_breaker_open = m.gauge(
            "serving_breaker_open_buckets",
            "buckets currently failing fast (breaker open)")
        self._m_unavailable = m.counter(
            "serving_unavailable_total",
            "requests rejected with typed Unavailable, by reason")
        # router/operator signals (docs/SERVING.md "Fleet"): the full
        # per-bucket breaker state (not just the open count) and the
        # retry-after hint the engine last attached to an Unavailable
        self._m_breaker_state = m.gauge(
            "serving_breaker_state",
            "per-bucket circuit state: 0=closed 1=half_open 2=open")
        self._m_retry_after = m.gauge(
            "serving_retry_after_seconds",
            "retry-after hint carried by the most recent typed "
            "Unavailable (0 when nothing is failing fast)")

    # -- compilation ------------------------------------------------------

    @property
    def buckets(self) -> Tuple[Tuple[int, Optional[int]], ...]:
        """The configured warmup bucket grid."""
        return tuple((b, s) for s in self.seq_buckets
                     for b in self.batch_buckets)

    @property
    def compiled_buckets(self) -> Tuple[Tuple[int, Optional[int]], ...]:
        with self._exe_lock:
            rect = [k for k in self._exe if k[0] != "packed"]
            packed = [k for k in self._exe if k[0] == "packed"]
        return tuple(sorted(rect, key=lambda k: (k[0], k[1] or 0))
                     + sorted(packed, key=lambda k: k[1:]))

    @property
    def compile_count(self) -> int:
        return int(self._m_compile.value)

    def warmup(self) -> None:
        """AOT-compile every configured bucket. After this returns, any
        request that fits a bucket dispatches with zero XLA compiles."""
        for bucket in self.buckets:
            self._ensure_executable(bucket, phase="warmup")
        for tokens, rows in self.packed_buckets:
            self._ensure_executable(("packed", tokens, rows),
                                    phase="warmup")

    def _graph_for(self, bucket):
        return self.packed_graph if bucket[0] == "packed" else self.graph

    def _input_structs(self, bucket):
        import jax
        if bucket[0] == "packed":
            _, tokens, rows = bucket
            return tuple(
                jax.ShapeDtypeStruct(spec.shape(tokens, rows), spec.dtype)
                for spec in self.packed_graph.inputs)
        b, s = bucket
        return tuple(
            jax.ShapeDtypeStruct(spec.shape(b, s), spec.dtype)
            for spec in self.graph.inputs)

    def _ensure_executable(self, bucket, phase: str = "lazy"):
        with self._exe_lock:
            exe = self._exe.get(bucket)
        if exe is not None:
            return exe
        import jax
        graph = self._graph_for(bucket)
        jitted = jax.jit(graph.fn,
                         donate_argnums=graph.donate_argnums)
        # on an exec-cache hit this deserializes the stored executable
        # — no XLA compile at all; on a miss it compiles once and
        # stores the blob for the next process
        exe, info = aot_compile(
            jitted, (self._params, *self._input_structs(bucket)),
            cache=self.exec_cache,
            donate_argnums=graph.donate_argnums,
            label=f"serve:{graph.kind}:{self._bucket_name(bucket)}")
        if self.exec_cache is not None:
            if info["hit"]:
                self._m_exec_hits.inc()
                self._m_exec_bytes.labels(direction="read").inc(
                    info["bytes"])
            else:
                self._m_exec_misses.inc()
                self._m_exec_bytes.labels(direction="written").inc(
                    info["bytes"])
            events_mod.emit("exec_cache",
                            bucket=self._bucket_name(bucket),
                            hit=bool(info["hit"]), phase=phase)
        with self._exe_lock:
            # a concurrent compile of the same bucket may have won —
            # keep the first, count only one executable
            if bucket not in self._exe:
                self._exe[bucket] = exe
                if not info["hit"]:
                    self._m_compile.labels(phase=phase).inc()
                self._m_buckets.set(len(self._exe))
            exe = self._exe[bucket]
        return exe

    # -- params -----------------------------------------------------------

    def update_params(self, params) -> None:
        """Swap device-resident weights without recompiling: shapes and
        dtypes must match the compiled executables' signature (weight
        refresh, not architecture change)."""
        import jax

        if params is self._params_src:
            return  # same host object — already resident
        new_leaves, new_def = jax.tree_util.tree_flatten(params)
        old_leaves, old_def = jax.tree_util.tree_flatten(self._params)
        if new_def != old_def or any(
                n.shape != o.shape or n.dtype != o.dtype
                for n, o in zip(new_leaves, old_leaves)):
            raise ValueError(
                "update_params requires the same pytree structure, "
                "shapes, and dtypes as the params the engine compiled "
                "against — rebuild the engine for a new architecture")
        # the whole tree swaps in one attribute assignment, so a
        # concurrent dispatch reads entirely-old or entirely-new params
        # (never a torn pytree — pinned by tests/test_serving.py);
        # _params_src must track the swap or a later update back to a
        # previously-seen host object would silently no-op
        self._params = jax.device_put(params)
        self._params_src = params

    # -- failure handling -------------------------------------------------

    def _bucket_name(self, bucket) -> str:
        if bucket[0] == "packed":
            return f"t{bucket[1]}_r{bucket[2]}"
        return f"b{bucket[0]}" + (f"_s{bucket[1]}" if bucket[1] else "")

    def _breaker_for(self, bucket) -> CircuitBreaker:
        with self._breaker_lock:
            breaker = self._breakers.get(bucket)
            if breaker is None:
                name = self._bucket_name(bucket)
                breaker = CircuitBreaker(
                    failure_threshold=self._breaker_failure_threshold,
                    reset_timeout_s=self._breaker_reset_s,
                    clock=self._breaker_clock,
                    on_transition=lambda old, new, _n=name:
                        self._on_breaker_transition(_n, old, new))
                self._breakers[bucket] = breaker
                self._m_breaker_state.labels(bucket=name).set(
                    _BREAKER_STATE_VALUES[breaker.state])
            return breaker

    def _on_breaker_transition(self, bucket_name: str, old: str,
                               new: str) -> None:
        events_mod.emit("breaker_transition", bucket=bucket_name,
                        old=old, new=new)
        self._m_breaker_transitions.labels(bucket=bucket_name,
                                           to=new).inc()
        self._m_breaker_state.labels(bucket=bucket_name).set(
            _BREAKER_STATE_VALUES[new])
        if new != OPEN:
            self._m_retry_after.set(0.0)
        self._update_health()

    def _update_health(self) -> None:
        """Health follows the breaker population: any open bucket is
        DEGRADED, every bucket open is UNAVAILABLE (the machine in
        serving/health.py). Never demotes below STARTING."""
        if self.health.state is HealthState.STARTING:
            return
        with self._breaker_lock:
            breakers = list(self._breakers.values())
        open_count = sum(1 for b in breakers if b.state == OPEN)
        self._m_breaker_open.set(open_count)
        if open_count == 0:
            self.health.set(HealthState.READY)
        elif open_count == len(breakers):
            self.health.set(HealthState.UNAVAILABLE)
        else:
            self.health.set(HealthState.DEGRADED)

    @property
    def ready(self) -> bool:
        return self.health.ready

    # -- dispatch ---------------------------------------------------------

    def bucket_for(self, batch: int, length: Optional[int] = None
                   ) -> Tuple[int, Optional[int]]:
        """Smallest configured bucket fitting (batch, length)."""
        b = next((x for x in self.batch_buckets if x >= batch), None)
        if self.graph.seq_bucketable:
            if length is None:
                raise ValueError("sequence-bucketed task needs a length")
            s = next((x for x in self.seq_buckets if x >= length), None)
        else:
            s = None
        if b is None or (self.graph.seq_bucketable and s is None):
            if not self.allow_unlisted_buckets:
                raise RequestTooLarge(
                    f"request (batch={batch}, length={length}) exceeds "
                    f"buckets batch≤{self.batch_buckets[-1]}"
                    + (f", seq≤{self.seq_buckets[-1]}"
                       if self.graph.seq_bucketable else ""))
            b = b if b is not None else batch
            if self.graph.seq_bucketable and s is None:
                if length > self.graph.max_seq_len:
                    raise RequestTooLarge(
                        f"length {length} exceeds the model's "
                        f"max_seq_len {self.graph.max_seq_len}")
                s = length
        return (b, s)

    def _pad_to_bucket(self, arrays: dict, bucket) -> tuple:
        b, s = bucket
        padded = []
        for spec in self.graph.inputs:
            arr = arrays[spec.name]
            shape = spec.shape(b, s)
            if arr.shape == shape:
                padded.append(arr)
                continue
            out = np.full(shape, spec.pad_value, dtype=np.dtype(spec.dtype))
            out[tuple(slice(0, d) for d in arr.shape)] = arr
            padded.append(out)
        return tuple(padded)

    def _guarded_execute(self, bucket, padded: tuple):
        """Breaker-gated executable call shared by both dispatch modes:
        fail fast when the bucket's circuit is open, record the outcome
        either way."""
        breaker = self._breaker_for(bucket)
        if not breaker.allow():
            # fail fast with backpressure instead of queueing work
            # behind a bucket that keeps failing (docs/RESILIENCE.md)
            retry_after = breaker.retry_after()
            self._m_unavailable.labels(reason="circuit_open").inc()
            self._m_retry_after.set(retry_after)
            raise Unavailable("circuit_open", bucket=bucket,
                              retry_after_s=retry_after)
        with self._exe_lock:
            known = bucket in self._exe
        if known:
            self._m_hits.inc()
        try:
            exe = self._ensure_executable(bucket)
            faults.maybe_raise("serve.dispatch")
            outputs = exe(self._params, *padded)
        except Unavailable:
            raise
        except Exception:
            self._m_dispatch_fail.labels(
                bucket=self._bucket_name(bucket)).inc()
            breaker.record_failure()
            raise
        breaker.record_success()
        self._m_dispatch.labels(bucket=self._bucket_name(bucket)).inc()
        return outputs

    def dispatch(self, arrays: Dict[str, np.ndarray],
                 lengths: Optional[np.ndarray] = None) -> ServeResult:
        """Run one bucketed forward. ``arrays`` maps the graph's input
        names to HOST arrays (rows ≤ the largest batch bucket). Returns
        device-resident outputs; nothing in here blocks on the device.

        ``lengths`` (per-request true token counts, host int array)
        makes the waste metrics exact: without it the intra-batch
        padding — short requests padded to the batch width upstream —
        is invisible and waste undercounts.
        """
        expect = {spec.name for spec in self.graph.inputs}
        if set(arrays) != expect:
            raise ValueError(
                f"dispatch inputs {sorted(arrays)} != expected "
                f"{sorted(expect)}")
        first = arrays[self.graph.inputs[0].name]
        n = first.shape[0]
        if n < 1:
            raise ValueError("empty request batch")
        length = first.shape[1] if self.graph.seq_bucketable else None
        for spec in self.graph.inputs:
            want = spec.shape(n, length)
            if tuple(arrays[spec.name].shape) != want:
                raise ValueError(
                    f"input {spec.name!r} shape "
                    f"{tuple(arrays[spec.name].shape)} != {want}")
        if lengths is not None and lengths.shape[0] != n:
            raise ValueError(
                f"lengths has {lengths.shape[0]} entries for {n} rows")
        bucket = self.bucket_for(n, length)
        # trace regions are host-side wall clocks around host work —
        # nothing here enters the jitted graph (serving-host-sync)
        with trace_mod.region("pad_or_pack"):
            padded = self._pad_to_bucket(arrays, bucket)
        with trace_mod.region("dispatch",
                              bucket=self._bucket_name(bucket)):
            outputs = self._guarded_execute(bucket, padded)

        self._m_occupancy.observe(n / bucket[0])
        if self.graph.seq_bucketable:
            total = bucket[0] * bucket[1]
            if lengths is not None:
                real = int(lengths.sum())
            else:
                # batch width as a lower bound — intra-batch padding
                # is invisible without per-request lengths
                real = n * length
            waste = 1.0 - real / total
            self._m_padded_tokens.labels(mode="rect").inc(total - real)
        else:
            waste = 1.0 - n / bucket[0]
        self._m_waste.observe(waste)
        return ServeResult(outputs=outputs, batch=n, length=length,
                           bucket=bucket, lengths=lengths)

    # -- packed (ragged) dispatch -----------------------------------------

    def packed_bucket_for(self, tokens: int, requests: int
                          ) -> Tuple[object, int, int]:
        """Smallest configured token-budget bucket fitting the batch.
        Packed mode is AOT-only — no lazy exact-shape fallback (the
        whole point is a closed executable set over the token axis)."""
        if not self.packed_buckets:
            raise ValueError("engine has no packed_buckets configured")
        fit = next(((t, r) for t, r in self.packed_buckets
                    if t >= tokens and r >= requests), None)
        if fit is None:
            t_max, r_max = self.packed_buckets[-1]
            raise RequestTooLarge(
                f"packed batch (tokens={tokens}, requests={requests}) "
                f"exceeds buckets tokens≤{t_max}, rows≤{r_max}")
        return ("packed",) + fit

    def _pad_packed(self, arrays: dict, bucket) -> tuple:
        _, tokens, rows = bucket
        total = int(arrays["lengths"].sum())
        padded = []
        for spec in self.packed_graph.inputs:
            arr = arrays[spec.name]
            shape = spec.shape(tokens, rows)
            if tuple(arr.shape) == shape:
                padded.append(arr)
                continue
            # unused rows become empty spans parked at the end of the
            # real tokens (offset=total, length=0): the ragged kernels
            # do zero work for them and the tail pad ids are inert
            fill = total if spec.name == "row_offsets" else spec.pad_value
            out = np.full(shape, fill, dtype=np.dtype(spec.dtype))
            out[:arr.shape[0]] = arr
            padded.append(out)
        return tuple(padded)

    def dispatch_packed(self, arrays: Dict[str, np.ndarray]
                        ) -> PackedServeResult:
        """Run one packed ragged forward. ``arrays`` holds the packed
        graph's inputs at their true sizes: ``packed_ids`` (total_tokens,)
        int32, ``row_offsets``/``lengths`` (n_requests,) int32. Padding
        to the token-budget bucket happens here; outputs stay on device.
        """
        if self.packed_graph is None or not self.packed_buckets:
            raise ValueError(
                "engine has no packed mode configured — pass "
                "packed_buckets (and a task or packed_graph)")
        expect = {spec.name for spec in self.packed_graph.inputs}
        if set(arrays) != expect:
            raise ValueError(
                f"dispatch_packed inputs {sorted(arrays)} != expected "
                f"{sorted(expect)}")
        lengths = arrays["lengths"]
        row_offsets = arrays["row_offsets"]
        n = lengths.shape[0]
        if n < 1:
            raise ValueError("empty request batch")
        if row_offsets.shape[0] != n:
            raise ValueError(
                f"row_offsets has {row_offsets.shape[0]} entries for "
                f"{n} lengths")
        max_len = int(lengths.max())
        if max_len > self.packed_graph.max_seq_len:
            raise RequestTooLarge(
                f"request length {max_len} exceeds the model's "
                f"max_seq_len {self.packed_graph.max_seq_len}")
        tokens = arrays["packed_ids"].shape[0]
        bucket = self.packed_bucket_for(tokens, n)
        with trace_mod.region("pad_or_pack"):
            padded = self._pad_packed(arrays, bucket)
        with trace_mod.region("dispatch",
                              bucket=self._bucket_name(bucket)):
            outputs = self._guarded_execute(bucket, padded)

        _, t_bucket, r_bucket = bucket
        real = int(lengths.sum())
        self._m_occupancy.observe(n / r_bucket)
        self._m_waste.observe(1.0 - real / t_bucket)
        self._m_padded_tokens.labels(mode="packed").inc(t_bucket - real)
        return PackedServeResult(outputs=outputs, batch=n,
                                 lengths=lengths, row_offsets=row_offsets,
                                 bucket=bucket)
