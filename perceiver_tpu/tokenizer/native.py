"""ctypes bridge to the C++ WordPiece core (csrc/wordpiece.cpp).

Builds the shared library on first use (g++ -O2, cached beside the
source) — no pybind11 in this image, so the ABI is plain C. Falls back
cleanly: callers catch ImportError/OSError and use the pure-Python
engine, which produces identical results (asserted by tests).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections import Counter
from typing import Iterable, List

_SRC = os.path.join(os.path.dirname(__file__), "csrc", "wordpiece.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "csrc", "libwordpiece.so")
_lock = threading.Lock()
_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            # Build to a process-unique temp path and rename into place:
            # rename is atomic, so concurrent processes (dataloader
            # workers on a cold cache) never dlopen a half-written ELF.
            tmp = f"{_LIB}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-pthread", _SRC, "-o", tmp],
                    check=True, capture_output=True)
                os.replace(tmp, _LIB)
            except subprocess.CalledProcessError as e:
                # normalize to OSError so callers' documented fallback
                # (except (ImportError, OSError)) catches compile failure
                raise OSError(
                    f"native tokenizer build failed: "
                    f"{e.stderr.decode(errors='replace')[:500]}") from e
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
        lib = ctypes.CDLL(_LIB)
        lib.wp_vocab_create.restype = ctypes.c_void_p
        lib.wp_vocab_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32]
        lib.wp_vocab_free.argtypes = [ctypes.c_void_p]
        lib.wp_encode_words.restype = ctypes.c_int32
        lib.wp_encode_words.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.wp_encode_docs.restype = None
        lib.wp_encode_docs.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.wp_encode_docs_raw.restype = None
        lib.wp_encode_docs_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.wp_train.restype = ctypes.c_void_p  # manual free
        lib.wp_train.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64]
        lib.wp_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeVocab:
    """Vocab handle for repeated fast encodes."""

    def __init__(self, tokenizer):
        lib = _load()
        self._lib = lib
        ordered = sorted(tokenizer.vocab.items(), key=lambda kv: kv[1])
        import numpy as np
        self._id_map = [i for _, i in ordered]  # dense idx -> real id
        self._id_map_np = np.asarray(self._id_map, np.int32)
        self._token_to_dense = {t: j for j, (t, _) in enumerate(ordered)}
        toks = (ctypes.c_char_p * len(ordered))(
            *[t.encode("utf-8") for t, _ in ordered])
        self._handle = lib.wp_vocab_create(toks, len(ordered))
        self._unk_dense = next(
            j for j, (t, _) in enumerate(ordered)
            if t == tokenizer.unk_token)
        self._prefix = tokenizer.prefix.encode("utf-8")
        self._max_chars = tokenizer.max_input_chars_per_word
        # ctypes releases the GIL during the C call, so the shared
        # result buffer (and its grow path) must be guarded for
        # concurrent encode() on one tokenizer instance.
        self._buf_lock = threading.Lock()
        self._buf = (ctypes.c_int32 * 4096)()

    def encode_words(self, words: List[str]) -> List[int]:
        """One FFI round-trip for a whole pre-tokenized word list."""
        payload = "\n".join(words).encode("utf-8")
        with self._buf_lock:
            buf = self._buf
            while True:
                n = self._lib.wp_encode_words(
                    self._handle, payload, len(payload), self._unk_dense,
                    self._max_chars, self._prefix, buf, len(buf))
                if n >= 0:
                    break
                buf = (ctypes.c_int32 * (len(buf) * 4))()
                self._buf = buf
            id_map = self._id_map
            return [id_map[buf[i]] for i in range(n)]

    def encode_docs_padded(self, docs_words: List[List[str]],
                           max_len: int, pad_id: int,
                           n_threads: int = 0):
        """Encode many pre-tokenized documents into a padded
        ``(n_docs, max_len)`` int32 matrix (real vocab ids, ``pad_id``
        past each document's length) plus a lengths vector, with the
        WordPiece matching split across C++ threads — the GIL is
        released for the whole call, so this is true multi-core
        tokenization of the corpus.
        """
        import numpy as np

        payloads = ["\n".join(ws).encode("utf-8") for ws in docs_words]
        offsets = np.zeros(len(payloads) + 1, np.int64)
        np.cumsum([len(p) for p in payloads], out=offsets[1:])
        blob = b"".join(payloads)
        out = np.zeros((len(payloads), max_len), np.int32)
        lengths = np.zeros(len(payloads), np.int32)
        if n_threads <= 0:
            n_threads = min(os.cpu_count() or 1, 16)
        self._lib.wp_encode_docs(
            self._handle, blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(payloads), self._unk_dense, self._max_chars, self._prefix,
            max_len, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_threads)
        return self._map_and_pad(out, lengths, pad_id), lengths

    def encode_docs_raw(self, texts: List[str], replaces, lowercase: bool,
                        specials: List[str], max_len: int, pad_id: int,
                        n_threads: int = 0):
        """Full-pipeline encode of raw ASCII documents (added-token
        matching, literal replaces, lowercasing, HF-Whitespace split,
        WordPiece) entirely inside threaded C++. Every text must be
        pure ASCII (empty strings are fine and yield empty rows — the
        caller's hook for routing non-ASCII documents elsewhere).
        Returns real-id ``(n, max_len)`` matrix + lengths.
        """
        import numpy as np

        payloads = [t.encode("ascii") for t in texts]
        offsets = np.zeros(len(payloads) + 1, np.int64)
        np.cumsum([len(p) for p in payloads], out=offsets[1:])
        blob = b"".join(payloads)

        find = (ctypes.c_char_p * max(len(replaces), 1))(
            *[f.encode("ascii") for f, _ in replaces] or [b""])
        repl = (ctypes.c_char_p * max(len(replaces), 1))(
            *[r.encode("ascii") for _, r in replaces] or [b""])
        sp_toks = (ctypes.c_char_p * max(len(specials), 1))(
            *[s.encode("ascii") for s in specials] or [b""])
        sp_dense = [self._token_to_dense[t] for t in specials]
        sp_ids = (ctypes.c_int32 * max(len(specials), 1))(
            *(sp_dense or [0]))

        out = np.zeros((len(payloads), max_len), np.int32)
        lengths = np.zeros(len(payloads), np.int32)
        if n_threads <= 0:
            n_threads = min(os.cpu_count() or 1, 16)
        self._lib.wp_encode_docs_raw(
            self._handle, blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(payloads), find, repl, len(replaces),
            1 if lowercase else 0, sp_toks, sp_ids, len(specials),
            self._unk_dense, self._max_chars, self._prefix, max_len,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_threads)
        return self._map_and_pad(out, lengths, pad_id), lengths

    def _map_and_pad(self, dense_out, lengths, pad_id: int):
        """Dense-id matrix → real ids, with positions past each row's
        length set to ``pad_id`` — which therefore may be ANY int (e.g.
        an ignore sentinel), not just a vocab id, matching the
        pure-Python fallback."""
        import numpy as np

        real = self._id_map_np[dense_out]
        cols = np.arange(dense_out.shape[1])
        real[cols[None, :] >= lengths[:, None]] = pad_id
        return real

    def __del__(self):
        try:
            self._lib.wp_vocab_free(self._handle)
        except Exception:
            pass  # interpreter teardown: ctypes/lib may be gone; leak


def count_words(tokenizer, data: Iterable[str]) -> Counter:
    """Shared corpus word-counting (normalize → pre-tokenize → count);
    both the native and pure-Python trainers feed from this so their
    inputs can never diverge."""
    counts: Counter = Counter()
    for text in data:
        for w in tokenizer.pre_tokenize(tokenizer.normalize(text)):
            counts[w] += 1
    return counts


def native_train(tokenizer, data: Iterable[str], vocab_size: int,
                 special_tokens: List[str], min_frequency: int) -> dict:
    """Count words host-side, train merges in C++; returns vocab dict."""
    lib = _load()
    items = sorted(count_words(tokenizer, data).items())  # deterministic
    words = (ctypes.c_char_p * len(items))(
        *[w.encode("utf-8") for w, _ in items])
    cts = (ctypes.c_int64 * len(items))(*[c for _, c in items])
    specials = (ctypes.c_char_p * len(special_tokens))(
        *[s.encode("utf-8") for s in special_tokens])
    ptr = lib.wp_train(words, cts, len(items), specials,
                       len(special_tokens),
                       tokenizer.prefix.encode("utf-8"),
                       vocab_size, min_frequency)
    try:
        raw = ctypes.string_at(ptr).decode("utf-8")
    finally:
        lib.wp_free(ptr)
    tokens = [t for t in raw.split("\n") if t]
    return {t: i for i, t in enumerate(tokens)}
