"""Persistent compile cache (ISSUE 4): serialized AOT executables.

The acceptance properties, each pinned here:

- a warm-start ``ServingEngine`` warmup over the FULL bucket grid
  performs **zero** XLA compiles — asserted via ``jax.monitoring``
  compile events in a fresh subprocess against a cache a previous
  subprocess populated;
- every cache failure mode degrades to a real compile, never a crash:
  truncated/corrupt blob (+ corrupt/miss counters), doctored version
  sidecar, missing entries;
- version skew keys differently (a jaxlib bump can never load a stale
  executable);
- eviction respects the size cap, dropping least-recently-used
  entries first;
- two engines sharing one cache directory don't race (atomic
  tempfile + rename publication);
- ``step_flops_and_fn``'s cache path returns a deserialized
  executable + sidecar flops on a hit (the trainer's zero-compile
  first dispatch).
"""

import json
import os
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.cache import (
    ExecutableCache,
    aot_compile,
    default_cache,
    source_tree_digest,
)
from perceiver_tpu.cache import exec_cache as exec_cache_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cache(tmp_path, **kw):
    kw.setdefault("native", False)
    return ExecutableCache(str(tmp_path / "ec"), **kw)


def _tiny_jit(mult=2.0):
    return jax.jit(lambda p, x: {"y": p * x + mult},
                   donate_argnums=(1,))


ARGS = (jnp.arange(4.0), jnp.ones((4,)))


class TestExecutableEntries:
    def test_miss_compile_store_then_hit_parity(self, tmp_path):
        cache = _cache(tmp_path)
        c1, info1 = aot_compile(_tiny_jit(), ARGS, cache=cache,
                                donate_argnums=(1,), label="t")
        assert not info1["hit"] and info1["bytes"] > 0
        assert cache.stats.misses == 1 and cache.stats.stores == 1
        c2, info2 = aot_compile(_tiny_jit(), ARGS, cache=cache,
                                donate_argnums=(1,))
        assert info2["hit"] and info2["key"] == info1["key"]
        assert cache.stats.hits == 1
        out1 = np.asarray(c1(jnp.arange(4.0), jnp.ones((4,)))["y"])
        out2 = np.asarray(c2(jnp.arange(4.0), jnp.ones((4,)))["y"])
        np.testing.assert_array_equal(out1, out2)
        # sidecar carries the cost analysis for warm-path consumers
        assert info2["sidecar"]["flops"] is not None

    def test_truncated_blob_falls_back_to_compile(self, tmp_path):
        cache = _cache(tmp_path)
        _, info = aot_compile(_tiny_jit(), ARGS, cache=cache)
        blob_path = cache._exe_path(info["key"])
        blob = open(blob_path, "rb").read()
        with open(blob_path, "wb") as f:
            f.write(blob[: len(blob) // 2])
        c, info2 = aot_compile(_tiny_jit(), ARGS, cache=cache)
        assert not info2["hit"], "corrupt entry must read as a miss"
        assert cache.stats.corrupt == 1
        # the fallback compiled + re-stored a good entry
        np.testing.assert_array_equal(
            np.asarray(c(jnp.arange(4.0), jnp.ones((4,)))["y"]),
            np.arange(4.0) + 2.0)
        _, info3 = aot_compile(_tiny_jit(), ARGS, cache=cache)
        assert info3["hit"]

    def test_garbage_blob_and_missing_sidecar(self, tmp_path):
        cache = _cache(tmp_path)
        _, info = aot_compile(_tiny_jit(), ARGS, cache=cache)
        key = info["key"]
        with open(cache._exe_path(key), "wb") as f:
            f.write(b"not a pickle at all")
        assert cache.load_executable(key) is None
        # the bad entry was dropped outright
        assert not os.path.exists(cache._exe_path(key))
        # entry without a sidecar is a miss, not a crash
        _, info = aot_compile(_tiny_jit(), ARGS, cache=cache)
        os.unlink(cache._sidecar_path(info["key"]))
        assert cache.load_executable(info["key"]) is None

    def test_jaxlib_version_mismatch_keys_differently(self, tmp_path,
                                                      monkeypatch):
        cache = _cache(tmp_path)
        text = "func.func public @main() { fake }"
        key_now = cache.executable_key(text)
        monkeypatch.setattr(exec_cache_mod, "_versions",
                            lambda: ("99.0.0", "99.0.0"))
        key_future = cache.executable_key(text)
        assert key_now != key_future, \
            "a jax/jaxlib bump must change every executable key"
        assert cache.load_executable(key_future) is None

    def test_doctored_version_sidecar_is_dropped(self, tmp_path):
        """Defense in depth: an entry whose sidecar claims another
        jaxlib (key collision / hand-copied file) is discarded."""
        cache = _cache(tmp_path)
        _, info = aot_compile(_tiny_jit(), ARGS, cache=cache)
        key = info["key"]
        side = json.load(open(cache._sidecar_path(key)))
        side["jaxlib"] = "0.0.1"
        with open(cache._sidecar_path(key), "w") as f:
            json.dump(side, f)
        assert cache.load_executable(key) is None
        assert not os.path.exists(cache._exe_path(key))

    def test_eviction_respects_size_cap_lru(self, tmp_path):
        cache = _cache(tmp_path)
        keys = []
        for i in range(3):
            _, info = aot_compile(_tiny_jit(float(i)), ARGS,
                                  cache=cache)
            keys.append(info["key"])
            time.sleep(0.02)  # distinct mtimes for LRU ordering
        per_entry = cache.entry_bytes() // 3
        # touch the oldest so the MIDDLE entry becomes LRU
        assert cache.load_executable(keys[0]) is not None
        time.sleep(0.02)
        small = ExecutableCache(cache.path, native=False,
                                max_bytes=2 * per_entry + per_entry // 2)
        small._evict()
        assert small.entry_bytes() <= small.max_bytes
        assert small.stats.evicted == 1
        assert not os.path.exists(small._exe_path(keys[1]))
        assert os.path.exists(small._exe_path(keys[0]))
        assert os.path.exists(small._exe_path(keys[2]))

    def test_default_cache_env_and_memoization(self, tmp_path,
                                               monkeypatch):
        monkeypatch.delenv("PERCEIVER_EXEC_CACHE", raising=False)
        assert default_cache() is None
        monkeypatch.setenv("PERCEIVER_EXEC_CACHE", str(tmp_path / "d"))
        c1 = default_cache()
        assert c1 is not None and c1 is default_cache()
        assert default_cache(str(tmp_path / "other")) is not c1

    def test_callback_graphs_bypass_cache(self, tmp_path):
        """jax.debug.print / io_callback graphs bake a host function
        pointer into the executable — garbage in any other process —
        so the cache must refuse them (compile fresh every time)."""
        from perceiver_tpu.cache import has_host_callbacks

        cache = _cache(tmp_path)

        def noisy(p, x):
            jax.lax.cond(
                x.sum() > 0,
                lambda v: jax.debug.print("overflow {n}", n=v),
                lambda v: None, x.sum())
            return {"y": p * x}

        jitted = jax.jit(noisy)
        assert has_host_callbacks(jitted.lower(*ARGS).as_text())
        for _ in range(2):
            c, info = aot_compile(jitted, ARGS, cache=cache)
            assert not info["hit"] and info["key"] is None
            np.testing.assert_array_equal(
                np.asarray(c(jnp.arange(4.0), jnp.ones((4,)))["y"]),
                np.arange(4.0))
        assert cache.stats.stores == 0 and cache.stats.hits == 0

    def test_executable_key_canonicalizes_callback_ptrs(self, tmp_path):
        """Two lowerings of the same callback-bearing program differ
        only in the per-lowering wrapper address — keys must agree
        (and only those digits are masked)."""
        cache = _cache(tmp_path)

        def make():
            def noisy(p, x):
                jax.lax.cond(
                    x.sum() > 0,
                    lambda v: jax.debug.print("n={n}", n=v),
                    lambda v: None, x.sum())
                return p * x
            return noisy

        t1 = jax.jit(make()).lower(*ARGS).as_text()
        t2 = jax.jit(make()).lower(*ARGS).as_text()
        assert t1 != t2, "wrapper address should differ per lowering"
        assert cache.executable_key(t1) == cache.executable_key(t2)
        # a genuine program difference still keys differently
        t3 = jax.jit(lambda p, x: p * x + 1).lower(*ARGS).as_text()
        assert cache.executable_key(t1) != cache.executable_key(t3)

    def test_source_tree_digest_tracks_content(self, tmp_path):
        a, b = tmp_path / "a", tmp_path / "b"
        for root in (a, b):
            root.mkdir()
            (root / "m.py").write_text("x = 1\n")
        assert source_tree_digest(str(a)) == source_tree_digest(str(b))
        c = tmp_path / "c"
        c.mkdir()
        (c / "m.py").write_text("x = 2\n")
        assert source_tree_digest(str(a)) != source_tree_digest(str(c))


class TestLoweringRecords:
    def test_roundtrip_and_corruption(self, tmp_path):
        cache = _cache(tmp_path)
        key = cache.lowering_key("seeded_target")
        assert cache.load_lowering(key) is None
        record = {"text": "module {}", "expected_donated": 0,
                  "bytes_accessed": 123.0}
        assert cache.store_lowering(key, record)
        got = cache.load_lowering(key)
        assert got["text"] == "module {}"
        with open(cache._lowering_path(key), "w") as f:
            f.write("{ not json")
        assert cache.load_lowering(key) is None
        assert cache.stats.corrupt >= 0  # counted as miss, no crash

    def test_key_binds_source_digest(self, tmp_path, monkeypatch):
        cache = _cache(tmp_path)
        k1 = cache.lowering_key("t")
        monkeypatch.setattr(exec_cache_mod, "source_tree_digest",
                            lambda root=None: "deadbeef")
        assert cache.lowering_key("t") != k1, \
            "a source edit must invalidate lowering records"

    def test_key_forks_on_mesh_descriptor(self, tmp_path):
        """The same target lowered over two meshes must be two records
        — the shardings (and therefore the GSPMD collectives in the
        stored compiled text) differ per topology, so serving a
        data2_model2 record to a data4_model1 run would gate the wrong
        graph. ``lower_target`` passes ``mesh.descriptor`` as the key
        extra; axis NAMING forks too (a renamed axis changes every
        PartitionSpec even at the same shape)."""
        cache = _cache(tmp_path)
        keys = {cache.lowering_key("t", extra=extra)
                for extra in ((), ("data2_model2",), ("data4_model1",),
                              ("batch2_shard2",))}
        assert len(keys) == 4, "mesh descriptor must be key material"
        # and the record served back is the one stored under that mesh
        k22 = cache.lowering_key("t", extra=("data2_model2",))
        k41 = cache.lowering_key("t", extra=("data4_model1",))
        cache.store_lowering(k22, {"text": "module @m22 {}",
                                   "expected_donated": 0,
                                   "compiled_text": "HloModule m22",
                                   "mesh": "data2_model2"})
        cache.store_lowering(k41, {"text": "module @m41 {}",
                                   "expected_donated": 0,
                                   "compiled_text": "HloModule m41",
                                   "mesh": "data4_model1"})
        assert cache.load_lowering(k22)["mesh"] == "data2_model2"
        assert cache.load_lowering(k41)["compiled_text"] == \
            "HloModule m41"

    def test_executable_key_forks_on_pool_geometry(self, tmp_path):
        """ISSUE 14: the decode engine's AOT key carries the pool
        geometry descriptor (``DecodeGeometry.descriptor``) as key
        extra — two engines with different page counts or page sizes
        must never share an executable, even if a future refactor made
        their HLO coincide (the page-table ABI differs: table width and
        page-index range are geometry-bound host-side contracts)."""
        from perceiver_tpu.serving.decode import DecodeGeometry

        cache = _cache(tmp_path)
        geoms = (
            DecodeGeometry(max_streams=8, num_pages=64, page_size=16,
                           max_seq_len=512),
            DecodeGeometry(max_streams=8, num_pages=32, page_size=16,
                           max_seq_len=512),   # fewer pages
            DecodeGeometry(max_streams=8, num_pages=64, page_size=8,
                           max_seq_len=512),   # narrower pages
            DecodeGeometry(max_streams=4, num_pages=64, page_size=16,
                           max_seq_len=512),   # fewer slots
        )
        descriptors = {g.descriptor for g in geoms}
        assert len(descriptors) == 4, \
            "geometry descriptor must distinguish slots/pages/page size"
        text = "module @decode_step {}"  # same HLO for every key
        keys = {cache.executable_key(text, donate_argnums=(1,),
                                     extra=(g.descriptor,))
                for g in geoms}
        assert len(keys) == 4, "pool geometry must be key material"
        # identical geometry still dedupes to one key (warm restart hit)
        again = DecodeGeometry(max_streams=8, num_pages=64,
                               page_size=16, max_seq_len=512)
        assert cache.executable_key(
            text, donate_argnums=(1,),
            extra=(again.descriptor,)) in keys


class TestStepFlopsCachePath:
    def test_hit_returns_sidecar_flops_and_executable(self, tmp_path):
        from perceiver_tpu.utils.flops import step_flops_and_fn

        cache = _cache(tmp_path)
        jitted = jax.jit(lambda s, b: (s + b.sum(), b.mean()),
                         donate_argnums=0)
        args = (jnp.zeros(()), jnp.ones((8, 8)))
        flops1, fn1 = step_flops_and_fn(jitted, *args, cache=cache,
                                        cache_label="test")
        assert cache.stats.stores == 1
        flops2, fn2 = step_flops_and_fn(
            jitted, jnp.zeros(()), jnp.ones((8, 8)), cache=cache)
        assert cache.stats.hits == 1
        assert flops2 == flops1 and flops2 is not None
        s1, _ = fn1(jnp.zeros(()), jnp.ones((8, 8)))
        s2, _ = fn2(jnp.zeros(()), jnp.ones((8, 8)))
        assert float(s1) == float(s2) == 64.0
        # without a cache the lowering-analysis path still returns
        # the original jit fn (no behavior change)
        flops3, fn3 = step_flops_and_fn(jitted, jnp.zeros(()),
                                        jnp.ones((8, 8)))
        assert fn3 is jitted and flops3 == flops1


# --- engine integration ------------------------------------------------------


def _tiny_task():
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    return MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=32, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")


def _arrays(batch, length, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(3, 110, (batch, length)).astype(np.int32)
    return {"input_ids": ids,
            "pad_mask": np.zeros((batch, length), bool)}


class TestEngineIntegration:
    def test_two_engines_sharing_one_dir_do_not_race(self, tmp_path):
        """Concurrent warmups over one cache directory: atomic rename
        publication means both engines finish with working
        executables and the directory holds exactly one entry per
        bucket, no temp droppings."""
        from perceiver_tpu.serving import ServingEngine, materialize

        cache_dir = str(tmp_path / "shared")
        task = _tiny_task()
        engines = [ServingEngine(task, batch_buckets=(1, 2),
                                 seq_buckets=(16,), warmup=False,
                                 exec_cache=cache_dir)
                   for _ in range(2)]
        errors = []

        def warm(e):
            try:
                e.warmup()
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=warm, args=(e,))
                   for e in engines]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        outs = []
        for e in engines:
            assert e.compiled_buckets == ((1, 16), (2, 16))
            outs.append(materialize(e.dispatch(_arrays(1, 9)), e.graph))
        for name in outs[0]:
            np.testing.assert_array_equal(outs[0][name], outs[1][name])
        names = os.listdir(cache_dir)
        assert not [n for n in names if n.startswith(".tmp-")]
        assert len([n for n in names if n.endswith(".exe")]) == 2

    def test_corrupt_entry_engine_falls_back_and_counts(self, tmp_path):
        from perceiver_tpu.serving import ServingEngine

        cache_dir = tmp_path / "ec"
        task = _tiny_task()
        ServingEngine(task, batch_buckets=(1,), seq_buckets=(16,),
                      exec_cache=str(cache_dir))
        for name in os.listdir(cache_dir):
            if name.endswith(".exe"):
                with open(cache_dir / name, "wb") as f:
                    f.write(b"rotted")
        eng = ServingEngine(task, batch_buckets=(1,), seq_buckets=(16,),
                            exec_cache=str(cache_dir))
        m = eng.metrics
        assert eng.compile_count == 1  # real compile happened
        assert m.get("serving_exec_cache_misses_total").value == 1
        assert m.get("serving_exec_cache_hits_total").value == 0
        eng.dispatch(_arrays(1, 16))

    def test_prefix_cache_is_not_executable_key_material(self, tmp_path):
        """ISSUE 18 pin: content-addressed prefix sharing is pure
        host-side bookkeeping — enabling it must not change the
        geometry descriptor or fork the exec-cache key, so a replica
        that toggles the cache on warm-restarts into the SAME
        deserialized decode executable (zero XLA compiles)."""
        import re

        from jax._src import monitoring as _monitoring

        from perceiver_tpu.ops.policy import Policy
        from perceiver_tpu.serving.decode import (
            DecodeEngine,
            DecodeGeometry,
        )
        from perceiver_tpu.serving.prefix_cache import PrefixCacheConfig

        geometry = DecodeGeometry(max_streams=2, num_pages=9,
                                  page_size=4, max_seq_len=32)
        # the descriptor grammar is frozen: runs/pages/seq/chunk lanes
        # only — no prefix-cache material may ever leak into it
        assert re.fullmatch(r"r\d+_p\d+x\d+_s\d+_q\d+",
                            geometry.descriptor), geometry.descriptor
        cache_dir = str(tmp_path / "ec")
        cold = DecodeEngine(_tiny_task(), geometry=geometry,
                            policy=Policy.fp32(), auto_step=False,
                            exec_cache=cache_dir)
        cold.close(timeout=2.0)
        events = []

        def listener(name, **kwargs):
            if "compile" in name:
                events.append(name)

        jax.monitoring.register_event_listener(listener)
        try:
            warm = DecodeEngine(_tiny_task(), geometry=geometry,
                                policy=Policy.fp32(), auto_step=False,
                                exec_cache=cache_dir,
                                prefix_cache=PrefixCacheConfig())
            warm.close(timeout=2.0)
        finally:
            _monitoring._unregister_event_listener_by_callback(listener)
        assert events == [], (
            f"prefix caching forked the executable key: {events}")


# --- THE acceptance criterion ------------------------------------------------

_WARM_START_CHILD = """
import json, os, sys
sys.path.insert(0, os.getcwd())  # repo root (the test sets cwd)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from perceiver_tpu.tasks import MaskedLanguageModelTask
from perceiver_tpu.serving import ServingEngine, materialize

task = MaskedLanguageModelTask(
    vocab_size=110, max_seq_len=32, num_latents=4,
    num_latent_channels=8, num_encoder_layers=1,
    num_encoder_self_attention_layers_per_block=1,
    num_encoder_cross_attention_heads=1,
    num_encoder_self_attention_heads=1,
    num_decoder_cross_attention_heads=1, loss_impl="dense")
engine = ServingEngine(task, batch_buckets=(1, 2),
                       seq_buckets=(16, 32), warmup=False,
                       exec_cache=sys.argv[1])
events = []
jax.monitoring.register_event_listener(
    lambda name, **kw: events.append(name) if "compile" in name
    else None)
engine.warmup()
res = engine.dispatch({
    "input_ids": np.full((1, 10), 5, np.int32),
    "pad_mask": np.zeros((1, 10), bool)})
out = materialize(res, engine.graph)
m = engine.metrics
print(json.dumps({
    "compile_events": events,
    "engine_compiles": engine.compile_count,
    "buckets": sorted([b, s] for (b, s) in engine.compiled_buckets),
    "hits": m.get("serving_exec_cache_hits_total").value,
    "misses": m.get("serving_exec_cache_misses_total").value,
    "bytes_read": m.get("serving_exec_cache_bytes_total").value_of(
        direction="read"),
    "out0": np.asarray(out["filled_ids"]).tolist(),
}))
"""


def _run_warm_start_child(script_path, cache_dir):
    r = subprocess.run(
        [sys.executable, str(script_path), str(cache_dir)],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO,
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_warm_start_full_grid_zero_compiles_across_processes(tmp_path):
    """Acceptance: a fresh process against a pre-populated cache warms
    the FULL bucket grid with zero XLA compiles (jax.monitoring), all
    buckets present, and bitwise-identical outputs."""
    script = tmp_path / "warm_child.py"
    script.write_text(_WARM_START_CHILD)
    cache_dir = tmp_path / "cache"

    cold = _run_warm_start_child(script, cache_dir)
    assert cold["misses"] == 4 and cold["engine_compiles"] == 4
    assert cold["compile_events"], "cold warmup must really compile"

    warm = _run_warm_start_child(script, cache_dir)
    assert warm["compile_events"] == [], (
        "warm-start warmup over the full bucket grid must perform "
        f"ZERO XLA compiles, saw {warm['compile_events']}")
    assert warm["engine_compiles"] == 0
    assert warm["hits"] == 4 and warm["misses"] == 0
    assert warm["bytes_read"] > 0
    assert warm["buckets"] == [[1, 16], [1, 32], [2, 16], [2, 32]]
    assert warm["out0"] == cold["out0"], \
        "deserialized executables must reproduce compiled outputs"


def test_bench_startup_script_cold_warm(tmp_path):
    """scripts/bench_startup.py emits bench.py-format cold/warm JSON
    with the warm serving phase compile-free. Slow-marked."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "bench_startup.py"),
         "--cache-dir", str(tmp_path / "bc"), "--keep-cache"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO,
        capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    lines = [json.loads(line) for line in r.stdout.splitlines()
             if line.strip().startswith("{")]
    by_metric = {obj["metric"]: obj for obj in lines}
    assert set(by_metric) == {"serving_warm_start_speedup",
                              "trainer_warm_start_speedup"}
    for obj in lines:
        assert set(obj) == {"metric", "value", "unit", "vs_baseline",
                            "detail"}
        assert obj["unit"] == "x" and obj["value"] > 0
        assert obj["detail"]["warm_s"] < obj["detail"]["cold_s"]
        assert obj["detail"]["warm_exec_cache_misses"] == 0
        assert obj["detail"]["warm_xla_compiles"] == 0
