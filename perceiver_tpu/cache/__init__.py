"""Persistent compile cache: serialized AOT executables on disk.

Every process used to pay the full XLA compile bill from scratch —
``ServingEngine.warmup`` compiled one executable per (batch, seq)
bucket at every startup, the trainer's first dispatch ate a
multi-second compile before any training happened, and
``scripts/check.py`` re-lowered every canonical target on every run.
TPU serving/training stacks instead treat compiled executables as
cacheable artifacts keyed by program + topology (PAPERS: pjit/TPUv4
scaling; Gemma-on-TPU serving); ``jax.experimental.
serialize_executable`` makes that implementable without forking XLA.

``ExecutableCache`` is the store: content-addressed files under one
directory, shareable between concurrent processes (single-writer
atomic rename), size-capped with LRU eviction, and failure-soft —
corruption, version skew, or a missing entry always degrades to a
real compile, never a crash. See docs/SERVING.md "Warm starts".
"""

from perceiver_tpu.cache.exec_cache import (  # noqa: F401
    CacheStats,
    ExecutableCache,
    aot_compile,
    canonicalize_hlo,
    compile_lowered,
    default_cache,
    enable_native_cache,
    has_host_callbacks,
    source_tree_digest,
    topology_fingerprint,
)
