"""bench_decode runner: the TTFT + O(1) gate pair drive exit codes
(scripts/bench_decode.py, docs/BENCHMARKING.md round 17).

The bench is run IN-PROCESS at test-sized load so its result dict and
gate decisions are directly assertable — the clean run must exit 0
with the span-derived TTFT phase breakdown populated, and each gate
must trip (exit 1) when seeded with an absurd threshold. A bench
whose gates cannot fail is not a merge gate.
"""

import importlib.util
import os

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_decode_under_test",
        os.path.join(_ROOT, "scripts", "bench_decode.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# at 4 tiny streams a step is ~1 ms, so scheduler jitter swamps the
# production 1.15x O(1) ratio — the in-process runs relax it (the
# seeded-violation test still proves the gate can trip)
_FAST_ARGS = ["--streams", "4", "--max-new-min", "12",
              "--max-new-max", "16", "--prompt-len", "6",
              "--max-chunk", "4", "--seed", "3", "--gate-ratio", "4.0"]


@pytest.fixture(scope="module")
def clean_run(bench):
    """One real tiny bench run shared by the assertions below (the
    engine build + decode dominates the cost; run it once)."""
    return bench.run(_FAST_ARGS)


def test_bench_decode_clean_run_passes_gates(clean_run):
    code, result = clean_run
    assert code == 0, result["detail"]
    d = result["detail"]
    assert result["metric"] == "decode_tokens_per_sec"
    assert d["post_warmup_compiles"] == 0
    assert d["o1_ratio"] <= d["o1_gate"]
    assert d["ttft_ratio"] <= d["ttft_gate"]
    # geometry scaled to offered concurrency, chunk lanes in the key
    assert d["geometry"].startswith("r4_") and d["geometry"].endswith(
        "_q4")


def test_bench_decode_phase_breakdown_is_span_derived(clean_run):
    _, result = clean_run
    phases = result["detail"]["phase_breakdown_ms"]
    # every stream contributes a queue_wait and a first_decode span;
    # prompt 6 over chunk 4 takes 2 chunks, both counted in
    # prefill_chunks (the completing one is ALSO first_decode — the
    # overlap is deliberate, see _ttft_phases)
    for phase in ("queue_wait", "prefill_chunks", "first_decode"):
        assert phase in phases, phases
        assert phases[phase]["spans"] == 4
        assert phases[phase]["p95"] >= phases[phase]["p50"] >= 0.0
    # the o1 gate windows report how many samples admission churn
    # excluded (docs/BENCHMARKING.md "Gate-sample windowing")
    win = result["detail"]["o1_window"]
    assert win["admissions"] == 4
    assert win["excluded_early"] >= 0 and win["excluded_last"] >= 0


def test_bench_decode_single_chunk_prompts_report_prefill_phase(bench):
    """Regression for the r17 harvest bug: a prompt that prefills in
    ONE chunk (prompt_len <= max_chunk — the production default) must
    still report a prefill_chunks phase. The r17 harvester only
    counted chunks strictly before the completing one, so
    BENCH_r17.json's breakdown had no prefill_chunks at all."""
    code, result = bench.run(
        ["--streams", "3", "--max-new-min", "12", "--max-new-max",
         "14", "--prompt-len", "4", "--max-chunk", "4", "--seed", "5",
         "--gate-ratio", "4.0"])
    assert code == 0, result["detail"]
    phases = result["detail"]["phase_breakdown_ms"]
    for phase in ("queue_wait", "prefill_chunks", "first_decode"):
        assert phase in phases, phases
        assert phases[phase]["spans"] > 0
        assert phases[phase]["p50"] >= 0.0


@pytest.fixture(scope="module")
def shared_run(bench):
    """One shared-prefix two-arm run shared by the assertions below.

    Same jitter story as _FAST_ARGS: at test scale both arms' TTFTs
    are a few ms, so a p95 over 4 streams is the max of 4 noisy
    samples and one scheduler stall in the warm arm blows the
    production 0.5x gate (observed 0.32-0.82 across identical runs).
    8 streams doubles the sample count (observed 0.38-0.68) and the
    relaxed 0.8x gate still requires the warm arm to beat the cold
    arm outright — a cache that silently re-prefills shows ~1.0x —
    while hit_rate/hit_tokens below prove the sharing directly.
    BENCH_r18.json holds the production 0.5x gate at real scale
    (warm_cold_ratio 0.088)."""
    return bench.run(_FAST_ARGS + ["--streams", "8",
                                   "--shared-prefix",
                                   "--shared-prefix-len", "16",
                                   "--prefix-ttft-gate", "0.8"])


def test_bench_decode_shared_prefix_gates_pass(shared_run):
    code, result = shared_run
    assert code == 0, result["detail"]
    sp = result["detail"]["shared_prefix"]
    # every warm stream admits after the seed published, so the trace
    # is deterministic: all 8 warm streams hit the 16-token chain
    assert sp["hit_rate"] == 1.0
    assert sp["hit_tokens"] == 8 * 16
    assert sp["warm_cold_ratio"] <= sp["warm_cold_gate"]
    assert sp["pages_indexed"] > 0
    assert result["detail"]["post_warmup_compiles"] == 0


def test_bench_decode_seeded_prefix_ttft_violation_exits_nonzero(bench):
    """An impossible warm/cold gate must flip the exit code — the warm
    arm still pays >= 1 step of tail prefill, so a near-zero ratio
    cannot pass."""
    code, result = bench.run(
        _FAST_ARGS + ["--shared-prefix", "--shared-prefix-len", "16",
                      "--prefix-ttft-gate", "0.0001"])
    assert code == 1
    assert result["detail"]["shared_prefix"]["warm_cold_ratio"] > 0.0001


def test_bench_decode_seeded_ttft_violation_exits_nonzero(bench):
    """An impossible TTFT gate must flip the exit code — TTFT always
    spans >= 1 full step, so a sub-1x ratio cannot pass."""
    code, result = bench.run(_FAST_ARGS + ["--ttft-gate-ratio", "0.01"])
    assert code == 1
    assert result["detail"]["ttft_ratio"] > 0.01


def test_bench_decode_seeded_o1_violation_exits_nonzero(bench):
    """Same for the O(1) gate: a near-zero allowed growth ratio trips
    on any real run."""
    code, result = bench.run(_FAST_ARGS + ["--gate-ratio", "0.0001"])
    assert code == 1
    assert result["detail"]["o1_ratio"] > 0.0001


@pytest.fixture(scope="module")
def tenants_run(bench):
    """One mixed-tenant two-arm run shared by the assertions below.

    Same jitter story as the other in-process runs: at 4 tiny streams
    both arms' p95s are maxima over a handful of ~ms samples, so the
    production 2.0x isolation gate is relaxed to 4.0x (BENCH_r20.json
    holds the production gate at real scale); the per-tenant counters
    and typed sheds asserted below are deterministic either way."""
    return bench.run(_FAST_ARGS + ["--tenants",
                                   "--tenant-isolation-gate", "4.0"])


def test_bench_decode_tenants_gates_pass(tenants_run):
    code, result = tenants_run
    assert code == 0, result["detail"]
    d = result["detail"]
    assert result["metric"] == "decode_tenant_isolation_ratio"
    assert d["post_warmup_compiles"] == 0
    # zero dropped gold requests in either arm, and gold never shed
    assert d["solo"]["gold"]["dropped"] == 0
    assert d["mixed"]["gold"]["dropped"] == 0
    assert d["mixed"]["gold"]["shed"] == 0
    # the flood was real: bronze oversubscribed its quota and the
    # surplus shed typed, observable in the per-tenant counter
    bronze = d["mixed"]["bronze"]
    assert bronze["quota_shed"] >= 1
    assert bronze["submitted"] == 2 * d["streams"]
    assert bronze["completed"] + bronze["quota_shed"] \
        <= bronze["submitted"]
    # per-tenant emissions are populated for both tenants
    for tenant in ("gold", "bronze"):
        assert d["mixed"][tenant]["tokens_per_step"] >= 0
    assert d["mixed"]["gold"]["ttft_p95_ms"] > 0
    assert d["ttft_ratio"] <= d["isolation_gate"]
    assert d["gap_p95_ratio"] <= d["isolation_gate"]


def test_bench_decode_seeded_tenant_violation_exits_nonzero(bench):
    """An impossible isolation gate must flip the exit code — the
    mixed arm's gold TTFT is a real measurement > 0, so a near-zero
    allowed ratio cannot pass."""
    code, result = bench.run(
        _FAST_ARGS + ["--tenants", "--tenant-isolation-gate", "0.0001"])
    assert code == 1
    assert max(result["detail"]["ttft_ratio"],
               result["detail"]["gap_p95_ratio"]) > 0.0001
