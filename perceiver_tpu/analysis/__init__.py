"""Static analysis subsystem: lowered-graph passes + source lint.

Two halves, one currency (``report.Violation``):

* Graph passes (``passes``) lower each canonical train step
  (``targets``) to StableHLO and gate dtype policy, host transfers,
  buffer donation, and compile-cache closure — the properties TPU
  performance lives or dies on, checked where they are decided.
* Source lint (``lint``) walks the AST for the bug classes that
  should never reach a lowering in the first place.
* Racecheck (``racecheck``) gates the *host* side: lock-discipline
  over declared guarded attributes, the global lock-order graph, and
  callback-under-lock sites — with its runtime twin (the seeded
  interleaving harness) in ``perceiver_tpu.utils.concurrency``.

``scripts/check.py`` is the CLI; ``tests/test_graphcheck.py`` keeps
every pass honest against seeded violations. See docs/ANALYSIS.md.
"""

from perceiver_tpu.analysis.report import (  # noqa: F401
    DtypeAllow,
    RaceAllow,
    ReplicationAllow,
    Report,
    TransferAllow,
    Violation,
)
from perceiver_tpu.analysis.passes import (  # noqa: F401
    cache_key_stability,
    donation_check,
    dtype_policy,
    hbm_budget,
    load_hbm_budgets,
    recompile_budget,
    run_graph_checks,
    transfer_guard,
    write_hbm_budgets,
)
from perceiver_tpu.analysis.shardcheck import (  # noqa: F401
    collective_budget,
    collective_inventory,
    load_shard_budgets,
    per_shard_hbm_budget,
    replication_check,
    run_shard_passes,
    write_shard_budgets,
)
from perceiver_tpu.analysis.targets import (  # noqa: F401
    CANONICAL_TARGETS,
    DECODE_TARGETS,
    FAST_TARGETS,
    MeshSpec,
    PACKED_SERVING_TARGETS,
    SERVING_TARGETS,
    SHARDED_TARGETS,
    StepTarget,
    cost_bytes_accessed,
    lower_target,
    make_decode_step,
    make_packed_serve_step,
    make_serve_step,
    make_sharded_decode_step,
    make_sharded_serve_step,
    make_train_step,
)
from perceiver_tpu.analysis.lint import (  # noqa: F401
    default_lint_paths,
    lint_paths,
    lint_source,
)
from perceiver_tpu.analysis.racecheck import (  # noqa: F401
    check_callback_under_lock,
    check_guarded_attrs,
    check_lock_order_cycles,
    collect_lock_order_edges,
    default_race_paths,
    run_racecheck,
)
