"""Version compatibility shims for the parallel kernels.

``shard_map`` moved twice across the jax line this repo spans:
``jax.experimental.shard_map.shard_map`` (≤ 0.4.x, keyword
``check_rep``) → ``jax.shard_map`` (0.5+, keyword ``check_vma``).
The SPMD attention kernels call one spelling — this one — and the
shim resolves whichever the installed jax provides, translating the
replication-check keyword. Semantics are identical: ``check_vma``
(varying-mesh-axes checking) is the renamed successor of
``check_rep`` (replication checking).
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where available, else the
    ``jax.experimental.shard_map`` fallback with ``check_vma``
    translated to its old name ``check_rep``."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy

    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside ``shard_map``.
    ``jax.lax.axis_size`` where it exists; older jax constant-folds
    ``psum(1, axis)`` to the same Python int (the classic idiom)."""
    size = getattr(jax.lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return jax.lax.psum(1, axis_name)
