"""WordPiece tokenizer: normalize → pre-tokenize → encode/decode/train.

Drop-in replacement for the surface of the Rust HF ``tokenizers``
library the reference uses (``perceiver/tokenizer.py``,
``data/imdb.py:52-68``): ``encode``/``encode_batch`` with padding and
truncation, ``decode`` with WordPiece cleanup, ``token_to_id``,
``get_vocab_size``, ``save``/``from_file`` — and reads/writes the same
JSON file format, byte-compatible with the shipped
``.cache/imdb-tokenizer-10003.json`` (verified by parity tests).

Pipeline parity:

- normalizers: ``Replace(pattern, content)`` (IMDB passes
  ``Replace('<br />', ' ')``, ``data/imdb.py:101``), then NFD →
  Lowercase → StripAccents (``tokenizer.py:37``).
- pre-tokenizer: HF ``Whitespace`` — the regex ``\\w+|[^\\w\\s]+``.
- model: greedy longest-match WordPiece with ``##`` continuation
  prefix, ``max_input_chars_per_word=100``, ``[UNK]`` fallback.
- trainer: likelihood-scored pair merging (the algorithm behind HF's
  ``WordPieceTrainer``): score = freq(pair) / (freq(a) · freq(b)).

This module is the pure-Python engine; when the compiled C++ core
(``perceiver_tpu/tokenizer/csrc``) is available it transparently takes
over encode/train hot paths (see ``perceiver_tpu.tokenizer.native``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import unicodedata
from typing import Iterable, List, Optional, Sequence

from perceiver_tpu.tokenizer.vocab import (
    PAD_TOKEN,
    PAD_TOKEN_ID,
    UNK_TOKEN,
    SPECIAL_TOKENS,
)

_WHITESPACE_RE = re.compile(r"\w+|[^\w\s]+")

# env escape hatch: PERCEIVER_TPU_NO_NATIVE=1 pins the pure-Python engine
_USE_NATIVE = os.environ.get("PERCEIVER_TPU_NO_NATIVE") != "1"

# HF WordPiece decoder cleanup=true replacements, applied PER TOKEN
# (after the leading space is attached) — not on the joined string;
# the rule list mirrors tokenizers' decoders::wordpiece::cleanup.
_CLEANUP = [(" .", "."), (" ?", "?"), (" !", "!"), (" ,", ","),
            (" ' ", "'"), (" n't", "n't"), (" 'm", "'m"),
            (" do not", " don't"), (" 's", "'s"), (" 've", "'ve"),
            (" 're", "'re")]


def _cleanup_token(s: str) -> str:
    for a, b in _CLEANUP:
        s = s.replace(a, b)
    return s


# --- normalizers -------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Replace:
    pattern: str
    content: str

    def __call__(self, text: str) -> str:
        return text.replace(self.pattern, self.content)

    def to_json(self):
        return {"type": "Replace", "pattern": {"String": self.pattern},
                "content": self.content}


class NFD:
    def __call__(self, text: str) -> str:
        return unicodedata.normalize("NFD", text)

    def to_json(self):
        return {"type": "NFD"}


class Lowercase:
    def __call__(self, text: str) -> str:
        return text.lower()

    def to_json(self):
        return {"type": "Lowercase"}


class StripAccents:
    def __call__(self, text: str) -> str:
        return "".join(c for c in text if unicodedata.category(c) != "Mn")

    def to_json(self):
        return {"type": "StripAccents"}


def _normalizer_from_json(spec) -> object:
    t = spec["type"]
    if t == "Replace":
        return Replace(spec["pattern"]["String"], spec["content"])
    if t == "NFD":
        return NFD()
    if t == "Lowercase":
        return Lowercase()
    if t == "StripAccents":
        return StripAccents()
    raise ValueError(f"Unsupported normalizer: {t}")


# --- encoding result ---------------------------------------------------------


@dataclasses.dataclass
class Encoding:
    ids: List[int]
    tokens: List[str]

    @property
    def attention_mask(self) -> List[int]:
        return [0 if t == PAD_TOKEN else 1 for t in self.tokens]


# --- tokenizer ---------------------------------------------------------------


class WordPieceTokenizer:
    """Normalize → whitespace pre-tokenize → greedy WordPiece."""

    def __init__(self, vocab: Optional[dict] = None,
                 normalizers: Sequence[object] = (),
                 unk_token: str = UNK_TOKEN,
                 continuing_subword_prefix: str = "##",
                 max_input_chars_per_word: int = 100):
        self.vocab = dict(vocab or {})
        self.ids_to_tokens = {i: t for t, i in self.vocab.items()}
        self.normalizers = list(normalizers)
        self.unk_token = unk_token
        self.prefix = continuing_subword_prefix
        self.max_input_chars_per_word = max_input_chars_per_word
        self._padding = None  # (pad_id, pad_token) when enabled
        self._truncation = None  # max_length when enabled
        self._native = None  # lazily built C++ vocab handle
        self._native_failed = not _USE_NATIVE

    # -- vocabulary access (HF surface) --

    def get_vocab_size(self) -> int:
        return len(self.vocab)

    def token_to_id(self, token: str) -> Optional[int]:
        return self.vocab.get(token)

    def id_to_token(self, i: int) -> Optional[str]:
        return self.ids_to_tokens.get(i)

    # -- padding / truncation (HF surface, data/imdb.py:54-57) --

    def enable_padding(self, pad_id: int = PAD_TOKEN_ID,
                       pad_token: str = PAD_TOKEN):
        self._padding = (pad_id, pad_token)

    def no_padding(self):
        self._padding = None

    def enable_truncation(self, max_length: int):
        self._truncation = max_length

    def no_truncation(self):
        self._truncation = None

    # -- pipeline --

    def normalize(self, text: str) -> str:
        for n in self.normalizers:
            text = n(text)
        return text

    @staticmethod
    def pre_tokenize(text: str) -> List[str]:
        return _WHITESPACE_RE.findall(text)

    def _invalidate_native(self):
        self._native = None

    def _native_vocab(self):
        if self._native_failed:
            return None
        if self._native is None:
            try:
                from perceiver_tpu.tokenizer.native import NativeVocab
                self._native = NativeVocab(self)
            except Exception:
                self._native_failed = True
                return None
        return self._native

    def _encode_word(self, word: str) -> List[str]:
        if len(word) > self.max_input_chars_per_word:
            return [self.unk_token]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = self.prefix + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces

    def _added_token_re(self) -> Optional[re.Pattern]:
        specials = [t for t in SPECIAL_TOKENS if t in self.vocab]
        if not specials:
            return None
        return re.compile("|".join(re.escape(t) for t in specials))

    def encode(self, text: str) -> Encoding:
        # Added special tokens (non-normalized) are matched on the raw
        # input before the normalizer runs — HF added_tokens semantics;
        # this is what lets '[MASK]' in a raw string survive lowercasing
        # (the reference's predict_masked_samples path, utils.py:27).
        ids: List[int] = []
        pattern = self._added_token_re()
        segments = ([text] if pattern is None
                    else self._split_on_added(text, pattern))
        for seg in segments:
            if seg in self.vocab and pattern is not None \
                    and pattern.fullmatch(seg):
                ids.append(self.vocab[seg])
                continue
            words = self.pre_tokenize(self.normalize(seg))
            nv = self._native_vocab()
            if nv is not None:
                # words never contain whitespace (whitespace pre-
                # tokenization), so the '\n'-joined batch ABI is safe
                ids.extend(nv.encode_words(words))
            else:
                for word in words:
                    ids.extend(self.vocab[t]
                               for t in self._encode_word(word))
        if self._truncation is not None:
            ids = ids[:self._truncation]
        return Encoding(ids=ids,
                        tokens=[self.ids_to_tokens[i] for i in ids])

    @staticmethod
    def _split_on_added(text: str, pattern: re.Pattern) -> List[str]:
        out, last = [], 0
        for m in pattern.finditer(text):
            if m.start() > last:
                out.append(text[last:m.start()])
            out.append(m.group(0))
            last = m.end()
        if last < len(text):
            out.append(text[last:])
        return out

    def _ascii_raw_chain(self):
        """``(replaces, lowercase)`` when the normalizer chain can run
        byte-exactly in C++ for ASCII input: leading literal ASCII
        ``Replace``s followed only by NFD / Lowercase / StripAccents
        (identity / tolower on ASCII). None when the chain has custom
        normalizers — those documents take the Python path.
        """
        replaces = []
        tail = list(self.normalizers)
        while tail and isinstance(tail[0], Replace):
            r = tail.pop(0)
            # empty pattern: str.replace('', c) interleaves c between
            # every character — not reproduced natively, so fall back
            if not r.pattern or not (r.pattern.isascii()
                                     and r.content.isascii()):
                return None
            replaces.append((r.pattern, r.content))
        if not all(isinstance(n, (NFD, Lowercase, StripAccents))
                   for n in tail):
            return None
        return replaces, any(isinstance(n, Lowercase) for n in tail)

    def encode_batch_padded(self, texts: Sequence[str], max_len: int,
                            pad_id: int = PAD_TOKEN_ID):
        """Corpus-scale batch encode → ``(ids, lengths)`` where ``ids``
        is a padded ``(n, max_len)`` int32 matrix (truncated at
        ``max_len``, ``pad_id`` beyond each row's length).

        Semantics match ``encode`` exactly (added-token matching before
        normalization, then normalize → pre-tokenize → WordPiece), but
        the WordPiece matching for ALL documents runs in one GIL-free
        native call across C++ threads — and when the normalizer chain
        is the factory layout (literal Replaces then NFD/Lowercase/
        StripAccents) the WHOLE pipeline for ASCII documents runs in
        C++ (NFD and StripAccents are identities on ASCII), with only
        non-ASCII documents taking the Python normalizer. Falls back to
        the per-document Python path off-native.
        """
        import numpy as np

        # an enable_truncation limit below max_len caps every row the
        # same way encode() would — on BOTH the native and Python paths
        cap = (min(max_len, self._truncation)
               if self._truncation is not None else max_len)

        nv = self._native_vocab()
        if nv is None:
            # pure-Python engine: one encode() per text — the single
            # source of truth for per-document semantics
            ids = np.full((len(texts), max_len), pad_id, np.int32)
            lengths = np.zeros(len(texts), np.int32)
            for d, text in enumerate(texts):
                row = self.encode(text).ids[:cap]
                ids[d, :len(row)] = row
                lengths[d] = len(row)
            return ids, lengths

        chain = self._ascii_raw_chain()
        if chain is not None:
            replaces, lowercase = chain
            ascii_ok = [t.isascii() for t in texts]
            ids, lengths = nv.encode_docs_raw(
                [t if ok else "" for t, ok in zip(texts, ascii_ok)],
                replaces, lowercase,
                [t for t in SPECIAL_TOKENS if t in self.vocab],
                cap, pad_id)
            if cap < max_len:
                ids = np.pad(ids, ((0, 0), (0, max_len - cap)),
                             constant_values=pad_id)
            for d, ok in enumerate(ascii_ok):
                if ok:
                    continue
                row = self.encode(texts[d]).ids[:cap]
                ids[d, :] = pad_id
                ids[d, :len(row)] = row
                lengths[d] = len(row)
            return ids, lengths

        pattern = self._added_token_re()
        docs: List[List[str]] = []
        for text in texts:
            words: List[str] = []
            segments = ([text] if pattern is None
                        else self._split_on_added(text, pattern))
            for seg in segments:
                if seg in self.vocab and pattern is not None \
                        and pattern.fullmatch(seg):
                    # special tokens are vocab entries, so the native
                    # longest-match resolves them to their own id
                    words.append(seg)
                else:
                    words.extend(self.pre_tokenize(self.normalize(seg)))
            docs.append(words)

        ids, lengths = nv.encode_docs_padded(docs, cap, pad_id)
        if cap < max_len:
            ids = np.pad(ids, ((0, 0), (0, max_len - cap)),
                         constant_values=pad_id)
        return ids, lengths

    def encode_batch(self, texts: Sequence[str]) -> List[Encoding]:
        encs = [self.encode(t) for t in texts]
        if self._padding is not None and encs:
            pad_id, pad_token = self._padding
            width = max(len(e.ids) for e in encs)
            for e in encs:
                n = width - len(e.ids)
                e.ids.extend([pad_id] * n)
                e.tokens.extend([pad_token] * n)
        return encs

    def decode(self, ids: Iterable[int],
               skip_special_tokens: bool = True) -> str:
        tokens = []
        for i in ids:
            t = self.ids_to_tokens.get(int(i))
            if t is None:
                continue
            if skip_special_tokens and t in SPECIAL_TOKENS:
                continue
            tokens.append(t)
        out = []
        for j, t in enumerate(tokens):
            if t.startswith(self.prefix):
                s = t[len(self.prefix):]
            elif j > 0:
                s = " " + t
            else:
                s = t
            out.append(_cleanup_token(s))  # decoder cleanup=true, per token
        return "".join(out)

    # -- persistence (HF-compatible JSON) --

    def to_json(self) -> dict:
        return {
            "version": "1.0",
            "truncation": None,
            "padding": None,
            "added_tokens": [
                {"id": self.vocab[t], "special": True, "content": t,
                 "single_word": False, "lstrip": False, "rstrip": False,
                 "normalized": False}
                for t in SPECIAL_TOKENS if t in self.vocab],
            "normalizer": {
                "type": "Sequence",
                "normalizers": [n.to_json() for n in self.normalizers]},
            "pre_tokenizer": {"type": "Whitespace"},
            "post_processor": None,
            "decoder": {"type": "WordPiece", "prefix": self.prefix,
                        "cleanup": True},
            "model": {
                "type": "WordPiece",
                "unk_token": self.unk_token,
                "continuing_subword_prefix": self.prefix,
                "max_input_chars_per_word": self.max_input_chars_per_word,
                "vocab": self.vocab,
            },
        }

    def save(self, path: str):
        # atomic publish: concurrent readers (multi-host shared cache
        # dirs) must never see a truncated JSON
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f, ensure_ascii=False)
        os.replace(tmp, path)

    @classmethod
    def from_file(cls, path: str) -> "WordPieceTokenizer":
        with open(path, encoding="utf-8") as f:
            spec = json.load(f)
        norm = spec.get("normalizer") or {"type": "Sequence",
                                          "normalizers": []}
        if norm["type"] == "Sequence":
            normalizers = [_normalizer_from_json(n)
                           for n in norm["normalizers"]]
        else:
            normalizers = [_normalizer_from_json(norm)]
        model = spec["model"]
        if model["type"] != "WordPiece":
            raise ValueError(f"Unsupported model: {model['type']}")
        return cls(
            vocab=model["vocab"], normalizers=normalizers,
            unk_token=model.get("unk_token", UNK_TOKEN),
            continuing_subword_prefix=model.get("continuing_subword_prefix",
                                                "##"),
            max_input_chars_per_word=model.get("max_input_chars_per_word",
                                               100))

    # -- training --

    def train_from_iterator(self, data: Iterable[str], trainer:
                            "WordPieceTrainer"):
        trainer.train(self, data)


@dataclasses.dataclass
class WordPieceTrainer:
    """Count-scored merge training (HF WordPieceTrainer algorithm).

    HF's ``WordPieceTrainer`` wraps ``BpeTrainer`` with a ``##``
    continuation prefix: merges are selected by highest raw pair
    *count* (not the likelihood score of the original WordPiece
    paper), ties broken by the lowest (id_a, id_b) in vocab order.
    Vocab construction order also follows HF: special tokens, then the
    plain-character alphabet sorted by codepoint, then ``##``-prefixed
    continuation forms in word order, then merges.
    """

    vocab_size: int
    special_tokens: Sequence[str] = dataclasses.field(
        default_factory=lambda: list(SPECIAL_TOKENS))
    min_frequency: int = 0

    def train(self, tokenizer: WordPieceTokenizer, data: Iterable[str]):
        try:
            from perceiver_tpu.tokenizer.native import native_train
            vocab = native_train(tokenizer, data, self.vocab_size,
                                 list(self.special_tokens),
                                 self.min_frequency)
        except (ImportError, OSError):
            vocab = self._train_py(tokenizer, data)
        tokenizer.vocab = vocab
        tokenizer.ids_to_tokens = {i: t for t, i in vocab.items()}
        tokenizer._invalidate_native()

    def _train_py(self, tokenizer: WordPieceTokenizer,
                  data: Iterable[str]) -> dict:
        from collections import Counter
        from perceiver_tpu.tokenizer.native import count_words
        prefix = tokenizer.prefix

        word_counts: Counter = count_words(tokenizer, data)
        # sorted word order: deterministic, and identical to the input
        # order the native trainer receives (native.py sorts too)
        ordered = sorted(word_counts)

        vocab: dict = {}
        for t in self.special_tokens:
            vocab.setdefault(t, len(vocab))
        # HF vocab order: plain alphabet chars sorted by codepoint ...
        for c in sorted({c for w in ordered for c in w}):
            vocab.setdefault(c, len(vocab))
        # ... then ##-continuation forms in word order
        words = {}
        for w in ordered:
            syms = [w[0]] + [prefix + c for c in w[1:]]
            for s in syms:
                vocab.setdefault(s, len(vocab))
            words[w] = syms

        min_f = max(self.min_frequency, 1)
        while len(vocab) < self.vocab_size:
            pair_freq: Counter = Counter()
            for w, syms in words.items():
                c = word_counts[w]
                for a, b in zip(syms, syms[1:]):
                    pair_freq[(a, b)] += c
            best, best_f = None, 0
            for pair, f in pair_freq.items():
                if f < min_f:
                    continue
                if f > best_f or (
                        f == best_f
                        and (vocab[pair[0]], vocab[pair[1]])
                        < (vocab[best[0]], vocab[best[1]])):
                    best, best_f = pair, f
            if best is None:
                break
            a, b = best
            merged = a + (b[len(prefix):] if b.startswith(prefix) else b)
            vocab.setdefault(merged, len(vocab))
            for w, syms in words.items():
                j, out = 0, []
                while j < len(syms):
                    if (j + 1 < len(syms) and syms[j] == a
                            and syms[j + 1] == b):
                        out.append(merged)
                        j += 2
                    else:
                        out.append(syms[j])
                        j += 1
                words[w] = out
        return vocab


# --- factory functions (reference tokenizer.py:22-40) ------------------------


def create_tokenizer(*normalizers) -> WordPieceTokenizer:
    return WordPieceTokenizer(
        normalizers=list(normalizers) + [NFD(), Lowercase(), StripAccents()])


def load_tokenizer(path: str) -> WordPieceTokenizer:
    return WordPieceTokenizer.from_file(path)


def save_tokenizer(tokenizer: WordPieceTokenizer, path: str):
    tokenizer.save(path)


def train_tokenizer(tokenizer: WordPieceTokenizer, data: Iterable[str],
                    vocab_size: int):
    trainer = WordPieceTrainer(vocab_size=vocab_size,
                               special_tokens=SPECIAL_TOKENS)
    tokenizer.train_from_iterator(data, trainer)
