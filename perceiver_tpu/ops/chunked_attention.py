"""Memory-efficient blockwise attention (pure JAX, differentiable).

Computes exact softmax attention while streaming the key/value sequence
in fixed-size chunks under ``lax.scan``, carrying online-softmax
statistics ``(m, l, acc)`` — the Rabe & Staats / FlashAttention
recurrence. Peak memory is O(Lq · chunk) instead of O(Lq · Lk), which
is what makes the reference's large-input configs feasible on a TPU
chip: the 512×512 LArTPC segmentation model (``run.py:79``) cross-
attends 32 latent queries against M = 262,144 input tokens, where a
materialized (B, H, Lq, Lk) fp32 weight tensor would be ~128 MB per
(batch, head) pair.

Differentiable out of the box (the scan transposes cleanly), so it
also serves as the backward path for the Pallas flash kernel
(``perceiver_tpu.ops.pallas_attention``), keeping the backward pass
memory-bounded too.

Masking is expressed as an additive fp32 bias over keys (``(B, Lk)``,
0 where attended, ``NEG_INF`` where padded) — the same semantics the
einsum path applies via ``key_padding_mask`` (reference
``data/imdb.py:64``: True at padding).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def pad_mask_to_bias(key_padding_mask, dtype=jnp.float32):
    """(B, Lk) bool, True at padding → additive (B, Lk) bias."""
    return jnp.where(key_padding_mask, NEG_INF, 0.0).astype(dtype)


# --- bf16-cotangent dots ----------------------------------------------
# The online-softmax recurrence keeps its statistics (m, l, acc) in
# fp32, so under autodiff every cotangent reaching the two block dots
# is fp32 — XLA then upcasts the dots' bf16 operands and runs the
# ENTIRE backward at the MXU's fp32 rate. These custom-vjp wrappers
# keep the fp32-accumulated forward bitwise identical and cast the
# cotangent to bf16 before the grad contractions — the same trade the
# production flash-attention backward makes (and that ops/attention.py
# _qk_dot makes for the materialized path). Applied only when the
# operands are bf16; the fp32 policy path is untouched.


@jax.custom_vjp
def _qk_block_dot(q, k_blk):
    return jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                      preferred_element_type=jnp.float32)


def _qk_block_dot_fwd(q, k_blk):
    return _qk_block_dot(q, k_blk), (q, k_blk)


def _qk_block_dot_bwd(res, g):
    q, k_blk = res
    gb = g.astype(jnp.bfloat16)
    dq = jnp.einsum("bhqk,bhkd->bhqd", gb, k_blk)
    dk = jnp.einsum("bhqk,bhqd->bhkd", gb, q)
    return dq.astype(q.dtype), dk.astype(k_blk.dtype)


_qk_block_dot.defvjp(_qk_block_dot_fwd, _qk_block_dot_bwd)


@jax.custom_vjp
def _pv_block_dot(p, v_blk):
    return jnp.einsum("bhqk,bhkd->bhqd", p, v_blk,
                      preferred_element_type=jnp.float32)


def _pv_block_dot_fwd(p, v_blk):
    return _pv_block_dot(p, v_blk), (p, v_blk)


def _pv_block_dot_bwd(res, g):
    p, v_blk = res
    gb = g.astype(jnp.bfloat16)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gb, v_blk)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gb)
    return dp.astype(p.dtype), dv.astype(v_blk.dtype)


_pv_block_dot.defvjp(_pv_block_dot_fwd, _pv_block_dot_bwd)


def fold_block(q, k_blk, v_blk, bias_blk, scale, m, l, acc,
               dropout_rate: float = 0.0, dropout_key=None):
    """One online-softmax block fold — THE shared recurrence.

    Folds a key/value block into running statistics. Used by the kv
    scan here and by the ring/sequence-parallel paths
    (``perceiver_tpu.parallel.ring_attention``), so all blockwise
    implementations share one copy of the numerics (including the
    uniform-average convention for fully-masked rows — all-NEG_INF
    logits give p = 1, matching plain softmax's uniform weights).

    q: (B,H,Lq,D); k_blk, v_blk: (B,H,Lk,D); bias_blk: (B,Lk) or None;
    m, l: (B,H,Lq,1); acc: (B,H,Lq,D) — fp32 accumulators.

    Attention-weight dropout (torch semantics: applied to the
    normalized softmax weights) streams exactly: dropping weight w_k
    after softmax equals dropping the exp value in the OUTPUT
    accumulator while the denominator ``l`` keeps every exp value —
    out = (1/l)·Σ_k mask_k/(1−rate)·exp_k·v_k. So ``acc`` folds the
    dropped exp block and ``l`` the undropped one.
    """
    bf16_ops = (q.dtype == jnp.bfloat16 and k_blk.dtype == jnp.bfloat16)
    if bf16_ops:
        s = _qk_block_dot(q, k_blk) * scale
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
    if bias_blk is not None:
        s = s + bias_blk[:, None, None, :].astype(jnp.float32)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    if dropout_key is not None and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate,
                                    p.shape)
        p_acc = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    else:
        p_acc = p
    if v_blk.dtype == jnp.bfloat16:
        pv = _pv_block_dot(p_acc.astype(v_blk.dtype), v_blk)
    else:
        pv = jnp.einsum("bhqk,bhkd->bhqd", p_acc.astype(v_blk.dtype),
                        v_blk, preferred_element_type=jnp.float32)
    acc_new = acc * alpha + pv
    return m_new, l_new, acc_new


def finalize_softmax(l, acc, dtype):
    """acc / l with a 0/0 guard (see fully-masked-row note above)."""
    return (acc / jnp.maximum(l, 1e-30)).astype(dtype)


def chunked_attention(q, k, v, *, bias: Optional[jax.Array] = None,
                      scale: Optional[float] = None,
                      chunk_size: int = 1024,
                      q_chunk_size: Optional[int] = None,
                      dropout_rate: float = 0.0, rng=None):
    """Exact attention with kv streamed in chunks.

    q: (B, H, Lq, D); k, v: (B, H, Lk, D).
    bias: optional (B, Lk) additive key bias (fp32, NEG_INF at pad).
    q_chunk_size: additionally block the query axis (lax.map over query
    slices) — needed when Lq is huge (the 262k-query decoder), where
    even one (B, H, Lq, chunk) logit block would blow HBM.
    dropout_rate/rng: attention-weight dropout (torch placement, after
    softmax — see ``fold_block`` for why it streams exactly); each kv
    chunk's mask comes from ``fold_in(rng, chunk_index)``, each query
    chunk from a further fold, so no (Lq, Lk) mask materializes.
    Returns (B, H, Lq, D) in q's dtype.

    The kv scan body is rematerialized (``jax.checkpoint``), so the
    backward pass recomputes each chunk's softmax block instead of
    saving all of them — keeping grad memory O(Lq · chunk) as well.

    Fully-masked rows (every key padded) return the uniform average of
    v — the same garbage-by-construction the plain-softmax path
    produces (all logits collapse to NEG_INF, so softmax is uniform);
    the ``maximum(l, ...)`` guard only protects against exact 0/0.
    """
    b, h, lq, d = q.shape
    if q_chunk_size is not None and lq > q_chunk_size:
        qc = q_chunk_size
        q_pad = (-lq) % qc
        qp = jnp.pad(q, ((0, 0), (0, 0), (0, q_pad), (0, 0)))
        nq = (lq + q_pad) // qc
        qs = qp.reshape(b, h, nq, qc, d).transpose(2, 0, 1, 3, 4)

        def one_q_chunk(args):
            qi, idx = args
            r = (jax.random.fold_in(rng, idx)
                 if rng is not None and dropout_rate > 0.0 else None)
            return chunked_attention(qi, k, v, bias=bias, scale=scale,
                                     chunk_size=chunk_size,
                                     dropout_rate=dropout_rate, rng=r)

        out = jax.lax.map(one_q_chunk, (qs, jnp.arange(nq)))
        out = out.transpose(1, 2, 0, 3, 4).reshape(b, h, nq * qc, d)
        return out[:, :, :lq]
    lk = k.shape[2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    chunk = min(chunk_size, lk)
    pad = (-lk) % chunk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        bias = jnp.pad(bias if bias is not None
                       else jnp.zeros((b, lk), jnp.float32),
                       ((0, 0), (0, pad)), constant_values=NEG_INF)
    n_chunks = (lk + pad) // chunk

    # chunk-major stacking for scan: (n, B, H, chunk, D)
    kc = k.reshape(b, h, n_chunks, chunk, d).transpose(2, 0, 1, 3, 4)
    vc = v.reshape(b, h, n_chunks, chunk, d).transpose(2, 0, 1, 3, 4)
    if bias is not None:
        bc = bias.reshape(b, n_chunks, chunk).transpose(1, 0, 2)
        xs = (kc, vc, bc, jnp.arange(n_chunks))
    else:
        xs = (kc, vc, jnp.arange(n_chunks))

    m0 = jnp.full((b, h, lq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
    dropping = rng is not None and dropout_rate > 0.0

    def body(carry, x):
        if bias is not None:
            k_i, v_i, b_i, ci = x
        else:
            (k_i, v_i, ci), b_i = x, None
        dk = jax.random.fold_in(rng, ci) if dropping else None
        return fold_block(q, k_i, v_i, b_i, scale, *carry,
                          dropout_rate=dropout_rate, dropout_key=dk), None

    (m, l, acc), _ = jax.lax.scan(jax.checkpoint(body), (m0, l0, acc0), xs)
    return finalize_softmax(l, acc, q.dtype)
