"""Paged decode attention kernel vs. pure-jax reference.

The kernel runs in Pallas interpreter mode on the CPU test backend —
the identical kernel body that compiles on TPU (ops/paged_attention.py,
docs/SERVING.md "Autoregressive decode"). Properties pinned here:

- the kernel matches masked-softmax attention over each stream's own
  page walk, for full and partial last pages;
- **placement invariance**: the same logical stream scattered across
  scrambled physical pages is BITWISE identical to the contiguous
  placement — the property that makes host-side page recycling safe;
- zero-length streams return exactly zero (not NaN);
- table entries beyond a stream's used pages are ignored (clamped,
  predicated off), so the allocator never has to sanitize tails;
- bf16 inputs survive both kernel and reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.ops.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_reference,
)


def _dense_reference(q, k, v, length):
    """Straight masked attention over one stream's dense (T, H, D)."""
    qf = q.astype(np.float32)                      # (H, Nq, D)
    kf = k[:length].astype(np.float32)             # (t, H, D)
    vf = v[:length].astype(np.float32)
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = np.einsum("hnd,thd->hnt", qf, kf) * scale
    w = np.exp(logits - logits.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    return np.einsum("hnt,thd->hnd", w, vf)


def _make_case(rng, *, r=4, h=2, nq=8, d=16, num_pages=32, page_size=8,
               pps=4, lengths=(0, 3, 8, 29), dtype=np.float32):
    """Build a pool with each stream's tokens on randomly chosen
    pages, plus the dense per-stream views the oracle uses."""
    q = rng.standard_normal((r, h, nq, d)).astype(dtype)
    k_pages = rng.standard_normal(
        (num_pages, page_size, h, d)).astype(dtype)
    v_pages = rng.standard_normal(
        (num_pages, page_size, h, d)).astype(dtype)
    perm = rng.permutation(np.arange(1, num_pages))
    tables = np.zeros((r, pps), np.int32)
    taken = 0
    for i in range(r):
        tables[i] = perm[taken:taken + pps]
        taken += pps
    lengths = np.asarray(lengths, np.int32)
    dense_k = np.stack([
        k_pages[tables[i]].reshape(pps * page_size, h, d)
        for i in range(r)])
    dense_v = np.stack([
        v_pages[tables[i]].reshape(pps * page_size, h, d)
        for i in range(r)])
    return q, k_pages, v_pages, tables, lengths, dense_k, dense_v


def test_kernel_matches_dense_oracle_fp32():
    rng = np.random.default_rng(0)
    q, kp, vp, tables, lengths, dk, dv = _make_case(rng)
    out = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths)))
    for i, t in enumerate(lengths):
        if t == 0:
            np.testing.assert_array_equal(out[i], 0.0)
        else:
            np.testing.assert_allclose(
                out[i], _dense_reference(q[i], dk[i], dv[i], int(t)),
                rtol=2e-5, atol=2e-5)


def test_reference_matches_dense_oracle():
    rng = np.random.default_rng(1)
    q, kp, vp, tables, lengths, dk, dv = _make_case(rng)
    out = np.asarray(paged_decode_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths)))
    for i, t in enumerate(lengths):
        if t == 0:
            np.testing.assert_array_equal(out[i], 0.0)
        else:
            np.testing.assert_allclose(
                out[i], _dense_reference(q[i], dk[i], dv[i], int(t)),
                rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_kernel_matches_reference(dtype):
    rng = np.random.default_rng(2)
    q, kp, vp, tables, lengths, _, _ = _make_case(
        rng, lengths=(5, 1, 32, 17),
        dtype=np.float32)
    args = [jnp.asarray(a).astype(dtype) for a in (q, kp, vp)]
    got = paged_decode_attention(
        *args, jnp.asarray(tables), jnp.asarray(lengths))
    want = paged_decode_attention_reference(
        *args, jnp.asarray(tables), jnp.asarray(lengths))
    assert got.dtype == want.dtype
    tol = 2e-5 if dtype == np.float32 else 2e-2
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=tol, atol=tol)


def test_placement_invariance_bitwise():
    """Contiguous vs scrambled physical pages: bitwise identical.

    This is the contract host-side page recycling stands on — a
    stream's numerics depend only on its LOGICAL token order, never on
    which physical pages the allocator happened to hand out."""
    rng = np.random.default_rng(3)
    r, h, nq, d = 3, 2, 8, 16
    num_pages, page_size, pps = 64, 8, 5
    lengths = np.asarray([37, 12, 40], np.int32)
    q = rng.standard_normal((r, h, nq, d)).astype(np.float32)
    tokens_k = rng.standard_normal(
        (r, pps * page_size, h, d)).astype(np.float32)
    tokens_v = rng.standard_normal(
        (r, pps * page_size, h, d)).astype(np.float32)

    def place(order):
        kp = np.asarray(
            rng.standard_normal((num_pages, page_size, h, d)),
            np.float32)  # junk in unused pages must not matter
        vp = np.asarray(
            rng.standard_normal((num_pages, page_size, h, d)),
            np.float32)
        tables = np.zeros((r, pps), np.int32)
        for i in range(r):
            pages = order[i * pps:(i + 1) * pps]
            tables[i] = pages
            for j, p in enumerate(pages):
                kp[p] = tokens_k[i, j * page_size:(j + 1) * page_size]
                vp[p] = tokens_v[i, j * page_size:(j + 1) * page_size]
        return jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables)

    contiguous = np.arange(1, 1 + r * pps)
    scrambled = np.random.default_rng(7).permutation(
        np.arange(1, num_pages))[:r * pps]
    outs = []
    for order in (contiguous, scrambled):
        kp, vp, tables = place(order)
        outs.append(np.asarray(paged_decode_attention(
            jnp.asarray(q), kp, vp, tables, jnp.asarray(lengths))))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_table_tail_entries_ignored():
    """Entries past ceil(length / page_size) may be arbitrary garbage
    (even out of range — they are clamped)."""
    rng = np.random.default_rng(4)
    q, kp, vp, tables, lengths, _, _ = _make_case(
        rng, lengths=(9, 3, 16, 1))
    junk = np.array(tables)
    for i, t in enumerate(lengths):
        used = max(1, -(-int(t) // 8))
        junk[i, used:] = 10_000 + i  # out of range on purpose
    a = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths))
    b = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(junk), jnp.asarray(lengths))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_survives_jit():
    rng = np.random.default_rng(5)
    q, kp, vp, tables, lengths, _, _ = _make_case(rng)
    f = jax.jit(paged_decode_attention)
    got = np.asarray(f(jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                       jnp.asarray(tables), jnp.asarray(lengths)))
    want = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
