"""Content-addressed on-disk cache of serialized XLA executables.

Entry anatomy (see docs/SERVING.md "Warm starts" for the operator
view):

- key: sha256 over (jax version, jaxlib version, backend platform +
  device kind + device/process counts, donation layout, hash of the
  lowered StableHLO text). The StableHLO text is the program identity
  — shapes, dtypes, shardings, and donation aliasing are all printed
  there (``analysis/hlo.py`` gates on the same text), so two lowerings
  that could need different executables can never share a key.
- ``<key>.exe``: pickle of ``(payload, in_tree, out_tree)`` from
  ``jax.experimental.serialize_executable.serialize``.
- ``<key>.json``: sidecar with the cost-analysis flops / bytes
  accessed of the lowering (so warm paths skip re-analysis), versions
  (defense in depth against a doctored key), and a label.
- ``<key>.low.json``: a *lowering* record — StableHLO text + derived
  properties for the analysis gates, keyed by target name + source
  digest instead of the text itself (the text is what it caches).

Failure policy: every read path degrades to a miss — a truncated
blob, version skew, json rot, or a concurrently-evicted file all
return ``None`` and count ``stats.corrupt``/``stats.misses``; the
caller then performs the real compile it would have done anyway.
Nothing in here is allowed to raise on a cache problem.

Concurrency: writers serialize to a temp file in the cache directory
and ``os.replace`` it into place — readers see either the whole entry
or no entry, and the last concurrent writer of one key wins with both
executables being equivalent by construction. Eviction tolerates
losing races with other processes' evictions.

Trust: entries are pickles, so the cache directory is code — share it
only within the trust domain that already shares checkpoints.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import re
import tempfile
import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

_ENV_VAR = "PERCEIVER_EXEC_CACHE"
_DEFAULT_MAX_BYTES = 4 << 30  # 4 GiB — hundreds of serving buckets

# Host-callback custom calls (jax.debug.print / io_callback /
# pure_callback) bake the address of a per-lowering C++ wrapper into
# the module — as an i64 constant operand and as backend_config text.
_CALLBACK_PTR = re.compile(
    r'custom_call @\S*callback\S*\([^\n]*?backend_config = "(\d+)"')
_CALLBACK_CALL = re.compile(r"custom_call @\S*callback")


def canonicalize_hlo(text: str) -> str:
    """Key material from StableHLO text: host-callback wrapper
    addresses are fresh every lowering (same process or not), so two
    lowerings of the SAME program differ only in those digits — mask
    exactly them. Only the pointer values harvested from callback
    custom calls are replaced, never arbitrary numbers."""
    for ptr in {m.group(1) for m in _CALLBACK_PTR.finditer(text)}:
        text = text.replace(ptr, "<host-callback-ptr>")
    return text


def has_host_callbacks(text: str) -> bool:
    """A module with host callbacks must NEVER be served from the
    executable cache: the compiled artifact embeds a host function
    pointer that is garbage in any other process (jax's serializer
    refuses such executables too — this guard just makes the policy
    explicit and skips the doomed serialize)."""
    return _CALLBACK_CALL.search(text) is not None


def topology_fingerprint(backend: Optional[str] = None) -> str:
    """Stable identity of the device world an executable targets:
    platform, device kind, device count, process count. Deliberately
    independent of ``JAX_PLATFORMS`` spelling — two processes that
    resolve to the same backend share keys however they selected it."""
    import jax

    devices = jax.devices(backend)
    kinds = ",".join(sorted({d.device_kind for d in devices}))
    return (f"{devices[0].platform}:{kinds}:d{len(devices)}"
            f":p{jax.process_count()}")


def _versions() -> Tuple[str, str]:
    import jax
    import jaxlib

    return jax.__version__, jaxlib.__version__


_SOURCE_DIGEST: Dict[str, str] = {}


def source_tree_digest(root: Optional[str] = None) -> str:
    """Content hash of every ``.py`` file in the package. Lowering
    records are only valid for the exact code that produced them — a
    one-line model edit must invalidate them, and mtimes lie across
    checkouts, so this hashes contents (a few ms, memoized)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.abspath(root)
    cached = _SOURCE_DIGEST.get(root)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as f:
                h.update(hashlib.sha256(f.read()).digest())
    digest = h.hexdigest()[:16]
    _SOURCE_DIGEST[root] = digest
    return digest


def enable_native_cache(path: str) -> bool:
    """Point jax's own persistent compilation cache
    (``jax_compilation_cache_dir``) at ``path`` — covers the compiles
    we don't AOT through this cache (lazy jit fallbacks, helper fns).
    Best-effort: unsupported backends/versions simply return False."""
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass  # flag name drifts across jax versions
        return True
    except Exception:
        return False


@dataclasses.dataclass
class CacheStats:
    """Process-local counters (the serving metrics mirror these)."""

    hits: int = 0
    misses: int = 0
    corrupt: int = 0
    evicted: int = 0
    stores: int = 0
    bytes_read: int = 0
    bytes_written: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


class ExecutableCache:
    """One cache directory of serialized executables + lowering
    records, shareable between concurrent processes."""

    # lock discipline (gated by check.py --race): the stats struct's
    # fields are bumped from whichever thread compiles/loads (dotted
    # keys — the struct itself is assigned once in __init__ and never
    # rebound). On-disk state needs no lock here: every write is an
    # atomic tmp+rename, which is the cross-PROCESS discipline.
    _GUARDED = {
        "stats.hits": "_lock",
        "stats.misses": "_lock",
        "stats.corrupt": "_lock",
        "stats.evicted": "_lock",
        "stats.stores": "_lock",
        "stats.bytes_read": "_lock",
        "stats.bytes_written": "_lock",
    }

    def __init__(self, path: str, *,
                 max_bytes: int = _DEFAULT_MAX_BYTES,
                 native: bool = True):
        self.path = os.path.abspath(os.path.expanduser(str(path)))
        self.max_bytes = int(max_bytes)
        self.stats = CacheStats()
        self._lock = threading.Lock()
        os.makedirs(self.path, exist_ok=True)
        if native:
            enable_native_cache(os.path.join(self.path, "xla"))

    # -- keys -------------------------------------------------------------

    def executable_key(self, lowered_text: str, *,
                       donate_argnums: Sequence[int] = (),
                       backend: Optional[str] = None,
                       extra: Sequence[Any] = ()) -> str:
        jax_v, jaxlib_v = _versions()
        material = json.dumps({
            "kind": "exe",
            "jax": jax_v,
            "jaxlib": jaxlib_v,
            "topology": topology_fingerprint(backend),
            "donate": sorted(int(i) for i in donate_argnums),
            "hlo": hashlib.sha256(
                canonicalize_hlo(lowered_text).encode()).hexdigest(),
            "extra": [str(x) for x in extra],
        }, sort_keys=True)
        return hashlib.sha256(material.encode()).hexdigest()

    def lowering_key(self, name: str, *,
                     backend: Optional[str] = None,
                     extra: Sequence[Any] = ()) -> str:
        """Key for a lowering record: unlike executables the text IS
        the payload, so the key binds the program identity through the
        source tree digest instead."""
        jax_v, jaxlib_v = _versions()
        material = json.dumps({
            "kind": "low",
            "name": name,
            "jax": jax_v,
            "jaxlib": jaxlib_v,
            "topology": topology_fingerprint(backend),
            "source": source_tree_digest(),
            "extra": [str(x) for x in extra],
        }, sort_keys=True)
        return hashlib.sha256(material.encode()).hexdigest()

    # -- paths ------------------------------------------------------------

    def _exe_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.exe")

    def _sidecar_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.json")

    def _lowering_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.low.json")

    # -- atomic write -----------------------------------------------------

    def _write_atomic(self, path: str, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.path, prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _drop(self, key: str) -> None:
        for path in (self._exe_path(key), self._sidecar_path(key),
                     self._lowering_path(key)):
            try:
                os.unlink(path)
            except OSError:
                pass

    def _touch(self, *paths: str) -> None:
        # mtime is the LRU clock — a hit must refresh it or steady
        # traffic evicts its own hottest entries
        for path in paths:
            try:
                os.utime(path)
            except OSError:
                pass

    # -- executables ------------------------------------------------------

    def sidecar(self, key: str) -> Optional[dict]:
        try:
            with open(self._sidecar_path(key)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def load_executable(self, key: str):
        """Deserialize the cached executable for ``key``, or None
        (miss). Never raises on a cache problem; counts stats."""
        jax_v, jaxlib_v = _versions()
        side = self.sidecar(key)
        if side is None or not os.path.exists(self._exe_path(key)):
            with self._lock:
                self.stats.misses += 1
            return None
        if side.get("jax") != jax_v or side.get("jaxlib") != jaxlib_v:
            # keys already embed versions, so this only trips on a
            # doctored/collided entry — treat as stale, rebuild
            with self._lock:
                self.stats.misses += 1
            self._drop(key)
            return None
        try:
            with open(self._exe_path(key), "rb") as f:
                blob = f.read()
            payload, in_tree, out_tree = pickle.loads(blob)
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            compiled = deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            # truncated/corrupt blob, or an executable this
            # backend/jaxlib cannot load — fall back to a fresh compile
            with self._lock:
                self.stats.corrupt += 1
                self.stats.misses += 1
            self._drop(key)
            return None
        with self._lock:
            self.stats.hits += 1
            self.stats.bytes_read += len(blob)
        self._touch(self._exe_path(key), self._sidecar_path(key))
        return compiled

    def store_executable(self, key: str, compiled, *,
                         sidecar: Optional[dict] = None) -> bool:
        """Serialize + write ``compiled`` under ``key``. Returns False
        (without raising) when the executable does not support
        serialization or the write fails."""
        jax_v, jaxlib_v = _versions()
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception:
            return False
        meta = {
            "jax": jax_v,
            "jaxlib": jaxlib_v,
            "topology": topology_fingerprint(),
            "created": time.time(),
            "payload_bytes": len(blob),
            **(sidecar or {}),
        }
        try:
            self._write_atomic(self._exe_path(key), blob)
            self._write_atomic(
                self._sidecar_path(key),
                json.dumps(meta, sort_keys=True).encode() + b"\n")
        except OSError:
            self._drop(key)
            return False
        with self._lock:
            self.stats.stores += 1
            self.stats.bytes_written += len(blob)
        self._evict()
        return True

    # -- lowering records -------------------------------------------------

    def load_lowering(self, key: str) -> Optional[dict]:
        try:
            with open(self._lowering_path(key)) as f:
                record = json.load(f)
        except (OSError, ValueError):
            with self._lock:
                self.stats.misses += 1
            return None
        if not isinstance(record, dict) or "text" not in record:
            with self._lock:
                self.stats.corrupt += 1
                self.stats.misses += 1
            self._drop(key)
            return None
        with self._lock:
            self.stats.hits += 1
        self._touch(self._lowering_path(key))
        return record

    def store_lowering(self, key: str, record: dict) -> bool:
        try:
            data = json.dumps(record, sort_keys=True).encode() + b"\n"
            self._write_atomic(self._lowering_path(key), data)
        except (OSError, TypeError, ValueError):
            return False
        with self._lock:
            self.stats.stores += 1
            self.stats.bytes_written += len(data)
        self._evict()
        return True

    # -- eviction ---------------------------------------------------------

    def entry_bytes(self) -> int:
        return sum(size for _, _, size in self._entries())

    def _entries(self):
        """[(mtime, key-group paths, bytes)] for every complete-ish
        entry, oldest first. Grouped so an .exe and its sidecar live
        and die together."""
        groups: Dict[str, list] = {}
        try:
            names = os.listdir(self.path)
        except OSError:
            return []
        for name in names:
            if name.startswith(".tmp-") or name == "xla":
                continue
            key = name.split(".", 1)[0]
            groups.setdefault(key, []).append(
                os.path.join(self.path, name))
        out = []
        for key, paths in groups.items():
            mtime, size = 0.0, 0
            for p in paths:
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                mtime = max(mtime, st.st_mtime)
                size += st.st_size
            out.append((mtime, paths, size))
        return sorted(out)

    def _evict(self) -> None:
        """Drop least-recently-used entries until under ``max_bytes``.
        Races with concurrent processes are benign: a lost unlink is
        someone else's eviction."""
        entries = self._entries()
        total = sum(size for _, _, size in entries)
        for _, paths, size in entries:
            if total <= self.max_bytes:
                break
            for p in paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            total -= size
            with self._lock:
                self.stats.evicted += 1


# -- the blessed compile sites ------------------------------------------------
# The ``uncached-compile`` lint rule flags raw ``.lower().compile()``
# everywhere outside this package: every AOT compile in the tree is
# supposed to flow through here so it can populate the cache.


def compile_lowered(lowered, *, cache: Optional[ExecutableCache] = None,
                    key: Optional[str] = None,
                    sidecar: Optional[dict] = None):
    """Compile a ``jax.stages.Lowered`` and (best-effort) store the
    result. The raw compile lives here so callers stay cache-honest."""
    compiled = lowered.compile()
    if cache is not None and key:
        cache.store_executable(key, compiled, sidecar=sidecar)
    return compiled


def _cost_summary(stage) -> Dict[str, Optional[float]]:
    """flops / bytes accessed from a Lowered or Compiled cost
    analysis, best-effort (None where the backend exposes none)."""
    try:
        cost = stage.cost_analysis()
    except Exception:
        return {"flops": None, "bytes_accessed": None}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not cost:
        return {"flops": None, "bytes_accessed": None}
    flops = float(cost.get("flops", 0.0)) or None
    accessed = cost.get("bytes accessed")
    return {"flops": flops,
            "bytes_accessed": float(accessed) if accessed is not None
            else None}


# Lowering is serialized process-wide: two lowerings tracing
# CONCURRENTLY can suffix shared private helpers nondeterministically
# (``@_where`` in one module, ``@_where_1`` in the other for the same
# program — observed with two engines warming over one cache dir),
# which forks the text hash and stores duplicate entries. Serial
# lowerings are byte-deterministic, so one lock restores key
# stability; compiles still run in parallel.
_LOWER_LOCK = threading.Lock()


def aot_compile(jitted, args, *, cache: Optional[ExecutableCache] = None,
                donate_argnums: Sequence[int] = (),
                label: str = "", extra_key: Sequence[Any] = (),
                kwargs: Optional[dict] = None):
    """Lower ``jitted`` at ``args`` and return ``(compiled, info)``,
    deserializing from ``cache`` instead of compiling when the key
    hits. ``info``: ``{"hit": bool, "key": str|None, "bytes": int,
    "sidecar": dict|None}`` (``bytes`` = blob read on hit / written on
    miss, 0 without a cache)."""
    with _LOWER_LOCK:
        lowered = jitted.lower(*args, **(kwargs or {}))
        text = None if cache is None else lowered.as_text()
    if cache is None or has_host_callbacks(text):
        # callback-bearing executables embed host pointers — always
        # compile them fresh, never store or load
        return (compile_lowered(lowered),
                {"hit": False, "key": None, "bytes": 0, "sidecar": None})
    key = cache.executable_key(text, donate_argnums=donate_argnums,
                               extra=extra_key)
    compiled = cache.load_executable(key)
    if compiled is not None:
        side = cache.sidecar(key)
        return (compiled,
                {"hit": True, "key": key,
                 "bytes": int((side or {}).get("payload_bytes", 0)),
                 "sidecar": side})
    sidecar = {"label": label, **_cost_summary(lowered)}
    before = cache.stats.bytes_written
    compiled = compile_lowered(lowered, cache=cache, key=key,
                               sidecar=sidecar)
    return (compiled,
            {"hit": False, "key": key,
             "bytes": cache.stats.bytes_written - before,
             "sidecar": sidecar})


# -- process-default cache ----------------------------------------------------

_DEFAULT_CACHES: Dict[str, ExecutableCache] = {}
_DEFAULT_LOCK = threading.Lock()


def default_cache(path: Optional[str] = None
                  ) -> Optional[ExecutableCache]:
    """The process-wide cache: ``path`` if given, else the
    ``PERCEIVER_EXEC_CACHE`` env var, else None (caching off). One
    ``ExecutableCache`` per directory per process, so the engine, the
    trainer, and the predict compat path share stats."""
    path = path or os.environ.get(_ENV_VAR)
    if not path:
        return None
    key = os.path.abspath(os.path.expanduser(path))
    with _DEFAULT_LOCK:
        cache = _DEFAULT_CACHES.get(key)
        if cache is None:
            cache = ExecutableCache(key)
            _DEFAULT_CACHES[key] = cache
        return cache
