"""TPU-platform detection.

JAX platform names are not stable across deployments: real chips
report ``tpu``, while plugin backends surface their own name (this
container's tunnel plugin reports ``axon``). Rather than sprinkling
hard-coded quirk lists through the codebase (VERDICT r1 weak #5), the
alias set lives here once and is extensible without a code change via
``PERCEIVER_TPU_PLATFORM_ALIASES`` (comma-separated platform names to
treat as TPU-class, default ``axon``).
"""

from __future__ import annotations

import os


def tpu_platform_names() -> tuple:
    aliases = os.environ.get("PERCEIVER_TPU_PLATFORM_ALIASES", "axon")
    return ("tpu",) + tuple(
        a.strip() for a in aliases.split(",") if a.strip())


def is_tpu_platform(name: str) -> bool:
    return name in tpu_platform_names()
