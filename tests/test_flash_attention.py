"""Chunked and Pallas flash attention vs. the reference einsum path.

The Pallas kernel runs in interpreter mode on the CPU test backend —
the identical kernel body that compiles on TPU (SURVEY.md §4 plan (c)).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.ops import mha_init, mha_apply
from perceiver_tpu.ops.chunked_attention import (
    chunked_attention,
    pad_mask_to_bias,
)
from perceiver_tpu.ops.pallas_attention import flash_attention
from perceiver_tpu.ops.policy import Policy


def _reference_attention(q, k, v, bias=None, scale=None):
    """Materialized-softmax attention on (B, H, L, D) arrays."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        s = s + bias[:, None, None, :]
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)


def _qkv(key, b=2, h=2, lq=16, lk=100, d=24):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, h, lq, d)),
            jax.random.normal(kk, (b, h, lk, d)),
            jax.random.normal(kv, (b, h, lk, d)))


class TestChunked:
    def test_matches_reference(self):
        q, k, v = _qkv(jax.random.key(0))
        out = chunked_attention(q, k, v, chunk_size=32)
        np.testing.assert_allclose(out, _reference_attention(q, k, v),
                                   atol=1e-5, rtol=1e-5)

    def test_with_padding_mask(self):
        q, k, v = _qkv(jax.random.key(1))
        pad = jnp.arange(100)[None, :] >= jnp.array([70, 100])[:, None]
        bias = pad_mask_to_bias(pad)
        out = chunked_attention(q, k, v, bias=bias, chunk_size=17)
        ref = _reference_attention(q, k, v, bias=bias)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_gradients_match(self):
        q, k, v = _qkv(jax.random.key(2), lk=64)

        def loss_chunked(q, k, v):
            return chunked_attention(q, k, v, chunk_size=16).sum()

        def loss_ref(q, k, v):
            return _reference_attention(q, k, v).sum()

        g1 = jax.grad(loss_chunked, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


class TestFlash:
    def test_matches_reference(self):
        q, k, v = _qkv(jax.random.key(3))
        out = flash_attention(q, k, v, block_q=8, block_k=64)
        np.testing.assert_allclose(out, _reference_attention(q, k, v),
                                   atol=1e-5, rtol=1e-5)

    def test_with_padding_mask(self):
        q, k, v = _qkv(jax.random.key(4))
        pad = jnp.arange(100)[None, :] >= jnp.array([70, 100])[:, None]
        bias = pad_mask_to_bias(pad)
        out = flash_attention(q, k, v, bias=bias, block_q=8, block_k=32)
        ref = _reference_attention(q, k, v, bias=bias)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_non_divisible_shapes(self):
        # Lq, Lk, D all off the tile grid → wrapper pads and slices.
        q, k, v = _qkv(jax.random.key(5), lq=13, lk=77, d=20)
        out = flash_attention(q, k, v, block_q=8, block_k=32)
        np.testing.assert_allclose(out, _reference_attention(q, k, v),
                                   atol=1e-5, rtol=1e-5)

    def test_gradients_match(self):
        q, k, v = _qkv(jax.random.key(6), lk=48)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, block_q=8, block_k=16).sum()

        def loss_ref(q, k, v):
            return _reference_attention(q, k, v).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)

    def test_bias_gradient_matches(self):
        """A differentiable (learned) additive key bias must get the
        same gradient as the materialized-softmax path — the VJP must
        not silently zero it."""
        q, k, v = _qkv(jax.random.key(8), lk=48)
        bias0 = jnp.zeros((q.shape[0], k.shape[2]), jnp.float32)

        def loss_flash(b):
            return (flash_attention(q, k, v, bias=b, block_q=8,
                                    block_k=16) ** 2).sum()

        def loss_ref(b):
            return (_reference_attention(q, k, v, bias=b) ** 2).sum()

        g1 = jax.grad(loss_flash)(bias0)
        g2 = jax.grad(loss_ref)(bias0)
        assert float(jnp.abs(g1).max()) > 0
        np.testing.assert_allclose(g1, g2, atol=1e-4, rtol=1e-4)

    def test_under_jit(self):
        q, k, v = _qkv(jax.random.key(7))
        out = jax.jit(lambda *a: flash_attention(*a, block_q=8,
                                                 block_k=64))(q, k, v)
        np.testing.assert_allclose(out, _reference_attention(q, k, v),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("d", [16, 64])
    def test_both_layouts_match_reference(self, d):
        """d=16 exercises the transposed (skinny-head) kernel, d=64 the
        standard D-in-lanes kernel; both must match, incl. with a pad
        mask and through the VJP."""
        q, k, v = _qkv(jax.random.key(9), lq=32, lk=96, d=d)
        pad = jnp.arange(96)[None, :] >= jnp.array([80, 96])[:, None]
        bias = pad_mask_to_bias(pad)
        out = flash_attention(q, k, v, bias=bias, block_q=16, block_k=32)
        ref = _reference_attention(q, k, v, bias=bias)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

        def loss(q, k, v):
            return (flash_attention(q, k, v, bias=bias, block_q=16,
                                    block_k=32) ** 2).sum()

        def loss_ref(q, k, v):
            return (_reference_attention(q, k, v, bias=bias) ** 2).sum()

        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("layout,d", [("standard", 16),
                                          ("transposed", 64),
                                          ("transposed", 192)])
    def test_forced_layout_matches_reference(self, monkeypatch, layout, d):
        """PERCEIVER_TPU_FLASH_LAYOUT pins the block layout regardless
        of head dim (the on-chip A/B knob) — numerics must hold in the
        non-default pairing too, incl. transposed at D > 128."""
        monkeypatch.setenv("PERCEIVER_TPU_FLASH_LAYOUT", layout)
        q, k, v = _qkv(jax.random.key(13), lq=32, lk=64, d=d)
        out = flash_attention(q, k, v, block_q=16, block_k=32)
        np.testing.assert_allclose(out, _reference_attention(q, k, v),
                                   atol=1e-5, rtol=1e-5)

    def test_skinny_layout_bf16(self):
        """bf16 through the transposed kernel (16-sublane tiles)."""
        q, k, v = (x.astype(jnp.bfloat16) for x in
                   _qkv(jax.random.key(10), lq=32, lk=64, d=16))
        out = flash_attention(q, k, v, block_q=16, block_k=32)
        ref = _reference_attention(q.astype(jnp.float32),
                                   k.astype(jnp.float32),
                                   v.astype(jnp.float32))
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref,
                                   atol=2e-2, rtol=2e-2)


class TestMhaImpls:
    """All three impls agree through the full projected MHA op."""

    @pytest.mark.parametrize("impl", ["chunked", "flash"])
    def test_impl_matches_einsum(self, impl):
        key = jax.random.key(8)
        params = mha_init(key, q_dim=32, num_heads=4, k_dim=48, v_dim=48)
        policy = Policy.fp32()
        q = jax.random.normal(jax.random.key(9), (2, 10, 32))
        kv = jax.random.normal(jax.random.key(10), (2, 50, 48))
        pad = jnp.arange(50)[None, :] >= jnp.array([35, 50])[:, None]
        ref = mha_apply(params, q, kv, kv, num_heads=4,
                        key_padding_mask=pad, policy=policy)
        out = mha_apply(params, q, kv, kv, num_heads=4,
                        key_padding_mask=pad, policy=policy,
                        impl=impl, kv_chunk_size=16)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_attn_mask_rejected(self):
        params = mha_init(jax.random.key(0), q_dim=16, num_heads=2)
        x = jnp.zeros((1, 4, 16))
        mask = jnp.zeros((4, 4), bool)
        with pytest.raises(NotImplementedError):
            mha_apply(params, x, x, x, num_heads=2, attn_mask=mask,
                      impl="chunked")

    def test_dropout_degrades_to_chunked(self):
        """dropout>0 on the flash impl degrades to the chunked path
        (which streams attention-weight dropout exactly) with a
        one-time warning, instead of raising (VERDICT r5 item 7)."""
        import perceiver_tpu.ops.attention as attn_mod

        params = mha_init(jax.random.key(0), q_dim=16, num_heads=2)
        x = jax.random.normal(jax.random.key(2), (1, 8, 16))
        rng = jax.random.key(1)
        attn_mod._DROPOUT_DEGRADE_WARNED.clear()
        with pytest.warns(UserWarning, match="falling back"):
            out = mha_apply(params, x, x, x, num_heads=2,
                            dropout_rate=0.1, deterministic=False,
                            rng=rng, impl="flash", kv_chunk_size=4)
        ref = mha_apply(params, x, x, x, num_heads=2, dropout_rate=0.1,
                        deterministic=False, rng=rng, impl="chunked",
                        kv_chunk_size=4)
        np.testing.assert_allclose(out, ref, atol=1e-6, rtol=1e-6)
        # the warning fires once per impl per process
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            mha_apply(params, x, x, x, num_heads=2, dropout_rate=0.1,
                      deterministic=False, rng=rng, impl="flash",
                      kv_chunk_size=4)
        # deterministic (eval) calls keep the flash kernel — no dropout
        # is applied, so nothing to degrade for
        mha_apply(params, x, x, x, num_heads=2, dropout_rate=0.1,
                  deterministic=True, impl="flash", kv_chunk_size=4)

    def test_dropout_plus_flash_warns_at_config_time(self):
        """--model.dropout>0 with a non-dropout-capable impl constructs
        fine (the impl degrades to chunked at trace time) but warns
        when the task config is built, so the degrade is visible before
        the first trace."""
        import perceiver_tpu.ops.attention as attn_mod

        from perceiver_tpu.tasks.image import ImageClassifierTask
        attn_mod._DROPOUT_DEGRADE_WARNED.clear()
        with pytest.warns(UserWarning, match="falling back"):
            ImageClassifierTask(image_shape=(28, 28, 1), num_classes=10,
                                dropout=0.1, attention_impl="flash")
        # dropout-capable impls construct silently
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            ImageClassifierTask(image_shape=(28, 28, 1), num_classes=10,
                                dropout=0.1, attention_impl="chunked")


class TestDropoutTracesUnderEveryImpl:
    """A dropout>0 config must trace a train step under EVERY
    attention impl (VERDICT r5 item 7): the non-dropout-capable
    kernels degrade to chunked instead of raising mid-trace."""

    def _tiny_task(self, impl, decoder_impl=None):
        from perceiver_tpu.tasks import MaskedLanguageModelTask

        return MaskedLanguageModelTask(
            vocab_size=96, max_seq_len=32, num_latents=8,
            num_latent_channels=16, num_encoder_layers=1,
            num_encoder_self_attention_layers_per_block=1,
            num_encoder_cross_attention_heads=2,
            num_encoder_self_attention_heads=2,
            num_decoder_cross_attention_heads=2, dropout=0.1,
            attention_impl=impl, decoder_attention_impl=decoder_impl,
            kv_chunk_size=16, loss_impl="dense")

    @pytest.mark.parametrize("impl", [None, "einsum", "chunked",
                                      "flash", "seqpar", "ring",
                                      "ulysses"])
    def test_train_step_traces(self, impl):
        import perceiver_tpu.ops.attention as attn_mod

        from perceiver_tpu.ops.policy import Policy

        attn_mod._DROPOUT_DEGRADE_WARNED.clear()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)
            task = self._tiny_task(impl, decoder_impl="flash")
            if impl in ("seqpar", "ring", "ulysses"):
                from perceiver_tpu.parallel import make_mesh
                model = task.build(mesh=make_mesh(
                    8, seq_parallel=2, model_parallel=1))
            else:
                model = task.build()
        params = model.init(jax.random.key(0))
        rng = np.random.default_rng(0)
        batch = {
            "input_ids": jnp.asarray(
                rng.integers(3, 96, (2, 32)), jnp.int32),
            "pad_mask": jnp.zeros((2, 32), bool),
        }

        def step(p):
            def loss_fn(p):
                loss, _ = task.loss_and_metrics(
                    model, p, batch, rng=jax.random.key(3),
                    deterministic=False, policy=Policy.fp32())
                return loss

            return jax.value_and_grad(loss_fn)(p)

        # trace + lower (no compile/run: the degrade fires at trace
        # time, which is where the old NotImplementedError lived)
        jax.jit(step).lower(params)


class TestChunkedDropout:
    """Streamed attention dropout in the chunked impl: exact vs. the
    materialized construction with the identical per-chunk masks."""

    def _masked_reference(self, q, k, v, rng, rate, chunk):
        """softmax → apply the SAME per-chunk bernoulli masks →  @ v."""
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
        w = jax.nn.softmax(s, axis=-1)
        lk = k.shape[2]
        keeps = []
        for ci in range(lk // chunk):
            dk = jax.random.fold_in(rng, ci)
            keeps.append(jax.random.bernoulli(
                dk, 1.0 - rate, (*w.shape[:3], chunk)))
        keep = jnp.concatenate(keeps, axis=-1)
        w = jnp.where(keep, w / (1.0 - rate), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)

    def test_dropout_matches_materialized_masking(self):
        q, k, v = _qkv(jax.random.key(5), lk=96)
        rng = jax.random.key(42)
        out = chunked_attention(q, k, v, chunk_size=32,
                                dropout_rate=0.3, rng=rng)
        ref = self._masked_reference(q, k, v, rng, 0.3, 32)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_dropout_mean_preserved(self):
        """E[dropped attention] == undropped attention (1/(1-p) scaling),
        checked loosely over many independent masks."""
        q, k, v = _qkv(jax.random.key(6), b=1, h=1, lq=4, lk=32, d=8)
        base = chunked_attention(q, k, v, chunk_size=16)
        one = jax.jit(lambda r: chunked_attention(
            q, k, v, chunk_size=16, dropout_rate=0.2, rng=r))
        outs = jax.vmap(one)(jax.random.split(jax.random.key(0), 200))
        np.testing.assert_allclose(jnp.mean(outs, axis=0), base, atol=0.08)

    def test_mha_chunked_dropout_accepted_and_differs(self):
        params = mha_init(jax.random.key(0), q_dim=16, num_heads=2)
        x = jax.random.normal(jax.random.key(1), (2, 8, 16))
        out_det = mha_apply(params, x, x, x, num_heads=2, impl="chunked")
        out_drop = mha_apply(params, x, x, x, num_heads=2, impl="chunked",
                             dropout_rate=0.5, deterministic=False,
                             rng=jax.random.key(2))
        assert out_drop.shape == out_det.shape
        assert not np.allclose(out_drop, out_det)

    def test_dropout_gradients_flow(self):
        q, k, v = _qkv(jax.random.key(7), lk=32)

        def loss(q, k, v):
            return chunked_attention(q, k, v, chunk_size=16,
                                     dropout_rate=0.2,
                                     rng=jax.random.key(3)).sum()

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g in grads:
            assert jnp.all(jnp.isfinite(g))
            assert jnp.any(g != 0)


class TestQueryChunking:
    def test_q_chunked_matches_reference(self):
        q, k, v = _qkv(jax.random.key(11), lq=37, lk=64)
        out = chunked_attention(q, k, v, chunk_size=16, q_chunk_size=8)
        np.testing.assert_allclose(out, _reference_attention(q, k, v),
                                   atol=1e-5, rtol=1e-5)

    def test_q_chunked_gradients(self):
        q, k, v = _qkv(jax.random.key(12), lq=24, lk=32)

        def loss_a(q, k, v):
            return chunked_attention(q, k, v, chunk_size=8,
                                     q_chunk_size=8).sum()

        def loss_b(q, k, v):
            return _reference_attention(q, k, v).sum()

        g1 = jax.grad(loss_a, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_b, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)
