"""Step-FLOPs estimation and MFU computation.

The reference never measures throughput or efficiency (SURVEY §6); the
rebuild's north-star metric is MFU (BASELINE.md: ≥40% on v5e-8 for MLM
pretraining), so the trainer and bench report it directly.

FLOPs come from XLA's own HLO cost analysis of the lowered step
(``Lowered.cost_analysis()`` — tracing+lowering only, no extra compile,
and matmul FLOPs are invariant under XLA's later optimization passes).
Peak chip FLOP/s is resolved from the device kind; unknown hardware
(e.g. the CPU test backend) yields ``None`` and callers skip the MFU
scalar rather than report garbage.
"""

from __future__ import annotations

from typing import Optional

import jax

# bf16 (MXU) peak FLOP/s per chip, by device-kind substring.
# Sources: public TPU spec sheets (cloud.google.com/tpu/docs/system-
# architecture-tpu-vm); fp32 runs at roughly 1/2 the bf16 rate on the
# MXU generations below.
_PEAK_BF16 = {
    "v6": 918e12,   # Trillium
    "v5p": 459e12,
    "v5e": 197e12,  # v5 lite (v5litepod)
    "v5lite": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops(device: Optional[jax.Device] = None,
                      precision: str = "bf16") -> Optional[float]:
    """Peak FLOP/s for one chip, or None when unknown (CPU/GPU)."""
    from perceiver_tpu.utils.platform import is_tpu_platform
    device = device or jax.devices()[0]
    if not is_tpu_platform(device.platform):
        return None
    kind = device.device_kind.lower().replace(" ", "").replace("-", "")
    for tag, peak in _PEAK_BF16.items():
        if tag in kind:
            return peak if precision == "bf16" else peak / 2
    return None


def _flops_of(cost) -> Optional[float]:
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not cost:
        return None
    flops = float(cost.get("flops", 0.0))
    return flops if flops > 0 else None


def lowered_step_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """Total FLOPs of one call of ``jitted_fn`` at these arg shapes,
    from lowering alone (no compile). Returns None on backends that
    only expose post-compile analysis (e.g. the axon TPU plugin)."""
    try:
        return _flops_of(jitted_fn.lower(*args, **kwargs).cost_analysis())
    except Exception:
        return None


def step_flops_and_fn(jitted_fn, *args, num_devices: int = 1,
                      on_lowered=None, cache=None,
                      cache_label: str = "train_step", **kwargs):
    """Returns ``(global_flops, fn)`` where ``fn`` is what the caller
    should invoke from now on.

    Prefers lowering-only cost analysis (keeps the original jit fn);
    the lowered HLO is the pre-partitioning module, so its count is
    already global. Where that is unavailable, AOT-compiles — the same
    compile the first jit call would have done, so no double
    compilation — and takes the analysis from the compiled module.
    That module is the SPMD-*partitioned* per-device program, so its
    count is scaled by ``num_devices`` (the devices the computation
    spans) to stay global. AOT executables require argument shapes and
    shardings to stay fixed, which the static-shape input pipeline
    guarantees.

    ``cache`` (a ``perceiver_tpu.cache.ExecutableCache``) switches the
    step to the persistent-compile-cache AOT path: a key hit
    deserializes the stored executable — the first dispatch performs
    ZERO XLA compiles — with flops read from the entry's sidecar; a
    miss compiles once (the compile the first jit call would have done
    anyway) and stores executable + sidecar for the next process.

    ``on_lowered``, when given, receives the ``Lowered`` object
    best-effort (the bench's graphcheck provenance hook — dtype audit
    from the very lowering being timed, without a second trace)."""
    from perceiver_tpu.cache import compile_lowered, has_host_callbacks

    try:
        lowered = jitted_fn.lower(*args, **kwargs)
    except Exception:
        return None, jitted_fn
    if on_lowered is not None:
        try:
            on_lowered(lowered)
        except Exception:
            pass  # provenance must never fail the measurement
    if cache is not None:
        try:
            text = lowered.as_text()
            # callback-bearing steps (e.g. the packed-CE overflow
            # warning on CPU) embed host pointers — never cacheable
            key = None if has_host_callbacks(text) \
                else cache.executable_key(text)
        except Exception:
            key = None
        if key is not None:
            exe = cache.load_executable(key)
            if exe is not None:
                flops = (cache.sidecar(key) or {}).get("flops")
                if flops is None:
                    try:
                        flops = _flops_of(lowered.cost_analysis())
                    except Exception:
                        flops = None
                return flops, exe
            try:
                flops = _flops_of(lowered.cost_analysis())
            except Exception:
                flops = None
            try:
                compiled = compile_lowered(lowered)
            except Exception:
                return flops, jitted_fn
            if flops is None:
                try:
                    flops = _flops_of(compiled.cost_analysis())
                    if flops is not None:
                        flops *= max(num_devices, 1)
                except Exception:
                    flops = None
            # sidecar carries the already-global flops so warm starts
            # skip cost analysis entirely
            cache.store_executable(key, compiled,
                                   sidecar={"label": cache_label,
                                            "flops": flops})
            return flops, compiled
    try:
        flops = _flops_of(lowered.cost_analysis())
    except Exception:
        flops = None
    if flops is not None:
        return flops, jitted_fn
    try:
        compiled = compile_lowered(lowered)
        flops = _flops_of(compiled.cost_analysis())
        if flops is not None:
            flops *= max(num_devices, 1)
        return flops, compiled
    except Exception:
        return None, jitted_fn


def mfu(flops_per_step: Optional[float], steps: int, seconds: float,
        num_devices: int = 1,
        peak_flops_per_device: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization in [0, 1] over a measured interval."""
    if not flops_per_step or not peak_flops_per_device or seconds <= 0 \
            or steps <= 0:
        return None
    achieved = flops_per_step * steps / seconds
    return achieved / (peak_flops_per_device * num_devices)
