#!/usr/bin/env python
"""Benchmark: IMDB-MLM training throughput on one TPU chip.

Measures the BASELINE.md primary metric — tokens/sec/chip for MLM
pretraining at seq_len=512 with the reference model config (64×64
latents, 3 encoder layers, 6 self-attn layers/block, vocab 10003) —
on full jitted train steps (forward + backward + AdamW update) in
bf16, with the packed fused-CE loss path and several optimizer steps
per dispatch (lax.scan). Prints JSON result lines to stdout, one per
completed config, later lines superseding earlier — the final line is
the one the driver should record.

Config comes from BENCH_BATCH / BENCH_INNER_STEPS / BENCH_LOSS_IMPL
when set (pinned exactly — sweeps rely on that); otherwise a ladder of
configs is climbed smallest-first, each completed rung flushed
immediately, so an OOM, compile failure, or kill at any point leaves
every number collected so far instead of none.

``BENCH_TASK=img_clf`` switches to the secondary BASELINE.md metric:
MNIST imgs/sec/chip with the ``scripts/img_clf.py`` model config
(32×128 latents, 3 layers, 3 self-attn layers/block, 32 bands).

``vs_baseline`` is null: the reference publishes no throughput numbers
(BASELINE.json "published": {}).

For a real-TPU target the bench runs under a SUPERVISOR (``BENCH_WAIT``
seconds of probe-retry budget, default 1350; ``BENCH_PROBE_INTERVAL``
between probes, default 120): the axon tunnel's availability windows
are short and rare, so instead of failing on the first dead probe the
supervisor keeps execution-probing in a subprocess and launches the
actual bench the moment a probe matmul completes. ``BENCH_WAIT=0``
(or ``BENCH_PLATFORM=cpu``) runs the ladder directly.

Driver contract (VERDICT r3 weak #1 — the bench must be un-failable):
the driver hard-kills ``python bench.py`` at ~1800 s and parses stdout
for a JSON result line, so

  * ``BENCH_WAIT`` defaults INSIDE that budget (1350 s), leaving room
    for a started-late attempt and the final status line;
  * the supervisor flushes a structured status JSON line (same
    metric/value/unit/vs_baseline schema, ``"measured": false``,
    ``value`` 0.0 as an explicit sentinel) after every failed probe —
    a tail-only or last-line parse always finds a parseable object no
    matter when the kill lands;
  * an unpinned ladder runs SMALLEST config first and flushes each
    config's result the moment it completes, so a mid-ladder death
    still records the numbers collected so far (later lines supersede
    earlier ones; the supervisor re-emits the best-throughput result
    as the final line).
"""

import json
import os
import subprocess
import sys
import threading
import time
from functools import partial

import numpy as np

from perceiver_tpu.utils.timing import fence

# Persistent XLA compilation cache, shared across processes: in a
# short tunnel window every probe/child/watcher step pays cold
# compiles (the batch-512 rung took ~650 s on the v5e compiler) — with
# the cache, only the FIRST process in a window compiles each config.
# Must be set before jax initializes; harmless for CPU smoke runs.
# Script runs only: when this module is imported as a library (the
# supervisor tests exec it in-process) the setdefault would leak into
# the host process's os.environ and from there into every child it
# spawns — subtly changing their XLA compilation behaviour.
if __name__ == "__main__":
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))  # same dir the watcher exports

# Rung dicts, most → least aggressive. The top rung IS the round-5
# on-chip winner (logs/perf_matrix_r05.jsonl: pallas streaming CE +
# chunked encoder/decoder attention + remat at B512/inner16 →
# 3.29M tokens/s/chip) so `python bench.py` with no env vars measures
# the winning config — the driver never sets knobs (VERDICT r5 item 2).
# The C=128 rung exists because C=64 is bandwidth-capped at ~0.12 MFU
# by physics (docs/BENCHMARKING.md): the ≥40% MFU north star can only
# be measured at C≥128 (graph ceiling 91.9%, VERDICT r5 item 1).
# Packed/einsum rungs stay as the A/B comparison + degrade ladder.
_LADDER = [
    dict(batch=512, inner=16, loss="pallas", attn="chunked",
         dec="chunked", remat=True),
    dict(batch=512, inner=16, loss="pallas", attn="chunked",
         dec="chunked", remat=True, channels=128),
    dict(batch=512, inner=8, loss="packed"),
    dict(batch=256, inner=8, loss="packed"),
    dict(batch=128, inner=4, loss="packed"),
    dict(batch=64, inner=1, loss="packed"),
    dict(batch=64, inner=1, loss="dense"),
]

# Default probe-retry budget, seconds. MUST stay inside the driver's
# observed ~1800 s hard-kill window (BENCH_r03.json: rc=124, capture
# stops at +1770 s) with room for a final status line.
_DEFAULT_WAIT = "1350"

# What the sentinel status line reports when no measurement exists yet
# (keyed by BENCH_TASK; must match the metric the runner would emit).
_TASK_METRIC = {
    "": ("imdb_mlm_tokens_per_sec_per_chip", "tokens/s"),
    "img_clf": ("mnist_imgs_per_sec_per_chip", "imgs/s"),
    "seg": ("lartpc_seg_pixels_per_sec_per_chip", "pixels/s"),
}


def _log(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)
    _WATCHDOG.kick()


_T0 = time.monotonic()


class _Watchdog:
    """Hard-exit if no progress for BENCH_WATCHDOG seconds (0 disables).

    A half-dead tunnel (backend initializes, first dispatch never
    completes — observed 2026-07-31) blocks the main thread inside
    ``block_until_ready``, where Python signal handlers cannot run; a
    daemon thread + ``os._exit`` is the only reliable escape. Progress
    is "a _log line was printed": init, compile, warmup, and every
    timed dispatch all log, so any healthy phase keeps the clock fresh.
    """

    def __init__(self):
        self.timeout = float(os.environ.get("BENCH_WATCHDOG", "600"))
        self._last = time.monotonic()
        self._allow = self.timeout
        if self.timeout > 0:
            threading.Thread(target=self._run, daemon=True).start()

    def kick(self):
        self._last = time.monotonic()
        self._allow = self.timeout

    def allow(self, seconds: float):
        """Grant the CURRENT phase a larger no-progress budget (a cold
        XLA compile of the big configs can legitimately exceed the
        dispatch-phase timeout with no intermediate log lines)."""
        self._last = time.monotonic()
        self._allow = max(self.timeout, seconds)

    def _run(self):
        while True:
            time.sleep(5)
            if self.timeout <= 0:
                continue  # disabled after start (supervisor mode)
            idle = time.monotonic() - self._last
            if idle > self._allow:
                print(f"[bench] WATCHDOG: no progress for {idle:.0f}s "
                      f"(> {self._allow:.0f}s) — device or tunnel "
                      f"presumed dead, exiting", file=sys.stderr,
                      flush=True)
                os._exit(3)


_WATCHDOG = _Watchdog()


def probe_backend() -> None:
    """Initialize the backend once, before the ladder.

    Backend bring-up is the single most failure-prone step (a down
    axon tunnel hangs for many minutes before raising UNAVAILABLE);
    doing it here means a dead backend fails the bench once, fast and
    with a clear message, instead of once per ladder config.
    """
    import jax
    # The container's sitecustomize pins the platform via jax.config
    # (env JAX_PLATFORMS alone is ignored after that) — BENCH_PLATFORM
    # is the working override, e.g. BENCH_PLATFORM=cpu for smoke runs.
    want = os.environ.get("BENCH_PLATFORM")
    if want:
        jax.config.update("jax_platforms", want)
    _log("initializing backend ...")
    devs = jax.devices()
    _log(f"backend up: {devs}")


def _bench_train(task, stacked_batch: dict, *, batch_size: int,
                 inner_steps: int, units_per_step: int, metric: str,
                 unit: str, detail: dict) -> dict:
    """Shared measurement core: jit inner_steps optimizer steps into one
    dispatch (lax.scan), AOT-compile, warm up, time, report."""
    import jax
    import optax

    from perceiver_tpu.ops.policy import Policy
    from perceiver_tpu.utils.flops import (
        device_peak_flops,
        mfu,
        step_flops_and_fn,
    )

    model = task.build()
    policy = Policy.bf16()

    params = model.init(jax.random.key(0))
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_steps(params, opt_state, stacked, rng):
        """inner_steps optimizer steps in one dispatch (lax.scan)."""

        def one(carry, xs):
            params, opt_state = carry
            batch_i, key_i = xs

            def loss_fn(p):
                loss, _ = task.loss_and_metrics(
                    model, p, batch_i, rng=key_i,
                    deterministic=False, policy=policy)
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        keys = jax.random.split(rng, inner_steps)
        (params, opt_state), losses = jax.lax.scan(
            one, (params, opt_state), (stacked, keys))
        return params, opt_state, losses[-1]

    key = jax.random.key(1)
    # HLO cost analysis counts a while/scan body ONCE, not trip-count
    # times, so the dispatch's reported FLOPs already approximate one
    # optimizer step — use as-is (verified on the CPU backend: the
    # number is invariant in inner_steps).
    _log("tracing + compiling train_steps ...")
    _WATCHDOG.allow(3 * _WATCHDOG.timeout)  # cold compiles are slow

    # graphcheck provenance (ISSUE 1): the dtype audit of the very
    # lowering being timed, so every result row carries machine-
    # readable proof of what the matmuls ran in. BENCH_GRAPHCHECK=0
    # skips it (saves the as_text walk on slow hosts).
    graphcheck = {}

    def _audit_lowered(lowered):
        if os.environ.get("BENCH_GRAPHCHECK", "1") == "0":
            return
        try:
            # cost-analysis bytes of the very lowering being timed —
            # the same number the hbm_budget merge gate pins
            # (perceiver_tpu/analysis/hbm_budgets.json), riding the
            # result so every row carries its traffic provenance
            from perceiver_tpu.analysis.targets import (
                cost_bytes_accessed,
            )
            graphcheck["hbm_bytes"] = cost_bytes_accessed(lowered)
            from perceiver_tpu.analysis import hlo
            s = hlo.dot_flop_summary(list(hlo.iter_dots(
                lowered.as_text())))
            graphcheck.update(
                bf16_flop_fraction=s["bf16_flop_fraction"],
                flop_weighted_k_ceiling=s["flop_weighted_k_ceiling"],
                n_dot_general=s["n_dot_general"])
        except Exception as e:  # noqa: BLE001 — provenance only
            graphcheck["error"] = f"{type(e).__name__}: {e}"[:160]

    step_flops, train_steps = step_flops_and_fn(
        train_steps, params, opt_state, stacked_batch, key,
        on_lowered=_audit_lowered)
    _log("compiled; warming up ...")
    # warmup (compile already done when step_flops_and_fn AOT-compiled)
    t_warm = time.perf_counter()
    params, opt_state, loss = train_steps(params, opt_state, stacked_batch,
                                          key)
    # host-fetch fence, NOT block_until_ready: the axon tunnel acks
    # block_until_ready before the chip finishes (utils/timing.py), so
    # without a real fence the warmup's work would bleed into and
    # corrupt the timed window below
    fence(loss)
    _log(f"warm ({time.perf_counter() - t_warm:.2f}s); timing ...")

    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    try:
        n_dispatch = int(os.environ.get("BENCH_DISPATCHES", "0")) \
            or max(64 // inner_steps, 8)
        n_steps = n_dispatch * inner_steps
        # all dispatch keys up front: an eager jax.random.fold_in
        # inside the timed loop costs host tracing + a tunnel dispatch
        # (~200 ms each in the b256 profile trace) that has nothing to
        # do with step throughput. Iterating the split performs the
        # eager slices HERE, before the clock starts.
        dispatch_keys = list(jax.random.split(key, n_dispatch))
        fence(jax.random.key_data(dispatch_keys[-1]))
        dt = 0.0
        for i in range(n_dispatch):
            key = dispatch_keys[i]
            t_i = time.perf_counter()
            params, opt_state, loss = train_steps(params, opt_state,
                                                  stacked_batch, key)
            # liveness only — a hung tunnel shows up as a stalled
            # dispatch i in the log instead of one silent multi-minute
            # wait. NOT a fence: the axon tunnel acks this before the
            # chip finishes, and dispatches stay pipelined exactly as
            # the real trainer pipelines them.
            jax.block_until_ready(loss)
            dt += time.perf_counter() - t_i
            # the log write stays OUT of the summed segments (slow
            # stderr must not inflate the measurement)
            _log(f"dispatch {i + 1}/{n_dispatch} enqueued (+{dt:.2f}s)")
        # the one TRUE fence: host-fetch of the final loss scalar — it
        # data-depends on every step, so the summed wall clock includes
        # all n_steps of real chip work plus one tunnel round trip
        t_f = time.perf_counter()
        final_loss = fence(loss)
        dt += time.perf_counter() - t_f
        _log(f"fenced: {n_steps} steps in {dt:.2f}s")
    finally:
        # always close the trace — a mid-loop OOM must not leave the
        # profiler open (the next ladder config's start_trace would
        # fail, destroying the degrade-down-the-ladder fallback) — and
        # a failing stop must neither mask the original error nor keep
        # the session open
        if profile_dir:
            try:
                jax.profiler.stop_trace()
                _trace_ok = True
            except Exception as e:  # noqa: BLE001
                _trace_ok = False
                _log(f"stop_trace failed: {e}")
    if profile_dir and _trace_ok:
        _log(f"profile trace written to {profile_dir}")

    steps_per_sec = n_steps / dt
    util = mfu(step_flops, n_steps, dt,
               peak_flops_per_device=device_peak_flops())

    return {
        "metric": metric,
        "value": round(steps_per_sec * units_per_step, 1),
        "unit": unit,
        "vs_baseline": None,
        "detail": {
            **detail,
            "batch_size": batch_size,
            "inner_steps": inner_steps,
            "steps_per_sec": round(steps_per_sec, 3),
            "precision": "bf16",
            "mfu": round(util, 4) if util is not None else None,
            "step_tflops": (round(step_flops / 1e12, 3)
                            if step_flops else None),
            "loss": final_loss,
            "device": str(jax.devices()[0]),
            # truthful evidence labeling (VERDICT r2 #7): what the
            # numbers were actually measured on, machine-readable
            "platform": jax.devices()[0].platform,
            "device_kind": getattr(jax.devices()[0], "device_kind", None),
            # cost-analysis bytes/step of the timed lowering (the
            # hbm_budget gate's metric; None off cost-model backends)
            "hbm_bytes": graphcheck.pop("hbm_bytes", None),
            # lowered-graph dtype provenance (scripts/check.py gates
            # the same numbers at merge; here they ride the result)
            "graphcheck": graphcheck or None,
        },
    }


def _knobs(rung: dict) -> dict:
    """Perf knobs (trace-driven, r05): the levers that cut HBM traffic
    are the streaming CE (loss_impl=pallas, MLM only),
    non-materializing attention (attn=chunked|flash), decoder ditto
    (dec), and remat (recompute instead of storing scan residuals —
    FLOPs are nearly free at this MFU). The RUNG supplies the defaults
    (the ladder's top rung carries the round-5 winner combination);
    BENCH_ATTN_IMPL / BENCH_DEC_IMPL / BENCH_KV_CHUNK / BENCH_REMAT
    override them exactly — sweeps rely on that. Shared TaskConfig
    fields, so every BENCH_TASK honors them; the values are echoed
    into the result detail dict so rows from different knob
    combinations stay distinguishable."""
    remat_env = os.environ.get("BENCH_REMAT")
    return dict(
        attention_impl=(os.environ.get("BENCH_ATTN_IMPL")
                        or rung.get("attn")),
        decoder_attention_impl=(os.environ.get("BENCH_DEC_IMPL")
                                or rung.get("dec")),
        kv_chunk_size=int(os.environ.get("BENCH_KV_CHUNK", "1024")),
        remat=(remat_env == "1" if remat_env is not None
               else bool(rung.get("remat", False))))


def run(rung: dict) -> dict:
    import jax.numpy as jnp

    from perceiver_tpu.tasks import MaskedLanguageModelTask

    batch_size, inner_steps = rung["batch"], rung["inner"]
    loss_impl = rung["loss"]
    seq_len, vocab = 512, 10003
    channels = int(os.environ.get("BENCH_CHANNELS",
                                  str(rung.get("channels", 64))))
    knobs = _knobs(rung)
    task = MaskedLanguageModelTask(
        vocab_size=vocab, max_seq_len=seq_len, loss_impl=loss_impl,
        num_latent_channels=channels, **knobs)
    rng = np.random.default_rng(0)
    stacked = {
        "input_ids": jnp.asarray(rng.integers(
            3, vocab, (inner_steps, batch_size, seq_len)), jnp.int32),
        "pad_mask": jnp.zeros((inner_steps, batch_size, seq_len), bool),
    }
    return _bench_train(
        task, stacked, batch_size=batch_size, inner_steps=inner_steps,
        units_per_step=batch_size * seq_len,
        metric="imdb_mlm_tokens_per_sec_per_chip", unit="tokens/s",
        detail={"seq_len": seq_len, "loss_impl": loss_impl,
                "num_latent_channels": channels, **knobs})


def run_img(rung: dict) -> dict:
    """Secondary BASELINE.md metric: MNIST imgs/sec/chip with the
    ``scripts/img_clf.py`` model config (32×128 latents, 3 layers,
    3 self-attn layers/block, 32 frequency bands)."""
    import jax.numpy as jnp

    from perceiver_tpu.tasks import ImageClassifierTask

    batch_size, inner_steps = rung["batch"], rung["inner"]
    knobs = _knobs(rung)  # CE over 10 classes; no fused-loss variants
    task = ImageClassifierTask(
        image_shape=(28, 28, 1), num_classes=10, num_frequency_bands=32,
        num_latents=32, num_latent_channels=128, num_encoder_layers=3,
        num_encoder_self_attention_layers_per_block=3,
        num_decoder_cross_attention_heads=1, **knobs)
    rng = np.random.default_rng(0)
    stacked = {
        "image": jnp.asarray(rng.normal(
            0, 1, (inner_steps, batch_size, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(
            0, 10, (inner_steps, batch_size)), jnp.int32),
    }
    return _bench_train(
        task, stacked, batch_size=batch_size, inner_steps=inner_steps,
        units_per_step=batch_size,
        metric="mnist_imgs_per_sec_per_chip", unit="imgs/s",
        detail={"image_shape": [28, 28, 1], **knobs})


def run_seg(rung: dict):
    """``BENCH_TASK=seg``: the 512×512 / 262,144-output-query LArTPC
    segmentation config (``run.py:72-112``) — pixels/sec/chip, the
    decoder-query-chunking + long-kv memory stress config.
    ``BENCH_SEG_SIZE`` overrides the side length (smoke runs use 64;
    pinned values are honored exactly, like every other BENCH_* env)."""
    import jax.numpy as jnp

    from perceiver_tpu.tasks import SegmentationTask

    batch_size, inner_steps = rung["batch"], rung["inner"]
    knobs = _knobs(rung)  # weighted CE over 3 classes; no fused variants
    side = int(os.environ.get("BENCH_SEG_SIZE", "512"))
    task = SegmentationTask(image_shape=(side, side, 1),
                            query_chunk_size=min(16384, side * side),
                            **knobs)
    rng = np.random.default_rng(0)
    stacked = {
        "image": jnp.asarray(
            rng.random((inner_steps, batch_size, side, side, 1)) *
            (rng.random((inner_steps, batch_size, side, side, 1)) < 0.01),
            jnp.float32),
        "label": jnp.asarray(rng.integers(
            0, 3, (inner_steps, batch_size, side, side)), jnp.int32),
    }
    return _bench_train(
        task, stacked, batch_size=batch_size, inner_steps=inner_steps,
        units_per_step=batch_size * side * side,
        metric="lartpc_seg_pixels_per_sec_per_chip", unit="pixels/s",
        detail={"image_shape": [side, side, 1],
                "num_output_queries": side * side, **knobs})


# Probe run in a SUBPROCESS: a half-dead tunnel blocks block_until_ready
# uninterruptibly, but a child process can always be SIGKILLed by the
# supervisor's timeout. Success requires the matmul to EXECUTE (the
# 2026-07-31 failure mode initialized + compiled fine, then hung on the
# first dispatch).
def _tpu_aliases() -> tuple:
    # mirrors perceiver_tpu.utils.platform.tpu_platform_names without
    # importing the package (bench.py must work from any cwd before
    # the heavy imports); the axon tunnel plugin reports platform
    # "axon", not "tpu"
    extra = os.environ.get("PERCEIVER_TPU_PLATFORM_ALIASES", "")
    return ("tpu", "axon") + tuple(
        a.strip() for a in extra.split(",") if a.strip())


# The alias tuple is interpolated at probe-launch time so the probe
# source stays self-contained (importing the package in the probe
# would make any unrelated import error look like a dead tunnel)
# while keeping a single alias definition in this file.
_PROBE_SRC = """
import os, jax, jax.numpy as jnp
want = os.environ.get("BENCH_PLATFORM")
if want:
    jax.config.update("jax_platforms", want)
d = jax.devices()
assert d[0].platform in {aliases!r}, d
x = jnp.ones((512, 512), jnp.bfloat16)
(x @ x).block_until_ready()
"""


def _exec_probe(timeout: float = 90.0) -> bool:
    try:
        src = _PROBE_SRC.format(aliases=_tpu_aliases())
        r = subprocess.run([sys.executable, "-c", src],
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL, timeout=timeout)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _emit_status(verdict: str, *, probes_failed: int, attempts: int,
                 results: list) -> None:
    """Flush one structured JSON line to stdout describing the current
    supervisor state. Same schema as a measurement (metric/value/unit/
    vs_baseline) so the driver's parse always succeeds; ``measured``
    distinguishes a sentinel from a real number, and later lines
    supersede earlier ones. If any config HAS completed, the best
    throughput seen so far is re-emitted instead of a zero sentinel —
    a driver kill at any moment records the best number collected."""
    if results:
        best = max(results, key=lambda r: r.get("value") or 0)
        obj = dict(best)
    else:
        metric, unit = _TASK_METRIC.get(
            os.environ.get("BENCH_TASK", ""), _TASK_METRIC[""])
        obj = {"metric": metric, "value": 0.0, "unit": unit,
               "vs_baseline": None, "measured": False,
               "note": ("value 0.0 is a SENTINEL (no measurement "
                        "completed), not a measured throughput")}
    obj["verdict"] = verdict
    obj["supervisor"] = {
        "waited_s": round(time.monotonic() - _T0, 1),
        "probes_failed": probes_failed,
        "bench_attempts": attempts,
        "budget_s": float(os.environ.get("BENCH_WAIT", _DEFAULT_WAIT)),
        "probe_timeout_s": 90.0,
    }
    print(json.dumps(obj), flush=True)


def _record_result(result: dict) -> None:
    """Mirror a completed measurement to BENCH_RESULTS_FILE (set by the
    supervisor) so the parent can re-emit the best number on its own
    exit paths without sitting between the child and stdout."""
    path = os.environ.get("BENCH_RESULTS_FILE")
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(result) + "\n")
            f.flush()
    except OSError as e:
        # never fail the bench over the mirror — but a silent mirror
        # loss can later make the supervisor under-report, so say so
        _log(f"results-file mirror write failed: {e}")


def _run_child(child_env: dict) -> tuple:
    """Run the actual bench as a child process. The child INHERITS
    stdout — its flushed per-config result lines reach the driver's
    capture directly, even if this supervisor is hard-killed before
    the child finishes (a pipe tee here would lose exactly the lines
    the un-failable contract exists to preserve). The child mirrors
    each result to a temp file, parsed after it exits so the
    supervisor can re-emit the best result. Returns ``(rc, results)``."""
    import tempfile
    fd, path = tempfile.mkstemp(prefix="bench_results_", suffix=".jsonl")
    os.close(fd)
    try:
        rc = subprocess.call(
            [sys.executable, os.path.abspath(__file__)],
            env=dict(child_env, BENCH_RESULTS_FILE=path))
        results = []
        with open(path) as f:
            for line in f:
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and obj.get("metric") and \
                        obj.get("measured", True):
                    results.append(obj)
        return rc, results
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def supervise() -> int:
    """Bounded wait-retry: probe every BENCH_PROBE_INTERVAL seconds for
    up to BENCH_WAIT seconds; run the actual bench (as a child process,
    ``BENCH_WAIT=0``) the moment a probe matmul executes.

    The driver's end-of-round bench is the ONE chance to land a number
    in the round record, and the axon tunnel's availability windows are
    short and unpredictable (round 2: one ~1-minute window in ~12 h) —
    exiting on the first failed probe converts a flaky tunnel into a
    guaranteed rc≠0. The child keeps its own in-process watchdog, so a
    tunnel that dies mid-run fails the child in minutes (rc=3) and the
    supervisor goes back to probing with the remaining budget.

    Un-failable under the driver's clock: a status JSON line is flushed
    after every failed probe and on every exit path, and any result a
    child flushed before dying is kept — so whether the tunnel is down,
    half-dead, or flaps mid-ladder, stdout always ends with a parseable
    object (see module docstring).
    """
    budget = float(os.environ.get("BENCH_WAIT", _DEFAULT_WAIT))
    interval = float(os.environ.get("BENCH_PROBE_INTERVAL", "120"))
    deadline = time.monotonic() + budget
    attempts = completed_failures = probes_failed = 0
    results = []  # every parsed measurement any child flushed
    # The TPU runtime admits ONE process: a background watcher
    # (scripts/tpu_watch_and_run.sh) collecting evidence in the same
    # availability window would hold the chip and fail every probe
    # here. This marker asks the watcher to stand down while the
    # driver's end-of-round bench owns the wait budget; the watcher
    # treats a stale (>4 h) marker as abandoned.
    pause_marker = os.environ.get(
        "BENCH_PAUSE_MARKER",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "logs", "tpu_evidence", ".driver_bench_active"))
    try:
        os.makedirs(os.path.dirname(pause_marker), exist_ok=True)
        with open(pause_marker, "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        pause_marker = None
    # the supervisor never enters jax (probes and children are separate
    # processes with their own timeouts/watchdogs), so its in-process
    # watchdog can only misfire — e.g. hard-exiting rc=3 while blocked
    # in subprocess.call on a healthy long-running child
    _WATCHDOG.timeout = 0
    try:
        while True:
            if pause_marker:
                try:
                    # re-WRITE (not just utime) every loop: the marker
                    # must come back even if a concurrent supervisor's
                    # exit or the watcher's stale-marker sweep deleted
                    # it — losing it permanently would hand the chip
                    # to the watcher for the rest of the wait budget
                    with open(pause_marker, "w") as f:
                        f.write(str(os.getpid()))
                except OSError:
                    pass
            t_probe = time.monotonic()
            if _exec_probe():
                attempts += 1
                _log(f"probe OK — starting bench attempt {attempts}")
                rc, child_results = _run_child(
                    dict(os.environ, BENCH_WAIT="0"))
                results.extend(child_results)
                if rc == 0:
                    # a child that exits 0 has by construction printed
                    # at least one real result line to the shared
                    # stdout — if the results-file mirror failed (so
                    # results is empty), emit NOTHING rather than a
                    # 0.0 sentinel that would supersede it
                    if results:
                        _emit_status("ok", probes_failed=probes_failed,
                                     attempts=attempts, results=results)
                    return 0
                _log(f"bench attempt {attempts} failed rc={rc}")
                _emit_status("bench_attempt_failed",
                             probes_failed=probes_failed,
                             attempts=attempts, results=results)
                # rc=3: child watchdog (tunnel died mid-run); rc=5:
                # child saw the backend UNAVAILABLE (window closed
                # right after the probe). Those are transient — keep
                # waiting. Anything else (incl. -9: the kernel
                # OOM-killing the child at a fixed ladder config
                # repeats identically every attempt) counts toward the
                # deterministic-failure cap.
                if rc not in (3, 5):
                    completed_failures += 1  # likely deterministic
                    if completed_failures >= 2:
                        _log("two completed-but-failed attempts — "
                             "giving up (failure looks deterministic, "
                             "not a tunnel flake)")
                        _emit_status("bench_failed_deterministically",
                                     probes_failed=probes_failed,
                                     attempts=attempts, results=results)
                        return 0 if results else rc
            else:
                probes_failed += 1
                _log("probe: backend down or dispatch hung")
                _emit_status("waiting_for_tpu",
                             probes_failed=probes_failed,
                             attempts=attempts, results=results)
            if time.monotonic() >= deadline:
                _log(f"BENCH_WAIT budget ({budget:.0f}s) exhausted "
                     f"— backend never yielded a usable window")
                _emit_status("ok_partial" if results
                             else "tpu_tunnel_down",
                             probes_failed=probes_failed,
                             attempts=attempts, results=results)
                return 0 if results else 4
            time.sleep(max(0.0, interval - (time.monotonic() - t_probe)))
    finally:
        if pause_marker:
            try:
                # remove only OUR marker — a concurrent supervisor
                # (or a test) must not strip a live instance's
                # protection
                with open(pause_marker) as f:
                    if f.read().strip() == str(os.getpid()):
                        os.unlink(pause_marker)
            except OSError:
                pass


def main():
    # Supervisor mode: only for a real-TPU target (BENCH_PLATFORM unset
    # or a TPU-class platform, incl. the axon plugin) with a nonzero
    # wait budget. CPU smoke runs, sweeps, and the supervisor's own
    # children (BENCH_WAIT=0) run directly.
    if (float(os.environ.get("BENCH_WAIT", _DEFAULT_WAIT)) > 0
            and os.environ.get("BENCH_PLATFORM", "tpu") in _tpu_aliases()):
        raise SystemExit(supervise())

    pinned = any(k in os.environ for k in
                 ("BENCH_BATCH", "BENCH_INNER_STEPS", "BENCH_LOSS_IMPL"))
    top = _LADDER[0]
    if pinned:
        # a pinned config carries NO rung knob defaults — exactly the
        # env vars the sweep set (BENCH_ATTN_IMPL etc.), nothing more,
        # so historical sweep rows stay comparable
        configs = [dict(
            batch=int(os.environ.get("BENCH_BATCH", str(top["batch"]))),
            inner=int(os.environ.get("BENCH_INNER_STEPS",
                                     str(top["inner"]))),
            loss=os.environ.get("BENCH_LOSS_IMPL", top["loss"]))]
    else:
        # SMALLEST config first (driver contract, module docstring):
        # each completed rung flushes its JSON line immediately, so a
        # kill or tunnel death mid-climb still leaves every number
        # collected so far on stdout; climbing stops at the first
        # failed rung after a success (an OOM at batch B repeats at
        # batch 2B). The primary track (packed ladder up to the pallas
        # winner rungs) climbs first — fastest route to a number; the
        # dense rung runs last as the fallback when the fused impls
        # break for an impl-specific reason, and the impl comparison.
        rungs = list(reversed(_LADDER))
        configs = ([c for c in rungs if c["loss"] != "dense"]
                   + [c for c in rungs if c["loss"] == "dense"])

    runner = {"img_clf": run_img, "seg": run_seg}.get(
        os.environ.get("BENCH_TASK", ""), run)
    if runner is run_seg and not pinned:
        # the 262k-query config is memory-bound in BATCH, not in
        # inner_steps — its ladder climbs the axis that matters
        configs = [dict(batch=1, inner=1, loss="n/a"),
                   dict(batch=2, inner=1, loss="n/a"),
                   dict(batch=4, inner=1, loss="n/a")]
    elif runner is not run:
        # loss_impl/channels don't apply to these tasks — collapse
        # ladder entries that only differ in them (keep first-seen
        # order and the first-seen rung's attention/remat knobs)
        seen, deduped = set(), []
        for c in configs:
            if (c["batch"], c["inner"]) not in seen:
                seen.add((c["batch"], c["inner"]))
                deduped.append(dict(c, loss="n/a"))
        configs = deduped

    try:
        probe_backend()  # fail fast (and once) if no backend comes up
    except Exception as e:  # noqa: BLE001
        # rc=5 tells a supervising parent this was the tunnel, not the
        # bench — a transient to wait out, never a deterministic failure
        _log(f"backend init failed: {type(e).__name__}: {str(e)[:300]}")
        raise SystemExit(5)

    results, last_err = [], None
    batch_cap = None  # set by the first failure after a success
    max_ok_batch = 0
    for i, rung in enumerate(configs):
        b, inner, impl = rung["batch"], rung["inner"], rung["loss"]
        if batch_cap is not None and b > batch_cap:
            # an OOM at batch B repeats at every larger rung — but
            # smaller later rungs (the dense comparison at the
            # already-proven batch) still run
            _log(f"skipping batch={b} {impl} (cap {batch_cap} after "
                 f"a failed rung)")
            continue
        _log(f"config {i + 1}/{len(configs)}: "
             f"batch={b} inner={inner} loss={impl} "
             f"attn={rung.get('attn')} dec={rung.get('dec')} "
             f"remat={bool(rung.get('remat'))} "
             f"C={rung.get('channels', 64)}")
        try:
            result = runner(rung)
            _log("done")
            # flush NOW: a kill mid-climb must not lose this rung
            print(json.dumps(result), flush=True)
            _record_result(result)
            results.append(result)
            max_ok_batch = max(max_ok_batch, b)
        except Exception as e:  # noqa: BLE001
            # keep only the message: holding the exception would pin
            # the failed run's frames (and its device buffers) alive,
            # starving the other configs of the memory they need
            last_err = f"{type(e).__name__}: {str(e)[:300]}"
            _log(f"config (batch={b}, inner={inner}, {impl}) "
                 f"failed: {last_err[:220]}")
            if "UNAVAILABLE" in last_err or "Unable to initialize" in last_err:
                # dead backend, not resource pressure — other configs
                # would hit the same wall after the same long hang.
                # rc=5 = transient-tunnel signal to a supervising
                # parent, but only if nothing was measured: with a
                # number already on stdout, exiting 0 records it
                # instead of sending the supervisor back to probing
                _log(f"backend unavailable: {last_err}")
                if results:
                    break
                raise SystemExit(5)
            if results:
                batch_cap = max_ok_batch
            # before any success keep trying every rung — a later one
            # may still produce the round's only number (e.g. the
            # dense fallback when the packed impl fails for an
            # impl-specific reason)
    if not results:
        raise SystemExit(f"all bench configs failed; last: {last_err}")
    if len(results) > 1:
        # re-emit the best rung so a last-line parse records the best
        # throughput, not merely the largest completed config
        best = max(results, key=lambda r: r.get("value") or 0)
        print(json.dumps(best), flush=True)


if __name__ == "__main__":
    main()
