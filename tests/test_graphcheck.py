"""Self-verification of the static-analysis subsystem (ISSUE 1).

Every graph pass must demonstrably FAIL on a seeded violation — a
gate that cannot catch its target defect is worse than no gate,
because it certifies trees it never checked. Each pass therefore gets
a tiny synthetic module that violates it (fp32 dot, host callback,
un-donated state, drifting compile key), a clean twin, and an
allowlist round-trip where applicable; the lint rules get seeded
source snippets. The headline-config regression pins
``bf16_flop_fraction == 1.0`` on the exact B=512/C=64 step bench.py
times, and the slow full sweep runs what ``scripts/check.py --all``
gates at merge.
"""

import itertools
from functools import partial

import jax
import jax.numpy as jnp
import pytest

from perceiver_tpu.analysis import (
    CANONICAL_TARGETS,
    DtypeAllow,
    StepTarget,
    TransferAllow,
    donation_check,
    dtype_policy,
    hlo,
    lint_source,
    lower_target,
    recompile_budget,
    run_graph_checks,
    transfer_guard,
)


def _lower_text(fn, *args):
    return fn.lower(*args).as_text()


# --- dtype_policy -----------------------------------------------------------


def _fp32_dot_text():
    @jax.jit
    def f(a, b):
        return a @ b

    x = jnp.ones((16, 32), jnp.float32)
    return _lower_text(f, x, x.T)


def test_dtype_policy_fails_on_fp32_dot():
    violations, summary = dtype_policy(_fp32_dot_text(), where="seeded")
    assert violations, "fp32 dot_general must violate dtype_policy"
    assert "f32" in violations[0].message
    assert summary["bf16_flop_fraction"] == 0.0


def test_dtype_policy_passes_bf16_dot():
    @jax.jit
    def f(a, b):
        return a @ b

    x = jnp.ones((16, 32), jnp.bfloat16)
    violations, summary = dtype_policy(_lower_text(f, x, x.T),
                                       where="clean",
                                       require_full_bf16=True)
    assert not violations
    assert summary["bf16_flop_fraction"] == 1.0


def test_dtype_policy_allowlist_consumes_budget():
    allow = (DtypeAllow(dtype="f32", max_count=1,
                        reason="seeded test exception"),)
    violations, _ = dtype_policy(_fp32_dot_text(), where="seeded",
                                 allowlist=allow)
    assert not violations
    # budget of 1 cannot cover two fp32 dots
    @jax.jit
    def g(a, b):
        return (a @ b) @ (a @ b).T

    x = jnp.ones((8, 8), jnp.float32)
    violations, _ = dtype_policy(_lower_text(g, x, x), where="seeded",
                                 allowlist=allow)
    assert violations


def test_dtype_policy_headline_requirement():
    violations, _ = dtype_policy(
        _fp32_dot_text(), where="seeded",
        allowlist=(DtypeAllow(dtype="f32", max_count=8,
                              reason="mask the per-dot findings"),),
        require_full_bf16=True)
    assert any("bf16_flop_fraction" in v.message for v in violations)


# --- transfer_guard ---------------------------------------------------------


def _callback_text():
    @jax.jit
    def f(x):
        jax.debug.print("x sum {s}", s=x.sum())
        return x * 2

    return _lower_text(f, jnp.ones((4,)))


def test_transfer_guard_fails_on_host_callback():
    violations = transfer_guard(_callback_text(), where="seeded")
    assert violations
    assert "callback" in violations[0].message


def test_transfer_guard_allowlist():
    text = _callback_text()
    markers = hlo.count_host_markers(text)
    assert markers, "seeded callback must be visible to the walker"
    allow = tuple(TransferAllow(marker=m, max_count=n,
                                reason="seeded test exception")
                  for m, n in markers.items())
    assert not transfer_guard(text, where="seeded", allowlist=allow)


def test_transfer_guard_passes_clean_module():
    @jax.jit
    def f(x):
        return x * 2

    assert not transfer_guard(_lower_text(f, jnp.ones((4,))),
                              where="clean")


# --- donation_check ---------------------------------------------------------


def _state_step(donate):
    dec = (partial(jax.jit, donate_argnums=(0,)) if donate else jax.jit)

    @dec
    def step(state, batch):
        new = jax.tree.map(lambda s: s + batch.sum(), state)
        return new

    state = {"w": jnp.ones((8, 8)), "b": jnp.ones((8,))}
    return _lower_text(step, state, jnp.ones((4,)))


def test_donation_check_fails_on_undonated_state():
    violations = donation_check(_state_step(donate=False),
                                where="seeded", expected_donated=2)
    assert violations
    assert "0/2" in violations[0].message


def test_donation_check_passes_donated_state():
    assert not donation_check(_state_step(donate=True), where="clean",
                              expected_donated=2)


@pytest.mark.filterwarnings("ignore:Some donated buffers were not usable")
def test_donation_check_fails_on_shape_drifted_state():
    # donated but unaliasable: the output state shape differs from the
    # input, so lowering cannot alias — exactly what forgetting to
    # keep state shapes stable across the step looks like
    @partial(jax.jit, donate_argnums=(0,))
    def step(state):
        return {"w": state["w"][:4]}

    text = _lower_text(step, {"w": jnp.ones((8, 8))})
    assert donation_check(text, where="seeded", expected_donated=1)


# --- recompile_budget -------------------------------------------------------


def _tiny_mlm():
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    return MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=16, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")


def _tiny_batch(batch=2, seq=16, vocab=110):
    import numpy as np

    rng = np.random.default_rng(0)
    return {
        "input_ids": jnp.asarray(
            rng.integers(3, vocab, (batch, seq)), jnp.int32),
        "pad_mask": jnp.zeros((batch, seq), bool),
    }


def test_recompile_budget_passes_stable_target():
    target = StepTarget(name="tiny_stable",
                        build=lambda: (_tiny_mlm(), _tiny_batch()))
    violations, fp = recompile_budget(target)
    assert not violations
    assert fp


def test_recompile_budget_fails_on_drifting_shapes():
    counter = itertools.count(2)
    target = StepTarget(
        name="tiny_drift",
        build=lambda: (_tiny_mlm(), _tiny_batch(batch=next(counter))))
    violations, _ = recompile_budget(target)
    assert any("different step signatures" in v.message
               for v in violations)


# --- lint rules -------------------------------------------------------------


_JIT_ITEM = """
import jax

@jax.jit
def f(x):
    return x.sum().item()
"""

_JIT_FLOAT = """
import jax
from functools import partial

@partial(jax.jit, static_argnums=(1,))
def f(x, n):
    return float(x) + n
"""

_JIT_NUMPY = """
import jax
import numpy as np

@jax.jit
def f(x):
    return np.asarray(x) * 2
"""

_JIT_TIME_RNG = """
import jax
import time
import numpy as np

@jax.jit
def f(x):
    t = time.time()
    return x * np.random.normal() + t
"""

_JIT_CALL_FORM = """
import jax

def step(state):
    return state.item()

run = jax.jit(step, donate_argnums=0)
"""

_HOST_SIDE_CLEAN = """
import time
import numpy as np

def host_loop(x):
    t = time.time()
    return float(np.asarray(x).sum()) + t
"""

_SHAPE_ACCESS_CLEAN = """
import jax

@jax.jit
def f(x):
    return x * int(x.shape[0])
"""


def _checks(src, path="<memory>"):
    return [v.check for v in lint_source(src, path)]


def test_lint_flags_item_in_jit():
    assert "jit-host-sync" in _checks(_JIT_ITEM)


def test_lint_flags_float_of_traced_param():
    assert "jit-host-sync" in _checks(_JIT_FLOAT)


def test_lint_flags_numpy_in_jit():
    assert "jit-host-sync" in _checks(_JIT_NUMPY)


def test_lint_flags_time_and_np_random_in_jit():
    checks = _checks(_JIT_TIME_RNG)
    assert checks.count("jit-python-rng-time") == 2


def test_lint_follows_jit_call_form():
    # jax.jit(fn, ...) marks fn traced even without a decorator
    assert "jit-host-sync" in _checks(_JIT_CALL_FORM)


def test_lint_ignores_host_side_code():
    assert not _checks(_HOST_SIDE_CLEAN)


def test_lint_allows_static_shape_access():
    assert not _checks(_SHAPE_ACCESS_CLEAN)


def test_lint_ops_numpy_mix_scoped_to_ops():
    src = "import numpy as np\nimport jax.numpy as jnp\n"
    assert "ops-numpy-mix" in _checks(src, "perceiver_tpu/ops/new.py")
    assert not _checks(src, "perceiver_tpu/data/new.py")
    np_only = "import numpy as np\n"
    assert not _checks(np_only, "perceiver_tpu/ops/fourier2.py")


_IMPL_UNVALIDATED = """
import dataclasses
from typing import Optional

@dataclasses.dataclass(frozen=True)
class Config:
    dropout: float = 0.0
    attention_impl: Optional[str] = None

    def __post_init__(self):
        # the reverted tasks/base.py shape: a feature guard using a
        # positive membership test, but no domain validation
        if self.dropout > 0.0 and self.attention_impl in ("flash",):
            raise ValueError("no dropout for flash")
"""

def test_lint_catches_missing_impl_validation():
    # the exact pre-fix tasks/base.py shape (ADVICE r5): feature guard
    # present, domain validation absent — must be flagged
    assert "impl-field-validation" in _checks(_IMPL_UNVALIDATED)


def test_lint_accepts_not_in_domain_validation():
    src = _IMPL_UNVALIDATED.replace(
        'raise ValueError("no dropout for flash")',
        'raise ValueError("no dropout for flash")\n'
        '        if self.attention_impl not in (None, "einsum"):\n'
        '            raise ValueError("bad impl")')
    assert "impl-field-validation" not in _checks(src)


def test_lint_suppression_marker():
    src = _JIT_ITEM.replace(".item()", ".item()  # graphcheck: ignore")
    assert not _checks(src)


def test_lint_clean_on_fixed_tree_files():
    # the files this PR fixed must stay clean under the rules that
    # flagged them (regression for the ADVICE r5 finding)
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("perceiver_tpu/tasks/base.py",
                "perceiver_tpu/models/perceiver.py"):
        with open(os.path.join(root, rel)) as f:
            assert not lint_source(f.read(), rel), rel


# --- headline regression + full sweep ---------------------------------------


def test_headline_config_bf16_flop_fraction_is_one():
    """B=512/C=64 packed MLM (bench.py _LADDER[0]): every dot FLOP in
    the lowered train step runs on bf16 operands — the round-4 audit's
    9.1%-at-fp32 regression, pinned forever."""
    target = CANONICAL_TARGETS[0]
    assert target.name == "mlm_b512_c64_packed" and target.headline
    lowered = lower_target(target)
    summary = hlo.dot_flop_summary(list(hlo.iter_dots(lowered.text)))
    assert summary["bf16_flop_fraction"] == 1.0
    violations, _ = dtype_policy(lowered.text, where=target.name,
                                 require_full_bf16=True)
    assert not violations
    # and its donation + transfer contracts hold
    assert not donation_check(lowered.text, where=target.name,
                              expected_donated=lowered.expected_donated)
    assert not transfer_guard(lowered.text, where=target.name,
                              allowlist=target.transfer_allow)


def test_full_graph_sweep_is_clean():
    """What ``scripts/check.py --graph`` gates at merge: every
    canonical target, all four passes including the double-lowering
    recompile check. Slow-marked (see conftest)."""
    report = run_graph_checks(CANONICAL_TARGETS, recompile=True)
    assert report.ok, report.format()
    assert set(report.checks_run) == {"dtype_policy", "transfer_guard",
                                      "donation_check",
                                      "recompile_budget"}


def test_full_lint_sweep_is_clean():
    """What ``scripts/check.py --lint`` gates at merge. Slow-marked."""
    import os

    from perceiver_tpu.analysis import default_lint_paths, lint_paths

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = lint_paths(default_lint_paths(root))
    assert report.ok, report.format()
