"""Serving metrics: counters, gauges, latency histograms, Prometheus
text exposition.

Deliberately dependency-free (no prometheus_client in the image): the
three metric kinds the serving plane needs are small, and owning them
keeps the hot path allocation-free — ``observe``/``inc`` are a lock,
two adds, and a ring-buffer store.

Quantiles: Prometheus histograms only expose cumulative bucket counts
(quantiles are computed server-side), but the offline load generator
and the tests need exact-ish tail latencies locally — so ``Histogram``
additionally keeps a bounded reservoir (last ``reservoir`` samples)
and computes p50/p95/p99 from it. The text exposition stays pure
Prometheus (``_bucket``/``_sum``/``_count``).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, Optional, Tuple

from perceiver_tpu.utils.concurrency import guarded_by

# seconds; spans 100 µs → 10 s, roughly log-spaced (serving latencies
# on CPU tests sit in the ms range, on chips in the 100 µs range)
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def escape_label_value(value: str) -> str:
    """Escape a label value per the exposition format 0.0.4 spec:
    backslash, double-quote, and line feed — in that order, so the
    escaping backslashes aren't themselves re-escaped."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def unescape_label_value(value: str) -> str:
    """Inverse of :func:`escape_label_value` (used by the exposition
    parser in ``obs/promparse.py`` and the round-trip tests)."""
    out = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:  # unknown escape: keep verbatim, as Prometheus does
                out.append(ch + nxt)
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    out = repr(float(v))
    return out[:-2] if out.endswith(".0") else out


@guarded_by("_lock", "_values")
class Counter:
    """Monotonic counter family; ``labels(...)`` returns a child whose
    increments are tracked per label set."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def labels(self, **labels) -> "_CounterChild":
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values.setdefault(key, 0.0)
        return _CounterChild(self, key)

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    def _inc(self, key, amount: float) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    @property
    def value(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def value_of(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def items(self):
        """Snapshot of (labels dict, value) per label set."""
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]

    def collect(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            yield f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}"


class _CounterChild:
    __slots__ = ("_parent", "_key")

    def __init__(self, parent: Counter, key):
        self._parent = parent
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._parent._inc(self._key, amount)


@guarded_by("_lock", "_value", "_children")
class Gauge:
    """Set-to-current-value metric (queue depth, bucket count).

    Like :class:`Counter`, ``labels(...)`` returns a per-label-set
    child — how the engine exposes per-bucket breaker state and the
    fleet router per-replica occupancy on one metric name."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self._children: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def labels(self, **labels) -> "_GaugeChild":
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._children.setdefault(key, 0.0)
        return _GaugeChild(self, key)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def _set_child(self, key, value: float) -> None:
        with self._lock:
            self._children[key] = float(value)

    def _remove_child(self, key) -> None:
        with self._lock:
            self._children.pop(key, None)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def value_of(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._children.get(key, 0.0)

    def items(self):
        """Snapshot of (labels dict, value) per label set."""
        with self._lock:
            return [(dict(k), v)
                    for k, v in sorted(self._children.items())]

    def collect(self) -> Iterable[str]:
        with self._lock:
            value = self._value
            children = sorted(self._children.items())
        if not children or value != 0.0:
            yield f"{self.name} {_fmt_value(value)}"
        for key, v in children:
            yield f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}"


class _GaugeChild:
    __slots__ = ("_parent", "_key")

    def __init__(self, parent: Gauge, key):
        self._parent = parent
        self._key = key

    def set(self, value: float) -> None:
        self._parent._set_child(self._key, value)

    def remove(self) -> None:
        """Drop this label set from the exposition (a retired
        replica's gauges must not linger as stale zeros)."""
        self._parent._remove_child(self._key)


class PagePoolGauges:
    """Occupancy pair for one paged KV arena: used/free page gauges.

    The decode engine owns one per arena (the target pool and, under
    speculative decoding, the draft pool — told apart by the ``arena``
    label), and calls :meth:`update` from the same critical sections
    that mutate the pool, so the exposition can never show a
    used/free pair that sums past the arena size. Exported through
    fleet aggregation like every other engine metric (the replica's
    registry render is scraped verbatim).
    """

    USED = "serving_page_pool_used_pages"
    FREE = "serving_page_pool_free_pages"

    def __init__(self, registry: "MetricsRegistry", *,
                 arena: str = "target"):
        self.arena = arena
        used = registry.gauge(
            self.USED, "decode-arena pages currently allocated, by arena")
        free = registry.gauge(
            self.FREE, "decode-arena pages on the free list, by arena")
        self._used = used.labels(arena=arena)
        self._free = free.labels(arena=arena)

    def update(self, pool) -> None:
        """Snapshot one :class:`~perceiver_tpu.serving.decode.PagePool`."""
        self._used.set(pool.allocated_pages)
        self._free.set(pool.free_pages)


@guarded_by("_lock", "_counts", "_sum", "_count", "_reservoir",
            "_reservoir_n")
class Histogram:
    """Cumulative-bucket histogram + bounded reservoir for quantiles."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
                 reservoir: int = 8192):
        if tuple(buckets) != tuple(sorted(buckets)):
            raise ValueError("histogram buckets must be sorted")
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._reservoir_cap = reservoir
        self._reservoir = [0.0] * reservoir
        self._reservoir_n = 0  # total observed (ring write index)

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            self._reservoir[self._reservoir_n % self._reservoir_cap] = value
            self._reservoir_n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Exact quantile over the retained reservoir (the last
        ``reservoir`` observations), or None before any sample."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            n = min(self._reservoir_n, self._reservoir_cap)
            if n == 0:
                return None
            window = sorted(self._reservoir[:n])
        return window[min(int(q * n), n - 1)]

    def collect(self) -> Iterable[str]:
        with self._lock:
            counts = list(self._counts)
            total, acc = self._count, self._sum
        cum = 0
        for bound, c in zip(self.buckets + (math.inf,), counts):
            cum += c
            yield (f"{self.name}_bucket{{le=\"{_fmt_value(bound)}\"}} "
                   f"{cum}")
        yield f"{self.name}_sum {_fmt_value(acc)}"
        yield f"{self.name}_count {total}"


@guarded_by("_lock", "_metrics")
class MetricsRegistry:
    """Namespace of metrics with Prometheus text exposition.

    One registry per serving engine (tests build throwaways); metric
    constructors are idempotent by name so the engine, batcher, and
    api front-ends can all resolve the same metric.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}")
                return existing
            metric = cls(name, help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines = []
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"
