"""Self-verification of the racecheck subsystem (ISSUE 16).

Same contract as test_graphcheck.py: every pass must demonstrably
FAIL on a seeded violation — a lock-discipline gate that cannot catch
an unlocked access certifies code it never checked. Each static pass
(guarded-attrs, lock-order, callback-under-lock) gets a tiny synthetic
module that violates it plus a clean twin; the allowlist and the
suppression comment round-trip; a corrupt registry fails loudly; and
the end-to-end run over the real tree exits clean, both in-process
and as the literal ``scripts/check.py --race`` subprocess.

The second half proves the *runtime* harness: the InterleaveScheduler
replays a seeded interleaving bitwise-identically, and the real
concurrency fixes this PR landed (Router health writes under the
router lock, ParamsVersionStore CURRENT-pointer serialization) each
get a deterministic regression test whose pre-fix shape fails under a
fixed seed while the fixed code runs clean under the same one.
"""

import ast
import os
import subprocess
import sys
import textwrap

import pytest

from perceiver_tpu.analysis import RaceAllow, run_racecheck
from perceiver_tpu.analysis.lint import lint_source
from perceiver_tpu.analysis.racecheck import (
    check_callback_under_lock,
    check_guarded_attrs,
    check_lock_order_cycles,
    collect_lock_order_edges,
)
from perceiver_tpu.utils.concurrency import (
    InstrumentedLock,
    InterleaveScheduler,
    SchedPoint,
    UnguardedAccessError,
    guarded,
    guarded_by,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse(src):
    return ast.parse(textwrap.dedent(src))


def _race_file(tmp_path, src, name="fake.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return str(p)


# --- pass 1: guarded-attrs --------------------------------------------------


GUARDED_ESCAPE = """
import threading

class Store:
    _GUARDED = {"items": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def bad_add(self, x):
        self.items.append(x)        # <- unlocked touch

    def good_add(self, x):
        with self._lock:
            self.items.append(x)
"""


def test_guarded_attrs_fails_on_seeded_escape():
    vs = check_guarded_attrs(_parse(GUARDED_ESCAPE), "fake.py")
    assert len(vs) == 1, vs
    v = vs[0]
    assert v.check == "guarded-attrs"
    assert "Store.bad_add" in v.message and "'items'" in v.message
    # __init__ and the locked method are exempt/clean
    clean = GUARDED_ESCAPE.replace(
        "self.items.append(x)        # <- unlocked touch",
        "pass")
    assert check_guarded_attrs(_parse(clean), "fake.py") == []


def test_guarded_attrs_star_and_dotted_keys():
    src = """
    import threading

    class Mgr:
        _GUARDED = {"*.count": "_lock", "stats.hits": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()

        def bad(self, rec):
            rec.count += 1
            self.stats.hits += 1

        def good(self, rec):
            with self._lock:
                rec.count += 1
                self.stats.hits += 1
    """
    vs = check_guarded_attrs(_parse(src), "fake.py")
    assert {v.message.split("'")[1] for v in vs} == {"count",
                                                     "stats.hits"}
    assert all("Mgr.bad" in v.message for v in vs), vs


def test_guarded_attrs_condition_alias_counts_as_lock():
    src = """
    import threading

    class Q:
        _GUARDED = {"_q": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)

        def pop(self):
            with self._cv:          # Condition over _lock: holds it
                return self._q.pop()
    """
    assert check_guarded_attrs(_parse(src), "fake.py") == []


def test_guarded_attrs_tuple_value_accepts_either_lock():
    src = """
    import threading

    class Q:
        _GUARDED = {"_q": ("_lock", "_not_empty")}

        def __init__(self):
            self._lock = threading.Lock()

        def via_cond(self):
            with self._not_empty:
                return len(self._q)

        def bad(self):
            return len(self._q)
    """
    vs = check_guarded_attrs(_parse(src), "fake.py")
    assert len(vs) == 1 and "Q.bad" in vs[0].message, vs


def test_locked_suffix_convention():
    src = """
    import threading

    class C:
        _GUARDED = {"_state": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()

        def _flush_locked(self):
            self._state.clear()     # exempt: callee-side lock-held

        def good(self):
            with self._lock:
                self._flush_locked()

        def bad(self):
            self._flush_locked()    # call site outside any lock frame
    """
    vs = check_guarded_attrs(_parse(src), "fake.py")
    assert len(vs) == 1, vs
    assert "C.bad" in vs[0].message and "_flush_locked" in vs[0].message


def test_nested_def_analyzed_with_no_locks_held():
    # a closure defined under the lock may run later on another
    # thread — its guarded touches must still be flagged
    src = """
    import threading

    class C:
        _GUARDED = {"_state": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()

        def bad(self):
            with self._lock:
                def later():
                    return self._state
                return later
    """
    vs = check_guarded_attrs(_parse(src), "fake.py")
    assert len(vs) == 1 and "'_state'" in vs[0].message, vs


def test_guarded_globals_registry(tmp_path):
    src = """
    import threading

    _lock = threading.Lock()
    _cache = {}

    _GUARDED_GLOBALS = {"_cache": "_lock"}

    def bad():
        return _cache.get("k")

    def good():
        with _lock:
            return _cache.get("k")
    """
    vs = check_guarded_attrs(_parse(src), "fake.py")
    assert len(vs) == 1, vs
    assert "_cache" in vs[0].message and "_GUARDED_GLOBALS" in vs[0].message


def test_registry_corruption_fails_loudly():
    for bad_registry in (
            '_GUARDED = "items->lock"',            # not a dict
            '_GUARDED = {1: "_lock"}',             # non-string key
            '_GUARDED = {"items": 7}',             # non-string value
    ):
        src = f"""
        class C:
            {bad_registry}
            def f(self):
                pass
        """
        vs = check_guarded_attrs(_parse(src), "fake.py")
        assert len(vs) == 1, (bad_registry, vs)
        assert "corrupt" in vs[0].message, vs[0].message
    # the runtime half enforces the same contract
    with pytest.raises(TypeError):
        guarded_by("", "x")
    with pytest.raises(TypeError):
        guarded_by("_lock")
    with pytest.raises(TypeError):
        guarded_by("_lock", 3)


def test_guarded_by_decorator_builds_registry():
    @guarded_by("_lock", "a", "b")
    class C:
        pass

    assert C._GUARDED == {"a": "_lock", "b": "_lock"}

    @guarded_by("_other", "c")
    class D(C):
        pass

    # merges with (and inherits) the base registry
    assert D._GUARDED == {"a": "_lock", "b": "_lock", "c": "_other"}
    assert C._GUARDED == {"a": "_lock", "b": "_lock"}


# --- pass 2: lock-order -----------------------------------------------------


LOCK_CYCLE = """
import threading

class A:
    def __init__(self):
        self._lock_x = threading.Lock()
        self._lock_y = threading.Lock()

    def forward(self):
        with self._lock_x:
            with self._lock_y:
                pass

    def backward(self):
        with self._lock_y:
            with self._lock_x:
                pass
"""


def test_lock_order_cycle_detected():
    edges, selfv = collect_lock_order_edges(_parse(LOCK_CYCLE), "fake.py")
    assert selfv == []
    assert len(edges) == 2
    vs = check_lock_order_cycles(edges)
    assert len(vs) == 1, vs
    assert vs[0].check == "lock-order"
    assert "cycle" in vs[0].message
    # consistent order on both paths -> clean
    clean = LOCK_CYCLE.replace("with self._lock_y:\n            "
                               "with self._lock_x:",
                               "with self._lock_x:\n            "
                               "with self._lock_y:")
    edges, _ = collect_lock_order_edges(_parse(clean), "fake.py")
    assert check_lock_order_cycles(edges) == []


def test_lock_order_self_deadlock_and_rlock_exemption():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def boom(self):
            with self._lock:
                with self._lock:
                    pass
    """
    _, selfv = collect_lock_order_edges(_parse(src), "fake.py")
    assert len(selfv) == 1 and "self-deadlock" in selfv[0].message, selfv
    rlock = src.replace("threading.Lock()", "threading.RLock()")
    _, selfv = collect_lock_order_edges(_parse(rlock), "fake.py")
    assert selfv == []


def test_lock_order_graph_is_global_across_classes():
    # the cycle only exists once edges from BOTH classes land in one
    # graph — two components taking shared module-level locks in
    # opposite orders, neither wrong in isolation
    fwd = """
    import threading

    lock_one = threading.Lock()
    lock_two = threading.Lock()

    class A:
        def f(self):
            with lock_one:
                with lock_two:
                    pass
    """
    bwd = """
    class B:
        def g(self):
            with lock_two:
                with lock_one:
                    pass
    """
    ea, _ = collect_lock_order_edges(_parse(fwd), "fake.py")
    eb, _ = collect_lock_order_edges(_parse(bwd), "fake.py")
    assert check_lock_order_cycles(ea) == []
    assert check_lock_order_cycles(eb) == []
    vs = check_lock_order_cycles(list(ea) + list(eb))
    assert len(vs) == 1 and "cycle" in vs[0].message, vs
    # and the combined module trips end-to-end through the one-shot API
    both, _ = collect_lock_order_edges(
        _parse(fwd + bwd), "fake.py")
    assert len(check_lock_order_cycles(both)) == 1


# --- pass 3: callback-under-lock --------------------------------------------


CALLBACK_UNDER_LOCK = """
import threading

class Breaker:
    def __init__(self, on_transition):
        self._lock = threading.Lock()
        self.on_transition = on_transition

    def trip(self):
        with self._lock:
            self.on_transition("closed", "open")   # PR 5 shape
"""


def test_callback_under_lock_detected():
    vs = check_callback_under_lock(_parse(CALLBACK_UNDER_LOCK), "fake.py")
    assert len(vs) == 1, vs
    assert vs[0].check == "callback-under-lock"
    assert "on_transition" in vs[0].message
    # snapshot-then-fire outside the lock is the sanctioned shape
    clean = """
    import threading

    class Breaker:
        def __init__(self, on_transition):
            self._lock = threading.Lock()
            self.on_transition = on_transition

        def trip(self):
            with self._lock:
                old, new = "closed", "open"
            self.on_transition(old, new)
    """
    assert check_callback_under_lock(_parse(clean), "fake.py") == []


# --- allowlist + suppression round-trips ------------------------------------


def test_allowlist_round_trip(tmp_path):
    path = _race_file(tmp_path, GUARDED_ESCAPE)
    r = run_racecheck(paths=[path], allowlist=())
    assert not r.ok and len(r.violations) == 1
    r = run_racecheck(paths=[path], allowlist=(
        RaceAllow(attr="Store.items",
                  reason="seeded test escape"),))
    assert r.ok, r.format()
    # the budget is per-entry: a second unlocked touch still fails
    two = GUARDED_ESCAPE + "\n    def also_bad(self):\n" \
                           "        return len(self.items)\n"
    path2 = _race_file(tmp_path, two, "two.py")
    r = run_racecheck(paths=[path2], allowlist=(
        RaceAllow(attr="Store.items",
                  reason="seeded test escape"),))
    assert not r.ok and len(r.violations) == 1, r.format()
    r = run_racecheck(paths=[path2], allowlist=(
        RaceAllow(attr="Store.items",
                  reason="seeded test escape", max_count=2),))
    assert r.ok, r.format()


def test_suppression_comment_round_trip(tmp_path):
    suppressed = GUARDED_ESCAPE.replace(
        "# <- unlocked touch", "# graphcheck: ignore")
    path = _race_file(tmp_path, suppressed)
    r = run_racecheck(paths=[path], allowlist=())
    assert r.ok, r.format()


# --- satellite: blocking-under-lock + condition hygiene (lint) --------------


BLOCKING_UNDER_LOCK = """
import pickle
import threading
import time

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self, sock):
        with self._lock:
            time.sleep(0.1)
            blob = pickle.dumps({})
            sock.sendall(blob)
            open("/tmp/x")
            send_msg(sock, {}, 1.0)

    def good(self, sock):
        with self._lock:
            blob = dict(x=1)
        sock.sendall(pickle.dumps(blob))
"""


def test_blocking_under_lock_seeded():
    vs = [v for v in lint_source(textwrap.dedent(BLOCKING_UNDER_LOCK),
                                 "perceiver_tpu/serving/fake.py")
          if v.check == "blocking-under-lock"]
    assert len(vs) == 5, vs
    for needle in ("time.sleep", "pickle.dumps", "sendall", "open()",
                   "send_msg"):
        assert any(needle in v.message for v in vs), (needle, vs)
    # out of scope: the same source under obs/ is not checked
    assert [v for v in lint_source(textwrap.dedent(BLOCKING_UNDER_LOCK),
                                   "perceiver_tpu/obs/fake.py")
            if v.check == "blocking-under-lock"] == []


def test_blocking_under_lock_nested_def_resets_frame():
    src = """
    import threading, time

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def ok(self):
            with self._lock:
                def later():
                    time.sleep(0.1)   # runs after release
                return later
    """
    assert [v for v in lint_source(textwrap.dedent(src),
                                   "perceiver_tpu/serving/fake.py")
            if v.check == "blocking-under-lock"] == []


def test_condition_wait_requires_timeout():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._not_empty = threading.Condition(self._lock)

        def bad(self):
            with self._not_empty:
                self._not_empty.wait()

        def good(self):
            with self._not_empty:
                self._not_empty.wait(0.05)
    """
    vs = [v for v in lint_source(textwrap.dedent(src),
                                 "perceiver_tpu/fleet/fake.py")
          if v.check == "distributed-blocking-io"]
    assert len(vs) == 1 and "wait() with no timeout" in vs[0].message, vs
    # keyword timeout also passes
    kw = src.replace("self._not_empty.wait()",
                     "self._not_empty.wait(timeout=0.05)")
    assert [v for v in lint_source(textwrap.dedent(kw),
                                   "perceiver_tpu/fleet/fake.py")
            if v.check == "distributed-blocking-io"] == []


# --- end-to-end over the real tree ------------------------------------------


def test_racecheck_real_tree_clean():
    r = run_racecheck(repo_root=ROOT)
    assert r.ok, r.format()
    assert set(r.checks_run) == {"guarded-attrs", "lock-order",
                                 "callback-under-lock"}


def test_check_cli_race_exits_zero():
    """``scripts/check.py --race`` — the literal CI face — exits 0 on
    this tree and reports all three passes in the roster."""
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "check.py"),
         "--race"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    for name in ("guarded-attrs", "lock-order", "callback-under-lock"):
        assert name in r.stdout, r.stdout


# --- the deterministic interleaving harness ---------------------------------


def _two_thread_trace(seed):
    sched = InterleaveScheduler(seed=seed)
    log = []

    def worker(tag):
        def run():
            for i in range(3):
                log.append((tag, i))
                sched.point(f"{tag}:{i}")
        return run

    sched.spawn(worker("a"), name="a")
    sched.spawn(worker("b"), name="b")
    sched.run()
    return list(sched.trace), list(log)


def test_interleaving_is_seed_deterministic():
    t1, l1 = _two_thread_trace(seed=1234)
    t2, l2 = _two_thread_trace(seed=1234)
    assert t1 == t2 and l1 == l2
    assert {name for name, _ in t1} == {"a", "b"}
    assert len(l1) == 6


def test_scheduler_reraises_worker_exception():
    sched = InterleaveScheduler(seed=0)

    def boom():
        sched.point("pre")
        raise ValueError("seeded failure")

    sched.spawn(boom, name="boom")
    with pytest.raises(ValueError, match="seeded failure"):
        sched.run()


def test_instrumented_lock_tracks_ownership():
    lock = InstrumentedLock(name="t")
    assert not lock.held_by_current_thread()
    with lock:
        assert lock.held_by_current_thread()
        assert lock.locked()
    assert not lock.held_by_current_thread()
    assert lock.acquisitions == 1
    # non-blocking contention path
    assert lock.acquire(blocking=False)
    assert not lock.acquire(blocking=False)
    lock.release()
    # a threading.Condition accepts it as its lock (_is_owned protocol)
    cond = __import__("threading").Condition(InstrumentedLock(name="c"))
    with cond:
        cond.wait(0.01)


def test_guarded_proxy_raises_off_lock():
    lock = InstrumentedLock(name="g")
    state = guarded({"k": 1}, lock, label="test dict")
    with pytest.raises(UnguardedAccessError):
        state["k"]
    with pytest.raises(UnguardedAccessError):
        state["k"] = 2
    with pytest.raises(UnguardedAccessError):
        len(state)
    with pytest.raises(UnguardedAccessError):
        "k" in state
    with lock:
        state["k"] = 2
        assert state["k"] == 2
        assert len(state) == 1


def test_sched_point_shim_is_noop_off_harness():
    sched = InterleaveScheduler(seed=0)
    hook = SchedPoint(sched, "shim")
    hook()  # unmanaged thread: must not park or deadlock
    assert sched.trace == []


# --- regression: Router health writes under the router lock -----------------
# The fix this PR landed in fleet/router.py: submit() and _probe_loop()
# used to write `state.health = ...` with no lock while _pick() read it
# under self._lock on other threads. These tests instrument the REAL
# Router: if the `with self._lock:` around either write is ever
# removed again, the guard below raises deterministically.


class _FakeHandle:
    def __init__(self, health="DEGRADED"):
        self.health = health

    def dispatch(self, arrays):
        return {"outputs": dict(arrays), "health": self.health}

    def status(self):
        return {"health": self.health}


def _guarded_router(sched, seed_note=""):
    from perceiver_tpu.fleet.router import Router

    router = Router(prober_interval_s=None, max_attempts=2)
    ilock = InstrumentedLock(sched, name="router._lock")
    router._lock = ilock
    router.add("r0", _FakeHandle())
    state = router._replicas["r0"]

    class _HealthWriteGuarded(state.__class__):
        def __setattr__(self, name, value):
            if name == "health" and not ilock.held_by_current_thread():
                raise UnguardedAccessError(
                    "health written without holding the router lock "
                    f"({seed_note})")
            super().__setattr__(name, value)

    state.__class__ = _HealthWriteGuarded
    return router, state


def test_router_prefix_health_write_fails_deterministically():
    # the literal pre-fix statement shape: raises on every run, no
    # timing involved — this is what turns the race into an assertion
    router, state = _guarded_router(None, seed_note="pre-fix shape")
    with pytest.raises(UnguardedAccessError):
        state.health = "UNAVAILABLE"       # verbatim pre-fix write
    with router._lock:
        state.health = "UNAVAILABLE"       # the fixed shape


def test_router_submit_health_write_holds_lock_under_interleaving():
    # two submitters race through the real submit() under a seeded
    # schedule; the instrumented lock yields at every acquisition, so
    # the health write interleaves against _pick on the sibling thread
    def run_once(seed):
        sched = InterleaveScheduler(seed=seed)
        router, state = _guarded_router(sched, seed_note=f"seed={seed}")
        results = []
        sched.spawn(lambda: results.append(router.submit({"x": 1})),
                    name="submit-a")
        sched.spawn(lambda: results.append(router.submit({"x": 2})),
                    name="submit-b")
        sched.run()
        return results, list(sched.trace), state

    for seed in (0, 7, 1234):
        results, trace, state = run_once(seed)
        assert len(results) == 2, results
        assert state.health == "DEGRADED"  # reply health took effect
        # bitwise-reproducible: same seed, same interleaving
        results2, trace2, _ = run_once(seed)
        assert trace == trace2 and len(results2) == 2


# --- regression: ParamsVersionStore CURRENT pointer serialization ----------
# The fix this PR landed in training/checkpoint.py: two threads of one
# process share the pid-suffixed CURRENT temp name; unserialized, one
# thread's os.replace() consumes the temp file out from under the
# sibling mid-write. The pre-fix body (reproduced verbatim below with
# a yield point in the write→replace window) fails deterministically
# under the seeded schedule; the real, locked set_current survives the
# same schedule.


def _prefix_set_current(directory, version, point):
    # verbatim pre-fix body of ParamsVersionStore.set_current, with a
    # sched point in the racy window between write and replace
    tmp = os.path.join(directory, f".CURRENT.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(version + "\n")
    point()
    os.replace(tmp, os.path.join(directory, "CURRENT"))


# the losing interleaving is any schedule where both threads pass the
# temp-file write before either replaces it; under Random(4) the drawn
# schedule is exactly that (both writes park before a replace runs),
# so the failure replays on every run — no luck involved
RACY_SEED = 4


def test_params_version_store_prefix_race_fails_deterministically(
        tmp_path):
    seed = RACY_SEED
    sched = InterleaveScheduler(seed=seed)
    point = SchedPoint(sched, "write->replace window")
    sched.spawn(lambda: _prefix_set_current(str(tmp_path), "v1", point),
                name="a")
    sched.spawn(lambda: _prefix_set_current(str(tmp_path), "v2", point),
                name="b")
    with pytest.raises(FileNotFoundError):
        sched.run()


def test_params_version_store_set_current_survives_same_schedule(
        tmp_path):
    from perceiver_tpu.training.checkpoint import ParamsVersionStore

    store = ParamsVersionStore(str(tmp_path))
    os.makedirs(store.path("v1"))
    os.makedirs(store.path("v2"))
    seed = RACY_SEED
    sched = InterleaveScheduler(seed=seed)
    store._lock = InstrumentedLock(sched, name="store._lock")
    sched.spawn(lambda: store.set_current("v1"), name="a")
    sched.spawn(lambda: store.set_current("v2"), name="b")
    sched.run()  # no FileNotFoundError: the lock serializes the window
    assert store.current() in {"v1", "v2"}
    # and replay is deterministic: the winner is seed-stable
    store2 = ParamsVersionStore(str(tmp_path))
    sched2 = InterleaveScheduler(seed=RACY_SEED)
    store2._lock = InstrumentedLock(sched2, name="store._lock")
    sched2.spawn(lambda: store2.set_current("v1"), name="a")
    sched2.spawn(lambda: store2.set_current("v2"), name="b")
    sched2.run()
    assert store2.current() == store.current()


# --- regression: ContinuousBatchScheduler under seeded interleaving ---------
# ISSUE 17 replaced AdmissionQueue with the unified prefill+decode
# scheduler; the admission deque is still the only shared state (the
# chunk planner is pure), so the same discipline holds: every deque
# touch under _lock, declared in _GUARDED for the static pass, and
# proven here against the REAL class with the deque wrapped in a
# guarded() proxy under adversarial schedules.


def test_continuous_batch_scheduler_guarded_declaration():
    from perceiver_tpu.serving.batcher import (
        AdmissionQueue,
        ContinuousBatchScheduler,
    )

    assert ContinuousBatchScheduler._GUARDED == {"_queue": "_lock"}
    # the compat subclass inherits the declaration (the static pass
    # reads the MRO the same way)
    assert AdmissionQueue._GUARDED == {"_queue": "_lock"}


def test_continuous_batch_scheduler_interleaved_offer_take_plan():
    """Producers offer while the step loop takes and plans chunks —
    the guarded deque raises on any off-lock access, conservation
    holds on every seed, and each seed replays bitwise."""
    import itertools

    from perceiver_tpu.serving.batcher import ContinuousBatchScheduler

    def run_once(seed):
        sched = InterleaveScheduler(seed=seed)
        ticks = itertools.count()
        q = ContinuousBatchScheduler(
            max_depth=6, token_budget=3, max_chunk=2,
            clock=lambda: next(ticks) * 1e-3)
        lock = InstrumentedLock(sched, name="scheduler._lock")
        q._lock = lock
        q._queue = guarded(q._queue, lock, label="scheduler deque")
        offered, rejected, admitted, shed = [], [], [], []
        plans = []

        def producer():
            for i in range(8):
                item = f"s{i}"
                deadline = 0.0 if i % 4 == 3 else None
                if q.offer(item, cost=1 + i % 2, deadline=deadline):
                    offered.append(item)
                else:
                    rejected.append(item)

        def stepper():
            remaining = {}
            for _ in range(40):
                a, s = q.take(budget=3, slots=2)
                admitted.extend(a)
                shed.extend(s)
                for item in a:
                    remaining[item] = 3
                rems = [remaining[k] for k in sorted(remaining)]
                plan = q.plan_chunks(0, rems)
                plans.append(tuple(plan))
                for k, c in zip(sorted(remaining), plan):
                    remaining[k] -= c
                    if remaining[k] == 0:
                        del remaining[k]
                if (len(offered) + len(rejected) == 8
                        and q.depth == 0 and not remaining):
                    return

        sched.spawn(producer, name="producer")
        sched.spawn(stepper, name="step-loop")
        sched.run()
        leftover = q.drain_all()
        return (tuple(admitted), tuple(shed), tuple(rejected),
                tuple(leftover), tuple(plans), tuple(sched.trace))

    for seed in (0, 9, 4242):
        first = run_once(seed)
        admitted, shed, rejected, leftover, plans, _ = first
        everything = sorted(list(admitted) + list(shed)
                            + list(rejected) + list(leftover))
        assert everything == sorted(f"s{i}" for i in range(8)), (
            f"seed {seed}: lost/duplicated streams: {everything}")
        # the pure planner respects budget/chunk caps on every step
        assert all(sum(p) <= 3 and all(c <= 2 for c in p)
                   for p in plans), plans
        assert run_once(seed) == first, f"seed {seed} not deterministic"
