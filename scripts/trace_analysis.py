#!/usr/bin/env python
"""Analyze a jax.profiler trace (the Chrome-trace JSON the TPU runtime
writes under ``<dir>/plugins/profile/*/vm.trace.json.gz``) into the
step-time accounting VERDICT r4 asked for: top step-time consumers with
% of step, per-op HBM bytes accessed, and an implied-bandwidth roofline
check.

Usage::

    python scripts/trace_analysis.py logs/trace_b256 \
        --steps-per-module 8 --out logs/trace_analysis_r05.json

``--steps-per-module`` is the bench's inner_steps (one XLA module
execution = that many optimizer steps under the lax.scan).

Method: XLA op events carry ``bytes_accessed`` and device durations.
``while`` ops are inclusive containers (their body ops appear as
separate events in the same lane), so totals sum NON-while ops only;
op events are further restricted to the matched train-module
``[ts, ts+dur]`` windows, so warmup/compile/probe ops captured in the
same trace cannot inflate ms/step or GB/step (ADVICE r5). The
roofline verdict compares implied bandwidth (bytes/duration) to the
HBM spec — implied ≈ spec means the step is memory-bound and the
optimization lever is traffic, not scheduling. ``bytes_accessed`` is
XLA's cost-model estimate (fusion operand bytes, not measured DMA),
so implied bandwidth above spec is reported as an accounting
artifact, never as measured saturation.

Note: this analyzes *profiler* traces (XLA op timelines captured by
``jax.profiler`` — see the obs server's ``/profile`` endpoint and
``TrainerConfig.profile_dir``).  Per-request *tracing* — trace_id,
phase spans, ``/traces/<id>`` — is the other kind of trace and lives
in ``perceiver_tpu/obs/trace.py`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import bisect
import collections
import glob
import gzip
import json
import os
import re
import sys

# v5e HBM ~819 GB/s — the default for --hbm-gbps; the trace itself
# does not carry the device kind, so pass the right number when the
# trace came from a different chip (v4 ~1228, v5p ~2765).
DEFAULT_HBM_GBPS = 819.0
DEFAULT_HBM_KIND = "TPU v5 lite (assumed; override with --hbm-gbps)"


def load_trace(trace_dir: str) -> dict:
    pats = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not pats:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(pats[-1]) as f:
        return json.load(f)


def classify(long_name: str, name: str) -> str:
    """Bucket an HLO op into a human attribution for the report.

    The shape signatures are the flagship MLM config's (B, 4 heads,
    64 latents, 512 tokens, vocab 10003) — attribution degrades to
    "other" gracefully on different configs.
    """
    ln = long_name or ""
    if "10003" in ln or re.search(r"\b100[0-9]{2}\b", ln):
        return "vocab-CE region (logits/log-softmax/vocab matmuls)"
    if "dynamic-update-slice" in name or "dynamic-slice" in name:
        return "scan residual stacking (saved activations for backward)"
    if re.search(r"f32\[\d+,\d+,4,64,512\]|f32\[\d+,4,64,512\]", ln):
        return "fp32 cross-attention weights (materialized)"
    if re.search(r"f32\[\d+,\d+,4,512,64\]|f32\[\d+,4,512,64\]", ln):
        return "fp32 decoder-attention weights (materialized)"
    if re.search(r"\[(\d+,)?\d+,4,64,64\]|\[(\d+,)?\d+,4,16,64\]"
                 r"|\[(\d+,)?\d+,4,16,512\]", ln):
        return "self-attention inner (weights/softmax/head reshapes)"
    if re.search(r"\[(1,)?6,\d+,4?,?64,64\]|\[6,\d+,64", ln):
        return "self-attn block residuals/copies (6-layer scan)"
    if re.search(r"s32\[131072\]|u32\[\d+,64\]|\[2044\d", ln):
        return "packed-CE position packing (cumsum/scatter)"
    if re.search(r"f32\[\d+,512\]|f32\[\d+,512,64\]", ln):
        return "layernorm / token-array elementwise (fp32)"
    if "convolution" in ln or "dot" in ln:
        return "matmul"
    if name.startswith("copy"):
        return "layout copies"
    if name.startswith("while"):
        return "while"
    return "other"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--steps-per-module", type=int, required=True,
                    help="optimizer steps per XLA module execution "
                         "(bench inner_steps)")
    ap.add_argument("--module-re", default=r"jit_train_steps",
                    help="regex naming the train-step module")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--hbm-gbps", type=float, default=DEFAULT_HBM_GBPS,
                    help="HBM spec bandwidth of the chip the trace was "
                         "captured on (default: v5e 819)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    tr = load_trace(args.trace_dir)
    ev = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
    tids = {}
    for e in tr["traceEvents"]:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e["tid"])] = e["args"].get("name")
    # ALL lanes per name — the dict inversion {name: (pid,tid)} kept
    # one arbitrary lane per name, silently analyzing whichever device
    # survived on multi-device traces (ADVICE r5)
    lanes = collections.defaultdict(list)
    for key, name in tids.items():
        lanes[name].append(key)
    mod_lanes = sorted(lanes.get("XLA Modules", []))
    op_lanes = sorted(lanes.get("XLA Ops", []))
    if len(mod_lanes) > 1 or len(op_lanes) > 1:
        # per-device accounting must not be summed into one ms/step;
        # fail loudly instead of silently picking a device
        raise SystemExit(
            f"multi-device trace: {len(mod_lanes)} 'XLA Modules' lanes "
            f"{mod_lanes} / {len(op_lanes)} 'XLA Ops' lanes {op_lanes}. "
            "Per-step totals are per-device; re-capture a single-device "
            "trace or strip the trace to one device's lanes first.")
    if not mod_lanes:
        raise SystemExit("trace has no 'XLA Modules' lane")
    mods = sorted((e for e in ev if (e["pid"], e["tid"]) == mod_lanes[0]
                   and re.search(args.module_re, e["name"])),
                  key=lambda e: e["ts"])
    if not mods:
        raise SystemExit(f"no module matching {args.module_re!r}")
    n_steps = len(mods) * args.steps_per_module
    busy_s = sum(m["dur"] for m in mods) / 1e6
    span_s = (mods[-1]["ts"] + mods[-1]["dur"] - mods[0]["ts"]) / 1e6
    gaps_ms = [(mods[i]["ts"] - mods[i - 1]["ts"] - mods[i - 1]["dur"]) / 1e3
               for i in range(1, len(mods))]

    # restrict per-step totals to ops inside the matched module
    # execution windows: a capture routinely also holds warmup,
    # compile-time, and probe ops, which otherwise inflate ms/step and
    # GB/step (this is what produced the round-5 ">100% of spec"
    # number). Midpoint containment tolerates µs rounding at edges.
    starts = [m["ts"] for m in mods]
    ends = [m["ts"] + m["dur"] for m in mods]

    def in_module_window(e) -> bool:
        mid = e["ts"] + e.get("dur", 0) / 2.0
        i = bisect.bisect_right(starts, mid) - 1
        return i >= 0 and mid <= ends[i]

    ops_all = [e for e in ev
               if op_lanes and (e["pid"], e["tid"]) == op_lanes[0]]
    ops = [e for e in ops_all if in_module_window(e)]
    n_outside = len(ops_all) - len(ops)
    per_op = collections.defaultdict(lambda: [0, 0.0, 0, "", ""])
    tot_d = tot_b = 0.0
    for e in ops:
        a = e.get("args", {})
        if a.get("hlo_category") == "while":
            continue  # inclusive container; bodies are separate events
        b = int(a.get("bytes_accessed", 0))
        tot_d += e["dur"]
        tot_b += b
        rec = per_op[e["name"]]
        rec[0] += 1
        rec[1] += e["dur"]
        rec[2] += b
        if not rec[3]:
            rec[3] = a.get("long_name", "")[:220]
            rec[4] = a.get("hlo_category", "")
    step_ms = tot_d / 1e3 / n_steps
    step_gb = tot_b / 1e9 / n_steps
    implied_gbps = tot_b / (tot_d / 1e6) / 1e9 if tot_d else 0.0

    top = []
    for name, (cnt, d, b, long_name, cat) in sorted(
            per_op.items(), key=lambda kv: -kv[1][1])[:args.top]:
        top.append({
            "op": name,
            "hlo_category": cat,
            "ms_per_step": round(d / 1e3 / n_steps, 3),
            "pct_of_step": round(100 * d / tot_d, 2),
            "mb_per_step": round(b / 1e6 / n_steps, 1),
            "gbps": round(b / (d / 1e6) / 1e9, 0) if d else None,
            "runs_per_step": round(cnt / n_steps, 1),
            "attribution": classify(long_name, name),
            "long_name": long_name,
        })

    buckets = collections.defaultdict(lambda: [0.0, 0])
    for name, (cnt, d, b, long_name, _cat) in per_op.items():
        k = classify(long_name, name)
        buckets[k][0] += d
        buckets[k][1] += b
    bucket_rows = sorted(
        ({"bucket": k,
          "ms_per_step": round(d / 1e3 / n_steps, 2),
          "pct_of_step": round(100 * d / tot_d, 1),
          "gb_per_step": round(b / 1e9 / n_steps, 2)}
         for k, (d, b) in buckets.items()),
        key=lambda r: -r["ms_per_step"])

    report = {
        "trace_dir": args.trace_dir,
        "module": mods[0]["name"].split("(")[0],
        "module_executions": len(mods),
        "steps_per_module": args.steps_per_module,
        "ops_outside_module_windows_dropped": n_outside,
        "device_busy_s": round(busy_s, 3),
        "trace_span_s": round(span_s, 3),
        "dispatch_gaps_ms": [round(g, 1) for g in gaps_ms],
        "per_step": {
            "device_ms": round(step_ms, 2),
            "hbm_gb_accessed": round(step_gb, 2),
        },
        "implied_bandwidth_gbps": round(implied_gbps, 0),
        "roofline": None,
        "top_ops": top,
        "buckets": bucket_rows,
    }
    kind = (DEFAULT_HBM_KIND if args.hbm_gbps == DEFAULT_HBM_GBPS
            else f"{args.hbm_gbps:.0f} GB/s chip")
    frac = implied_gbps / args.hbm_gbps
    if frac > 1.0:
        # physically impossible as a measurement: bytes_accessed is
        # XLA's cost-model estimate (fusion operand bytes, not DMA
        # counters), so > spec means the estimate over-counts (or the
        # --hbm-gbps spec is wrong for this chip) — never "the chip
        # exceeds its memory system" (ADVICE r5)
        report["roofline"] = (
            f"implied bandwidth {implied_gbps:.0f} GB/s is "
            f"{100 * frac:.0f}% of {kind} spec ({args.hbm_gbps:.0f} "
            "GB/s) — ACCOUNTING ARTIFACT: bytes_accessed is a "
            "cost-model estimate, not measured DMA traffic; treat the "
            "step as HBM-bound but do not quote this as measured "
            "saturation")
    elif frac > 0.7:
        report["roofline"] = (
            f"implied bandwidth {implied_gbps:.0f} GB/s is "
            f"{100 * frac:.0f}% of {kind} spec ({args.hbm_gbps:.0f} "
            "GB/s): the step is HBM-BOUND — reduce bytes/step, not "
            "schedule")
    else:
        report["roofline"] = (
            f"implied bandwidth {implied_gbps:.0f} GB/s is only "
            f"{100 * frac:.0f}% of {kind} spec: overhead/latency "
            "bound, not bandwidth")
    out = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    print(out)


if __name__ == "__main__":
    main()
