"""Best-effort dataset download (reference ``data/imdb.py:92-94`` /
torchvision MNIST semantics: fetch when absent, behind the same
datamodule surface).

Zero-egress environments are first-class: every fetch is wrapped, uses
a short connect timeout, and returns False on any failure so callers
fall back (to local files or synthetic data) instead of crashing.
``PERCEIVER_TPU_OFFLINE=1`` skips attempts entirely.
"""

from __future__ import annotations

import os
import shutil
import tarfile


def offline() -> bool:
    return os.environ.get("PERCEIVER_TPU_OFFLINE", "") not in ("", "0")


# URLs that already failed in this process — retried next process, but
# never within one (a firewalled host must not stall repeatedly on the
# same connect timeout during a single run)
_failed_urls: set = set()


def fetch(url: str, dest: str, timeout: float = 15.0) -> bool:
    """Download ``url`` to ``dest`` atomically. False on any failure.
    The temp name is per-process so concurrent callers (multi-host
    runs sharing a data_dir) never interleave writes; last finished
    rename wins, each with a complete file."""
    if offline() or url in _failed_urls:
        return False
    tmp = f"{dest}.part.{os.getpid()}"
    try:
        import urllib.request
        with urllib.request.urlopen(url, timeout=timeout) as r, \
                open(tmp, "wb") as f:
            shutil.copyfileobj(r, f)
        os.replace(tmp, dest)
        return True
    except Exception:
        _failed_urls.add(url)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def extract_tgz(path: str, dest_dir: str) -> bool:
    """Extract a .tar.gz safely (no paths escaping ``dest_dir``).
    On failure the archive is deleted so the next run re-fetches
    instead of being stuck on a corrupt cached file."""
    try:
        with tarfile.open(path, "r:gz") as tf:
            try:
                tf.extractall(dest_dir, filter="data")
            except TypeError:
                # filter= landed in 3.10.12/3.11.4; older patch
                # releases get a conservative manual check instead:
                # no links at all (symlink members could redirect
                # later writes outside dest_dir) and no names
                # escaping dest_dir ("." itself is fine)
                base = os.path.realpath(dest_dir)
                for m in tf.getmembers():
                    if not (m.isfile() or m.isdir()):
                        # no links (could redirect later writes), no
                        # devices/FIFOs — what filter="data" rejects
                        raise ValueError(f"special tar member {m.name}")
                    target = os.path.realpath(
                        os.path.join(dest_dir, m.name))
                    if not (target == base or
                            target.startswith(base + os.sep)):
                        raise ValueError(f"unsafe tar member {m.name}")
                tf.extractall(dest_dir)
        return True
    except Exception:
        try:
            os.unlink(path)
        except OSError:
            pass
        return False
