"""UResNet: shapes, BatchNorm state threading, gradient flow.

Mirrors SURVEY.md §4 plan (a)/(b): unit coverage the reference never
had for ``uresnet.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.models.uresnet import UResNet
from perceiver_tpu.ops.conv import (
    batch_norm_apply,
    batch_norm_init,
    conv_apply,
    conv_init,
    conv_transpose_apply,
)
from perceiver_tpu.ops.policy import Policy

FP32 = Policy.fp32()


def test_conv_shapes():
    key = jax.random.key(0)
    p = conv_init(key, 3, 8, kernel=3)
    x = jnp.ones((2, 16, 16, 3))
    assert conv_apply(p, x, policy=FP32).shape == (2, 16, 16, 8)
    assert conv_apply(p, x, stride=2, policy=FP32).shape == (2, 8, 8, 8)


def test_conv_transpose_doubles():
    key = jax.random.key(0)
    p = {"w": jax.random.normal(key, (3, 3, 8, 4))}
    x = jnp.ones((2, 8, 8, 8))
    assert conv_transpose_apply(p, x, policy=FP32).shape == (2, 16, 16, 4)


def test_batch_norm_train_vs_eval():
    params, state = batch_norm_init(4)
    x = jax.random.normal(jax.random.key(1), (8, 4, 4, 4)) * 3.0 + 1.0
    y, new_state = batch_norm_apply(params, state, x, train=True,
                                    policy=FP32)
    # train mode normalizes with batch stats
    np.testing.assert_allclose(np.mean(y, axis=(0, 1, 2)), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.std(y, axis=(0, 1, 2)), 1.0, atol=1e-3)
    # running stats moved toward the batch stats
    assert not np.allclose(new_state["mean"], state["mean"])
    # eval mode uses running stats, state unchanged
    y2, s2 = batch_norm_apply(params, new_state, x, train=False,
                              policy=FP32)
    assert s2 is new_state
    assert not np.allclose(np.asarray(y), np.asarray(y2))


@pytest.fixture(scope="module")
def tiny_uresnet():
    model = UResNet(num_classes=3, input_channels=1, inplanes=4,
                    head_kernels=4)
    variables = model.init(jax.random.key(0))
    return model, variables


def test_uresnet_output_shape(tiny_uresnet):
    model, variables = tiny_uresnet
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 1))
    logits, _ = model.apply(variables, x, train=False, policy=FP32)
    assert logits.shape == (2, 32, 32, 3)
    assert np.isfinite(np.asarray(logits)).all()


def test_uresnet_train_updates_bn_state(tiny_uresnet):
    model, (params, state) = tiny_uresnet
    x = jax.random.normal(jax.random.key(2), (2, 32, 32, 1)) * 2.0
    logits, new_state = model.apply((params, state), x, train=True,
                                    policy=FP32)
    before = state["stem1"]["bn"]["mean"]
    after = new_state["stem1"]["bn"]["mean"]
    assert not np.allclose(np.asarray(before), np.asarray(after))
    jax.tree.map(lambda a: None, new_state)  # same treedef as state
    assert (jax.tree.structure(new_state) == jax.tree.structure(state))


def test_uresnet_gradients_flow(tiny_uresnet):
    model, (params, state) = tiny_uresnet
    # 32×32 batch 2 keeps the deepest stage's BN over >1 element —
    # normalizing a single element zeroes its gradient by construction
    x = jax.random.normal(jax.random.key(3), (2, 32, 32, 1))
    labels = jnp.zeros((2, 32, 32), jnp.int32)

    @jax.jit
    def loss_fn(p):
        logits, _ = model.apply((p, state), x, train=True, policy=FP32)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, labels[..., None], -1).mean()

    grads = jax.grad(loss_fn)(params)
    norms = [float(jnp.linalg.norm(g))
             for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    # every learned tensor receives gradient (BN biases included)
    assert all(n > 0 for n in norms)
