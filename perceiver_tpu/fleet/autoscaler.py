"""Occupancy-driven autoscaler for the serving fleet.

Deterministic by construction: no timer thread — the owner calls
``tick()`` (tests drive ticks directly; a deployment loop calls it on
its own cadence). Each tick samples the router's mean in-flight per
replica and applies hysteresis: only ``consecutive`` samples past a
threshold trigger a resize, and every resize resets the streak — so a
single bursty sample never flaps the fleet. Bounds are hard:
``min_replicas <= size <= max_replicas`` always (docs/SERVING.md
"Fleet").

Multi-tenancy: :func:`allocate_replicas` turns the router's observed
per-tenant demand (``router.tenant_demand()``) into a per-tenant
replica allocation over the current pool via the same deterministic
largest-remainder arithmetic the decode planner uses — so capacity
planning and token planning agree on what "fair share" means
(docs/SERVING.md "Multi-tenancy").
"""

from __future__ import annotations

from typing import Dict, Optional

from perceiver_tpu.serving.tenancy import weighted_fair_shares


def allocate_replicas(demand: Dict[str, float],
                      replicas: int) -> Dict[str, int]:
    """Split ``replicas`` across tenants proportionally to observed
    ``demand`` (e.g. in-flight counts), deterministic largest-remainder.

    Zero/negative demand entries still appear in the result (with 0
    unless the floor-of-one pass can lift them); with no positive
    demand at all, replicas split evenly so an idle fleet stays
    balanced rather than collapsing onto one tenant.
    """
    if replicas < 0:
        raise ValueError(f"replicas must be >= 0, got {replicas}")
    if not demand:
        return {}
    weights = {t: max(0.0, float(d)) for t, d in demand.items()}
    if not any(weights.values()):
        weights = {t: 1.0 for t in weights}
    return weighted_fair_shares(replicas, weights)


class Autoscaler:
    """Scale a :class:`~perceiver_tpu.fleet.supervisor.Fleet` (or any
    object with ``size()``/``scale_to(n)``/``router.occupancy()``)
    between ``min_replicas`` and ``max_replicas``."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_above: float = 1.5,
                 scale_down_below: float = 0.25,
                 consecutive: int = 3):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}")
        if scale_down_below >= scale_up_above:
            raise ValueError("scale_down_below must sit strictly under "
                             "scale_up_above (hysteresis band)")
        if consecutive < 1:
            raise ValueError("consecutive must be >= 1")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.scale_up_above = scale_up_above
        self.scale_down_below = scale_down_below
        self.consecutive = consecutive
        self._fleet = None
        self._up_streak = 0
        self._down_streak = 0
        self.resizes: list = []  # (direction, new_size) audit trail

    def bind(self, fleet) -> None:
        self._fleet = fleet

    def allocation(self) -> Dict[str, int]:
        """Per-tenant replica allocation for the current pool, from
        the router's observed demand. Purely advisory (the router
        still load-balances every request); deployments use it to
        decide which tenants justify the next scale-up."""
        fleet = self._fleet
        if fleet is None:
            raise RuntimeError("autoscaler not bound to a fleet")
        demand = fleet.router.tenant_demand()
        return allocate_replicas(demand, fleet.size())

    def tick(self) -> Optional[int]:
        """Sample once; returns the new size if this tick resized,
        else None. Enforces the min bound even without load (a fleet
        below ``min_replicas`` — e.g. poisoned slots — scales up)."""
        fleet = self._fleet
        if fleet is None:
            raise RuntimeError("autoscaler not bound to a fleet")
        size = fleet.size()
        if size < self.min_replicas:
            fleet.scale_to(self.min_replicas)
            self._up_streak = self._down_streak = 0
            self.resizes.append(("up", self.min_replicas))
            return self.min_replicas
        occupancy = fleet.router.occupancy()
        if occupancy > self.scale_up_above:
            self._up_streak += 1
            self._down_streak = 0
        elif occupancy < self.scale_down_below:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if self._up_streak >= self.consecutive \
                and size < self.max_replicas:
            self._up_streak = self._down_streak = 0
            fleet.scale_to(size + 1)
            self.resizes.append(("up", size + 1))
            return size + 1
        if self._down_streak >= self.consecutive \
                and size > self.min_replicas:
            self._up_streak = self._down_streak = 0
            fleet.scale_to(size - 1)
            self.resizes.append(("down", size - 1))
            return size - 1
        return None
