"""Sharded (pjit/GSPMD) canonical train step.

The single-device train step (``analysis/targets.make_train_step``)
becomes an SPMD program by declaring shardings, not by rewriting
math: params take the tensor-parallel layout from
``parallel/sharding.param_spec``, optimizer moments take the
ZeRO-style layout from ``parallel/sharding.zero_sharding`` (no device
holds a full copy of any large moment), and the batch splits over the
``data`` axis. GSPMD inserts the gradient all-reduces and
tensor-parallel collectives at compile time; the shardcheck passes
(``analysis/shardcheck``) then gate what it inserted — bytes moved per
mesh axis, no large replicated residents, per-shard HBM.

Every ``jax.jit`` here carries explicit ``in_shardings`` /
``out_shardings``: silent propagation is how replication sneaks in,
and the ``unsharded-pjit`` lint rule enforces exactly that on this
module.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from perceiver_tpu.parallel.sharding import param_sharding, zero_sharding


def sharded_batch_sharding(batch, mesh: Mesh):
    """Leading-axis (data-parallel) shardings for a batch pytree."""
    return jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P("data")), batch)


def make_sharded_train_step(task, batch, mesh: Mesh):
    """The canonical pjit optimizer step over a data×model mesh:
    forward + backward + AdamW with (params, opt_state) donated, every
    argument and result under an explicit sharding. Returns
    ``(jitted_fn, args)`` with the same calling convention as
    ``make_train_step`` so ``analysis/targets.lower_target`` treats
    both uniformly."""
    import optax

    from perceiver_tpu.ops.policy import Policy

    model = task.build()
    policy = Policy.bf16()
    params = model.init(jax.random.key(0))
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    p_shard = param_sharding(params, mesh)
    o_shard = zero_sharding(opt_state, mesh)
    b_shard = sharded_batch_sharding(batch, mesh)
    replicated = NamedSharding(mesh, P())

    @partial(jax.jit,
             in_shardings=(p_shard, o_shard, b_shard, replicated),
             out_shardings=(p_shard, o_shard, replicated),
             donate_argnums=(0, 1))
    def train_step(params, opt_state, batch_i, key):
        def loss_fn(p):
            loss, _ = task.loss_and_metrics(
                model, p, batch_i, rng=key, deterministic=False,
                policy=policy)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return train_step, (params, opt_state, batch, jax.random.key(1))
