"""make_coherence_corpus: the VERDICT-r2 #4 relabeling must produce
balanced, style-pure, genuinely coherence-separated examples — the
properties the transfer-wins claim rests on."""

import importlib.util
import os

import pytest

_SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                       "make_coherence_corpus.py")


@pytest.fixture(scope="module")
def mcc():
    spec = importlib.util.spec_from_file_location("mcc", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _doc(marker: str, n_sents: int = 14) -> str:
    # every sentence carries its doc marker, so provenance of any half
    # is recoverable from the output text
    return " ".join(f"the {marker} topic sentence number {i} continues "
                    f"with enough words to be counted."
                    for i in range(n_sents))


def test_halves_are_sentence_aligned_and_consecutive(mcc):
    doc = _doc("alpha")
    h, t = mcc.halves(doc, half_chars=200)
    assert h in doc and t in doc
    assert doc.index(t) > doc.index(h)
    # sentence-aligned: both end at a sentence boundary
    assert h.endswith(".") and t.endswith(".")
    # consecutive: head + tail is a contiguous span of the doc
    assert f"{h} {t}" in doc


def test_halves_rejects_short_docs(mcc):
    assert mcc.halves(_doc("beta", n_sents=2), half_chars=400) is None


def test_build_split_balance_and_provenance(mcc, tmp_path):
    src = tmp_path / "src" / "train"
    for style in ("neg", "pos"):
        d = src / style
        d.mkdir(parents=True)
        for i in range(8):
            (d / f"{i}_5.txt").write_text(_doc(f"{style}doc{i}"))
    out = tmp_path / "out" / "train"
    import glob as _glob
    style_files = {style: sorted(_glob.glob(str(src / style / "*.txt")))
                   for style in ("neg", "pos")}
    stats = mcc.build_split(style_files, str(out), half_chars=200,
                            seed=0)
    assert stats["pos"] == stats["neg"] > 0

    import glob
    import re

    def markers(text):
        return set(re.findall(r"(negdoc\d+|posdoc\d+)", text))

    for path in glob.glob(str(out / "pos" / "*.txt")):
        with open(path) as f:
            ms = markers(f.read())
        assert len(ms) == 1, f"coherent example mixes docs: {ms}"
    for path in glob.glob(str(out / "neg" / "*.txt")):
        with open(path) as f:
            ms = markers(f.read())
        assert len(ms) == 2, f"spliced example not from 2 docs: {ms}"
        # style purity: a splice never crosses the API/prose classes
        styles = {m[:3] for m in ms}
        assert len(styles) == 1, f"splice crosses styles: {ms}"
