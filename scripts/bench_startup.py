#!/usr/bin/env python
"""Cold vs warm time-to-first-dispatch, with the persistent compile
cache (``perceiver_tpu/cache``) as the only variable.

Measures the two startup bills the cache was built to kill:

- ``serving``: ``ServingEngine`` construction + full bucket-grid
  warmup + one dispatched-and-materialized request;
- ``trainer``: the first train-step dispatch
  (``step_flops_and_fn`` AOT path + one executed step).

Each phase runs in a FRESH subprocess — executable caches only matter
across processes, and an in-process re-run would hit jit's own live
cache and prove nothing. The cold run starts from an empty cache
directory (and populates it); the warm run replays against it. Emits
one ``bench.py``-format JSON line per phase pair::

    {"metric": "serving_warm_start_speedup", "value": ..., "unit":
     "x", "vs_baseline": null, "detail": {"cold_s": ..., "warm_s":
     ..., "warm_xla_compiles": 0, ...}}

On CPU use the (default) tiny preset — the point is the contract
(warm compiles = 0) and the shape of the win, not its chip-scale
magnitude::

    JAX_PLATFORMS=cpu python scripts/bench_startup.py
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _tiny_mlm_task():
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    return MaskedLanguageModelTask(
        vocab_size=128, max_seq_len=64, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")


def _canonical_mlm_task():
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    return MaskedLanguageModelTask(vocab_size=10003, max_seq_len=512)


def _buckets(preset: str):
    if preset == "tiny":
        return (1, 4), (16, 32)
    return (1, 8, 32), (128, 512)


def _compile_event_counter():
    import jax

    events = []
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name)
        if "compile" in name else None)
    return events


def _phase_serving(cache_dir: str, preset: str) -> dict:
    import numpy as np

    from perceiver_tpu.serving import ServingEngine, materialize

    task = _tiny_mlm_task() if preset == "tiny" else _canonical_mlm_task()
    batch_buckets, seq_buckets = _buckets(preset)
    t0 = time.perf_counter()
    engine = ServingEngine(task, batch_buckets=batch_buckets,
                           seq_buckets=seq_buckets, exec_cache=cache_dir,
                           warmup=False)
    # events scoped to the warmup+dispatch contract — params init
    # above legitimately compiles small host-side ops either way
    events = _compile_event_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    rng = np.random.default_rng(0)
    ids = rng.integers(3, task.vocab_size,
                       (batch_buckets[0], seq_buckets[0])).astype(np.int32)
    arrays = {"input_ids": ids,
              "pad_mask": np.zeros(ids.shape, bool)}
    materialize(engine.dispatch(arrays), engine.graph)
    m = engine.metrics
    return {
        "ttfd_s": time.perf_counter() - t0,
        "warmup_s": warmup_s,
        "buckets": len(engine.buckets),
        "xla_compiles": len(events),
        "engine_compiles": engine.compile_count,
        "exec_cache_hits": m.get("serving_exec_cache_hits_total").value,
        "exec_cache_misses": m.get(
            "serving_exec_cache_misses_total").value,
    }


def _phase_trainer(cache_dir: str, preset: str) -> dict:
    import jax

    from perceiver_tpu.analysis.targets import make_train_step
    from perceiver_tpu.cache import default_cache
    from perceiver_tpu.utils.flops import step_flops_and_fn

    task = _tiny_mlm_task() if preset == "tiny" else _canonical_mlm_task()
    import numpy as np

    batch = 8 if preset == "tiny" else 64
    rng = np.random.default_rng(0)
    data = {
        "input_ids": rng.integers(
            3, task.vocab_size,
            (batch, task.max_seq_len)).astype(np.int32),
        "pad_mask": np.zeros((batch, task.max_seq_len), bool),
    }
    step, args = make_train_step(task, data)
    cache = default_cache(cache_dir)
    events = _compile_event_counter()
    t0 = time.perf_counter()
    flops, fn = step_flops_and_fn(step, *args, cache=cache,
                                  cache_label="bench_startup:train")
    out = fn(*args)
    jax.block_until_ready(out)
    return {
        "first_step_s": time.perf_counter() - t0,
        "step_flops": flops,
        "xla_compiles": len(events),
        "exec_cache_hits": cache.stats.hits,
        "exec_cache_misses": cache.stats.misses,
    }


_PHASES = {"serving": _phase_serving, "trainer": _phase_trainer}


def _run_child(phase: str, cache_dir: str, preset: str) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__), "--phase", phase,
           "--cache-dir", cache_dir, "--preset", preset]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          cwd=_REPO, timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(
            f"phase {phase} failed:\n{proc.stdout}\n{proc.stderr}")
    # last stdout line is the phase's JSON record
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser(
        description="cold vs warm time-to-first-dispatch bench")
    ap.add_argument("--preset", default="tiny",
                    choices=["tiny", "canonical"],
                    help="tiny: CPU-sized model (default); canonical: "
                         "the pinned MLM serve/train shapes")
    ap.add_argument("--cache-dir", default=None,
                    help="cache directory (default: a fresh temp dir, "
                         "removed afterwards unless --keep-cache)")
    ap.add_argument("--keep-cache", action="store_true",
                    help="leave the populated cache dir behind")
    ap.add_argument("--out", default=None,
                    help="also append the result lines to this path")
    ap.add_argument("--phase", default=None, choices=sorted(_PHASES),
                    help=argparse.SUPPRESS)  # internal: child mode
    args = ap.parse_args()

    if args.phase:
        # child mode: one measurement in THIS process, JSON to stdout
        print(json.dumps(_PHASES[args.phase](args.cache_dir,
                                             args.preset)), flush=True)
        return 0

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="exec-cache-")
    os.makedirs(cache_dir, exist_ok=True)
    results = []
    try:
        for phase in ("serving", "trainer"):
            print(f"[bench_startup] {phase}: cold run ...",
                  file=sys.stderr, flush=True)
            cold = _run_child(phase, cache_dir, args.preset)
            print(f"[bench_startup] {phase}: warm run ...",
                  file=sys.stderr, flush=True)
            warm = _run_child(phase, cache_dir, args.preset)
            key = "ttfd_s" if phase == "serving" else "first_step_s"
            detail = {
                "preset": args.preset,
                "cold_s": round(cold[key], 4),
                "warm_s": round(warm[key], 4),
                "cold_xla_compiles": cold["xla_compiles"],
                "warm_xla_compiles": warm["xla_compiles"],
                "warm_exec_cache_hits": warm["exec_cache_hits"],
                "warm_exec_cache_misses": warm["exec_cache_misses"],
            }
            if phase == "serving":
                detail["buckets"] = cold["buckets"]
            result = {
                "metric": f"{phase}_warm_start_speedup",
                "value": round(cold[key] / max(warm[key], 1e-9), 3),
                "unit": "x",
                "vs_baseline": None,
                "detail": detail,
            }
            results.append(result)
            print(json.dumps(result), flush=True)
    finally:
        if not args.keep_cache and args.cache_dir is None:
            shutil.rmtree(cache_dir, ignore_errors=True)
    if args.out:
        with open(args.out, "a") as f:
            for result in results:
                f.write(json.dumps(result) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
