"""Model tests: encoder/decoder/IO/MLM shapes, masking stats, recurrence."""

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_tpu.adapters import (
    ClassificationOutputAdapter,
    ImageInputAdapter,
    TextInputAdapter,
    TextOutputAdapter,
)
from perceiver_tpu.models import (
    PerceiverEncoder,
    PerceiverDecoder,
    PerceiverIO,
    PerceiverMLM,
    TextMasking,
)
from perceiver_tpu.models.masking import IGNORE_INDEX
from perceiver_tpu.ops import Policy

FP32 = Policy.fp32()


def make_image_io(num_layers=3):
    input_adapter = ImageInputAdapter(image_shape=(28, 28, 1),
                                      num_frequency_bands=32)
    output_adapter = ClassificationOutputAdapter(num_classes=10)
    encoder = PerceiverEncoder(
        input_adapter=input_adapter, latent_shape=(32, 128),
        num_layers=num_layers, num_self_attention_layers_per_block=3)
    decoder = PerceiverDecoder(output_adapter=output_adapter,
                               latent_shape=(32, 128),
                               num_cross_attention_heads=1)
    return PerceiverIO(encoder, decoder)


def test_perceiver_io_image_classifier_shapes():
    model = make_image_io()
    params = model.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    logits = model.apply(params, x, policy=FP32)
    assert logits.shape == (2, 10)
    assert np.isfinite(np.asarray(logits)).all()


def test_encoder_returns_latent_and_pad_mask():
    model = make_image_io()
    params = model.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    latent, pad = model.encoder.apply(params["encoder"], x, policy=FP32)
    assert latent.shape == (2, 32, 128)
    assert pad is None


def test_encoder_weight_shared_recurrence_changes_output():
    """num_layers=1 vs 3 must differ; layer_n params shared across
    iterations (reference model.py:162-166,185-187)."""
    m1, m3 = make_image_io(1), make_image_io(3)
    p3 = m3.init(jax.random.key(0))
    assert "layer_n" not in m1.init(jax.random.key(0))["encoder"]
    x = jax.random.normal(jax.random.key(1), (1, 28, 28, 1))
    l3, _ = m3.encoder.apply(p3["encoder"], x, policy=FP32)
    # manually: one layer_1 pass only
    p1 = {k: v for k, v in p3["encoder"].items() if k != "layer_n"}
    l1, _ = m1.encoder.apply(p1, x, policy=FP32)
    assert not np.allclose(np.asarray(l1), np.asarray(l3), atol=1e-4)


def test_latent_init_statistics():
    model = make_image_io()
    params = model.init(jax.random.key(0))
    lat = np.asarray(params["encoder"]["latent"])
    assert lat.shape == (32, 128)
    assert np.all(np.abs(lat) <= 2.0)
    assert 0.01 < lat.std() < 0.03  # N(0, 0.02)


def test_decoder_validates_latent_shape():
    model = make_image_io()
    params = model.init(jax.random.key(0))
    try:
        model.decoder.apply(params["decoder"], jnp.zeros((2, 16, 128)),
                            policy=FP32)
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_decoder_query_chunking_is_exact():
    output_adapter = ClassificationOutputAdapter(
        num_classes=3, num_outputs=64, num_output_channels=16)
    dec_full = PerceiverDecoder(output_adapter=output_adapter,
                                latent_shape=(8, 32))
    dec_chunk = PerceiverDecoder(output_adapter=output_adapter,
                                 latent_shape=(8, 32), query_chunk_size=16)
    params = dec_full.init(jax.random.key(0))
    latent = jax.random.normal(jax.random.key(1), (2, 8, 32))
    y_full = dec_full.apply(params, latent, policy=FP32)
    y_chunk = dec_chunk.apply(params, latent, policy=FP32)
    np.testing.assert_allclose(np.asarray(y_full), np.asarray(y_chunk),
                               atol=1e-5)


def make_mlm(vocab_size=100, max_seq_len=32):
    input_adapter = TextInputAdapter(vocab_size=vocab_size,
                                     max_seq_len=max_seq_len,
                                     num_input_channels=64)
    output_adapter = TextOutputAdapter(vocab_size=vocab_size,
                                       max_seq_len=max_seq_len,
                                       num_output_channels=64)
    encoder = PerceiverEncoder(input_adapter=input_adapter,
                               latent_shape=(16, 64), num_layers=2,
                               num_self_attention_layers_per_block=2)
    decoder = PerceiverDecoder(output_adapter=output_adapter,
                               latent_shape=(16, 64))
    masking = TextMasking(vocab_size=vocab_size, unk_token_id=1,
                          mask_token_id=2, num_special_tokens=3)
    return PerceiverMLM(encoder, decoder, masking)


def test_mlm_forward_with_masking():
    model = make_mlm()
    params = model.init(jax.random.key(0))
    x = jax.random.randint(jax.random.key(1), (2, 20), 3, 100)
    pad = jnp.zeros((2, 20), bool).at[:, 16:].set(True)
    logits, labels = model.apply(params, x, pad, rng=jax.random.key(2),
                                 policy=FP32)
    # logits sliced to input length (reference model.py:316)
    assert logits.shape == (2, 20, 100)
    assert labels.shape == (2, 20)


def test_mlm_forward_without_masking():
    model = make_mlm()
    params = model.init(jax.random.key(0))
    x = jax.random.randint(jax.random.key(1), (2, 20), 3, 100)
    logits, labels = model.apply(params, x, masking=False, policy=FP32)
    assert logits.shape == (2, 20, 100)
    assert labels is None


def test_text_masking_statistics():
    """Net corruption stats: 15% selected; of those 80% MASK, 10%
    random, 10% unchanged (reference model.py:276-289)."""
    masking = TextMasking(vocab_size=1000, unk_token_id=1, mask_token_id=2,
                          num_special_tokens=3)
    x = jax.random.randint(jax.random.key(0), (400, 512), 3, 1000)
    xm, labels = masking.apply(jax.random.key(1), x)
    x, xm, labels = map(np.asarray, (x, xm, labels))

    selected = labels != IGNORE_INDEX
    sel_rate = selected.mean()
    assert 0.145 < sel_rate < 0.155

    n_sel = selected.sum()
    masked = (xm == 2) & selected
    changed_random = selected & (xm != 2) & (xm != x)
    unchanged = selected & (xm == x)
    assert abs(masked.sum() / n_sel - 0.8) < 0.01
    # "random" can coincide with the original id (~1/1000), fold into tol
    assert abs(changed_random.sum() / n_sel - 0.1) < 0.01
    assert abs(unchanged.sum() / n_sel - 0.1) < 0.01
    # labels hold original ids at selected positions
    np.testing.assert_array_equal(labels[selected], x[selected])
    # random replacements never produce special tokens
    assert (xm[changed_random] >= 3).all()


def test_text_masking_protects_pad_and_unk():
    masking = TextMasking(vocab_size=50, unk_token_id=1, mask_token_id=2,
                          num_special_tokens=3)
    x = jnp.full((8, 64), 1, dtype=jnp.int32)  # all UNK
    pad = jnp.zeros((8, 64), bool).at[:, 32:].set(True)
    xm, labels = masking.apply(jax.random.key(0), x, pad)
    np.testing.assert_array_equal(np.asarray(xm), np.asarray(x))
    assert (np.asarray(labels) == IGNORE_INDEX).all()


def test_dropout_only_active_in_training():
    model = make_image_io()
    object.__setattr__(model.encoder, "dropout", 0.5)
    params = model.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, 28, 28, 1))
    y1 = model.apply(params, x, policy=FP32)
    y2 = model.apply(params, x, policy=FP32)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))
    t1 = model.apply(params, x, rng=jax.random.key(2), deterministic=False,
                     policy=FP32)
    t2 = model.apply(params, x, rng=jax.random.key(3), deterministic=False,
                     policy=FP32)
    assert not np.allclose(np.asarray(t1), np.asarray(t2))


def test_model_under_jit():
    model = make_image_io()
    params = model.init(jax.random.key(0))
    fn = jax.jit(lambda p, x: model.apply(p, x, policy=FP32))
    x = jax.random.normal(jax.random.key(1), (2, 28, 28, 1))
    np.testing.assert_allclose(np.asarray(fn(params, x)),
                               np.asarray(model.apply(params, x,
                                                      policy=FP32)),
                               atol=1e-5)


def test_attention_impl_parity_through_model():
    """Encoder/decoder with chunked or flash attention match einsum."""
    import dataclasses

    input_adapter = ImageInputAdapter(image_shape=(14, 14, 1),
                                      num_frequency_bands=8)
    output_adapter = ClassificationOutputAdapter(num_classes=10)
    enc = PerceiverEncoder(input_adapter=input_adapter,
                           latent_shape=(16, 32), num_layers=2,
                           num_self_attention_layers_per_block=2)
    dec = PerceiverDecoder(output_adapter=output_adapter,
                           latent_shape=(16, 32),
                           num_cross_attention_heads=1)
    model = PerceiverIO(enc, dec)
    params = model.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 14, 14, 1))
    ref = model.apply(params, x, policy=FP32)

    for impl in ("chunked", "flash"):
        m2 = PerceiverIO(
            dataclasses.replace(enc, attention_impl=impl, kv_chunk_size=64),
            dataclasses.replace(dec, attention_impl=impl, kv_chunk_size=64))
        out = m2.apply(params, x, policy=FP32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)


def test_remat_is_numerically_transparent():
    """remat=True must change memory behavior only: identical forward
    outputs and gradients (PerceiverEncoder.remat, the lever for the
    seq-2048 configs)."""
    import dataclasses

    model = make_image_io()
    params = model.init(jax.random.key(0))
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 28, 28, 1)), jnp.float32)

    remat_model = PerceiverIO(
        dataclasses.replace(model.encoder, remat=True), model.decoder)

    def loss(m):
        def f(p):
            return (m.apply(p, x, policy=FP32) ** 2).mean()
        return f

    out_a = model.apply(params, x, policy=FP32)
    out_b = remat_model.apply(params, x, policy=FP32)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-6, atol=1e-6)

    ga = jax.grad(loss(model))(params)
    gb = jax.grad(loss(remat_model))(params)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        # transparent up to fp32 reassociation: recomputation under
        # remat re-fuses the same ops, so ~1-ulp drift on small grad
        # elements is expected, structural drift is not
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=5e-6)
