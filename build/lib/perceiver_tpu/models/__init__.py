"""Model library: Perceiver encoder/decoder/IO/MLM and text masking."""

from perceiver_tpu.models.perceiver import (  # noqa: F401
    PerceiverEncoder,
    PerceiverDecoder,
    PerceiverIO,
    PerceiverMLM,
)
from perceiver_tpu.models.masking import TextMasking  # noqa: F401
from perceiver_tpu.models.uresnet import UResNet  # noqa: F401
