"""Dropout with explicit PRNG threading (JAX-functional)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dropout(x, rate: float, *, rng=None, deterministic: bool = True):
    """Inverted dropout. No-op when deterministic or rate == 0."""
    if deterministic or rate == 0.0:
        return x
    if rng is None:
        raise ValueError("dropout needs an rng when not deterministic")
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x)).astype(x.dtype)
