"""Ring attention & sequence-parallel attention over a mesh axis.

The reference has no long-context machinery (SURVEY §5: its answer to
long inputs is the Perceiver latent bottleneck itself). This module
adds the TPU-native long-context layer the brief requires as
first-class: exact softmax attention over sequences sharded across a
mesh axis, with cross-device traffic riding ICI.

Two entry points, both meant to run *inside* ``shard_map`` over a
``jax.sharding.Mesh`` axis (each function sees per-device shards and
uses named-axis collectives):

- ``ring_attention(q, k, v, axis_name=...)`` — q, k, v are all sharded
  along their sequence axes. Each of the ``N`` devices holds a q-shard
  and streams all N k/v-shards through in a ring: compute one block of
  the online-softmax recurrence (Rabe & Staats / FlashAttention), then
  ``lax.ppermute`` the k/v (+ key-bias) block to the next device.
  Peak memory per device is O(Lq/N · Lk/N); the k/v rotation overlaps
  with compute and crosses only neighbor ICI links. This is the
  self-attention path for the long-sequence MLM config
  (BASELINE.md configs[4], seq 2048 on a v5p-16 mesh).

- ``seq_parallel_cross_attention(q, k, v, axis_name=...)`` — q is
  *replicated* (the Perceiver latent array: small), k/v are sharded
  along the input sequence. A ring would make every device redo the
  same full computation, so instead each device attends its local k/v
  block only, producing partial ``(m, l, acc)`` softmax statistics,
  which are combined exactly with one ``pmax`` + two ``psum``s. This
  is the sequence-parallel form of the encoder's cross-attention
  (reference ``model.py:150-160``) for inputs too long for one chip
  (e.g. the 262,144-pixel LArTPC inputs, ``run.py:79``).

Both compute *exact* attention — the block recurrence is algebraically
identical to one softmax over the full key axis. Key-padding masks are
carried as additive fp32 biases over keys (same convention as
``perceiver_tpu.ops.chunked_attention.pad_mask_to_bias``).

Shapes (per device, inside shard_map): q ``(B, H, Lq, D)``,
k/v ``(B, H, Lk, D)``, bias ``(B, Lk)``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from perceiver_tpu.ops.chunked_attention import (
    NEG_INF,
    finalize_softmax,
    fold_block,
)
from perceiver_tpu.parallel.compat import axis_size, shard_map


def _init_stats(b, h, lq, d):
    return (jnp.full((b, h, lq, 1), NEG_INF, jnp.float32),
            jnp.zeros((b, h, lq, 1), jnp.float32),
            jnp.zeros((b, h, lq, d), jnp.float32))


def ring_attention(q, k, v, *, axis_name: str,
                   bias: Optional[jax.Array] = None,
                   scale: Optional[float] = None):
    """Exact attention with q/k/v sharded over ``axis_name``.

    Call inside shard_map. Each device computes its q-shard's attention
    over the FULL key sequence by rotating k/v (+ bias) around the ring
    one hop per step with ``lax.ppermute``.
    """
    n = axis_size(axis_name)
    b, h, lq, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    perm = [(i, (i + 1) % n) for i in range(n)]

    # Fold the resident block first, then (n-1) × (rotate, fold) — the
    # final rotation that would return each block home is never sent.
    m, l, acc = fold_block(q, k, v, bias, scale, *_init_stats(b, h, lq, d))
    if n == 1:
        return finalize_softmax(l, acc, q.dtype)

    def body(carry, _):
        m, l, acc, k_blk, v_blk, b_blk = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        if b_blk is not None:
            b_blk = jax.lax.ppermute(b_blk, axis_name, perm)
        m, l, acc = fold_block(q, k_blk, v_blk, b_blk, scale, m, l, acc)
        return (m, l, acc, k_blk, v_blk, b_blk), None

    (m, l, acc, _, _, _), _ = jax.lax.scan(
        body, (m, l, acc, k, v, bias), None, length=n - 1)
    return finalize_softmax(l, acc, q.dtype)


def seq_parallel_cross_attention(q, k, v, *, axis_name: str,
                                 bias: Optional[jax.Array] = None,
                                 scale: Optional[float] = None):
    """Exact cross-attention with q replicated, k/v sharded over
    ``axis_name``. Call inside shard_map.

    Each device folds only its local k/v block, then the partial
    softmax statistics are combined across the axis:
    ``m_g = pmax(m)``; ``l_g = psum(l · exp(m − m_g))``;
    ``acc_g = psum(acc · exp(m − m_g))``; output ``acc_g / l_g``.
    One max-reduce plus two sum-reduces over ICI, each sized by the
    (small) query array — no k/v ever moves.
    """
    b, h, lq, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    m, l, acc = fold_block(q, k, v, bias, scale, *_init_stats(b, h, lq, d))

    # The global max is a pure numerical-stability shift — the combined
    # softmax is invariant to it, so its gradient is exactly zero.
    # stop_gradient makes that explicit (pmax has no differentiation
    # rule), keeping the whole combine differentiable for training.
    m_g = jax.lax.pmax(jax.lax.stop_gradient(m), axis_name)
    corr = jnp.exp(m - m_g)
    l_g = jax.lax.psum(l * corr, axis_name)
    acc_g = jax.lax.psum(acc * corr, axis_name)
    return finalize_softmax(l_g, acc_g, q.dtype)


def make_ring_attention(mesh: Mesh, seq_axis: str = "data", *,
                        batch_axis: Optional[str] = None,
                        scale: Optional[float] = None):
    """shard_map-wrapped ring attention over ``mesh``.

    Returns ``f(q, k, v, bias=None) -> out`` taking GLOBAL arrays
    ``(B, H, L, D)`` with the sequence axis sharded over ``seq_axis``
    (and optionally batch over ``batch_axis``).
    """
    bspec = batch_axis
    qspec = P(bspec, None, seq_axis, None)
    bias_spec = P(bspec, seq_axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(qspec, qspec, qspec, bias_spec),
        out_specs=qspec, check_vma=False)
    def _ring(q, k, v, bias):
        return ring_attention(q, k, v, axis_name=seq_axis, bias=bias,
                              scale=scale)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(qspec, qspec, qspec),
        out_specs=qspec, check_vma=False)
    def _ring_nobias(q, k, v):
        return ring_attention(q, k, v, axis_name=seq_axis, scale=scale)

    def f(q, k, v, bias=None):
        if bias is None:
            return _ring_nobias(q, k, v)
        return _ring(q, k, v, bias)

    return f


def make_seq_parallel_cross_attention(mesh: Mesh, seq_axis: str = "data", *,
                                      batch_axis: Optional[str] = None,
                                      scale: Optional[float] = None):
    """shard_map-wrapped sequence-parallel cross-attention over ``mesh``.

    Returns ``f(q, k, v, bias=None) -> out`` for GLOBAL arrays: q
    ``(B, H, Lq, D)`` replicated along ``seq_axis``, k/v ``(B, H, Lk,
    D)`` with Lk sharded over ``seq_axis``. Output is replicated along
    ``seq_axis`` (every device gets the full attended latents).
    """
    bspec = batch_axis
    kv_spec = P(bspec, None, seq_axis, None)
    q_spec = P(bspec, None, None, None)
    bias_spec = P(bspec, seq_axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, bias_spec),
        out_specs=q_spec, check_vma=False)
    def _xattn(q, k, v, bias):
        return seq_parallel_cross_attention(
            q, k, v, axis_name=seq_axis, bias=bias, scale=scale)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec, check_vma=False)
    def _xattn_nobias(q, k, v):
        return seq_parallel_cross_attention(
            q, k, v, axis_name=seq_axis, scale=scale)

    def f(q, k, v, bias=None):
        if bias is None:
            return _xattn_nobias(q, k, v)
        return _xattn(q, k, v, bias)

    return f
