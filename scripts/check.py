#!/usr/bin/env python
"""Run the static-analysis gates: source lint + lowered-graph passes.

Usage::

    python scripts/check.py --all           # everything (the merge gate)
    python scripts/check.py --lint          # AST rules only (fast)
    python scripts/check.py --race          # racecheck passes only
    python scripts/check.py --graph         # graph passes, all targets
    python scripts/check.py --graph --fast  # skip the expensive targets
                                            # and the double-lowering
                                            # recompile check
    python scripts/check.py --all --json out.json

Exit code 0 iff no violations. See docs/ANALYSIS.md for what each
pass/rule checks and how to allowlist a finding.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The sharded canonical targets place over a 4-device mesh; on CPU
# that needs virtual devices, and the flag only takes effect if set
# before the first jax import (same recipe as tests/conftest.py).
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()


def main() -> int:
    ap = argparse.ArgumentParser(
        description="perceiver-tpu static analysis (lint + graph passes)")
    ap.add_argument("--all", action="store_true",
                    help="lint + graph passes over every target")
    ap.add_argument("--lint", action="store_true",
                    help="run the AST lint rules")
    ap.add_argument("--graph", action="store_true",
                    help="run the lowered-graph passes")
    ap.add_argument("--race", action="store_true",
                    help="run the racecheck passes (guarded-attrs, "
                         "lock-order, callback-under-lock) over the "
                         "concurrent host-side packages")
    ap.add_argument("--no-race", action="store_true",
                    help="escape hatch: drop racecheck from --all")
    ap.add_argument("--fast", action="store_true",
                    help="graph passes on the fast targets only, "
                         "without the double-lowering recompile check")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="lint these files/dirs instead of the default "
                         "(package + scripts + entry points)")
    ap.add_argument("--json", default=None,
                    help="also write the report as JSON")
    ap.add_argument("--exec-cache", default=None, metavar="DIR",
                    help="persistent compile-cache directory "
                         "(perceiver_tpu/cache): graph passes reuse "
                         "lowering records from previous runs of the "
                         "same source tree — a warm --graph --fast "
                         "run re-lowers nothing, and a warm --graph "
                         "run lowers each target once (the stability "
                         "passes then compare across processes)")
    ap.add_argument("--rebaseline-hbm", action="store_true",
                    help="re-measure every canonical target's "
                         "cost-analysis bytes and rewrite the "
                         "hbm_budgets.json manifest (only after an "
                         "INTENTIONAL traffic change — commit the "
                         "manifest diff with the justification)")
    ap.add_argument("--pin-missing-hbm", action="store_true",
                    help="measure and pin budgets ONLY for canonical "
                         "targets absent from hbm_budgets.json (the "
                         "new-target path — existing pins are copied "
                         "through untouched, never re-baselined)")
    ap.add_argument("--no-mesh", action="store_true",
                    help="skip the sharded (SPMD) canonical targets "
                         "and their shardcheck passes — the escape "
                         "hatch for environments that cannot simulate "
                         "a multi-device backend")
    ap.add_argument("--rebaseline-shard", action="store_true",
                    help="re-measure every sharded target's per-axis "
                         "collective bytes + per-shard bytes and "
                         "rewrite the shard_budgets.json manifest "
                         "(only after an INTENTIONAL sharding change "
                         "— commit the manifest diff with the "
                         "justification)")
    ap.add_argument("--pin-missing-shard", action="store_true",
                    help="measure and pin shard budgets ONLY for "
                         "sharded targets absent from "
                         "shard_budgets.json (existing pins copied "
                         "through untouched)")
    args = ap.parse_args()
    if not (args.all or args.lint or args.graph or args.race
            or args.rebaseline_hbm or args.pin_missing_hbm
            or args.rebaseline_shard or args.pin_missing_shard):
        args.all = True

    from perceiver_tpu.analysis import (
        CANONICAL_TARGETS,
        FAST_TARGETS,
        Report,
        collective_inventory,
        default_lint_paths,
        lint_paths,
        lower_target,
        run_graph_checks,
        run_racecheck,
        write_hbm_budgets,
        write_shard_budgets,
    )

    if args.rebaseline_hbm or args.pin_missing_hbm:
        import datetime

        from perceiver_tpu.analysis import load_hbm_budgets

        keep = {}
        targets = CANONICAL_TARGETS
        if args.pin_missing_hbm and not args.rebaseline_hbm:
            keep = load_hbm_budgets()
            targets = [t for t in CANONICAL_TARGETS if t.name not in keep]
            if not targets:
                print("[check] every canonical target already has a "
                      "pinned budget — nothing to do", file=sys.stderr)
        measured = {}
        for target in targets:
            print(f"[check] lowering {target.name} ...", file=sys.stderr)
            lowered = lower_target(target)
            if lowered.bytes_accessed is None:
                print(f"[check] {target.name}: no cost analysis — "
                      "cannot pin a budget", file=sys.stderr)
                return 1
            measured[target.name] = lowered.bytes_accessed
            print(f"[check] {target.name}: "
                  f"{lowered.bytes_accessed / 1e9:.2f} GB",
                  file=sys.stderr)
        if measured:
            write_hbm_budgets(
                measured, note=str(datetime.date.today()), keep=keep)
            print("[check] hbm_budgets.json rewritten — commit it with "
                  "the change that justified the re-baseline",
                  file=sys.stderr)
        if not (args.all or args.lint or args.graph or args.race
                or args.rebaseline_shard or args.pin_missing_shard):
            return 0

    if args.rebaseline_shard or args.pin_missing_shard:
        import datetime

        from perceiver_tpu.analysis import (
            SHARDED_TARGETS,
            load_shard_budgets,
        )

        keep = {}
        stargets = SHARDED_TARGETS
        if args.pin_missing_shard and not args.rebaseline_shard:
            keep = load_shard_budgets()
            stargets = [t for t in SHARDED_TARGETS if t.name not in keep]
            if not stargets:
                print("[check] every sharded target already has pinned "
                      "shard budgets — nothing to do", file=sys.stderr)
        measured = {}
        for target in stargets:
            print(f"[check] lowering+compiling {target.name} ...",
                  file=sys.stderr)
            lowered = lower_target(target)
            if lowered.bytes_accessed is None \
                    or not lowered.compiled_text:
                print(f"[check] {target.name}: no cost analysis or "
                      "compiled HLO — cannot pin shard budgets",
                      file=sys.stderr)
                return 1
            inv = collective_inventory(lowered.compiled_text,
                                       target.mesh)
            per_shard = lowered.bytes_accessed / target.mesh.n_devices
            measured[target.name] = {
                "mesh": target.mesh.descriptor,
                "collectives": inv["collectives"],
                "ops": inv["ops"],
                "per_shard": per_shard,
            }
            traffic = {a: f"{b / 1e6:.2f}MB"
                       for a, b in sorted(inv["collectives"].items())}
            print(f"[check] {target.name}: per-shard "
                  f"{per_shard / 1e9:.2f} GB, collectives {traffic}",
                  file=sys.stderr)
        if measured:
            write_shard_budgets(
                measured, note=str(datetime.date.today()), keep=keep)
            print("[check] shard_budgets.json rewritten — commit it "
                  "with the change that justified the re-baseline",
                  file=sys.stderr)
        if not (args.all or args.lint or args.graph or args.race):
            return 0

    cache = None
    compile_events = []
    if args.exec_cache:
        import jax

        from perceiver_tpu.cache import ExecutableCache

        cache = ExecutableCache(args.exec_cache)
        # count real XLA compiles so the warm-run contract ("zero
        # fresh compiles") is observable from the outside
        jax.monitoring.register_event_listener(
            lambda name, **kw: compile_events.append(name)
            if "compile" in name else None)

    report = Report()
    if args.all or args.lint:
        paths = args.paths or default_lint_paths(_REPO)
        print(f"[check] linting {len(paths)} root(s) ...",
              file=sys.stderr)
        report.merge(lint_paths(paths))
    if (args.all and not args.no_race) or args.race:
        print("[check] racecheck over the concurrent host-side "
              "packages ...", file=sys.stderr)
        report.merge(run_racecheck(repo_root=_REPO))
    if args.all or args.graph:
        targets = FAST_TARGETS if args.fast else CANONICAL_TARGETS
        if args.no_mesh:
            targets = tuple(t for t in targets if t.mesh is None)
        print(f"[check] lowering {len(targets)} canonical target(s) "
              "(CPU backend; no chip needed) ...", file=sys.stderr)
        report.merge(run_graph_checks(targets, recompile=not args.fast,
                                      cache=cache))
    if cache is not None:
        s = cache.stats
        print(f"[check] exec-cache: hits={s.hits} misses={s.misses} "
              f"stores={s.stores} xla_compiles={len(compile_events)} "
              f"dir={cache.path}", file=sys.stderr)

    print(report.format())
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report.to_json(), f, indent=1)
            f.write("\n")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
