#!/usr/bin/env python
"""Offline Poisson-arrival load generator for the serving engine.

Drives an ``MLMServer`` (or the classifier/segmentation servers) with
open-loop Poisson traffic — arrivals are scheduled ahead of time from
an exponential inter-arrival draw and submitted on time regardless of
completion, the regime that actually exposes queueing/tail behavior
(closed-loop clients self-throttle and hide it). Emits ONE JSON line
in the ``bench.py`` result-line format::

    {"metric": "serving_mlm_requests_per_sec", "value": ..., "unit":
     "req/s", "vs_baseline": null, "detail": {"p50_ms": ..., "p95_ms":
     ..., "p99_ms": ..., ...}}

Runs on any backend; on CPU use ``--preset tiny`` (the default), which
serves a test-sized model — the point of the CPU run is schema + queue
behavior, not throughput. On a chip, drop ``--preset tiny`` to load
the canonical task shapes and optionally ``--checkpoint``.

``--mode`` selects the dispatch path: ``padded`` (rectangular buckets,
the default), ``packed`` (ragged token-budget continuous batching —
docs/SERVING.md "Ragged serving"), or ``both``, which drives the SAME
mixed-length trace through each arm and emits one result line whose
detail carries the padded-vs-packed p50/p95/p99 + waste side by side.
The packed arm asserts ZERO post-warmup XLA compiles via
``jax.monitoring`` — a compile mid-traffic is a bucketing bug and
fails the run.

Examples::

    JAX_PLATFORMS=cpu python scripts/bench_serving.py --requests 200 \
        --rate 100
    JAX_PLATFORMS=cpu python scripts/bench_serving.py --mode both \
        --requests 200 --rate 100
    python scripts/bench_serving.py --task mlm --rate 2000 \
        --duration-s 30 --checkpoint /ckpts/mlm
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _tiny_mlm_task():
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    return MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=64, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")


def _full_mlm_task():
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    return MaskedLanguageModelTask(vocab_size=10003, max_seq_len=512)


def _make_tokenizer(vocab_size: int):
    """Self-contained tokenizer (no shipped artifact in this image):
    trained once on the synthetic review corpus."""
    from perceiver_tpu.data.imdb import _synthetic_reviews
    from perceiver_tpu.tokenizer import create_tokenizer, train_tokenizer
    from perceiver_tpu.tokenizer.wordpiece import Replace

    texts, _ = _synthetic_reviews(400, 0)
    tok = create_tokenizer(Replace("<br />", " "))
    train_tokenizer(tok, texts, vocab_size=vocab_size)
    return tok


def _request_texts(n: int, seq_buckets, seed: int):
    """Mixed-length fill-mask requests spanning every seq bucket."""
    from perceiver_tpu.data.imdb import _synthetic_reviews

    texts, _ = _synthetic_reviews(max(n, 16), seed)
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        base = texts[i % len(texts)].replace("<br />", " ")
        words = base.split()
        # repeat to reach a target bucket, then mask a few words
        target = int(rng.choice(seq_buckets))
        while len(words) < target // 2:
            words = words + words
        words = words[:max(3, min(len(words), target - 2))]
        for _ in range(max(1, len(words) // 16)):
            words[int(rng.integers(0, len(words)))] = "[MASK]"
        out.append(" ".join(words))
    return out


def _parse_packed_buckets(spec: str):
    """``"512x16,128x4"`` -> ((512, 16), (128, 4))."""
    out = []
    for part in spec.split(","):
        tokens, rows = part.lower().split("x")
        out.append((int(tokens), int(rows)))
    return tuple(out)


@contextlib.contextmanager
def _compile_events():
    """Collect XLA compile events (jax.monitoring) inside the block."""
    import jax
    from jax._src import monitoring as _monitoring

    events = []

    def listener(name, **kwargs):
        if "compile" in name:
            events.append(name)

    jax.monitoring.register_event_listener(listener)
    try:
        yield events
    finally:
        _monitoring._unregister_event_listener_by_callback(listener)


def _run_arm(arm: str, args, task, texts, arrivals, *, seq_buckets,
             batch_buckets, packed_buckets, tokenizer):
    """Build one engine+server (padded or packed), drive the shared
    Poisson trace through it, and return the per-arm detail dict.

    The packed arm counts XLA compile events across the whole traffic
    window — post-warmup compiles are a bucketing bug and make the
    bench exit nonzero.
    """
    from perceiver_tpu.serving import MLMServer, Overloaded, ServingEngine
    from perceiver_tpu.serving.metrics import MetricsRegistry

    packed = arm == "packed"
    print(f"[bench_serving] {arm}: building engine "
          + (f"packed_buckets={packed_buckets}" if packed
             else f"buckets={batch_buckets}x{seq_buckets}"),
          file=sys.stderr)
    t0 = time.perf_counter()
    metrics = MetricsRegistry()
    if packed:
        engine = ServingEngine(task, checkpoint=args.checkpoint,
                               batch_buckets=(), seq_buckets=(),
                               allow_unlisted_buckets=True,
                               packed_buckets=packed_buckets,
                               metrics=metrics)
    else:
        engine = ServingEngine(task, checkpoint=args.checkpoint,
                               batch_buckets=batch_buckets,
                               seq_buckets=seq_buckets, metrics=metrics)
    warmup_s = time.perf_counter() - t0
    print(f"[bench_serving] {arm}: warmup {engine.compile_count} bucket "
          f"executables in {warmup_s:.1f}s", file=sys.stderr)

    server = MLMServer(engine, tokenizer, max_batch=args.max_batch,
                       max_delay_ms=args.max_delay_ms,
                       max_depth=args.max_depth, packed=packed)

    # per-arm trace buffer sized to the whole trace so the span-derived
    # phase breakdown below never loses early requests to LRU eviction
    from perceiver_tpu.obs import trace as trace_mod

    arm_buffer = trace_mod.TraceBuffer(max_traces=len(texts) + 16)
    prev_buffer = trace_mod.set_default_buffer(arm_buffer)

    latencies_ms: list = []
    trace_ids: list = []
    shed = 0
    errors = 0
    lock = threading.Lock()
    futures = []

    def reap(fut, t_submit):
        nonlocal shed, errors
        try:
            result = fut.result()
        except Exception:  # noqa: BLE001 — counted, reported below
            with lock:
                errors += 1
            return
        dt_ms = (time.perf_counter() - t_submit) * 1e3
        ctx = getattr(fut, "trace_ctx", None)
        with lock:
            if isinstance(result, Overloaded):
                shed += 1
            else:
                latencies_ms.append(dt_ms)
                if ctx is not None:
                    trace_ids.append(ctx.trace_id)

    n = len(texts)
    print(f"[bench_serving] {arm}: offering {n} requests at "
          f"{args.rate} req/s (open loop)", file=sys.stderr)
    with _compile_events() as compiles:
        start = time.perf_counter()
        for i in range(n):
            delay = start + arrivals[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t_submit = time.perf_counter()
            fut = server.submit(texts[i], timeout_ms=args.timeout_ms)
            waiter = threading.Thread(target=reap, args=(fut, t_submit),
                                      daemon=True)
            waiter.start()
            futures.append(waiter)
        for w in futures:
            w.join(timeout=120)
        wall = time.perf_counter() - start
        server.close()
    trace_mod.set_default_buffer(prev_buffer)

    # span-derived per-phase latency: where each served request's time
    # actually went (queue vs dispatch vs the device materialize sync)
    phase_ms = {"queue_wait": [], "dispatch": [], "device": []}
    with lock:
        for tid in trace_ids:
            for span in arm_buffer.get(tid) or ():
                if span["phase"] in phase_ms:
                    phase_ms[span["phase"]].append(
                        span["duration_s"] * 1e3)

    def phase_pct(values, p):
        if not values:
            return None
        ranked = sorted(values)
        return round(ranked[min(int(p / 100 * len(ranked)),
                                len(ranked) - 1)], 3)

    served = len(latencies_ms)
    lat = np.asarray(sorted(latencies_ms)) if served else np.zeros(1)

    def pct(p):
        return round(float(lat[min(int(p / 100 * served), served - 1)]),
                     3) if served else None

    hist = metrics.get("serving_batch_size")
    occ = metrics.get("serving_batch_occupancy")
    waste = metrics.get("serving_padding_waste_fraction")
    dispatch = metrics.get("serving_bucket_dispatch_total")
    padded_tokens = metrics.get("serving_padded_tokens_total")
    detail = {
        "requests_per_sec": round(served / wall, 1) if wall > 0 else 0.0,
        "p50_ms": pct(50),
        "p95_ms": pct(95),
        "p99_ms": pct(99),
        "served": served,
        "shed": shed,
        "errors": errors,
        "wall_s": round(wall, 3),
        "warmup_s": round(warmup_s, 2),
        "aot_executables": engine.compile_count,
        "post_warmup_compiles": len(compiles),
        "lazy_compiles": int(metrics.get("serving_compile_total")
                             .value_of(phase="lazy")),
        "mean_batch_size": (round(hist.sum / hist.count, 2)
                            if hist and hist.count else None),
        "mean_occupancy": (round(occ.sum / occ.count, 3)
                           if occ and occ.count else None),
        "mean_padding_waste": (round(waste.sum / waste.count, 3)
                               if waste and waste.count else None),
        "padded_tokens_total": {
            labels.get("mode", ""): int(v)
            for labels, v in padded_tokens.items()
        } if padded_tokens else {},
        "bucket_dispatches": {
            labels.get("bucket", ""): int(v)
            for labels, v in dispatch.items()
        } if dispatch else {},
        "phase_breakdown_ms": {
            phase: {"p50": phase_pct(values, 50),
                    "p95": phase_pct(values, 95),
                    "spans": len(values)}
            for phase, values in phase_ms.items()
        },
    }
    return detail


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Poisson open-loop load generator for the serving "
                    "subsystem")
    ap.add_argument("--task", default="mlm", choices=["mlm"],
                    help="served task front-end (mlm = fill-mask)")
    ap.add_argument("--preset", default="tiny",
                    choices=["tiny", "canonical"],
                    help="tiny: CPU-sized model; canonical: the "
                         "pinned serve shapes (chip-sized)")
    ap.add_argument("--mode", default="padded",
                    choices=["padded", "packed", "both"],
                    help="dispatch path: rectangular buckets, ragged "
                         "packed batching, or a side-by-side comparison "
                         "over the same trace")
    ap.add_argument("--checkpoint", default=None,
                    help="params checkpoint dir (default: fresh init)")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="offered load, requests/second (Poisson)")
    ap.add_argument("--requests", type=int, default=200,
                    help="total requests to offer")
    ap.add_argument("--duration-s", type=float, default=None,
                    help="cap the offered window; overrides --requests "
                         "when both limits conflict")
    ap.add_argument("--batch-buckets", default="1,4,8",
                    help="comma-separated engine batch buckets")
    ap.add_argument("--seq-buckets", default=None,
                    help="comma-separated engine seq buckets (default: "
                         "16,32,64 tiny / 128,256,512 canonical)")
    ap.add_argument("--packed-buckets", default=None,
                    help="comma-separated TOKENSxROWS packed buckets "
                         "(default: 64x2,128x4,512x16 tiny / "
                         "2048x8,8192x32 canonical)")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-delay-ms", type=float, default=4.0)
    ap.add_argument("--max-depth", type=int, default=256)
    ap.add_argument("--timeout-ms", type=float, default=None,
                    help="per-request deadline (default: none)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", default=None,
                    help="also write the result object to this path")
    args = ap.parse_args()

    import jax

    tiny = args.preset == "tiny"
    task = _tiny_mlm_task() if tiny else _full_mlm_task()
    seq_buckets = tuple(
        int(s) for s in (args.seq_buckets.split(",") if args.seq_buckets
                         else (("16", "32", "64") if tiny
                               else ("128", "256", "512"))))
    batch_buckets = tuple(int(b) for b in args.batch_buckets.split(","))
    packed_buckets = _parse_packed_buckets(
        args.packed_buckets if args.packed_buckets
        else ("64x2,128x4,512x16" if tiny else "2048x8,8192x32"))

    rng = np.random.default_rng(args.seed)
    n = args.requests
    inter = rng.exponential(1.0 / args.rate, n)
    arrivals = np.cumsum(inter)
    if args.duration_s is not None:
        arrivals = arrivals[arrivals <= args.duration_s]
        n = len(arrivals)
    texts = _request_texts(n, seq_buckets, args.seed)
    tokenizer = _make_tokenizer(task.vocab_size)

    arms = (("padded", "packed") if args.mode == "both"
            else (args.mode,))
    per_arm = {}
    for arm in arms:
        per_arm[arm] = _run_arm(
            arm, args, task, texts, arrivals, seq_buckets=seq_buckets,
            batch_buckets=batch_buckets, packed_buckets=packed_buckets,
            tokenizer=tokenizer)

    # Acceptance gate: the packed path never compiles under traffic —
    # every dispatch must land in a warmed (tokens, rows) bucket.
    packed_compiles = (per_arm.get("packed") or {}).get(
        "post_warmup_compiles", 0)
    if packed_compiles:
        print(f"[bench_serving] FAIL: packed arm saw {packed_compiles} "
              "post-warmup XLA compile event(s); packed dispatch must "
              "be fully AOT", file=sys.stderr)

    headline = per_arm[arms[-1]]
    detail = {
        "mode": args.mode,
        "offered_rate_rps": round(args.rate, 1),
        "offered_requests": int(n),
        "batch_buckets": list(batch_buckets),
        "seq_buckets": list(seq_buckets),
        "packed_buckets": [list(tb) for tb in packed_buckets],
        "preset": args.preset,
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", None),
    }
    if args.mode == "both":
        detail["padded"] = per_arm["padded"]
        detail["packed"] = per_arm["packed"]
        pw, kw = (per_arm["padded"]["padded_tokens_total"],
                  per_arm["packed"]["padded_tokens_total"])
        rect_waste = pw.get("rect", 0)
        packed_waste = kw.get("packed", 0)
        detail["padded_tokens_rect_vs_packed"] = [rect_waste,
                                                  packed_waste]
        if rect_waste:
            detail["packed_waste_ratio"] = round(
                packed_waste / rect_waste, 4)
    else:
        detail.update(per_arm[args.mode])
    metric_name = (f"serving_{args.task}_requests_per_sec"
                   if args.mode == "padded"
                   else f"serving_{args.task}_packed_requests_per_sec")
    result = {
        "metric": metric_name,
        "value": headline["requests_per_sec"],
        "unit": "req/s",
        "vs_baseline": (per_arm["padded"]["requests_per_sec"]
                        if args.mode == "both" else None),
        "detail": detail,
    }
    print(json.dumps(result), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return 1 if packed_compiles else 0


if __name__ == "__main__":
    sys.exit(main())
