"""Serve-graph builders: one pure forward function per task.

This module is the single source of truth for what a *served* forward
pass computes — the engine AOT-compiles these functions per shape
bucket (``serving/engine.py``) and the static-analysis subsystem
lowers the very same functions as canonical serving targets
(``analysis/targets.py``), so the graph the gates certify is the graph
production dispatches. It therefore must not import from
``perceiver_tpu.analysis`` or ``perceiver_tpu.serving.engine``.

Design rules (mirroring the train-step targets):

- **bf16 policy end to end** — every matmul in the serve graph runs on
  bf16 operands (``dtype_policy`` pins the MLM serve graph's
  FLOP-weighted bf16 fraction at 1.0); statistics (softmax, top-k
  scores) are computed in fp32.
- **Device-side post-processing** — top-k, argmax, and mask filling
  happen inside the compiled graph, so the host round trip carries
  kilobytes (predictions), not the (B, L, V) logits tensor.
- **Donation where it aliases** — the MLM graph returns ``filled_ids``
  (same shape/dtype as ``input_ids``) and ``is_masked`` (same as
  ``pad_mask``), so both request buffers are donated and re-used by
  XLA in place. Graphs with no alias-compatible output donate nothing
  (a donated-but-unaliasable buffer is a ``donation_check`` violation,
  not an optimization).
- **No host callbacks** — serve graphs must stay dispatchable on the
  axon runtime, which rejects host callbacks; ``transfer_guard`` runs
  over every registered serving target with an empty allowlist.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from perceiver_tpu.ops.linear import linear_apply
from perceiver_tpu.ops.mlp import mlp_apply
from perceiver_tpu.ops.norm import layer_norm_apply
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY
from perceiver_tpu.tokenizer import MASK_TOKEN_ID, PAD_TOKEN_ID


@dataclasses.dataclass(frozen=True)
class InputSpec:
    """One request-tensor slot of a serve graph.

    ``shape(batch, seq)`` yields the bucket shape (``seq`` is ignored
    by fixed-shape tasks); ``pad_value`` is what bucket padding fills
    with — chosen so padded positions are inert (PAD tokens, masked-out
    key positions, zero pixels the segmentation pad-mask drops).
    """

    name: str
    dtype: object
    shape: Callable[[int, int], Tuple[int, ...]]
    pad_value: object


@dataclasses.dataclass(frozen=True)
class ServeGraph:
    """A task's serve computation plus everything needed to bucket it.

    ``fn(params, *inputs)`` returns a dict of device arrays whose
    leading axis is the bucket batch. ``donate_argnums`` index into
    ``fn``'s positional args (params is argnum 0 and never donated —
    it stays device-resident across requests)."""

    kind: str
    model: object
    fn: Callable
    inputs: Tuple[InputSpec, ...]
    output_names: Tuple[str, ...]
    donate_argnums: Tuple[int, ...]
    # text graphs bucket over (batch, seq); image graphs only batch
    seq_bucketable: bool
    # largest servable sequence (model position table size); None for
    # fixed-shape tasks
    max_seq_len: Optional[int] = None
    # outputs whose axis 1 is the (bucket-padded) sequence axis —
    # ``serving.api.materialize`` slices them back to request length
    seq_axis_outputs: Tuple[str, ...] = ()

    def init_params(self, seed: int = 0):
        return self.model.init(jax.random.key(seed))


def mlm_serve_graph(model, *, policy: Policy = DEFAULT_POLICY,
                    top_k: int = 3,
                    max_seq_len: Optional[int] = None) -> ServeGraph:
    """MLM fill-mask graph from a built ``PerceiverMLM`` — the entry
    the ``utils/predict.py`` compat wrapper uses (it holds a model +
    params, not a task config)."""
    if max_seq_len is None:
        # TextOutputAdapter: output_shape = (max_seq_len, channels)
        max_seq_len = model.decoder.output_adapter.output_shape[0]

    def fn(params, input_ids, pad_mask):
        logits, _ = model.apply(params, input_ids, pad_mask,
                                masking=False, policy=policy)
        # scores in fp32 (norm-dtype convention); the vocab projection
        # itself ran in bf16 inside the adapter
        scores, topk_ids = jax.lax.top_k(
            logits.astype(jnp.float32), top_k)
        topk_ids = topk_ids.astype(input_ids.dtype)
        is_masked = input_ids == MASK_TOKEN_ID
        filled_ids = jnp.where(is_masked, topk_ids[..., 0], input_ids)
        return {"filled_ids": filled_ids, "topk_ids": topk_ids,
                "topk_scores": scores, "is_masked": is_masked}

    return ServeGraph(
        kind="mlm", model=model, fn=fn,
        inputs=(
            InputSpec("input_ids", jnp.int32, lambda b, s: (b, s),
                      PAD_TOKEN_ID),
            InputSpec("pad_mask", jnp.bool_, lambda b, s: (b, s), True),
        ),
        output_names=("filled_ids", "topk_ids", "topk_scores",
                      "is_masked"),
        seq_axis_outputs=("filled_ids", "topk_ids", "topk_scores",
                          "is_masked"),
        # input_ids → filled_ids and pad_mask → is_masked alias
        # exactly (shape and dtype), so both request buffers donate
        donate_argnums=(1, 2),
        seq_bucketable=True, max_seq_len=max_seq_len)


def _mlm_graph(task, policy: Policy, top_k: int) -> ServeGraph:
    return mlm_serve_graph(task.build(), policy=policy, top_k=top_k,
                           max_seq_len=task.max_seq_len)


def _classifier_fn(model, policy: Policy):
    def fn(params, *inputs):
        logits = model.apply(params, *inputs, policy=policy)
        logits = logits.astype(jnp.float32)
        return {"logits": logits,
                "probs": jax.nn.softmax(logits, axis=-1),
                "label": jnp.argmax(logits, axis=-1).astype(jnp.int32)}
    return fn


def _text_clf_graph(task, policy: Policy) -> ServeGraph:
    model = task.build()
    return ServeGraph(
        kind="text_clf", model=model, fn=_classifier_fn(model, policy),
        inputs=(
            InputSpec("input_ids", jnp.int32, lambda b, s: (b, s),
                      PAD_TOKEN_ID),
            InputSpec("pad_mask", jnp.bool_, lambda b, s: (b, s), True),
        ),
        output_names=("logits", "probs", "label"),
        # (B, L) int32/bool cannot alias the (B, C)/(B,) outputs —
        # donating them would only trip donation_check
        donate_argnums=(),
        seq_bucketable=True, max_seq_len=task.max_seq_len)


def _img_clf_graph(task, policy: Policy) -> ServeGraph:
    model = task.build()
    shape = tuple(task.image_shape)
    return ServeGraph(
        kind="img_clf", model=model, fn=_classifier_fn(model, policy),
        inputs=(InputSpec("image", jnp.float32,
                          lambda b, s: (b, *shape), 0.0),),
        output_names=("logits", "probs", "label"),
        donate_argnums=(), seq_bucketable=False)


def _seg_graph(task, policy: Policy) -> ServeGraph:
    model = task.build()
    h, w, _ = task.image_shape

    def fn(params, image):
        logits = task.forward(model, params, image, policy=policy)
        logits = logits.astype(jnp.float32)
        b = image.shape[0]
        classes = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        conf = jnp.max(jax.nn.softmax(logits, axis=-1), axis=-1)
        return {"classes": classes.reshape(b, h, w),
                "confidence": conf.reshape(b, h, w)}

    return ServeGraph(
        kind="seg", model=model, fn=fn,
        inputs=(InputSpec("image", jnp.float32,
                          lambda b, s: (b, h, w), 0.0),),
        output_names=("classes", "confidence"),
        donate_argnums=(), seq_bucketable=False)


def build_serve_graph(task, *, policy: Policy = DEFAULT_POLICY,
                      top_k: int = 3) -> ServeGraph:
    """Serve graph for a task config (dispatch on the task type)."""
    # imported here so graphs stays importable without the full task
    # registry at module-import time
    from perceiver_tpu.tasks import (
        ImageClassifierTask,
        MaskedLanguageModelTask,
        SegmentationTask,
        TextClassifierTask,
    )

    if isinstance(task, MaskedLanguageModelTask):
        return _mlm_graph(task, policy, top_k)
    if isinstance(task, TextClassifierTask):
        return _text_clf_graph(task, policy)
    if isinstance(task, SegmentationTask):
        return _seg_graph(task, policy)
    if isinstance(task, ImageClassifierTask):
        return _img_clf_graph(task, policy)
    raise TypeError(
        f"no serve graph for task type {type(task).__name__}; supported: "
        "MaskedLanguageModelTask, TextClassifierTask, "
        "ImageClassifierTask, SegmentationTask")


def serve_graph_shardings(graph: ServeGraph, params, mesh):
    """GSPMD shardings for a serve graph's jit over a data×model mesh:
    params take the tensor-parallel layout (``parallel/sharding``),
    request tensors and every output shard their leading (batch) axis
    over ``data``. Donation survives sharding — a donated request
    buffer and the output it aliases carry the same spec, so the
    per-shard buffers still alias in place. Returns
    ``(params_sharding, input_shardings, output_shardings)`` ready for
    ``jax.jit(graph.fn, in_shardings=..., out_shardings=...)``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from perceiver_tpu.parallel.sharding import param_sharding

    batch_sh = NamedSharding(mesh, P("data"))
    return (param_sharding(params, mesh),
            tuple(batch_sh for _ in graph.inputs),
            {name: batch_sh for name in graph.output_names})


# --- packed (ragged) serve graphs --------------------------------------------
#
# The packed path replaces the [B, S] rectangle with one concatenated
# token axis plus per-request (row_offsets, lengths) descriptors — the
# layout the Pallas ragged kernels (ops/ragged_attention.py) consume.
# Padding then exists only at the tail of the token buffer (to the
# token-budget bucket) and in unused request rows, and both are inert:
# the ragged cross-attention kernel skips kv blocks outside a request's
# span, and zero-length rows produce zero latents.


@dataclasses.dataclass(frozen=True)
class PackedServeGraph:
    """A seq-bucketable task's serve computation over a packed batch.

    ``fn(params, packed_ids, row_offsets, lengths)`` returns a dict of
    device arrays: *token-axis* outputs are shaped ``(T, ...)`` along
    the packed token buffer (slice per request with ``row_offsets`` /
    ``lengths``); *request-axis* outputs are shaped ``(R, ...)``.
    ``inputs`` shape callables take ``(tokens, rows)`` — the
    token-budget bucket. ``max_seq_len`` caps any single request (the
    model's position-table size)."""

    kind: str
    model: object
    fn: Callable
    inputs: Tuple[InputSpec, ...]
    output_names: Tuple[str, ...]
    donate_argnums: Tuple[int, ...]
    max_seq_len: int
    token_axis_outputs: Tuple[str, ...] = ()
    request_axis_outputs: Tuple[str, ...] = ()

    def init_params(self, seed: int = 0):
        return self.model.init(jax.random.key(seed))


_PACKED_INPUTS = (
    InputSpec("packed_ids", jnp.int32, lambda t, r: (t,), PAD_TOKEN_ID),
    # pad value is a placeholder: the engine pads unused rows with the
    # batch's total real token count (an empty span parked at the end
    # of the real tokens), not a constant
    InputSpec("row_offsets", jnp.int32, lambda t, r: (r,), 0),
    InputSpec("lengths", jnp.int32, lambda t, r: (r,), 0),
)


def _packed_rows_positions(row_offsets, lengths, tokens: int,
                           max_seq_len: int):
    """Per-token (row, in-request position) from the span descriptors.

    ``searchsorted(side="right") - 1`` maps token index → owning row;
    repeated offsets (zero-length rows) resolve to the *last* row
    starting there, and tail padding clamps to the final row — both
    yield garbage rows whose outputs the host never reads (it slices by
    real spans), so only finiteness matters there."""
    del lengths
    n_rows = row_offsets.shape[0]
    tok = jnp.arange(tokens, dtype=jnp.int32)
    rows = jnp.clip(
        jnp.searchsorted(row_offsets, tok, side="right").astype(jnp.int32) - 1,
        0, n_rows - 1)
    positions = jnp.clip(tok - jnp.take(row_offsets, rows), 0,
                         max_seq_len - 1)
    return rows, positions


def _packed_encoder_apply(encoder, params, packed_ids, positions,
                          row_offsets, lengths, *, policy: Policy,
                          block_k: int = 128):
    """Encoder forward over a packed token axis → (R, N, C) latents.

    Mirrors ``PerceiverEncoder.apply`` (hoisted kv, layer_1 then a
    ``layer_n`` scan) with the masked einsum cross-attention swapped
    for ``ragged_cross_attention``: the kv projections run ONCE over
    the packed buffer — total real tokens, not B×S — and each
    request's latents attend only to the kv blocks its span covers."""
    from perceiver_tpu.models.perceiver import self_attention_block_apply
    from perceiver_tpu.ops.attention import cross_attention_kv
    from perceiver_tpu.ops.ragged_attention import ragged_cross_attention

    n_req = row_offsets.shape[0]
    n_lat, channels = encoder.latent_shape
    num_heads = encoder.num_cross_attention_heads
    max_len = encoder.input_adapter.max_seq_len

    # (T, C) → (1, T, C): the kv projections expect a batch axis
    x_kv = encoder.input_adapter.apply_packed(
        params["input_adapter"], packed_ids, positions, policy=policy)[None]
    latent = jnp.broadcast_to(
        policy.cast_param(params["latent"])[None], (n_req, n_lat, channels))

    def layer_kv(layer_params):
        kh, vh = cross_attention_kv(layer_params["cross"]["attn"], x_kv,
                                    num_heads=num_heads, policy=policy)
        # (1, T, H, Dh) → (H, T, Dh)
        return kh[0].swapaxes(0, 1), vh[0].swapaxes(0, 1)

    def one_layer(layer_params, kv, lat):
        attn = layer_params["cross"]["attn"]
        kh, vh = kv
        xq = layer_norm_apply(attn["norm_q"], lat, policy=policy)
        qh = linear_apply(attn["mha"]["q"], xq, policy=policy)
        head_dim = qh.shape[-1] // num_heads
        q = qh.reshape(n_req, n_lat, num_heads, head_dim).transpose(
            0, 2, 1, 3)
        o = ragged_cross_attention(
            q, kh, vh, row_offsets, lengths,
            scale=1.0 / (head_dim ** 0.5), block_k=block_k,
            max_len=max_len)
        o = o.transpose(0, 2, 1, 3).reshape(n_req, n_lat,
                                            num_heads * head_dim)
        o = linear_apply(attn["mha"]["out"], o, policy=policy)
        y = lat + o
        y = y + mlp_apply(layer_params["cross"]["mlp"], y, policy=policy)
        return self_attention_block_apply(
            layer_params["selfs"], y,
            num_heads=encoder.num_self_attention_heads, policy=policy)

    latent = one_layer(params["layer_1"], layer_kv(params["layer_1"]),
                       latent)
    if encoder.num_layers > 1:
        layer_n = params["layer_n"]
        kv_n = layer_kv(layer_n)

        def body(carry, _):
            return one_layer(layer_n, kv_n,
                             policy.cast_compute(carry)), None

        latent, _ = jax.lax.scan(body, latent, None,
                                 length=encoder.num_layers - 1)
    return latent


def _packed_mlm_decode(decoder, params, latent, positions, rows, *,
                       policy: Policy):
    """Per-token MLM decode: each packed token queries ITS request's
    latents via the block-diagonal ragged decode kernel, so the decoder
    runs over total real tokens instead of B×S query rows."""
    from perceiver_tpu.ops.ragged_attention import ragged_decode_attention

    n_req, n_lat, _ = latent.shape
    num_heads = decoder.num_cross_attention_heads
    tokens = positions.shape[0]
    attn = params["cross"]["attn"]

    query = jnp.take(policy.cast_param(params["query"]), positions, axis=0)
    xq = layer_norm_apply(attn["norm_q"], query, policy=policy)
    qh = linear_apply(attn["mha"]["q"], xq, policy=policy)
    head_dim = qh.shape[-1] // num_heads
    q = qh.reshape(tokens, num_heads, head_dim).swapaxes(0, 1)  # (H, T, Dh)

    xkv = layer_norm_apply(attn["norm_kv"], latent, policy=policy)
    kh = linear_apply(attn["mha"]["k"], xkv, policy=policy)
    vh = linear_apply(attn["mha"]["v"], xkv, policy=policy)
    kh = kh.reshape(n_req * n_lat, num_heads, head_dim).swapaxes(0, 1)
    vh = vh.reshape(n_req * n_lat, num_heads, head_dim).swapaxes(0, 1)

    o = ragged_decode_attention(q, kh, vh, rows, latents_per_row=n_lat,
                                scale=1.0 / (head_dim ** 0.5))
    o = o.swapaxes(0, 1).reshape(tokens, num_heads * head_dim)
    o = linear_apply(attn["mha"]["out"], o, policy=policy)
    x = query + o
    hidden = x + mlp_apply(params["cross"]["mlp"], x, policy=policy)
    return linear_apply(params["output_adapter"]["linear"], hidden,
                        policy=policy)  # (T, V)


def packed_mlm_serve_graph(model, *, policy: Policy = DEFAULT_POLICY,
                           top_k: int = 3,
                           max_seq_len: Optional[int] = None,
                           block_k: int = 128) -> PackedServeGraph:
    if max_seq_len is None:
        max_seq_len = model.decoder.output_adapter.output_shape[0]

    def fn(params, packed_ids, row_offsets, lengths):
        tokens = packed_ids.shape[0]
        rows, positions = _packed_rows_positions(
            row_offsets, lengths, tokens, max_seq_len)
        latent = _packed_encoder_apply(
            model.encoder, params["encoder"], packed_ids, positions,
            row_offsets, lengths, policy=policy, block_k=block_k)
        logits = _packed_mlm_decode(model.decoder, params["decoder"],
                                    latent, positions, rows, policy=policy)
        scores, topk_ids = jax.lax.top_k(
            logits.astype(jnp.float32), top_k)
        topk_ids = topk_ids.astype(packed_ids.dtype)
        is_masked = packed_ids == MASK_TOKEN_ID
        # lax.select, not jnp.where: jnp.where is a jitted wrapper
        # whose module-level _where func dedups against the identical
        # inner func of the jitted takes — a dedup that depends on
        # jit-cache retention across lowerings, so module text (and the
        # exec-cache key) would drift with process history
        filled_ids = jax.lax.select(is_masked, topk_ids[..., 0],
                                    packed_ids)
        return {"filled_ids": filled_ids, "topk_ids": topk_ids,
                "topk_scores": scores, "is_masked": is_masked}

    return PackedServeGraph(
        kind="mlm_packed", model=model, fn=fn, inputs=_PACKED_INPUTS,
        output_names=("filled_ids", "topk_ids", "topk_scores",
                      "is_masked"),
        token_axis_outputs=("filled_ids", "topk_ids", "topk_scores",
                            "is_masked"),
        # packed_ids (T,) int32 aliases filled_ids exactly; the span
        # descriptors are tiny and re-read by the host, so they stay
        donate_argnums=(1,),
        max_seq_len=max_seq_len)


def packed_text_clf_serve_graph(task, *,
                                policy: Policy = DEFAULT_POLICY,
                                block_k: int = 128) -> PackedServeGraph:
    model = task.build()
    max_seq_len = task.max_seq_len

    def fn(params, packed_ids, row_offsets, lengths):
        tokens = packed_ids.shape[0]
        _, positions = _packed_rows_positions(
            row_offsets, lengths, tokens, max_seq_len)
        latent = _packed_encoder_apply(
            model.encoder, params["encoder"], packed_ids, positions,
            row_offsets, lengths, policy=policy, block_k=block_k)
        # per-request latents are an ordinary (R, N, C) batch — the
        # rectangular decoder applies unchanged (latent kv, no padding)
        logits = model.decoder.apply(params["decoder"], latent,
                                     policy=policy)
        logits = logits.astype(jnp.float32)
        return {"logits": logits,
                "probs": jax.nn.softmax(logits, axis=-1),
                "label": jnp.argmax(logits, axis=-1).astype(jnp.int32)}

    return PackedServeGraph(
        kind="text_clf_packed", model=model, fn=fn, inputs=_PACKED_INPUTS,
        output_names=("logits", "probs", "label"),
        request_axis_outputs=("logits", "probs", "label"),
        donate_argnums=(),
        max_seq_len=max_seq_len)


def build_packed_serve_graph(task, *, policy: Policy = DEFAULT_POLICY,
                             top_k: int = 3) -> PackedServeGraph:
    """Packed serve graph for a seq-bucketable task config. Fixed-shape
    (image) tasks have nothing to pack — rectangles remain their only
    path."""
    from perceiver_tpu.tasks import (
        MaskedLanguageModelTask,
        TextClassifierTask,
    )

    if isinstance(task, MaskedLanguageModelTask):
        return packed_mlm_serve_graph(task.build(), policy=policy,
                                      top_k=top_k,
                                      max_seq_len=task.max_seq_len)
    if isinstance(task, TextClassifierTask):
        return packed_text_clf_serve_graph(task, policy=policy)
    raise TypeError(
        f"no packed serve graph for task type {type(task).__name__}; "
        "supported: MaskedLanguageModelTask, TextClassifierTask "
        "(fixed-shape tasks serve rectangles)")
