#!/bin/bash
# On-chip MLM quality training on the harvested real-text corpus
# (VERDICT r1 #3): the reference MLM recipe (seq 512, vocab 10003,
# batch 64, OneCycle) run as long as the TPU window allows, resumable
# across tunnel drops — re-invoking continues from the newest
# checkpoint (best-k or the SIGTERM/preempt save) with the same
# max_steps so the OneCycle schedule stays consistent.
#
# Usage: scripts/mlm_quality_run.sh [max_steps] [extra CLI args...]
set -u
cd "$(dirname "$0")/.."
MAX_STEPS=${1:-50000}
shift || true

EXP=mlm_tpu_quality
RESUME=()
# newest checkpoint across versions (regular or preempt saves)
latest=$(ls -dt logs/$EXP/version_*/checkpoints* 2>/dev/null | head -1)
if [[ -n "${latest:-}" ]]; then
  RESUME=(--trainer.resume_from_checkpoint "$latest")
  echo "resuming from $latest"
fi

exec python scripts/mlm.py fit \
  --data.data_dir=.cache \
  --optimizer.init_args.lr=0.002 \
  --trainer.max_steps="$MAX_STEPS" \
  --trainer.steps_per_execution=8 \
  --trainer.log_every_n_steps=100 \
  --experiment="$EXP" \
  "${RESUME[@]}" "$@"
