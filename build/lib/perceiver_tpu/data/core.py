"""Dataset/batching primitives.

The reference leans on torch ``DataLoader`` worker processes
(``data/imdb.py:112-126``, ``data/mnist.py:15``). On TPU the input
pipeline is a host-side NumPy concern: batches are assembled on CPU and
handed to jitted steps as static-shape arrays. Static shapes are a hard
requirement — a ragged final batch would trigger recompilation — so
every batch carries a boolean ``valid`` row mask and the final partial
batch is padded, letting eval metrics stay exact without dynamic
shapes (SURVEY §7.4).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Sequence

import numpy as np


class ArrayDataset:
    """A tuple of equal-length arrays with named fields."""

    def __init__(self, **fields: np.ndarray):
        lengths = {k: len(v) for k, v in fields.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"Field length mismatch: {lengths}")
        self.fields = fields
        self.length = next(iter(lengths.values())) if lengths else 0

    def __len__(self) -> int:
        return self.length

    def subset(self, indices: Sequence[int]) -> "ArrayDataset":
        return ArrayDataset(**{k: v[indices] for k, v in self.fields.items()})


class BatchIterator:
    """Deterministic, epoch-seeded batching over an ArrayDataset.

    Yields dict batches with an extra ``valid`` (B,) bool mask; the
    final partial batch is zero-padded to the full batch size.
    """

    def __init__(self, dataset: ArrayDataset, batch_size: int,
                 shuffle: bool = False, seed: int = 0,
                 drop_last: bool = False,
                 transform=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.transform = transform
        self.epoch = 0
        self.num_shards = 1
        self.shard_index = 0
        self.pad_remainder = False

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def set_sharding(self, num_shards: int, shard_index: int,
                     pad_remainder: bool = False):
        """Per-host dataset sharding — the DistributedSampler /
        ``replace_sampler_ddp`` equivalent (reference trainer.yaml:61):
        every host shuffles with the SAME seed, then takes a strided
        slice, so the union of hosts covers the epoch exactly once and
        each host yields the same number of batches (collective step
        counts must agree).

        ``pad_remainder=False`` (training): the trailing remainder is
        dropped for equal shards. ``pad_remainder=True`` (eval): short
        shards are padded with invalid rows instead, so every example
        is evaluated exactly once and metrics stay exact.
        """
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard {shard_index} not in [0, {num_shards})")
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.pad_remainder = pad_remainder

    def _shard_len(self) -> int:
        """Per-shard index count (including any remainder padding)."""
        n = len(self.dataset)
        if self.num_shards <= 1:
            return n
        if self.pad_remainder:
            return -(-n // self.num_shards)
        return n // self.num_shards

    def _indices(self) -> "tuple[np.ndarray, int]":
        """Returns ``(indices, n_valid)``; positions >= n_valid are
        remainder padding to be masked invalid."""
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            rng.shuffle(idx)
        if self.num_shards > 1:
            per = self._shard_len()
            idx = idx[self.shard_index::self.num_shards][:per]
            n_valid = len(idx)
            if n_valid < per:  # pad_remainder: equal length, masked tail
                idx = np.concatenate(
                    [idx, np.zeros(per - n_valid, dtype=idx.dtype)])
            return idx, n_valid
        return idx, n

    def __len__(self) -> int:
        n = self._shard_len()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        idx, n_valid = self._indices()
        n = len(idx)
        bs = self.batch_size
        limit = (n // bs) * bs if self.drop_last else n
        for start in range(0, limit, bs):
            take = idx[start:start + bs]
            valid = np.arange(start, start + len(take)) < n_valid
            if len(take) < bs:  # pad final partial batch, mask invalid rows
                pad = np.zeros(bs - len(take), dtype=idx.dtype)
                take = np.concatenate([take, pad])
                valid = np.concatenate(
                    [valid, np.zeros(bs - len(valid), dtype=bool)])
            batch = {k: v[take] for k, v in self.dataset.fields.items()}
            batch["valid"] = valid
            if self.transform is not None:
                batch = self.transform(batch, self.epoch,
                                       start // bs)
            yield batch
