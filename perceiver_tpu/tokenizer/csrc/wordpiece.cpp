// WordPiece tokenizer native core.
//
// The reference delegates tokenization to the Rust HF `tokenizers`
// library (reference perceiver/tokenizer.py:3-7); this is the
// framework's C++ equivalent for the two hot paths:
//
//   wp_encode_words — greedy longest-match WordPiece over a vocab hash
//     (byte-wise longest match; vocab entries are valid UTF-8, so
//     mid-codepoint splits can never match and char-boundary semantics
//     are preserved).
//   wp_train — likelihood-scored pair-merge training
//     (score = freq(pair) / (freq(a) * freq(b))) with incremental
//     pair/symbol-frequency bookkeeping, so training the IMDB corpus
//     to a 10k vocab is minutes of C++, not hours of Python.
//
// Normalization (NFD/lowercase/strip-accents) stays in Python: CPython's
// unicodedata is already a C extension and it is not on the hot path.
//
// Exposed over a plain C ABI for ctypes (no pybind11 in this image).
// Tie-breaking matches the pure-Python trainer exactly (score desc,
// then lexicographically smaller pair), so native and fallback engines
// produce identical vocabularies.

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct PairHash {
    size_t operator()(const std::pair<int32_t, int32_t>& p) const {
        return std::hash<int64_t>()(
            (static_cast<int64_t>(p.first) << 32) ^
            static_cast<uint32_t>(p.second));
    }
};

struct Vocab {
    std::unordered_map<std::string, int32_t> token_to_id;
    size_t max_token_bytes = 0;
};

size_t utf8_len(const std::string& s) {
    size_t n = 0;
    for (unsigned char c : s)
        if ((c & 0xC0) != 0x80) ++n;
    return n;
}

}  // namespace

extern "C" {

void* wp_vocab_create(const char** tokens, int32_t n) {
    auto* v = new Vocab();
    for (int32_t i = 0; i < n; ++i) {
        std::string t(tokens[i]);
        v->max_token_bytes = std::max(v->max_token_bytes, t.size());
        v->token_to_id.emplace(std::move(t), i);
    }
    return v;
}

void wp_vocab_free(void* v) { delete static_cast<Vocab*>(v); }

// Encode one pre-tokenized word. Appends piece ids to out (capacity cap);
// returns the number of ids written, or -1 if cap was insufficient.
int32_t wp_encode_word(void* vp, const char* word, int32_t unk_id,
                       int32_t max_chars, const char* prefix,
                       int32_t* out, int32_t cap) {
    const Vocab& v = *static_cast<Vocab*>(vp);
    std::string w(word);
    if (utf8_len(w) > static_cast<size_t>(max_chars)) {
        if (cap < 1) return -1;
        out[0] = unk_id;
        return 1;
    }
    const std::string pref(prefix);
    int32_t count = 0;
    size_t start = 0;
    std::string candidate;
    while (start < w.size()) {
        size_t end = w.size();
        int32_t piece = -1;
        size_t piece_end = 0;
        while (start < end) {
            candidate.clear();
            if (start > 0) candidate = pref;
            candidate.append(w, start, end - start);
            auto it = v.token_to_id.find(candidate);
            if (it != v.token_to_id.end()) {
                piece = it->second;
                piece_end = end;
                break;
            }
            --end;
        }
        if (piece < 0) {
            if (cap < 1) return -1;
            out[0] = unk_id;
            return 1;
        }
        if (count >= cap) return -1;
        out[count++] = piece;
        start = piece_end;
    }
    return count;
}

// Encode a batch of pre-tokenized words, '\n'-joined, in one call —
// per-word FFI round-trips cost more than the WordPiece matching itself.
// Returns the number of ids written, or -1 if cap was insufficient.
int32_t wp_encode_words(void* vp, const char* words, int32_t unk_id,
                        int32_t max_chars, const char* prefix,
                        int32_t* out, int32_t cap) {
    int32_t total = 0;
    const char* p = words;
    std::string word;
    while (*p) {
        const char* nl = strchr(p, '\n');
        size_t len = nl ? static_cast<size_t>(nl - p) : strlen(p);
        word.assign(p, len);
        int32_t n = wp_encode_word(vp, word.c_str(), unk_id, max_chars,
                                   prefix, out + total, cap - total);
        if (n < 0) return -1;
        total += n;
        if (!nl) break;
        p = nl + 1;
    }
    return total;
}

// ---------------------------------------------------------------------------
// Trainer
// ---------------------------------------------------------------------------

namespace {

struct Trainer {
    std::vector<std::string> id_to_sym;          // symbol strings
    std::unordered_map<std::string, int32_t> sym_to_id;
    std::vector<std::vector<int32_t>> words;     // word -> symbol ids
    std::vector<int64_t> counts;                 // word -> corpus count
    std::vector<int64_t> sym_freq;               // symbol -> occurrences
    using Pair = std::pair<int32_t, int32_t>;
    std::unordered_map<Pair, int64_t, PairHash> pair_freq;
    std::unordered_map<Pair, std::unordered_set<int32_t>, PairHash>
        pair_words;

    int32_t intern(const std::string& s) {
        auto it = sym_to_id.find(s);
        if (it != sym_to_id.end()) return it->second;
        int32_t id = static_cast<int32_t>(id_to_sym.size());
        id_to_sym.push_back(s);
        sym_to_id.emplace(s, id);
        sym_freq.push_back(0);
        return id;
    }

    void add_pairs_of(int32_t wi) {
        const auto& syms = words[wi];
        int64_t c = counts[wi];
        for (size_t j = 0; j + 1 < syms.size(); ++j) {
            Pair p{syms[j], syms[j + 1]};
            pair_freq[p] += c;
            pair_words[p].insert(wi);
        }
    }

    void remove_pairs_of(int32_t wi) {
        const auto& syms = words[wi];
        int64_t c = counts[wi];
        for (size_t j = 0; j + 1 < syms.size(); ++j) {
            Pair p{syms[j], syms[j + 1]};
            auto it = pair_freq.find(p);
            if (it != pair_freq.end()) {
                it->second -= c;
                if (it->second <= 0) {
                    pair_freq.erase(it);
                    pair_words.erase(p);
                }
            }
        }
    }
};

}  // namespace

// Train from unique words + counts. Returns a malloc'd buffer of
// '\n'-joined vocab tokens in id order (caller frees with wp_free).
char* wp_train(const char** word_strs, const int64_t* word_counts,
               int32_t n_words, const char** specials, int32_t n_specials,
               const char* prefix, int32_t vocab_size, int64_t min_freq) {
    Trainer tr;
    const std::string pref(prefix);

    // vocab under construction: specials first, then alphabet, then merges
    std::vector<std::string> vocab;
    std::unordered_set<std::string> vocab_set;
    auto add_vocab = [&](const std::string& t) {
        if (vocab_set.insert(t).second) vocab.push_back(t);
    };
    for (int32_t i = 0; i < n_specials; ++i) add_vocab(specials[i]);

    // split words into initial symbols (first char plain, rest ##'d)
    std::map<std::string, size_t> alphabet;  // ordered like sorted(set)
    tr.words.resize(n_words);
    tr.counts.assign(word_counts, word_counts + n_words);
    for (int32_t wi = 0; wi < n_words; ++wi) {
        const std::string w(word_strs[wi]);
        std::vector<std::string> chars;
        size_t i = 0;
        while (i < w.size()) {
            size_t j = i + 1;
            while (j < w.size() && (static_cast<unsigned char>(w[j]) & 0xC0)
                       == 0x80)
                ++j;
            chars.push_back(w.substr(i, j - i));
            i = j;
        }
        auto& syms = tr.words[wi];
        for (size_t k = 0; k < chars.size(); ++k) {
            std::string s = k == 0 ? chars[k] : pref + chars[k];
            alphabet[s] = 1;
            int32_t id = tr.intern(s);
            syms.push_back(id);
            tr.sym_freq[id] += tr.counts[wi];
        }
    }
    for (const auto& kv : alphabet) add_vocab(kv.first);
    for (int32_t wi = 0; wi < n_words; ++wi) tr.add_pairs_of(wi);

    const int64_t effective_min = min_freq > 1 ? min_freq : 1;
    while (static_cast<int32_t>(vocab.size()) < vocab_size &&
           !tr.pair_freq.empty()) {
        // argmax score; tie → lexicographically smaller (a, b)
        Trainer::Pair best{-1, -1};
        double best_score = -1.0;
        for (const auto& kv : tr.pair_freq) {
            if (kv.second < effective_min) continue;
            double score = static_cast<double>(kv.second) /
                (static_cast<double>(tr.sym_freq[kv.first.first]) *
                 static_cast<double>(tr.sym_freq[kv.first.second]));
            if (score > best_score) {
                best = kv.first;
                best_score = score;
            } else if (score == best_score && best.first >= 0) {
                const std::string& a1 = tr.id_to_sym[kv.first.first];
                const std::string& b1 = tr.id_to_sym[kv.first.second];
                const std::string& a0 = tr.id_to_sym[best.first];
                const std::string& b0 = tr.id_to_sym[best.second];
                if (a1 < a0 || (a1 == a0 && b1 < b0)) best = kv.first;
            }
        }
        if (best.first < 0) break;

        const std::string& a = tr.id_to_sym[best.first];
        const std::string& b = tr.id_to_sym[best.second];
        std::string merged = a + (b.rfind(pref, 0) == 0
                                  ? b.substr(pref.size()) : b);
        int32_t merged_id = tr.intern(merged);
        add_vocab(merged);

        // rewrite only the words containing the merged pair
        auto affected_it = tr.pair_words.find(best);
        if (affected_it == tr.pair_words.end()) break;
        std::vector<int32_t> affected(affected_it->second.begin(),
                                      affected_it->second.end());
        for (int32_t wi : affected) {
            tr.remove_pairs_of(wi);
            auto& syms = tr.words[wi];
            std::vector<int32_t> out;
            out.reserve(syms.size());
            size_t j = 0;
            while (j < syms.size()) {
                if (j + 1 < syms.size() && syms[j] == best.first &&
                    syms[j + 1] == best.second) {
                    out.push_back(merged_id);
                    tr.sym_freq[best.first] -= tr.counts[wi];
                    tr.sym_freq[best.second] -= tr.counts[wi];
                    tr.sym_freq[merged_id] += tr.counts[wi];
                    j += 2;
                } else {
                    out.push_back(syms[j]);
                    ++j;
                }
            }
            syms.swap(out);
            tr.add_pairs_of(wi);
        }
    }

    size_t total = 0;
    for (const auto& t : vocab) total += t.size() + 1;
    char* buf = static_cast<char*>(malloc(total + 1));
    char* p = buf;
    for (const auto& t : vocab) {
        memcpy(p, t.data(), t.size());
        p += t.size();
        *p++ = '\n';
    }
    *p = '\0';
    return buf;
}

void wp_free(char* p) { free(p); }

}  // extern "C"
