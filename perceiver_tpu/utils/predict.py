"""Masked-sample prediction — compat wrapper over the serving engine.

Parity target: reference ``perceiver/utils.py:22-43`` / SURVEY §3.5:
encode raw strings (containing ``[MASK]``) with the data collator, run
the MLM with ``masking=False``, take top-k vocab logits at each masked
position, substitute each of the k predictions, and decode back to k
complete strings per sample.

Historically this helper re-created a lambda per call — a fresh jit
cache key, i.e. one full XLA recompile *per prediction request* — and
pulled the whole (B, L, V) logits tensor to the host. It now routes
through ``perceiver_tpu.serving``: the serve graph (top-k and mask
filling on device) is AOT-compiled once per shape and cached per model
config, so a second call at the same shapes performs zero new
compiles, and weight refreshes (the trainer calls this every val
epoch with updated params) swap device buffers without recompiling.
The signature and return value are unchanged.
"""

from __future__ import annotations

from typing import Callable, List

from perceiver_tpu.serving.api import (  # noqa: F401 — public re-export
    predict_masked_samples as _serve_predict_masked_samples,
)


def predict_masked_samples(masked_samples: List[str],
                           encode_fn: Callable,
                           tokenizer,
                           model,
                           params,
                           num_predictions: int = 3,
                           policy=None) -> List[List[str]]:
    return _serve_predict_masked_samples(
        masked_samples, encode_fn, tokenizer, model, params,
        num_predictions=num_predictions, policy=policy)
