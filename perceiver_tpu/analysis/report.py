"""Violation records and reports shared by both analysis halves.

Every graph pass and lint rule reduces to the same currency: a
``Violation`` naming the pass/rule, where it fired, and why. A
``Report`` aggregates them; ``scripts/check.py`` turns a non-empty
report into a non-zero exit, which is the whole gating contract —
there is deliberately no warning level, because a warning that does
not fail the merge is re-discovered by hand a round later (the exact
failure mode this subsystem exists to end; see ISSUE 1 / ADVICE.md).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Violation:
    """One check failure.

    check:   pass or lint-rule name (``dtype_policy``, ``jit-host-sync``).
    where:   location — ``file.py:line`` for lint, target name for
             graph passes.
    message: what is wrong and, where possible, what to do instead.
    """

    check: str
    where: str
    message: str

    def format(self) -> str:
        return f"{self.where}: [{self.check}] {self.message}"


@dataclasses.dataclass
class Report:
    violations: List[Violation] = dataclasses.field(default_factory=list)
    # passes/rules that actually ran (a report that is empty because
    # nothing executed must not read as a clean tree)
    checks_run: List[str] = dataclasses.field(default_factory=list)

    def add(self, violation: Violation) -> None:
        self.violations.append(violation)

    def extend(self, violations) -> None:
        self.violations.extend(violations)

    def ran(self, check: str) -> None:
        if check not in self.checks_run:
            self.checks_run.append(check)

    def merge(self, other: "Report") -> None:
        self.extend(other.violations)
        for c in other.checks_run:
            self.ran(c)

    @property
    def ok(self) -> bool:
        return not self.violations

    def format(self) -> str:
        lines = [v.format() for v in self.violations]
        lines.append(f"{len(self.violations)} violation(s) from "
                     f"{len(self.checks_run)} check(s): "
                     f"{', '.join(self.checks_run) or '(none ran)'}")
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "checks_run": list(self.checks_run),
            "violations": [dataclasses.asdict(v) for v in self.violations],
        }


@dataclasses.dataclass(frozen=True)
class DtypeAllow:
    """One ``dtype_policy`` allowlist entry: permits up to ``max_count``
    matmul-class ops with the given operand dtype, optionally narrowed
    by a substring of the op's tensor-type signature. Every entry
    must carry a human reason — the allowlist IS the audit trail."""

    dtype: str                      # e.g. "f32"
    reason: str
    max_count: int = 1
    type_substr: Optional[str] = None

    def matches(self, dtype: str, type_sig: str) -> bool:
        if dtype != self.dtype:
            return False
        return self.type_substr is None or self.type_substr in type_sig


@dataclasses.dataclass(frozen=True)
class TransferAllow:
    """One ``transfer_guard`` allowlist entry: permits up to
    ``max_count`` occurrences of a host-transfer marker (custom-call
    target or op name substring) with a recorded reason."""

    marker: str
    reason: str
    max_count: int = 1


@dataclasses.dataclass(frozen=True)
class RaceAllow:
    """One ``guarded-attrs`` (racecheck) allowlist entry: permits up to
    ``max_count`` accesses of a guarded attribute outside its declared
    lock, identified as ``"ClassName.attr"`` (dotted and ``*.attr``
    keys use the key spelling, e.g. ``"Router.inflight"`` for the
    ``"*.inflight"`` declaration). Every entry must carry a human
    reason — the allowlist IS the audit trail, same contract as the
    dtype/transfer/replication allowlists."""

    attr: str                       # "ClassName.attr"
    reason: str
    max_count: int = 1


@dataclasses.dataclass(frozen=True)
class ReplicationAllow:
    """One ``replication_check`` allowlist entry: permits up to
    ``max_count`` tensors of the given type string (``"8192x64xf32"``)
    to live fully replicated above the size floor, with a recorded
    reason (e.g. a read-only embedding table replicated by design)."""

    type: str
    reason: str
    max_count: int = 1


def apply_dtype_allowlist(records: List[dict],
                          allowlist: Tuple[DtypeAllow, ...]):
    """Split fp32+ matmul records into (allowed, violating) under the
    allowlist's per-entry count budgets."""
    budgets = {id(a): a.max_count for a in allowlist}
    allowed, violating = [], []
    for rec in records:
        hit = None
        for a in allowlist:
            if budgets[id(a)] > 0 and a.matches(rec["dtype"], rec["sig"]):
                hit = a
                break
        if hit is not None:
            budgets[id(hit)] -= 1
            allowed.append(rec)
        else:
            violating.append(rec)
    return allowed, violating
