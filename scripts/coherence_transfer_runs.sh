#!/bin/bash
# Scratch-vs-transfer comparison on the COHERENCE corpus (VERDICT r2
# #4): labels that bag-of-words provably cannot solve (the BoW control
# in QUALITY_r03.json sits at chance), so an end-task win for the
# MLM-transfer recipe measures representation quality, not keyword
# lookup. Equal total budget: scratch 600 steps vs transfer 300
# (frozen phase 1) + 300 (unfrozen phase 2); plus the frozen-RANDOM-
# encoder probe as the control for the frozen-MLM probe.
#
# Usage: scripts/coherence_transfer_runs.sh [mlm_ckpt_dir]
set -u
cd "$(dirname "$0")/.."

DATA=.cache_coh
[[ -d $DATA/aclImdb ]] || { echo "run make_coherence_corpus.py first"; exit 1; }

# default MLM source: furthest-step checkpoint across the quality runs
MLM_CKPT=${1:-}
if [[ -z "$MLM_CKPT" ]]; then
  best_step=-1
  for d in logs/mlm_quality/version_*/checkpoints* \
           logs/mlm_quality_resumed_on_cpu/version_*/checkpoints* \
           logs/mlm_cpu_quality/version_*/checkpoints*; do
    [[ -d "$d" ]] || continue
    for s in "$d"/*/; do
      s=${s%/}; s=${s##*/}
      [[ "$s" =~ ^[0-9]+$ ]] || continue
      if (( s > best_step )); then best_step=$s; MLM_CKPT=$d; fi
    done
  done
  echo "using MLM checkpoint $MLM_CKPT (step $best_step)"
fi

COMMON=(--data.data_dir=$DATA --data.batch_size=32
        --trainer.log_every_n_steps=50 --trainer.accelerator=cpu)

run() {
  local name=$1; shift
  if ls "logs/$name"/version_*/events.* > /dev/null 2>&1; then
    echo "== $name already has a run — skipping"
    return 0
  fi
  echo "== $name: $(date -u +%FT%TZ)"
  python scripts/seq_clf.py fit "${COMMON[@]}" --experiment="$name" "$@" \
    > "logs/$name.log" 2>&1
  echo "== $name done rc=$? $(date -u +%FT%TZ)"
}

# control: frozen RANDOM encoder probe (what does the architecture +
# trainable decoder get on its own?)
run coh_frozen_random --model.freeze_encoder=true --trainer.max_steps=300

# phase 1: frozen MLM encoder probe
run coh_phase1 --model.freeze_encoder=true --model.mlm_ckpt="$MLM_CKPT" \
    --trainer.max_steps=300

# phase 2: unfreeze from the phase-1 checkpoint, reference recipe lr
PH1=$(ls -d logs/coh_phase1/version_*/checkpoints 2>/dev/null | sort -V | tail -1)
run coh_phase2 --model.clf_ckpt="$PH1" --optimizer.init_args.lr=0.0001 \
    --trainer.max_steps=300

# scratch at the SAME total budget as phase1+phase2
run coh_scratch --trainer.max_steps=600

python scripts/quality_summary.py coh_frozen_random coh_phase1 \
  coh_phase2 coh_scratch | tee QUALITY_r03_coherence.json
