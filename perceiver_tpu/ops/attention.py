"""Multi-head attention as einsum over the MXU.

Re-expresses the reference's ``nn.MultiheadAttention`` wrapper
(``perceiver/model.py:59-74``) — including the asymmetric ``kdim``/
``vdim`` path used by cross-attention, ``key_padding_mask`` /
``attn_mask`` forwarding, and dropout on attention weights — as pure
einsum-based functions:

- q is projected from ``q_dim`` (the embedding dim), k from ``k_dim``,
  v from ``v_dim``, all to ``q_dim``; output projection maps back to
  ``q_dim``. This matches torch's separate q/k/v projection weights
  when ``kdim``/``vdim`` differ from ``embed_dim``.
- ``key_padding_mask`` is boolean ``(B, Lk)``, True at padding
  positions (reference ``data/imdb.py:64``); masked logits get a large
  negative additive bias before the fp32 softmax.
- Attention-weight dropout matches torch's placement (after softmax).

Cross-attention (``perceiver/model.py:77-99``) pre-norms both q and kv;
self-attention (``model.py:102-116``) pre-norms its single input. The
embedding dim equals the number of q channels — the reference's stated
simplification vs. the paper (``model.py:78-82``).

Shapes are static and heads are a named einsum axis, so XLA tiles the
two batched matmuls straight onto the MXU and fuses scale/mask/softmax
between them. A fused Pallas kernel (``perceiver_tpu.ops.pallas_attention``)
can replace the softmax path for long-kv shapes.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from perceiver_tpu.ops.dropout import dropout
from perceiver_tpu.ops.initializers import uniform, xavier_uniform
from perceiver_tpu.ops.linear import linear_init, linear_apply
from perceiver_tpu.ops.norm import layer_norm_init, layer_norm_apply
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY

NEG_INF = -1e30  # large-negative bias; safe in fp32 softmax accumulation


def mha_init(key, q_dim: int, num_heads: int,
             k_dim: Optional[int] = None, v_dim: Optional[int] = None,
             dtype=jnp.float32):
    """Init q/k/v/out projections (torch MultiheadAttention scheme).

    torch distinguishes the packed case: with ``kdim == vdim ==
    embed_dim`` it stores one ``in_proj_weight`` of shape (3E, E) and
    xavier-inits THAT (bound √(6/4E)); per-matrix xavier on each E×E
    slice would be √2 larger (VERDICT r3 weak #5). With asymmetric
    dims torch xavier-inits the three matrices separately — matching
    the per-matrix scheme below.
    """
    if q_dim % num_heads != 0:
        raise ValueError(f"q_dim {q_dim} not divisible by num_heads {num_heads}")
    k_dim = q_dim if k_dim is None else k_dim
    v_dim = q_dim if v_dim is None else v_dim
    kq, kk, kv, ko = jax.random.split(key, 4)
    out = linear_init(ko, q_dim, q_dim, dtype)
    if k_dim == q_dim and v_dim == q_dim:
        packed_bound = math.sqrt(6.0 / (q_dim + 3 * q_dim))

        def proj(k, shape):
            return uniform(k, shape, packed_bound, dtype)
    else:
        def proj(k, shape):
            return xavier_uniform(k, shape, dtype)
    return {
        # torch: xavier-uniform projection weights, zero in-proj bias
        "q": {"w": proj(kq, (q_dim, q_dim)),
              "b": jnp.zeros((q_dim,), dtype)},
        "k": {"w": proj(kk, (k_dim, q_dim)),
              "b": jnp.zeros((q_dim,), dtype)},
        "v": {"w": proj(kv, (v_dim, q_dim)),
              "b": jnp.zeros((q_dim,), dtype)},
        "out": {"w": out["w"], "b": jnp.zeros((q_dim,), dtype)},
    }


def _split_heads(x, num_heads: int):
    b, l, e = x.shape
    return x.reshape(b, l, num_heads, e // num_heads)


@jax.custom_vjp
def _qk_dot(qh, kh):
    """QK^T with fp32 accumulation forward and a bf16 cotangent
    backward.

    Forward is bitwise-identical to the plain einsum (bf16 operands,
    ``preferred_element_type=f32`` — the MXU accumulates in fp32
    natively). Backward casts the incoming fp32 softmax cotangent to
    bf16 before the two large grad contractions, the same trade every
    production flash-attention backward makes: without it XLA upcasts
    both dots to fp32, which the TPU executes at a fraction of the
    bf16 MXU rate (graph audit: scripts/hlo_audit.py)."""
    return jnp.einsum("bqhd,bkhd->bhqk", qh, kh,
                      preferred_element_type=jnp.float32)


def _qk_dot_fwd(qh, kh):
    return _qk_dot(qh, kh), (qh, kh)


def _qk_dot_bwd(res, g):
    qh, kh = res
    gb = g.astype(jnp.bfloat16)
    dq = jnp.einsum("bhqk,bkhd->bqhd", gb, kh)
    dk = jnp.einsum("bhqk,bqhd->bkhd", gb, qh)
    return dq.astype(qh.dtype), dk.astype(kh.dtype)


_qk_dot.defvjp(_qk_dot_fwd, _qk_dot_bwd)


# The attention-kernel domain, the single source of truth for the
# config-time membership validation in models/perceiver.py and
# tasks/base.py (and the trace-time check in mha_apply below).
SPMD_IMPLS = ("seqpar", "ring", "ulysses")
ATTENTION_IMPLS = (None, "einsum", "chunked", "flash") + SPMD_IMPLS
# output-query ← latent cross-attention: the SPMD impls shard the
# encoder token axis and do not apply (tasks/base.py docstring)
DECODER_ATTENTION_IMPLS = (None, "einsum", "chunked", "flash")
_SPMD_IMPLS = SPMD_IMPLS


def mha_apply(params, q, k, v, *, num_heads: int,
              key_padding_mask=None, attn_mask=None,
              dropout_rate: float = 0.0, rng=None, deterministic: bool = True,
              policy: Policy = DEFAULT_POLICY, impl: Optional[str] = None,
              kv_chunk_size: int = 1024, spmd=None):
    """Scaled dot-product multi-head attention.

    q: (B, Lq, q_dim); k: (B, Lk, k_dim); v: (B, Lk, v_dim).
    key_padding_mask: (B, Lk) bool, True at padding.
    attn_mask: (Lq, Lk) or (B, Lq, Lk); bool (True = masked) or additive.
    impl: None/"einsum" (materialized weights, supports dropout and
    attn_mask), "chunked" (blockwise lax.scan, O(Lq·chunk) memory,
    supports streamed attention dropout),
    "flash" (fused Pallas TPU kernel; interpreter mode off-TPU), or one
    of the shard_map sequence-parallel kernels — "seqpar" (q replicated,
    kv sequence-sharded: the Perceiver cross-attention layout), "ring"
    (all of q/k/v sequence-sharded, ppermute kv rotation), "ulysses"
    (all-to-all heads↔sequence re-sharding). The spmd impls require
    ``spmd=(mesh, seq_axis, batch_axis)`` describing how the token axis
    is laid out (batch_axis may be None).
    Returns (B, Lq, q_dim).
    """
    if impl not in ATTENTION_IMPLS:
        raise ValueError(
            f"unknown attention impl {impl!r}; expected None, 'einsum', "
            "'chunked', 'flash', 'seqpar', 'ring', or 'ulysses'")
    if impl in ("chunked", "flash", *_SPMD_IMPLS):
        if attn_mask is not None:
            raise NotImplementedError(
                f"impl={impl!r} supports key_padding_mask only, "
                "not attn_mask")
        if (impl != "chunked" and dropout_rate > 0.0
                and not deterministic):
            raise NotImplementedError(
                f"impl={impl!r} does not support attention-weight "
                "dropout; use the einsum or chunked impl")
    if impl in _SPMD_IMPLS and spmd is None:
        raise ValueError(
            f"impl={impl!r} needs spmd=(mesh, seq_axis, batch_axis)")

    if k is q and v is q:
        # self-attention: pack the three projections into ONE matmul
        # (torch's in_proj). Identical numerics — the concatenated
        # weight produces the same three output blocks — but a single
        # wider MXU op instead of three skinny ones, which matters for
        # dispatch-bound small-channel configs.
        packed = {
            "w": jnp.concatenate([params[n]["w"] for n in ("q", "k", "v")],
                                 axis=1),
            "b": jnp.concatenate([params[n]["b"] for n in ("q", "k", "v")]),
        }
        qkv = linear_apply(packed, q, policy=policy)
        e = qkv.shape[-1] // 3
        qh, kh, vh = (_split_heads(qkv[..., i * e:(i + 1) * e], num_heads)
                      for i in range(3))
    else:
        qh = _split_heads(linear_apply(params["q"], q, policy=policy),
                          num_heads)
        kh = _split_heads(linear_apply(params["k"], k, policy=policy),
                          num_heads)
        vh = _split_heads(linear_apply(params["v"], v, policy=policy),
                          num_heads)

    head_dim = qh.shape[-1]
    if impl in ("chunked", "flash", *_SPMD_IMPLS):
        import perceiver_tpu.ops.chunked_attention as _ca
        bias = (_ca.pad_mask_to_bias(key_padding_mask)
                if key_padding_mask is not None else None)
        # (B, L, H, D) → (B, H, L, D)
        qt, kt, vt = (x.swapaxes(1, 2) for x in (qh, kh, vh))
        scale = 1.0 / (head_dim ** 0.5)
        if impl == "chunked":
            drop = dropout_rate if not deterministic else 0.0
            if drop > 0.0 and rng is None:
                # mirror the einsum path (ops/dropout.py): silently
                # skipping configured dropout would be invisible
                raise ValueError("dropout needs an rng when not "
                                 "deterministic")
            out = _ca.chunked_attention(qt, kt, vt, bias=bias, scale=scale,
                                        chunk_size=kv_chunk_size,
                                        dropout_rate=drop, rng=rng)
        elif impl == "flash":
            import perceiver_tpu.ops.pallas_attention as _pa
            out = _pa.flash_attention(qt, kt, vt, bias=bias, scale=scale,
                                      block_k=kv_chunk_size)
        else:
            from perceiver_tpu.parallel.ring_attention import (
                make_ring_attention,
                make_seq_parallel_cross_attention,
            )
            from perceiver_tpu.parallel.ulysses import (
                make_ulysses_attention,
            )
            mesh, seq_axis, batch_axis = spmd
            if impl == "seqpar":
                f = make_seq_parallel_cross_attention(
                    mesh, seq_axis, batch_axis=batch_axis, scale=scale)
            elif impl == "ring":
                f = make_ring_attention(mesh, seq_axis,
                                        batch_axis=batch_axis, scale=scale)
            else:
                f = make_ulysses_attention(
                    mesh, seq_axis, batch_axis=batch_axis, scale=scale,
                    kv_chunk_size=kv_chunk_size)
            out = f(qt, kt, vt, bias)
        out = out.swapaxes(1, 2)
        b, lq = out.shape[0], out.shape[1]
        out = out.reshape(b, lq, num_heads * head_dim)
        return linear_apply(params["out"], out, policy=policy)

    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, policy.norm_dtype))
    if policy.compute_dtype == jnp.bfloat16:
        # fp32-accumulated forward, bf16-cotangent backward (see
        # _qk_dot): without this the two QK-backward dots inherit the
        # fp32 softmax cotangent and run at the MXU's fp32 rate —
        # ~9% of headline-config step FLOPs at ~8x the cost
        # (logs/hlo_audit_r04_b512_c64.json)
        logits = _qk_dot(qh, kh)
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh, kh,
                            preferred_element_type=policy.norm_dtype)
    logits = logits.astype(policy.norm_dtype) * scale

    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            bias = jnp.where(attn_mask, NEG_INF, 0.0).astype(policy.norm_dtype)
        else:
            bias = attn_mask.astype(policy.norm_dtype)
        if bias.ndim == 2:
            bias = bias[None, None, :, :]
        elif bias.ndim == 3:
            bias = bias[:, None, :, :]
        logits = logits + bias
    if key_padding_mask is not None:
        pad = key_padding_mask[:, None, None, :]  # (B,1,1,Lk)
        logits = jnp.where(pad, NEG_INF, logits)

    weights = jax.nn.softmax(logits, axis=-1)
    weights = dropout(weights, dropout_rate, rng=rng,
                      deterministic=deterministic)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(policy.compute_dtype),
                     vh)
    b, lq = out.shape[0], out.shape[1]
    out = out.reshape(b, lq, num_heads * head_dim)
    return linear_apply(params["out"], out, policy=policy)


# --- pre-norm cross/self attention (reference model.py:77-116) ---------------


def cross_attention_init(key, num_q_channels: int, num_kv_channels: int,
                         num_heads: int, dtype=jnp.float32):
    return {
        "norm_q": layer_norm_init(num_q_channels, dtype),
        "norm_kv": layer_norm_init(num_kv_channels, dtype),
        "mha": mha_init(key, num_q_channels, num_heads,
                        k_dim=num_kv_channels, v_dim=num_kv_channels,
                        dtype=dtype),
    }


def cross_attention_apply(params, x_q, x_kv, *, num_heads: int,
                          key_padding_mask=None, attn_mask=None,
                          dropout_rate: float = 0.0, rng=None,
                          deterministic: bool = True,
                          policy: Policy = DEFAULT_POLICY,
                          impl: Optional[str] = None,
                          kv_chunk_size: int = 1024, spmd=None):
    """Pre-norm on q AND kv, then MHA (reference model.py:97-99)."""
    xq = layer_norm_apply(params["norm_q"], x_q, policy=policy)
    xkv = layer_norm_apply(params["norm_kv"], x_kv, policy=policy)
    return mha_apply(params["mha"], xq, xkv, xkv, num_heads=num_heads,
                     key_padding_mask=key_padding_mask, attn_mask=attn_mask,
                     dropout_rate=dropout_rate, rng=rng,
                     deterministic=deterministic, policy=policy,
                     impl=impl, kv_chunk_size=kv_chunk_size, spmd=spmd)


def self_attention_init(key, num_channels: int, num_heads: int,
                        dtype=jnp.float32):
    return {
        "norm": layer_norm_init(num_channels, dtype),
        "mha": mha_init(key, num_channels, num_heads, dtype=dtype),
    }


def self_attention_apply(params, x, *, num_heads: int,
                         key_padding_mask=None, attn_mask=None,
                         dropout_rate: float = 0.0, rng=None,
                         deterministic: bool = True,
                         policy: Policy = DEFAULT_POLICY,
                         impl: Optional[str] = None,
                         kv_chunk_size: int = 1024):
    """Pre-norm then MHA with q = k = v (reference model.py:110-116)."""
    xn = layer_norm_apply(params["norm"], x, policy=policy)
    return mha_apply(params["mha"], xn, xn, xn, num_heads=num_heads,
                     key_padding_mask=key_padding_mask, attn_mask=attn_mask,
                     dropout_rate=dropout_rate, rng=rng,
                     deterministic=deterministic, policy=policy,
                     impl=impl, kv_chunk_size=kv_chunk_size)
