"""LArTPC semantic-segmentation task (reference ``LAr_Perceiver``,
``run.py:72-112``).

Model: ImageInputAdapter(H, W, 1; 32 Fourier bands) → PerceiverEncoder
(32×64 latents, 3 layers, 3 self-attn layers/block) → PerceiverDecoder
with one cross-attention head over H·W output queries →
SemanticSegOutputAdapter (per-pixel class logits; the reference used
``ClassificationOutputAdapter`` with ``num_outputs=512·512``,
``run.py:82``). Zero-valued pixels form the encoder pad mask
(``run.py:107``).

The 512×512 config has 262,144 output queries — the decoder's
cross-attention is the memory hot spot (SURVEY §7 hard part (a)), so
the decoder runs with ``query_chunk_size`` by default: output queries
never attend to each other, making chunking exact.

Loss: class-weighted cross-entropy with background weight 0
(``run.py:234-237``); metrics: accuracy over non-background pixels and
per-class accuracies (``run.py:186-197``). The reference's layout
defect — reshaping (B, H·W, 3) logits as (B, 3, H·W), a scramble where
a transpose was meant (SURVEY §2.6.4) — is not reproduced.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from perceiver_tpu.adapters import ImageInputAdapter, SemanticSegOutputAdapter
from perceiver_tpu.models import PerceiverDecoder, PerceiverEncoder, PerceiverIO
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY
from perceiver_tpu.tasks.base import TaskConfig, masked_mean


@dataclasses.dataclass(frozen=True)
class SegmentationTask(TaskConfig):
    """Defaults mirror ``run.py:76-101`` (32×64 latents, 3 layers,
    3 self-attn layers/block, 1 decoder head, 64 output channels)."""

    image_shape: Tuple[int, int, int] = (512, 512, 1)
    num_classes: int = 3
    num_frequency_bands: int = 32
    num_latents: int = 32
    num_latent_channels: int = 64
    num_encoder_self_attention_layers_per_block: int = 3
    num_decoder_cross_attention_heads: int = 1
    num_output_channels: int = 64
    background_weight: float = 0.0  # run.py:235 weights[0] = 0
    query_chunk_size: Optional[int] = 16384

    @property
    def num_pixels(self) -> int:
        return self.image_shape[0] * self.image_shape[1]

    def build(self, mesh=None) -> PerceiverIO:
        input_adapter = ImageInputAdapter(
            image_shape=tuple(self.image_shape),
            num_frequency_bands=self.num_frequency_bands)
        output_adapter = SemanticSegOutputAdapter(
            num_classes=self.num_classes,
            num_outputs=self.num_pixels,
            num_output_channels=self.num_output_channels)
        encoder = PerceiverEncoder(
            input_adapter=input_adapter,
            latent_shape=self.latent_shape,
            num_layers=self.num_encoder_layers,
            num_cross_attention_heads=self.num_encoder_cross_attention_heads,
            num_self_attention_heads=self.num_encoder_self_attention_heads,
            num_self_attention_layers_per_block=(
                self.num_encoder_self_attention_layers_per_block),
            dropout=self.dropout,
            attention_impl=self.attention_impl,
            kv_chunk_size=self.kv_chunk_size,
            spmd=self.encoder_spmd(mesh),
            remat=self.remat)
        chunk = self.query_chunk_size
        if chunk is not None and self.num_pixels % chunk != 0:
            chunk = None  # tiny test configs: fall back to unchunked
        decoder = PerceiverDecoder(
            output_adapter=output_adapter,
            latent_shape=self.latent_shape,
            num_cross_attention_heads=self.num_decoder_cross_attention_heads,
            dropout=self.dropout,
            attention_impl=self.decoder_attention_impl,
            kv_chunk_size=self.kv_chunk_size,
            query_chunk_size=chunk)
        return PerceiverIO(encoder, decoder)

    def forward(self, model, params, images, *, rng=None,
                deterministic: bool = True,
                policy: Policy = DEFAULT_POLICY):
        """``images``: (B, H, W) or (B, H, W, 1) wire images. Returns
        (B, H·W, num_classes) logits. Pad mask = zero pixels."""
        b = images.shape[0]
        x = images.reshape(b, *self.image_shape)
        pad_mask = (x == 0.0).reshape(b, self.num_pixels)
        return model.apply(params, x, pad_mask, rng=rng,
                           deterministic=deterministic, policy=policy)

    def class_weights(self) -> jnp.ndarray:
        w = jnp.ones((self.num_classes,), jnp.float32)
        return w.at[0].set(self.background_weight)

    def loss_and_metrics(self, model, params, batch, *, rng=None,
                         deterministic: bool = True,
                         policy: Policy = DEFAULT_POLICY):
        logits = self.forward(model, params, batch["image"], rng=rng,
                              deterministic=deterministic, policy=policy)
        labels = batch["label"].reshape(logits.shape[0], -1)
        return segmentation_loss_and_metrics(
            logits, labels, self.class_weights(), batch.get("valid"))


def segmentation_loss_and_metrics(logits, labels, class_weights,
                                  valid=None):
    """Class-weighted CE + per-class accuracies over flattened pixels.

    ``logits`` (B, P, C); ``labels`` (B, P). torch
    ``F.cross_entropy(weight=w)`` semantics (run.py:234-237): per-pixel
    nll scaled by ``w[label]``, normalized by the summed weights.
    Shared by the Perceiver and U-ResNet segmentation paths.
    """
    num_classes = logits.shape[-1]
    row = (valid.astype(jnp.float32)[:, None] if valid is not None
           else jnp.ones((logits.shape[0], 1), jnp.float32))

    logsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logsm, labels[..., None], -1)[..., 0]
    w = class_weights[labels] * row
    loss = (nll * w).sum() / jnp.maximum(w.sum(), 1e-8)

    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == labels).astype(jnp.float32)
    metrics = {"loss": loss,
               "acc": masked_mean(correct, (labels > 0) * row)}
    for c in range(1, num_classes):
        metrics[f"acc{c}"] = masked_mean(correct, (labels == c) * row)
    return loss, metrics


@dataclasses.dataclass(frozen=True)
class UResNetSegmentationTask:
    """Dense-conv alternative to the Perceiver segmentation model: the
    U-ResNet the reference wires into ``LAr_Perceiver`` but never runs
    (``run.py:103,109-110``; SURVEY §2.3) — here a first-class, actually
    trainable choice (``run.py --model uresnet``).

    ``loss_and_metrics`` returns ``(loss, metrics, new_state)``: the
    third element is the updated BatchNorm running-stat pytree, which
    the caller threads (it must not receive optimizer updates).
    """

    image_shape: Tuple[int, int, int] = (512, 512, 1)
    num_classes: int = 3
    inplanes: int = 16
    background_weight: float = 0.0

    def build(self, mesh=None):
        del mesh  # dense conv net: GSPMD batch sharding only
        from perceiver_tpu.models.uresnet import UResNet
        return UResNet(num_classes=self.num_classes,
                       input_channels=self.image_shape[-1],
                       inplanes=self.inplanes)

    def class_weights(self) -> jnp.ndarray:
        w = jnp.ones((self.num_classes,), jnp.float32)
        return w.at[0].set(self.background_weight)

    def loss_and_metrics(self, model, variables, batch, *,
                         train: bool = False,
                         policy: Policy = DEFAULT_POLICY):
        b = batch["image"].shape[0]
        x = batch["image"].reshape(b, *self.image_shape)
        logits, new_state = model.apply(variables, x, train=train,
                                        policy=policy)
        loss, metrics = segmentation_loss_and_metrics(
            logits.reshape(b, -1, self.num_classes),
            batch["label"].reshape(b, -1).astype(jnp.int32),
            self.class_weights(), batch.get("valid"))
        return loss, metrics, new_state
