"""Serving subsystem: AOT bucketed-batch inference (docs/SERVING.md).

Layers, bottom to top:

- ``graphs``  — per-task serve computations (the single source of
  truth the engine compiles AND ``analysis/targets.py`` gates);
- ``engine``  — checkpoint loading, per-(batch, seq) bucket AOT
  compilation, device-resident params, sync-free dispatch;
- ``batcher`` — thread-safe micro-batching queue with deadlines and
  typed ``Overloaded`` load shedding, plus the unified prefill+decode
  continuous-batching scheduler (``ContinuousBatchScheduler``);
- ``decode``  — autoregressive streaming generation: O(1) paged KV
  caching through one AOT-compiled stepped executable;
- ``speculative`` — draft-model policy + the rejection rule the
  decode engine runs when ``DecodeGeometry.spec_k > 0``;
- ``errors``  — the typed failure vocabulary (``Unavailable``,
  ``BatchError``) every layer speaks (docs/RESILIENCE.md);
- ``tenancy`` — the multi-tenant registry: per-tenant quotas,
  priority classes, and the weighted fair-share arithmetic the
  router/arena/planner enforce (docs/SERVING.md "Multi-tenancy");
- ``health``  — the health/readiness state machine the engine exports
  via metrics;
- ``metrics`` — counters/gauges/latency histograms with Prometheus
  text exposition;
- ``api``     — task front-ends (MLM fill-mask, text/image
  classification, segmentation) and the ``predict_masked_samples``
  compat path.
"""

from perceiver_tpu.serving.batcher import (  # noqa: F401
    AdmissionQueue,
    ContinuousBatchScheduler,
    MicroBatcher,
    Overloaded,
    TokenBudgetBatcher,
)
from perceiver_tpu.serving.decode import (  # noqa: F401
    DecodeEngine,
    DecodeGeometry,
    DecodeResult,
    PagePool,
    StreamHandle,
    build_decode_graph,
)
from perceiver_tpu.serving.prefix_cache import (  # noqa: F401
    PrefixCacheConfig,
    PrefixIndex,
    ensure_private_page,
)
from perceiver_tpu.serving.speculative import (  # noqa: F401
    SpeculativeConfig,
    greedy_accept,
    shrink_task,
    speculative_accept,
)
from perceiver_tpu.serving.errors import (  # noqa: F401
    SHED_REASONS,
    BatchError,
    ServingError,
    Unavailable,
    retry_after_for,
)
from perceiver_tpu.serving.tenancy import (  # noqa: F401
    DEFAULT_TENANT,
    TenantRegistry,
    TenantSpec,
    weighted_fair_shares,
)
from perceiver_tpu.serving.health import (  # noqa: F401
    HealthMonitor,
    HealthState,
)
from perceiver_tpu.serving.engine import (  # noqa: F401
    PackedServeResult,
    RequestTooLarge,
    ServeResult,
    ServingEngine,
)
from perceiver_tpu.serving.graphs import (  # noqa: F401
    PackedServeGraph,
    ServeGraph,
    build_packed_serve_graph,
    build_serve_graph,
    mlm_serve_graph,
)
from perceiver_tpu.serving.metrics import MetricsRegistry  # noqa: F401
from perceiver_tpu.serving.api import (  # noqa: F401
    Generation,
    GenerationServer,
    ImageClassifierServer,
    MLMServer,
    SegmentationServer,
    TextClassifierServer,
    materialize,
    materialize_packed,
)
