"""Task front-ends over the serving engine.

One server class per task: tokenize / stack on the request thread
pool, coalesce through the micro-batcher, dispatch to the engine's
AOT buckets, materialize + slice per request. This is the layer that
*is allowed* to synchronize with the device — request latency is
measured here, where results are handed back to callers (the engine's
dispatch stays sync-free; see ``serving/engine.py``).

``predict_masked_samples`` at the bottom is the backward-compatible
rewrite of ``utils/predict.py``: same signature and return value, but
routed through a cached per-model engine, so repeated calls at the
same shapes perform **zero** new XLA compiles (the old helper re-jit
a fresh lambda per call).
"""

from __future__ import annotations

import dataclasses
import threading
import warnings
from typing import Dict, List, Optional, Sequence

import numpy as np

from perceiver_tpu.obs import trace as trace_mod
from perceiver_tpu.ops.policy import Policy, DEFAULT_POLICY
from perceiver_tpu.serving.batcher import (
    MicroBatcher,
    Overloaded,
    TokenBudgetBatcher,
)
from perceiver_tpu.serving.engine import (
    PackedServeResult,
    ServeResult,
    ServingEngine,
)
from perceiver_tpu.serving.graphs import mlm_serve_graph
from perceiver_tpu.serving.metrics import MetricsRegistry
from perceiver_tpu.tokenizer import PAD_TOKEN_ID


def materialize(result: ServeResult, graph=None) -> Dict[str, np.ndarray]:
    """Device outputs → host arrays sliced back to the request's real
    rows (and real sequence length on seq-axis outputs). This is the
    one deliberate device sync of the serving path."""
    n, length = result.batch, result.length
    seq_outputs = set(graph.seq_axis_outputs) if graph is not None else set()
    out = {}
    for name, arr in result.outputs.items():
        host = np.asarray(arr)[:n]
        if name in seq_outputs and length is not None:
            host = host[:, :length]
        out[name] = host
    return out


def materialize_packed(result: PackedServeResult,
                       graph) -> Dict[str, np.ndarray]:
    """Device outputs of a packed dispatch → host arrays: token-axis
    outputs sliced to the real packed span (per-request slicing then
    uses ``row_offsets``/``lengths``), request-axis outputs to the real
    rows."""
    total = int(np.asarray(result.lengths).sum())
    token_axis = set(graph.token_axis_outputs)
    out = {}
    for name, arr in result.outputs.items():
        host = np.asarray(arr)
        out[name] = (host[:total] if name in token_axis
                     else host[:result.batch])
    return out


class _Server:
    """Engine + micro-batcher plumbing shared by the task servers."""

    def __init__(self, engine: ServingEngine, *,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 2.0, max_depth: int = 64,
                 packed: bool = False):
        self.engine = engine
        self.metrics: MetricsRegistry = engine.metrics
        self.packed = packed
        if packed:
            # continuous batching: coalesce by real-token budget (the
            # largest packed bucket) instead of request count
            if not engine.packed_buckets:
                raise ValueError(
                    "packed=True needs an engine built with "
                    "packed_buckets")
            token_budget = max(t for t, _ in engine.packed_buckets)
            if max_batch is None:
                max_batch = max(r for _, r in engine.packed_buckets)
            # the packed serve path keeps the facade's future/worker
            # surface on purpose — the deprecation aims at new decode
            # callers, not at this single-shot pipeline
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", DeprecationWarning)
                self.batcher: MicroBatcher = TokenBudgetBatcher(
                    self._run_batch, token_budget=token_budget,
                    cost_fn=self._payload_cost, max_requests=max_batch,
                    max_delay_ms=max_delay_ms, max_depth=max_depth,
                    metrics=self.metrics)
        else:
            if max_batch is None:
                max_batch = (engine.batch_buckets[-1]
                             if engine.batch_buckets else 8)
            self.batcher = MicroBatcher(
                self._run_batch, max_batch=max_batch,
                max_delay_ms=max_delay_ms, max_depth=max_depth,
                metrics=self.metrics)
        self._close_lock = threading.Lock()
        self._closed = False

    def _run_batch(self, payloads: List[object]) -> Sequence[object]:
        raise NotImplementedError

    def _payload_cost(self, payload) -> int:
        """Token cost of one queued payload (packed mode). Text servers
        tokenize at submit, so the payload carries its length."""
        return int(payload[2])

    @property
    def health(self):
        """The engine's :class:`~perceiver_tpu.serving.health.
        HealthState` — what a /healthz handler reports."""
        return self.engine.health.state

    @property
    def ready(self) -> bool:
        """Readiness (READY or DEGRADED) — what a load balancer's
        /readyz probe should route on."""
        return self.engine.health.ready

    def metrics_text(self) -> str:
        """Prometheus text exposition of every serving metric."""
        return self.metrics.render()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted request has resolved (queue empty
        and nothing inside the runner). The rolling-update cutover
        calls this before ``engine.update_params``."""
        return self.batcher.drain(timeout)

    def close(self, timeout: float = 5.0):
        """Drain in-flight work, then stop the batcher. Idempotent:
        concurrent/repeated closes are no-ops. Requests still queued
        past ``timeout`` resolve with a typed
        ``Unavailable("shutting_down")``, never a silent dead future."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self.batcher.drain(timeout)
        self.batcher.close(timeout)


def _pack_rows(payloads: List[object]):
    """(text, ids, length) payloads → packed token buffer + spans."""
    lengths = np.array([p[2] for p in payloads], np.int32)
    offsets = np.zeros(len(payloads), np.int32)
    if len(payloads) > 1:
        offsets[1:] = np.cumsum(lengths[:-1])
    packed = np.concatenate([p[1] for p in payloads])
    return packed.astype(np.int32, copy=False), offsets, lengths


@dataclasses.dataclass(frozen=True)
class MaskFill:
    """Fill-mask result for one request.

    ``predictions[k]`` is the request text with every ``[MASK]``
    position replaced by its (k+1)-th best token, decoded.
    ``topk_tokens``/``topk_scores`` are per masked position (request
    order), each a list of k (token, score) candidates.
    """

    text: str
    predictions: List[str]
    masked_positions: List[int]
    topk_tokens: List[List[str]]
    topk_scores: List[List[float]]


class MLMServer(_Server):
    """Fill-mask serving: raw strings in, top-k filled strings out."""

    def __init__(self, engine: ServingEngine, tokenizer, **kwargs):
        super().__init__(engine, **kwargs)
        if not engine.graph.seq_bucketable:
            raise ValueError("MLMServer needs a text-task engine")
        self.tokenizer = tokenizer
        if self.packed:
            self._encode_len = engine.packed_graph.max_seq_len
        else:
            self._encode_len = (engine.seq_buckets[-1]
                                if engine.seq_buckets
                                else engine.graph.max_seq_len)

    def fill_mask(self, text: str, *,
                  timeout_ms: Optional[float] = None) -> MaskFill:
        """Blocking single-request entry (the RPC-handler shape):
        raises ``OverloadedError`` via the returned value contract —
        callers check ``isinstance(r, Overloaded)``."""
        return self.submit(text, timeout_ms=timeout_ms).result()

    def submit(self, text: str, *, timeout_ms: Optional[float] = None):
        if not self.packed:
            return self.batcher.submit(text, timeout_ms=timeout_ms)
        # packed mode tokenizes at submit: the batcher needs each
        # request's token cost to do budget-based coalescing
        ids, lengths = self.tokenizer.encode_batch_padded(
            [text], self._encode_len, pad_id=PAD_TOKEN_ID)
        n = max(1, int(lengths[0]))
        row = ids[0, :n].astype(np.int32, copy=False)
        return self.batcher.submit((text, row, n), timeout_ms=timeout_ms)

    def _run_batch(self, payloads: List[object]) -> List[MaskFill]:
        if self.packed:
            return self._run_packed(payloads)
        texts = payloads
        # batch tokenization on the worker thread: one GIL-free C++
        # call for the whole micro-batch (tokenizer/native.py)
        ids, lengths = self.tokenizer.encode_batch_padded(
            texts, self._encode_len, pad_id=PAD_TOKEN_ID)
        width = max(1, int(lengths.max()))
        ids = ids[:, :width]
        pad_mask = np.arange(width)[None, :] >= lengths[:, None]
        res = self.engine.dispatch(
            {"input_ids": ids.astype(np.int32, copy=False),
             "pad_mask": pad_mask},
            lengths=lengths)
        # "device" = the one deliberate sync of the serving path
        with trace_mod.region("device"):
            out = materialize(res, self.engine.graph)
        results = []
        for i, text in enumerate(texts):
            n = int(lengths[i])
            results.append(self._mask_fill(
                text, ids[i, :n], out["is_masked"][i, :n],
                out["topk_ids"][i, :n], out["topk_scores"][i, :n]))
        return results

    def _run_packed(self, payloads: List[object]) -> List[MaskFill]:
        packed, offsets, lengths = _pack_rows(payloads)
        res = self.engine.dispatch_packed(
            {"packed_ids": packed, "row_offsets": offsets,
             "lengths": lengths})
        with trace_mod.region("device"):
            out = materialize_packed(res, self.engine.packed_graph)
        results = []
        for i, (text, row_ids, n) in enumerate(payloads):
            s = int(offsets[i])
            results.append(self._mask_fill(
                text, row_ids, out["is_masked"][s:s + n],
                out["topk_ids"][s:s + n], out["topk_scores"][s:s + n]))
        return results

    def _mask_fill(self, text, row_ids, is_masked, topk_ids,
                   topk_scores) -> MaskFill:
        """Per-request decode shared by both dispatch modes: inputs are
        1-D over the request's real tokens."""
        masked = np.nonzero(is_masked)[0]
        k = topk_ids.shape[-1]
        preds = []
        for j in range(k):
            filled = np.where(is_masked, topk_ids[:, j], row_ids)
            preds.append(self.tokenizer.decode(filled.tolist()))
        return MaskFill(
            text=text, predictions=preds,
            masked_positions=[int(p) for p in masked],
            topk_tokens=[[self.tokenizer.id_to_token(int(t))
                          for t in topk_ids[p]] for p in masked],
            topk_scores=[[float(s) for s in topk_scores[p]]
                         for p in masked])


@dataclasses.dataclass(frozen=True)
class Classification:
    label: int
    probs: np.ndarray  # (num_classes,) fp32
    logits: np.ndarray


class TextClassifierServer(_Server):
    def __init__(self, engine: ServingEngine, tokenizer, **kwargs):
        super().__init__(engine, **kwargs)
        self.tokenizer = tokenizer
        if self.packed:
            self._encode_len = engine.packed_graph.max_seq_len
        else:
            self._encode_len = (engine.seq_buckets[-1]
                                if engine.seq_buckets
                                else engine.graph.max_seq_len)

    def classify(self, text: str, *,
                 timeout_ms: Optional[float] = None) -> Classification:
        return self.submit(text, timeout_ms=timeout_ms).result()

    def submit(self, text: str, *, timeout_ms: Optional[float] = None):
        if not self.packed:
            return self.batcher.submit(text, timeout_ms=timeout_ms)
        ids, lengths = self.tokenizer.encode_batch_padded(
            [text], self._encode_len, pad_id=PAD_TOKEN_ID)
        n = max(1, int(lengths[0]))
        row = ids[0, :n].astype(np.int32, copy=False)
        return self.batcher.submit((text, row, n), timeout_ms=timeout_ms)

    def _run_batch(self, payloads: List[object]) -> List[Classification]:
        if self.packed:
            packed, offsets, lengths = _pack_rows(payloads)
            res = self.engine.dispatch_packed(
                {"packed_ids": packed, "row_offsets": offsets,
                 "lengths": lengths})
            with trace_mod.region("device"):
                out = materialize_packed(res, self.engine.packed_graph)
            n = len(payloads)
        else:
            texts = payloads
            ids, lengths = self.tokenizer.encode_batch_padded(
                texts, self._encode_len, pad_id=PAD_TOKEN_ID)
            width = max(1, int(lengths.max()))
            ids = ids[:, :width]
            pad_mask = np.arange(width)[None, :] >= lengths[:, None]
            res = self.engine.dispatch(
                {"input_ids": ids.astype(np.int32, copy=False),
                 "pad_mask": pad_mask},
                lengths=lengths)
            with trace_mod.region("device"):
                out = materialize(res, self.engine.graph)
            n = len(texts)
        return [Classification(label=int(out["label"][i]),
                               probs=out["probs"][i],
                               logits=out["logits"][i])
                for i in range(n)]


class ImageClassifierServer(_Server):
    """Payload: one (H, W, C) float32 image per request."""

    def classify(self, image: np.ndarray, *,
                 timeout_ms: Optional[float] = None) -> Classification:
        return self.submit(image, timeout_ms=timeout_ms).result()

    def submit(self, image: np.ndarray, *,
               timeout_ms: Optional[float] = None):
        return self.batcher.submit(image, timeout_ms=timeout_ms)

    def _run_batch(self, images: List[np.ndarray]) -> List[Classification]:
        stacked = np.stack(images).astype(np.float32, copy=False)
        res = self.engine.dispatch({"image": stacked})
        with trace_mod.region("device"):
            out = materialize(res, self.engine.graph)
        return [Classification(label=int(out["label"][i]),
                               probs=out["probs"][i],
                               logits=out["logits"][i])
                for i in range(len(images))]


@dataclasses.dataclass(frozen=True)
class SegmentationMap:
    classes: np.ndarray     # (H, W) int32
    confidence: np.ndarray  # (H, W) fp32 max-prob


class SegmentationServer(_Server):
    """Payload: one (H, W) float32 wire image per request."""

    def segment(self, image: np.ndarray, *,
                timeout_ms: Optional[float] = None) -> SegmentationMap:
        return self.submit(image, timeout_ms=timeout_ms).result()

    def submit(self, image: np.ndarray, *,
               timeout_ms: Optional[float] = None):
        return self.batcher.submit(image, timeout_ms=timeout_ms)

    def _run_batch(self, images: List[np.ndarray]) -> List[SegmentationMap]:
        stacked = np.stack(images).astype(np.float32, copy=False)
        res = self.engine.dispatch({"image": stacked})
        with trace_mod.region("device"):
            out = materialize(res, self.engine.graph)
        return [SegmentationMap(classes=out["classes"][i],
                                confidence=out["confidence"][i])
                for i in range(len(images))]


# --- autoregressive generation ----------------------------------------------


@dataclasses.dataclass(frozen=True)
class Generation:
    """One finished generation: the decoded continuation + timing."""

    text: str                 # prompt + generated, decoded
    prompt_text: str
    token_ids: List[int]      # generated ids only
    ttft_s: Optional[float]


class GenerationServer:
    """Streaming text generation over a :class:`DecodeEngine`
    (docs/SERVING.md "Autoregressive decode").

    Unlike the batch servers above there is no micro-batcher in front:
    the decode engine IS the continuous batcher — every submit joins
    the stepped executable's next admission wave, and tokens stream
    back per step. When the engine was built with
    ``prefix_cache=PrefixCacheConfig(...)``, prompts sharing a
    page-aligned prefix reuse cached KV pages transparently
    (docs/SERVING.md "Prefix caching"; :meth:`prefix_cache_stats`
    surfaces the index accounting). This layer only tokenizes,
    decodes, and exposes the three delivery shapes: blocking
    (:meth:`generate`), incremental (:meth:`stream`), and push
    (:meth:`submit` with ``on_token``).
    """

    def __init__(self, engine, tokenizer):
        from perceiver_tpu.serving.decode import DecodeEngine

        if not isinstance(engine, DecodeEngine):
            raise TypeError(
                f"GenerationServer needs a DecodeEngine, got "
                f"{type(engine).__name__}")
        self.engine = engine
        self.tokenizer = tokenizer

    def submit(self, text: str, *, max_new_tokens: int,
               timeout_ms: Optional[float] = None,
               on_token=None):
        """Tokenize and enqueue one stream; returns the engine's
        ``StreamHandle``. ``on_token`` receives raw token *ids* as
        they are generated (decode per id via ``token_text``)."""
        ids, lengths = self.tokenizer.encode_batch_padded(
            [text], self.engine.geometry.max_seq_len,
            pad_id=PAD_TOKEN_ID)
        n = max(1, int(lengths[0]))
        row = ids[0, :n].astype(np.int32, copy=False)
        return self.engine.submit(row, max_new_tokens=max_new_tokens,
                                  timeout_ms=timeout_ms,
                                  on_token=on_token)

    def generate(self, text: str, *, max_new_tokens: int,
                 timeout_ms: Optional[float] = None,
                 timeout: Optional[float] = None):
        """Blocking entry: returns a :class:`Generation`, or the typed
        ``Overloaded`` value when the stream was shed."""
        handle = self.submit(text, max_new_tokens=max_new_tokens,
                             timeout_ms=timeout_ms)
        result = handle.result(timeout)
        if isinstance(result, Overloaded):
            return result
        return Generation(
            text=text + self.tokenizer.decode(result.tokens),
            prompt_text=text,
            token_ids=list(result.tokens),
            ttft_s=result.ttft_s)

    def stream(self, text: str, *, max_new_tokens: int,
               timeout_ms: Optional[float] = None):
        """Incremental entry: yields each generated token's text as it
        is emitted (blocking iterator; ends when the stream closes)."""
        handle = self.submit(text, max_new_tokens=max_new_tokens,
                             timeout_ms=timeout_ms)
        for tok in handle.tokens():
            yield self.token_text(tok)

    def token_text(self, token_id: int) -> str:
        return self.tokenizer.id_to_token(int(token_id))

    def metrics_text(self) -> str:
        return self.engine.metrics_text()

    def prefix_cache_stats(self):
        """Prefix-index accounting dict, or None when caching is off."""
        return self.engine.prefix_cache_stats()

    def close(self, timeout: float = 5.0) -> None:
        self.engine.close(timeout)


# --- predict_masked_samples compat path --------------------------------------

# engines cached per (model config, k, policy): the model dataclasses
# are frozen/hashable, so the cache key is the architecture itself —
# params refresh via update_params without touching the compiled
# executables (same shapes → same signature → zero recompiles)
_COMPAT_ENGINES: dict = {}
_COMPAT_LOCK = threading.Lock()


def _compat_engine(model, params, num_predictions: int,
                   policy: Optional[Policy]) -> ServingEngine:
    policy = policy if policy is not None else DEFAULT_POLICY
    key = (model, num_predictions, policy)
    with _COMPAT_LOCK:
        engine = _COMPAT_ENGINES.get(key)
        if engine is None:
            graph = mlm_serve_graph(model, policy=policy,
                                    top_k=num_predictions)
            engine = ServingEngine.from_graph(graph, params)
            _COMPAT_ENGINES[key] = engine
    engine.update_params(params)
    return engine


def predict_masked_samples(masked_samples: List[str], encode_fn,
                           tokenizer, model, params,
                           num_predictions: int = 3,
                           policy: Optional[Policy] = None
                           ) -> List[List[str]]:
    """Drop-in for the old ``utils.predict.predict_masked_samples``:
    k decoded fills per sample, but dispatched through a cached AOT
    engine — a second call at the same shapes compiles nothing."""
    ids, pad_mask = encode_fn(masked_samples)
    ids = np.asarray(ids, np.int32)
    pad_mask = np.asarray(pad_mask, bool)
    engine = _compat_engine(model, params, num_predictions, policy)
    out = materialize(
        engine.dispatch({"input_ids": ids, "pad_mask": pad_mask}),
        engine.graph)
    results: List[List[str]] = []
    for b in range(ids.shape[0]):
        preds = []
        for k in range(num_predictions):
            filled = np.where(out["is_masked"][b],
                              out["topk_ids"][b, :, k], ids[b])
            preds.append(tokenizer.decode(filled.tolist()))
        results.append(preds)
    return results
