#!/bin/bash
# On-chip MLM quality training on the harvested real-text corpus
# (VERDICT r1 #3): the reference MLM recipe (seq 512, vocab 10003,
# batch 64, OneCycle) run as long as the TPU window allows, resumable
# across tunnel drops — re-invoking continues from the newest
# checkpoint (best-k or the SIGTERM/preempt save) with the same
# max_steps so the OneCycle schedule stays consistent.
#
# Usage: scripts/mlm_quality_run.sh [max_steps] [extra CLI args...]
set -u
cd "$(dirname "$0")/.."
MAX_STEPS=${1:-50000}
shift || true

EXP=mlm_quality
# The CPU hedge run (same corpus/config) would fight this run for the
# single host core; stop it — its progress carries over via the
# furthest-step checkpoint selection below. SIGTERM triggers its
# preemption save, which can take a while on a loaded host: wait for
# the process to actually exit so the save is complete, not racing.
if pgrep -f "scripts/mlm.py.*mlm_cpu_quality" > /dev/null 2>&1; then
  pkill -f "scripts/mlm.py.*mlm_cpu_quality"
  for _ in $(seq 1 90); do
    pgrep -f "scripts/mlm.py.*mlm_cpu_quality" > /dev/null 2>&1 || break
    sleep 2
  done
fi

# Resume from the checkpoint dir holding the FURTHEST committed step
# (numeric orbax step subdirs), across this experiment's versions
# (regular + preempt saves) and the CPU hedge's. Mtime would lie: a
# fresh dir holds only hparams.json before the first save, and the
# slow CPU hedge saves more recently than a further-along TPU run.
RESUME=()
best_dir=""; best_step=-1
for d in logs/$EXP/version_*/checkpoints* \
         logs/mlm_quality_resumed_on_cpu/version_*/checkpoints* \
         logs/mlm_cpu_quality/version_*/checkpoints*; do
  [[ -d "$d" ]] || continue
  for s in "$d"/*/; do
    s=${s%/}; s=${s##*/}
    [[ "$s" =~ ^[0-9]+$ ]] || continue
    if (( s > best_step )); then best_step=$s; best_dir=$d; fi
  done
done
if [[ -n "$best_dir" ]]; then
  RESUME=(--trainer.resume_from_checkpoint "$best_dir")
  echo "resuming from $best_dir (step $best_step)"
fi

exec python scripts/mlm.py fit \
  --data.data_dir=.cache \
  --optimizer.init_args.lr=0.002 \
  --trainer.max_steps="$MAX_STEPS" \
  --trainer.steps_per_execution=8 \
  --trainer.log_every_n_steps=100 \
  --experiment="$EXP" \
  "${RESUME[@]}" "$@"
