"""True completion fences for benchmark timing.

``block_until_ready`` is the documented way to synchronize before
reading a wall clock — and on this container's axon tunnel it returns
long before the remote chip has finished executing (measured: a 13.7
TFLOP matmul chain "completes" in 0.2 ms ⇒ an impossible 84 PFLOP/s,
while a host fetch of one result element takes the honest 0.14-0.2 s).
Every timed region must therefore end with a HOST FETCH of a value
that data-depends on the computation: a device→host transfer cannot
complete before the producing computation does, on any backend.

The fence costs one tunnel round trip (~30-70 ms here), so timed
regions should cover enough work to amortize it, and the fence scalar
should be tiny (fetching a full activation tensor would measure
transfer bandwidth, not compute).
"""

from __future__ import annotations

import numpy as np


def fence(x) -> float:
    """Block until ``x`` is REALLY computed; returns one element as float.

    ``x`` may be a jax array of any shape or a pytree (the first
    jax.Array leaf is used — a host-side scalar leaf would device_get
    instantly and silently turn the fence into a no-op, the exact
    unfenced-timing bug this module exists to fix). A scalar is fetched
    directly; for larger arrays a one-element slice is dispatched on
    device first so only bytes for a single element cross the wire.
    """
    import jax

    leaves = jax.tree_util.tree_leaves(x)
    if not leaves:
        return 0.0
    leaf = next((l for l in leaves if isinstance(l, jax.Array)), None)
    if leaf is None:
        raise TypeError(
            "fence() needs at least one jax.Array leaf to synchronize "
            f"on; got only host-side leaves ({type(leaves[0]).__name__})")
    if getattr(leaf, "ndim", 0):
        leaf = leaf.ravel()[0]
    return float(np.asarray(jax.device_get(leaf)))
