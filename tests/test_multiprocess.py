"""TRUE multi-process distributed training (SURVEY §2.5 comm backend).

The rest of the distributed suite runs on a single process with 8
virtual devices — real pjit/Mesh code, but no cross-process
coordination. This test spawns TWO OS processes that form a real
``jax.distributed`` cluster over the CPU backend (Gloo collectives)
and train through the full Trainer path: per-host dataset sharding,
``make_array_from_process_local_data`` global-batch assembly, GSPMD
gradient all-reduce across processes, the prepare_data barrier, and
multi-host eval aggregation — the NCCL/DDP-equivalent story, actually
multi-process.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_training(tmp_path):
    port = _free_port()
    outs = [tmp_path / f"out_{i}.json" for i in range(2)]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PERCEIVER_TPU_OFFLINE": "1"}
    # each process must see exactly ONE local CPU device
    env.pop("XLA_FLAGS", None)
    # each worker logs to its own FILE: piping both and draining
    # sequentially can deadlock (a worker blocked writing a full pipe
    # while its peer blocks in a Gloo collective waiting for it), and
    # files survive a timeout kill for diagnosis
    log_files = [open(tmp_path / f"worker_{i}.log", "w+") for i in range(2)]
    try:
        procs = [
            subprocess.Popen(
                [sys.executable,
                 os.path.join(ROOT, "tests", "dist_worker.py"),
                 str(i), "2", str(port), str(outs[i]), str(tmp_path)],
                env=env, cwd=ROOT,
                stdout=log_files[i], stderr=subprocess.STDOUT, text=True)
            for i in range(2)
        ]
        try:
            for p in procs:
                p.wait(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise

        def tail(i):
            log_files[i].seek(0)
            return log_files[i].read()[-3000:]

        for i, p in enumerate(procs):
            assert p.returncode == 0, f"worker {i} failed:\n{tail(i)}"
    finally:
        for f in log_files:
            f.close()

    results = [json.loads(o.read_text()) for o in outs]
    for r in results:
        assert r["process_count"] == 2
        assert r["global_step"] == 3
        assert all(v == v for v in r.values())  # no NaNs
    # collective consistency: both processes computed IDENTICAL global
    # metrics from their assembled global batches
    assert results[0] == results[1], results
