"""Ragged paged attention: per-row (kv_len, query_len) over a shared
KV page pool (PAPERS: "Ragged Paged Attention").

Autoregressive decode keeps one KV cache entry per *consumed* token.
A rectangle per stream — ``(R, max_seq, H, Dh)`` — wastes HBM on
every stream shorter than the longest and fragments nothing-shaped
holes when streams leave mid-flight. The paged layout instead shares
one fixed pool of ``num_pages`` blocks of ``page_size`` tokens::

    k_pages, v_pages : (num_pages, page_size, H, Dh)   the shared pool
    page_tables      : (R, pages_per_stream) int32     logical→physical
    kv_lens          : (R,) int32                      tokens cached
    query_lens       : (R,) int32                      queries this step

Stream ``r``'s token ``t`` lives at physical page
``page_tables[r, t // page_size]``, slot ``t % page_size`` — so a
host-side allocator can hand any free page to any stream and recycle
freed pages without moving a byte (``serving/decode.PagePool``).

:func:`ragged_paged_attention` is the Pallas kernel family's entry:
grid ``(R, H, pages_per_stream)``, page table + both length vectors
ride scalar prefetch so the kv index map walks **only request r's own
page list**; steps past ``ceil(kv_len / page_size)`` replay the
clamped last page, which the pipeline elides, and compute under them
is predicated off. Rows are *ragged on both axes*: a chunked-prefill
row brings ``query_len > 1`` fresh queries, a decode row exactly one
— both execute in the same call, which is what lets the unified
serving step (``serving/decode.py``) run mixed prefill + decode
traffic through ONE compiled executable. ``causal=True`` aligns the
windows right: query ``i`` of row ``r`` attends kv positions
``< kv_lens[r] - (query_lens[r] - 1 - i)`` (the last query sees the
whole cache, earlier chunk queries see one token less each).
Perceiver latent rebuilds use the non-causal mode (latents attend
every cached token). Query rows past ``query_lens[r]`` and rows with
empty windows return exact zeros.

Online softmax shares its body with the flash and ragged kernels
(``ops/online_softmax.py``). Accumulation order is the logical page
order, independent of physical placement — so two placements of the
same stream (contiguous vs scrambled) produce **bitwise identical**
outputs, the property the decode parity tests pin.

Layout note: the kernel wants the token axis on the sublane dim, so
the wrapper relayouts pages to ``(P, H, page_size, Dp)`` (one
transpose + lane pad per call). The pools here are small — tens of
KiB for the canonical configs — so this stays cheap and O(1) per
step; a production TPU build would allocate the pool in kernel
layout directly and skip the copy.

:func:`ragged_paged_attention_reference` is the pure-jax gather
reference; it uses ``lax.select`` (never ``jnp.where``) because the
sharded decode serve graph lowers it, and jnp.where's jitted wrapper
makes module text drift with process history (see
serving/graphs.py).

:func:`paged_decode_attention` / ``_reference`` are kept as thin
decode-shaped delegates (all queries valid, non-causal) so existing
call sites and the engine's latent rebuild exercise the ragged code
path in production.

Both run in Pallas interpreter mode on non-TPU backends, so CPU
tests exercise the identical code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from perceiver_tpu.ops.chunked_attention import NEG_INF
from perceiver_tpu.ops.online_softmax import (
    online_softmax_finish,
    online_softmax_init,
    online_softmax_update,
)
from perceiver_tpu.ops.ragged_attention import _resolve_interpret
from perceiver_tpu.ops.tiling import round_up as _round_up


def _ragged_paged_kernel(tables_ref, kv_lens_ref, q_lens_ref, q_ref,
                         k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                         scale: float, page_size: int, n_steps: int,
                         nqp: int, causal: bool):
    r = pl.program_id(0)
    j = pl.program_id(2)
    kv_len = kv_lens_ref[r]

    @pl.when(j == 0)
    def _():
        online_softmax_init(m_ref, l_ref, acc_ref)

    # steps past the row's used pages replay the clamped last page
    # (see kv index map) — skip them; zero-length rows do no work and
    # finish with exact-zero outputs. The causal window of the LAST
    # query is the full cache, so kv_len bounds both modes.
    @pl.when(j * page_size < kv_len)
    def _():
        q = q_ref[0, 0]        # (Nqp, Dp)
        kblk = k_ref[0, 0]     # (page_size, Dp)
        vblk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (nqp, page_size), 1)
        if causal:
            # query i sees kv positions < kv_len - (q_len - 1 - i):
            # chunk queries are the cache's newest tokens, so earlier
            # ones must not see their successors. Padding rows
            # (i >= q_len) get windows past kv_len — garbage there is
            # finite and the wrapper zeroes those rows.
            qi = jax.lax.broadcasted_iota(
                jnp.int32, (nqp, page_size), 0)
            limit = kv_len - (q_lens_ref[r] - 1 - qi)
        else:
            limit = kv_len
        s = s + jnp.where(col < limit, 0.0, NEG_INF)
        online_softmax_update(s, vblk, m_ref, l_ref, acc_ref)

    @pl.when(j == n_steps - 1)
    def _():
        o_ref[0, 0] = online_softmax_finish(
            m_ref, l_ref, acc_ref).astype(o_ref.dtype)


def ragged_paged_attention(q, k_pages, v_pages, page_tables, kv_lens,
                           query_lens=None, *, causal: bool = False,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Attention of per-row ragged queries over a paged KV pool.

    q: (R, H, Nq, D) queries — row ``r``'s first ``query_lens[r]``
    query rows are live, the rest are padding; k_pages/v_pages:
    (num_pages, page_size, H, D) shared pool; page_tables:
    (R, pages_per_stream) int32; kv_lens: (R,) int32 — row r attends
    its first ``kv_lens[r]`` cached tokens, walked through its own
    page list. ``query_lens=None`` means every query row is live
    (the decode latent-rebuild shape). ``causal=True`` right-aligns
    the windows: query ``i`` sees kv positions
    ``< kv_lens[r] - (query_lens[r] - 1 - i)``. Table entries beyond
    the used pages may be arbitrary (clamped, never contribute).
    Padding query rows, rows with ``kv_lens[r] == 0``, and causal
    queries with empty windows return exact zeros. Returns
    (R, H, Nq, D) in q's dtype.
    """
    interpret = _resolve_interpret(interpret)
    r, h, nq, d = q.shape
    num_pages, page_size = k_pages.shape[:2]
    pps = page_tables.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    dp = _round_up(d, 128)
    nqp = _round_up(nq, 16)
    kv_lens = kv_lens.astype(jnp.int32)
    qlens = (jnp.full((r,), nq, jnp.int32) if query_lens is None
             else query_lens.astype(jnp.int32))

    qp = jnp.pad(q, ((0, 0), (0, 0), (0, nqp - nq), (0, dp - d)))
    # pool → kernel layout (P, H, page_size, Dp): token axis on the
    # sublane dim, head axis blockable at size 1 (see module docstring)
    kp = jnp.pad(jnp.transpose(k_pages, (0, 2, 1, 3)),
                 ((0, 0), (0, 0), (0, 0), (0, dp - d)))
    vp = jnp.pad(jnp.transpose(v_pages, (0, 2, 1, 3)),
                 ((0, 0), (0, 0), (0, 0), (0, dp - d)))

    def kv_index(rr, hh, j, tables, lens, qls):
        # clamp to the last used page: replayed blocks are elided by
        # the pipeline, and compute under them is predicated off
        used = jnp.maximum(
            (lens[rr] + page_size - 1) // page_size, 1)
        jj = jnp.minimum(j, used - 1)
        page = jnp.clip(tables[rr, jj], 0, num_pages - 1)
        return (page, hh, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(r, h, pps),
        in_specs=[
            pl.BlockSpec(
                (1, 1, nqp, dp),
                lambda rr, hh, j, tables, lens, qls: (rr, hh, 0, 0)),
            pl.BlockSpec((1, 1, page_size, dp), kv_index),
            pl.BlockSpec((1, 1, page_size, dp), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, nqp, dp),
            lambda rr, hh, j, tables, lens, qls: (rr, hh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nqp, 128), jnp.float32),
            pltpu.VMEM((nqp, 128), jnp.float32),
            pltpu.VMEM((nqp, dp), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_paged_kernel, scale=float(scale),
                          page_size=page_size, n_steps=pps, nqp=nqp,
                          causal=bool(causal)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, h, nqp, dp), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), kv_lens, qlens, qp, kp, vp)
    out = out[:, :, :nq, :d]
    return _zero_invalid_queries(out, kv_lens, qlens, causal)


def _zero_invalid_queries(out, kv_lens, qlens, causal: bool):
    """Exact zeros for padding query rows and empty attention windows
    — those rows accumulate finite garbage in the kernel (NEG_INF is
    finite by design, so fully-masked score blocks never NaN)."""
    r, _, nq, _ = out.shape
    qi = jnp.arange(nq, dtype=jnp.int32)
    if causal:
        limit = kv_lens[:, None] - (qlens[:, None] - 1 - qi[None, :])
    else:
        limit = jnp.broadcast_to(kv_lens[:, None], (r, nq))
    valid = (qi[None, :] < qlens[:, None]) & (limit > 0)
    return jax.lax.select(
        jnp.broadcast_to(valid[:, None, :, None], out.shape),
        out, jnp.zeros_like(out))


def ragged_paged_attention_reference(q, k_pages, v_pages, page_tables,
                                     kv_lens, query_lens=None, *,
                                     causal: bool = False,
                                     scale: Optional[float] = None):
    """Pure-jax reference for :func:`ragged_paged_attention`.

    Gathers each row's pages into a dense (R, pps·page_size, H, D)
    view and runs masked fp32 attention. This is also the impl the
    sharded (dp2×tp2) decode target lowers — GSPMD partitions gathers
    and einsums, not Pallas calls — hence ``lax.select`` throughout.
    """
    r, h, nq, d = q.shape
    num_pages, page_size = k_pages.shape[:2]
    pps = page_tables.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    kv_lens = kv_lens.astype(jnp.int32)
    qlens = (jnp.full((r,), nq, jnp.int32) if query_lens is None
             else query_lens.astype(jnp.int32))
    tables = jnp.clip(page_tables.astype(jnp.int32), 0, num_pages - 1)
    k = jnp.take(k_pages, tables.reshape(-1), axis=0).reshape(
        r, pps * page_size, k_pages.shape[2], d)
    v = jnp.take(v_pages, tables.reshape(-1), axis=0).reshape(
        r, pps * page_size, v_pages.shape[2], d)
    col = jnp.arange(pps * page_size, dtype=jnp.int32)
    qi = jnp.arange(nq, dtype=jnp.int32)
    if causal:
        limit = kv_lens[:, None] - (qlens[:, None] - 1 - qi[None, :])
    else:
        limit = jnp.broadcast_to(kv_lens[:, None], (r, nq))
    mask = col[None, None, :] < limit[:, :, None]      # (R, Nq, T)
    logits = jnp.einsum("rhnd,rthd->rhnt", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jax.lax.select(
        jnp.broadcast_to(mask[:, None, :, :], logits.shape),
        logits, jnp.full_like(logits, NEG_INF))
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("rhnt,rthd->rhnd", probs, v.astype(jnp.float32))
    valid = (qi[None, :] < qlens[:, None]) & (limit > 0)
    out = jax.lax.select(
        jnp.broadcast_to(valid[:, None, :, None], out.shape),
        out, jnp.zeros_like(out))
    return out.astype(q.dtype)


def tile_for_windows(page_tables, kv_lens, windows: int):
    """Tile ragged-paged operands so each row scores ``windows``
    right-aligned KV prefixes in one kernel call.

    Row ``r`` of the input becomes rows ``r*windows .. r*windows +
    windows - 1`` of the output: window ``j`` replays row r's own page
    walk against its first ``max(kv_lens[r] - (windows - 1 - j), 0)``
    cached tokens, so window ``windows - 1`` sees the full cache (the
    plain decode view) and window ``j`` hides the newest
    ``windows - 1 - j`` tokens. Speculative verify
    (serving/decode.py) is the consumer: after scattering a stream's
    feedback token plus ``k`` drafted tokens in one chunk, the
    target's prediction *at* drafted position ``i`` is exactly the
    full-cache view minus the drafts from ``i`` on — so one ragged
    call over the tiled rows scores every drafted position of every
    stream. No pages are copied: only the table rows repeat and the
    length vector fans out. Returns ``(page_tables, kv_lens)`` shaped
    ``(R*windows, pages_per_stream)`` / ``(R*windows,)``.
    """
    if windows < 1:
        raise ValueError(f"windows must be >= 1, got {windows}")
    r = page_tables.shape[0]
    tables = jnp.repeat(page_tables, windows, axis=0)
    back = jnp.arange(windows - 1, -1, -1, dtype=jnp.int32)
    lens = jnp.maximum(
        kv_lens.astype(jnp.int32)[:, None] - back[None, :], 0)
    return tables, lens.reshape(r * windows)


def paged_decode_attention(q, k_pages, v_pages, page_tables, lengths, *,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Decode attention over a paged KV pool — the decode-shaped
    delegate of :func:`ragged_paged_attention` (every query row live,
    non-causal): q's Nq axis is the latent axis of the rebuild, all
    latents attend row r's first ``lengths[r]`` cached tokens."""
    return ragged_paged_attention(
        q, k_pages, v_pages, page_tables, lengths,
        scale=scale, interpret=interpret)


def paged_decode_attention_reference(q, k_pages, v_pages, page_tables,
                                     lengths, *,
                                     scale: Optional[float] = None):
    """Pure-jax reference for :func:`paged_decode_attention` — the
    decode-shaped delegate of
    :func:`ragged_paged_attention_reference`."""
    return ragged_paged_attention_reference(
        q, k_pages, v_pages, page_tables, lengths, scale=scale)
